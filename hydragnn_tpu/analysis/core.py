"""jaxlint core: findings, suppressions, the rule registry, and the runner.

The analyzer is a plain-``ast`` pass — no imports of the analyzed code, so
it runs in milliseconds on the whole tree and can never be broken by a
module whose import requires an accelerator. Each rule is an object with a
``name``, a ``description`` and a ``check(module) -> Iterable[Finding]``;
rules register themselves via :func:`register` at import time
(``hydragnn_tpu.analysis`` imports every ``rules_*`` module).

Suppression: a finding is dropped when its line (or the line directly
above it, for black-wrapped statements) carries::

    # jaxlint: disable=rule-name[,other-rule]
    # jaxlint: disable            (all rules on that line)

Suppressions are meant to carry a justification comment — the CI gate
diffs are reviewed, a bare disable is a smell.
"""

import ast
import fnmatch
import os
import re
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Sequence, Set

# all four tags are accepted everywhere: `jaxlint` predates the
# concurrency (threadlint), sharding (shardlint) and numerics (numlint)
# suites, and a suppression should read as the suite it silences — but
# the engine is one engine
_SUPPRESS_RE = re.compile(
    r"#\s*(?:jaxlint|threadlint|shardlint|numlint):"
    r"\s*disable(?:=(?P<rules>[A-Za-z0-9_,\- ]+))?"
)


@dataclass(frozen=True)
class Finding:
    rule: str
    path: str  # repo-relative, forward slashes
    line: int
    col: int
    message: str
    snippet: str = ""  # stripped source line — the baseline fingerprint

    @property
    def fingerprint(self):
        """Line-number-free identity: findings survive unrelated edits
        above them, so a committed baseline does not rot on every rebase."""
        return (self.path, self.rule, self.snippet)

    def to_dict(self) -> dict:
        return {
            "rule": self.rule,
            "path": self.path,
            "line": self.line,
            "col": self.col,
            "message": self.message,
            "snippet": self.snippet,
        }


class ModuleInfo:
    """One parsed source file plus the per-line suppression table."""

    def __init__(self, path: str, rel_path: str, source: str):
        self.path = path
        self.rel_path = rel_path.replace(os.sep, "/")
        self.source = source
        self.lines = source.splitlines()
        self.tree = ast.parse(source, filename=path)
        self._suppressions = self._parse_suppressions()

    def _parse_suppressions(self) -> Dict[int, tuple]:
        """line (1-based) -> (rules-or-None-for-all, standalone_comment).
        Only STANDALONE comment directives also cover the next line — a
        trailing directive scopes to its own statement alone."""
        table: Dict[int, tuple] = {}
        for i, line in enumerate(self.lines, start=1):
            m = _SUPPRESS_RE.search(line)
            if not m:
                continue
            rules = m.group("rules")
            parsed = (
                None
                if rules is None
                else {r.strip() for r in rules.split(",") if r.strip()}
            )
            table[i] = (parsed, line.lstrip().startswith("#"))
        return table

    def suppressed(self, rule: str, line: int) -> bool:
        # the flagged line itself, or a standalone comment directive
        # directly above it (multi-line calls anchor past the comment
        # otherwise)
        for ln, need_standalone in ((line, False), (line - 1, True)):
            entry = self._suppressions.get(ln)
            if entry is None:
                continue
            rules, standalone = entry
            if need_standalone and not standalone:
                continue
            if rules is None or rule in rules:
                return True
        return False

    def line_text(self, line: int) -> str:
        if 1 <= line <= len(self.lines):
            return self.lines[line - 1].strip()
        return ""

    def finding(self, rule: str, node: ast.AST, message: str) -> Finding:
        return Finding(
            rule=rule,
            path=self.rel_path,
            line=getattr(node, "lineno", 0),
            col=getattr(node, "col_offset", 0),
            message=message,
            snippet=self.line_text(getattr(node, "lineno", 0)),
        )


# ---- rule registry --------------------------------------------------------

_RULES: Dict[str, "Rule"] = {}


class Rule:
    """Base class: subclasses set ``name``/``description`` and implement
    ``check``. ``hot_path_patterns`` narrows a rule to specific files.
    ``suite`` groups rules for ``--suite`` gating: the JAX/TPU rules are
    ``jax`` (the jaxlint gate), the concurrency/shutdown-safety rules are
    ``concurrency`` (the threadlint gate), the sharding-correctness
    rules are ``sharding`` (the shardlint gate), the numerics/kernel-
    safety rules are ``numerics`` (the numlint gate) — each gate
    ratchets against its own baseline file."""

    name = ""
    description = ""
    suite = "jax"

    def check(self, module: ModuleInfo) -> Iterable[Finding]:
        raise NotImplementedError

    def applies_to(self, module: ModuleInfo) -> bool:
        return True


def register(cls):
    """Class decorator: instantiate and register the rule by name."""
    inst = cls()
    if not inst.name:
        raise ValueError(f"rule {cls.__name__} has no name")
    _RULES[inst.name] = inst
    return cls


def all_rules() -> Dict[str, Rule]:
    return dict(_RULES)


def all_suites() -> Set[str]:
    return {r.suite for r in _RULES.values()}


def rules_in_suite(suite: str) -> Set[str]:
    return {name for name, r in _RULES.items() if r.suite == suite}


# ---- AST helpers shared by the rule modules -------------------------------


def dotted_name(node: ast.AST) -> str:
    """'jax.random.split' for an Attribute/Name chain, '' otherwise."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


def walk_no_nested_functions(node: ast.AST):
    """Walk a statement body without descending into nested def/class
    bodies (lambdas ARE descended — they execute where they appear when
    called per-iteration, e.g. inside ``tree_map``)."""
    stack = list(ast.iter_child_nodes(node))
    while stack:
        child = stack.pop()
        yield child
        if isinstance(
            child, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)
        ):
            continue
        stack.extend(ast.iter_child_nodes(child))


def function_defs(module: ModuleInfo):
    """Every (possibly nested / method) FunctionDef in the module."""
    for node in ast.walk(module.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node


def assigned_names(stmt: ast.stmt) -> Set[str]:
    """Names (re)bound by an assignment-like statement, incl. tuple
    targets and for-loop targets."""
    out: Set[str] = set()

    def collect(target):
        if isinstance(target, ast.Name):
            out.add(target.id)
        elif isinstance(target, (ast.Tuple, ast.List)):
            for elt in target.elts:
                collect(elt)
        elif isinstance(target, ast.Starred):
            collect(target.value)

    if isinstance(stmt, ast.Assign):
        for t in stmt.targets:
            collect(t)
    elif isinstance(stmt, (ast.AugAssign, ast.AnnAssign)):
        collect(stmt.target)
    elif isinstance(stmt, (ast.For, ast.AsyncFor)):
        collect(stmt.target)
    elif isinstance(stmt, (ast.With, ast.AsyncWith)):
        for item in stmt.items:
            if item.optional_vars is not None:
                collect(item.optional_vars)
    return out


# ---- runner ---------------------------------------------------------------

SKIP_DIRS = {"__pycache__", ".git", ".pytest_cache", "node_modules", "logs"}


def iter_python_files(paths: Sequence[str]) -> Iterable[str]:
    for path in paths:
        if os.path.isfile(path):
            if path.endswith(".py"):
                yield path
            continue
        for root, dirs, files in os.walk(path):
            dirs[:] = sorted(
                d for d in dirs
                if d not in SKIP_DIRS and not d.startswith(".")
            )
            for f in sorted(files):
                if f.endswith(".py"):
                    yield os.path.join(root, f)


@dataclass
class AnalysisResult:
    findings: List[Finding] = field(default_factory=list)
    suppressed: int = 0
    parse_errors: List[str] = field(default_factory=list)
    files_checked: int = 0

    def stats(self) -> Dict[str, int]:
        per_rule: Dict[str, int] = {r: 0 for r in sorted(_RULES)}
        for f in self.findings:
            per_rule[f.rule] = per_rule.get(f.rule, 0) + 1
        return per_rule


def analyze_paths(
    paths: Sequence[str],
    select: Optional[Set[str]] = None,
    ignore: Optional[Set[str]] = None,
    root: Optional[str] = None,
) -> AnalysisResult:
    """Run every registered rule over every ``.py`` under ``paths``.

    ``root`` anchors the repo-relative paths used for suppression-stable
    baselines (defaults to the common CWD)."""
    root = os.path.abspath(root or os.getcwd())
    rules = [
        r
        for name, r in sorted(all_rules().items())
        if (select is None or name in select)
        and (ignore is None or name not in ignore)
    ]
    result = AnalysisResult()
    for path in iter_python_files(paths):
        abspath = os.path.abspath(path)
        rel = os.path.relpath(abspath, root)
        try:
            with open(abspath, "r", encoding="utf-8") as f:
                source = f.read()
            module = ModuleInfo(abspath, rel, source)
        except (SyntaxError, UnicodeDecodeError, OSError) as e:
            result.parse_errors.append(f"{rel}: {e}")
            continue
        result.files_checked += 1
        for rule in rules:
            if not rule.applies_to(module):
                continue
            for finding in rule.check(module):
                if module.suppressed(finding.rule, finding.line):
                    result.suppressed += 1
                else:
                    result.findings.append(finding)
    result.findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return result


def matches_any(rel_path: str, patterns: Sequence[str]) -> bool:
    p = rel_path.replace(os.sep, "/")
    return any(
        fnmatch.fnmatch(p, pat) or fnmatch.fnmatch("/" + p, pat)
        for pat in patterns
    )
