"""jaxlint + threadlint + shardlint + numlint: analysis + runtime guards.

Static pass (``python -m hydragnn_tpu.analysis``): an AST-based rule
engine in four suites. The ``jax`` suite (jaxlint) targets JAX/TPU
anti-patterns — per-batch host syncs in step loops, jit wrappers rebuilt
per call, state-threading jits missing ``donate_argnums``, PRNG key
reuse, recompile-hazard static args, general hygiene. The
``concurrency`` suite (threadlint, ``--suite=concurrency``) targets the
always-on serving/telemetry surface — lock-order inversions, blocking
calls under held locks, leaked threads/executors, lock-free mutation of
lock-guarded state, unbounded or shutdown-hostile queues. The
``sharding`` suite (shardlint, ``--suite=sharding``) guards the 2-D
mesh layer — hardcoded axis strings, jit programs missing their
sharding contract, unknown PartitionSpec axes, sharding-less
``device_put``, legacy ``pmap``, leading-dim reshapes in sharded
bodies; its compiled-HLO sibling (``analysis/hlo.py``) ratchets each
step program's collective set against ``.shardlint-hlo.json``. The
``numerics`` suite (numlint, ``--suite=numerics``,
``rules_numerics.py``) guards precision and kernel safety — low-
precision accumulations, mixed-precision policy bypasses, unguarded
exp/log/sqrt/division, NaN-unsafe ``where`` branches, unmasked gathers
on padded neighbor ids, unbudgeted pallas VMEM; its compiled sibling
(``analysis/mem.py``) ratchets each step program's
``memory_analysis()`` peak/temp/output bytes against
``.numlint-mem.json``. See ``docs/static-analysis.md`` for the rule
catalog, suppression syntax, and the per-suite baseline ratchets.

Runtime guards (``hydragnn_tpu.analysis.guards``): what the static pass
cannot prove — a :class:`CompileSentinel` asserting the XLA compile
counter stays flat after warmup, :func:`no_host_syncs`, a
``jax.transfer_guard`` harness that turns implicit device->host
transfers into hard errors inside tests, :func:`lock_sanitizer`, a
lock-order/deadlock sanitizer with per-lock wait/hold metrics and a
stack-dumping watchdog, :func:`sharding_sentinel`, which asserts
program outputs LAND at their declared shardings, and
:func:`nan_sentinel`, which localizes a wrapped region's first
non-finite output leaf to a named head/param subtree.
"""

from hydragnn_tpu.analysis.core import (  # noqa: F401
    AnalysisResult,
    Finding,
    Rule,
    all_rules,
    analyze_paths,
    register,
)

# importing the rule modules populates the registry
from hydragnn_tpu.analysis import (  # noqa: F401  (registration side effect)
    rules_concurrency,
    rules_host_sync,
    rules_hygiene,
    rules_jit,
    rules_numerics,
    rules_prng,
    rules_sharding,
)
from hydragnn_tpu.analysis.guards import (  # noqa: F401
    CompileSentinel,
    InstrumentedLock,
    LockOrderViolation,
    LockSanitizer,
    NonFiniteError,
    ShardingSentinel,
    ShardingViolation,
    lock_sanitizer,
    nan_origin,
    nan_sentinel,
    no_host_syncs,
    no_implicit_transfers,
    nonfinite_report,
    sharding_sentinel,
)
