"""jaxlint: JAX/TPU anti-pattern static analysis + runtime guards.

Static pass (``python -m hydragnn_tpu.analysis``): an AST-based rule
engine targeting the failure modes this stack actually has — per-batch
host syncs in step loops, jit wrappers rebuilt per call, state-threading
jits missing ``donate_argnums``, PRNG key reuse, recompile-hazard static
args, and general hygiene. See ``docs/static-analysis.md`` for the rule
catalog, suppression syntax, and the baseline ratchet.

Runtime guards (``hydragnn_tpu.analysis.guards``): what the static pass
cannot prove — a :class:`CompileSentinel` asserting the XLA compile
counter stays flat after warmup, and :func:`no_host_syncs`, a
``jax.transfer_guard`` harness that turns implicit device->host
transfers into hard errors inside tests.
"""

from hydragnn_tpu.analysis.core import (  # noqa: F401
    AnalysisResult,
    Finding,
    Rule,
    all_rules,
    analyze_paths,
    register,
)

# importing the rule modules populates the registry
from hydragnn_tpu.analysis import (  # noqa: F401  (registration side effect)
    rules_host_sync,
    rules_hygiene,
    rules_jit,
    rules_prng,
)
from hydragnn_tpu.analysis.guards import (  # noqa: F401
    CompileSentinel,
    no_host_syncs,
    no_implicit_transfers,
)
