"""host-sync-in-hot-loop: per-batch device->host round trips.

``float(metrics["loss"])`` on a jit output blocks the host until the
dispatched program finishes AND serializes the async pipeline — on the
tunneled TPU backend each fetch costs a full network round trip, which is
exactly why the trainer accumulates packed device vectors and reads them
back once per epoch (``Trainer._acc_add`` / ``_acc_read``). This rule
fails CI when someone reintroduces the per-batch sync.

Scope: the per-step loops live in a handful of files (the hot set below);
everything else — epoch drivers doing once-per-epoch host work, data
pipelines operating on host numpy — does host conversions legitimately,
so the rule stays narrow rather than drowning the tree in suppressions.

A loop is **hot** when its body dispatches device work — it calls
something that looks like a compiled step (``*_step`` / ``*_multi`` /
``*_scan`` / ``put_batch*`` / ``_dispatch*`` / ``.apply``). Host-side
collection loops (masking already-fetched numpy arrays) never dispatch,
so they stay out of scope by construction.

Detection, two tiers:

- **hot loop bodies**: ``float(x)`` / ``int(x)`` on non-trivial
  expressions, ``.item()``, and ``np.asarray(x)`` / ``np.array(x)`` — the
  implicit-transfer spellings. Explicit ``jax.device_get`` is allowed: it
  is the documented way to do an INTENTIONAL bulk fetch (and the
  transfer-guard test enforces that only explicit fetches happen).
- **helpers called from hot loops** (same-file resolution, depth 1):
  ``float``/``int``/``.item()`` only — numpy conversions inside helpers
  routinely operate on host data (collate, mask collection) and are
  checked by the runtime transfer guard instead.
"""

import ast
import re
from typing import Dict, Iterable, List, Set

from hydragnn_tpu.analysis.core import (
    Finding,
    ModuleInfo,
    Rule,
    dotted_name,
    matches_any,
    register,
    walk_no_nested_functions,
)

# the files holding per-step dispatch loops (see module docstring for why
# this is a narrow, named set; extend it when a new per-batch loop lands)
HOT_FILE_PATTERNS = (
    "*/train/trainer.py",
    "*/train/predict.py",
    "*/train/partitioned.py",
    "*/serve/server.py",
    "train/trainer.py",
    "train/predict.py",
    "train/partitioned.py",
    "serve/server.py",
)

# a call whose terminal name matches marks its enclosing loop as
# device-dispatching ("hot")
_DISPATCH_HINT = re.compile(
    r"(_step|_multi|_scan|put_batch|_dispatch|train_epoch|^apply$)"
)

# int()/float() on these is host-side bookkeeping, not a device sync
_TRIVIAL_CALLEES = {
    "len",
    "round",
    "min",
    "max",
    "abs",
    "os.getenv",
    "getattr",
    "time.time",
    "time.monotonic",
    "time.perf_counter",
    "str",
    "repr",
    "input",
}

_NUMPY_CONVERTERS = {"np.asarray", "np.array", "numpy.asarray", "numpy.array"}


def _is_trivial_scalar_arg(arg: ast.AST) -> bool:
    """True for arguments that cannot be device values."""
    if isinstance(arg, ast.Constant):
        return True
    if isinstance(arg, ast.Call):
        return dotted_name(arg.func) in _TRIVIAL_CALLEES
    if isinstance(arg, ast.JoinedStr):
        return True
    return False


def _terminal_name(func: ast.AST) -> str:
    if isinstance(func, ast.Attribute):
        return func.attr
    if isinstance(func, ast.Name):
        return func.id
    return ""


@register
class HostSyncInHotLoop(Rule):
    name = "host-sync-in-hot-loop"
    description = (
        "Per-batch host synchronization (float()/int()/.item()/np.asarray "
        "on device values) inside a per-step dispatch loop — accumulate on "
        "device and read back once per epoch (Trainer._acc_add/_acc_read)"
    )

    def applies_to(self, module: ModuleInfo) -> bool:
        return matches_any(module.rel_path, HOT_FILE_PATTERNS)

    def check(self, module: ModuleInfo) -> Iterable[Finding]:
        defs = self._collect_defs(module)
        findings: List[Finding] = []
        seen: Set[int] = set()  # node ids — loops nest, report each once
        hot_helpers: Dict[str, str] = {}  # helper name -> reached-from

        for fn in self._functions(module):
            for loop in walk_no_nested_functions(fn):
                if not isinstance(loop, (ast.For, ast.While)):
                    continue
                body = list(self._loop_body_nodes(loop))
                if not self._dispatches(body):
                    continue
                for node in body:
                    if id(node) in seen:
                        continue
                    seen.add(id(node))
                    hit = self._classify(node, in_loop=True)
                    if hit:
                        findings.append(
                            module.finding(
                                self.name,
                                node,
                                f"{hit} inside the per-step loop of "
                                f"`{fn.name}` — this is a device->host "
                                "sync per batch; accumulate on device "
                                "and fetch once per epoch",
                            )
                        )
                    if isinstance(node, ast.Call):
                        helper = self._called_helper(node)
                        if helper and helper in defs:
                            hot_helpers.setdefault(helper, fn.name)

        for helper, reached_from in hot_helpers.items():
            for node in walk_no_nested_functions(defs[helper]):
                if id(node) in seen:
                    continue
                hit = self._classify(node, in_loop=False)
                if hit:
                    seen.add(id(node))
                    findings.append(
                        module.finding(
                            self.name,
                            node,
                            f"{hit} in `{helper}`, reached from the "
                            f"per-step loop of `{reached_from}` — this "
                            "runs once per batch; keep the value on "
                            "device",
                        )
                    )
        return findings

    # ---- helpers -------------------------------------------------------
    @staticmethod
    def _functions(module: ModuleInfo):
        for node in ast.walk(module.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                yield node

    @staticmethod
    def _collect_defs(module: ModuleInfo) -> Dict[str, ast.FunctionDef]:
        """name -> def, for same-file helper resolution (methods resolve
        by bare name: ``self._acc_add`` -> ``_acc_add``)."""
        defs: Dict[str, ast.FunctionDef] = {}
        for node in ast.walk(module.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                defs.setdefault(node.name, node)
        return defs

    @staticmethod
    def _loop_body_nodes(loop):
        """Every node in the loop's body (not its iterator — that runs
        once) without crossing nested def boundaries."""
        for stmt in loop.body + getattr(loop, "orelse", []):
            yield stmt
            yield from walk_no_nested_functions(stmt)

    @staticmethod
    def _dispatches(body_nodes) -> bool:
        for node in body_nodes:
            if isinstance(node, ast.Call) and _DISPATCH_HINT.search(
                _terminal_name(node.func)
            ):
                return True
        return False

    @staticmethod
    def _called_helper(call: ast.Call):
        """'self.helper(...)' or 'helper(...)' -> 'helper'."""
        f = call.func
        if (
            isinstance(f, ast.Attribute)
            and isinstance(f.value, ast.Name)
            and f.value.id in ("self", "cls")
        ):
            return f.attr
        if isinstance(f, ast.Name):
            return f.id
        return None

    @staticmethod
    def _classify(node: ast.AST, in_loop: bool):
        if not isinstance(node, ast.Call):
            return None
        name = dotted_name(node.func)
        if name in ("float", "int") and len(node.args) == 1:
            if not _is_trivial_scalar_arg(node.args[0]):
                return f"`{name}(...)`"
            return None
        if (
            isinstance(node.func, ast.Attribute)
            and node.func.attr == "item"
            and not node.args
            and not node.keywords
        ):
            return "`.item()`"
        if in_loop and name in _NUMPY_CONVERTERS and node.args:
            if not _is_trivial_scalar_arg(node.args[0]) and not isinstance(
                node.args[0], (ast.List, ast.Tuple, ast.Dict)
            ):
                return f"`{name}(...)`"
        return None
