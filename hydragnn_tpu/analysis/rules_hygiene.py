"""Hygiene rules: mutable-default-arg and float64-literal.

- **mutable-default-arg**: the classic Python footgun, with a JAX twist —
  a mutable default on a collate/config function is shared across calls,
  and a dict default that ends up in a jit closure is an unhashable
  recompile hazard.
- **float64-literal**: ``jnp`` calls with an explicit float64 dtype. On
  TPU the stack runs x32 (``jax_enable_x64`` off): the literal silently
  downcasts to f32 — the author THINKS they bought precision and did not.
  With x64 on it doubles memory traffic on the hot path instead. Host-side
  ``np.float64`` accumulation (the repo's exact-epoch-sum idiom) is
  untouched; only device-bound ``jnp``/``jax.numpy`` spellings flag.
"""

import ast
from typing import Iterable, List

from hydragnn_tpu.analysis.core import (
    Finding,
    ModuleInfo,
    Rule,
    dotted_name,
    register,
)


@register
class MutableDefaultArg(Rule):
    name = "mutable-default-arg"
    description = (
        "Mutable default argument (list/dict/set literal) — shared "
        "across calls, and unhashable if it reaches a jit static arg"
    )

    def check(self, module: ModuleInfo) -> Iterable[Finding]:
        findings: List[Finding] = []
        for node in ast.walk(module.tree):
            if not isinstance(
                node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
            ):
                continue
            defaults = list(node.args.defaults) + [
                d for d in node.args.kw_defaults if d is not None
            ]
            for d in defaults:
                if isinstance(d, (ast.List, ast.Dict, ast.Set)) or (
                    isinstance(d, ast.Call)
                    and dotted_name(d.func) in ("list", "dict", "set")
                ):
                    name = getattr(node, "name", "<lambda>")
                    findings.append(
                        module.finding(
                            self.name,
                            d,
                            f"mutable default in `{name}` is evaluated "
                            "once and shared across every call — default "
                            "to None and construct inside the body",
                        )
                    )
        return findings


_F64_DTYPES = {
    "np.float64",
    "numpy.float64",
    "jnp.float64",
    "jax.numpy.float64",
}


@register
class Float64Literal(Rule):
    name = "float64-literal"
    description = (
        "Explicit float64 dtype on a jnp call — silently downcast to f32 "
        "under the stack's x32 config (or doubles HBM traffic with x64 "
        "on); use f32, or np.* for host-side exact accumulation"
    )

    def check(self, module: ModuleInfo) -> Iterable[Finding]:
        findings: List[Finding] = []
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Call):
                continue
            callee = dotted_name(node.func)
            if callee in ("jnp.float64", "jax.numpy.float64"):
                findings.append(
                    module.finding(
                        self.name,
                        node,
                        "jnp.float64(...) literal — x32 mode silently "
                        "downcasts this to f32",
                    )
                )
                continue
            if not callee.startswith(("jnp.", "jax.numpy.")):
                continue
            for arg in [*node.args, *[k.value for k in node.keywords]]:
                if self._is_f64(arg):
                    findings.append(
                        module.finding(
                            self.name,
                            node,
                            f"float64 dtype passed to {callee} — device "
                            "arrays run x32; this either downcasts "
                            "silently or doubles memory traffic",
                        )
                    )
                    break
        return findings

    @staticmethod
    def _is_f64(arg: ast.AST) -> bool:
        if isinstance(arg, ast.Constant) and arg.value == "float64":
            return True
        return dotted_name(arg) in _F64_DTYPES
