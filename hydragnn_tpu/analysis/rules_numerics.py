"""Numerics & kernel-safety rules (numlint, ``--suite=numerics``).

The ROADMAP's MFU phase 2 (superblock Pallas kernels, int8 aggregation,
wider bf16) makes precision and on-chip memory MORE dangerous to get
wrong: a bf16 accumulation, an unclamped ``exp``, or an unmasked gather
in a padded-edge kernel all pass tier-1 on CPU f32 and land as silent
per-head accuracy loss, not a crash. These rules are the lint half of
numlint; the compiled-memory ratchet (``analysis/mem.py``) and the
``nan_sentinel`` runtime harness (``analysis/guards.py``) are the
post-compile and runtime halves.

Every rule here is a heuristic over dataflow the AST can see — a
per-function map of reaching assignments, so ``count = jnp.maximum(
count, 1.0)`` upstream of ``x / count`` reads as guarded. Sites the
pass cannot prove safe but a human can are suppressed in place with
``# numlint: disable=rule-name`` plus a justification (the CI gate
diffs are reviewed; a bare disable is a smell).
"""

import ast
from typing import Dict, Iterable, List, Optional, Set, Tuple

from hydragnn_tpu.analysis.core import (
    Finding,
    ModuleInfo,
    Rule,
    dotted_name,
    function_defs,
    matches_any,
    register,
    walk_no_nested_functions,
)

# numeric model/kernel code — where an accumulation or an unclamped
# transcendental turns into per-head accuracy loss
_NUMERIC_PATTERNS = (
    "hydragnn_tpu/models/*", "models/*", "*/models/*",
    "hydragnn_tpu/graph/*", "graph/*", "*/graph/*",
    "hydragnn_tpu/ops/*", "ops/*", "*/ops/*",
)
# the padded-edge kernels: gathers here must honor fused_mp's masking
# contract (_safe_gather / explicit where-mask of every padded slot)
_OPS_PATTERNS = (
    "hydragnn_tpu/ops/*", "ops/*", "*/ops/*",
)
# the ONE sanctioned precision-decision point plus the step builder
# that applies it (train/steps.py casts batches/params per the policy)
_PRECISION_SANCTIONED = (
    "hydragnn_tpu/models/create.py", "models/create.py",
    "*/models/create.py",
    "hydragnn_tpu/train/steps.py", "train/steps.py", "*/train/steps.py",
)

_F32_DTYPES = {
    "jnp.float32", "jnp.float64", "jax.numpy.float32",
    "jax.numpy.float64", "np.float32", "np.float64", "numpy.float32",
    "numpy.float64",
}
_LOW_DTYPES = {
    "jnp.bfloat16", "jnp.float16", "jax.numpy.bfloat16",
    "jax.numpy.float16", "np.float16", "numpy.float16",
}
_CREATION_TAILS = {
    "array", "asarray", "zeros", "ones", "full", "empty", "arange",
    "linspace", "zeros_like", "ones_like", "full_like",
}


def _tail(callee: str) -> str:
    return callee.rsplit(".", 1)[-1]


def _call_tail(node: ast.Call) -> str:
    # an Attribute callee keeps its method name even when the receiver
    # is itself a call (`jnp.where(...).sum(...)` — dotted_name returns
    # '' there, and the `.sum` is exactly the accumulation to check)
    if isinstance(node.func, ast.Attribute):
        return node.func.attr
    return _tail(dotted_name(node.func))


def _is_dtype(node: ast.AST, names: Set[str], strings: Tuple[str, ...]):
    if isinstance(node, ast.Constant) and node.value in strings:
        return True
    return dotted_name(node) in names


def _is_f32_dtype(node: ast.AST) -> bool:
    return _is_dtype(node, _F32_DTYPES, ("float32", "float64"))


def _is_low_dtype(node: ast.AST) -> bool:
    return _is_dtype(node, _LOW_DTYPES, ("bfloat16", "float16"))


# ---- per-function reaching-assignment dataflow ----------------------------

Env = Dict[str, List[Tuple[int, ast.AST]]]


def _env_of(scope: ast.AST) -> Env:
    """name -> ordered [(lineno, rhs expr)] for simple assignments in a
    function (or module) body, nested defs excluded."""
    env: Env = {}
    for node in walk_no_nested_functions(scope):
        target = None
        if (
            isinstance(node, ast.Assign)
            and len(node.targets) == 1
            and isinstance(node.targets[0], ast.Name)
        ):
            target = node.targets[0].id
        elif (
            isinstance(node, ast.AnnAssign)
            and isinstance(node.target, ast.Name)
            and node.value is not None
        ):
            target = node.target.id
        if target is not None:
            env.setdefault(target, []).append((node.lineno, node.value))
    for entries in env.values():
        entries.sort(key=lambda e: e[0])
    return env


def _reaching(
    env: Env, name: str, line: int
) -> Optional[Tuple[int, ast.AST]]:
    """The LAST assignment to ``name`` strictly before ``line`` — so a
    clamp reassignment (``count = jnp.maximum(count, 1.0)``) wins over
    the raw reduction it replaced."""
    best = None
    for ln, val in env.get(name, ()):
        if ln < line and (best is None or ln > best[0]):
            best = (ln, val)
    return best


def _scopes(module: ModuleInfo):
    """(scope_node, env, is_kernel) for module top level and every
    function. Pallas kernel bodies (``def kernel``/``*_kernel``) are
    exempt from the accumulation rules — the WRAPPER's visible upcast is
    the contract; inside the kernel everything is already f32 refs."""
    yield module.tree, _env_of(module.tree), False
    for fn in function_defs(module):
        kernel = fn.name == "kernel" or fn.name.endswith("_kernel")
        yield fn, _env_of(fn), kernel


def _has_f32_marker(expr: ast.AST) -> bool:
    for sub in ast.walk(expr):
        if isinstance(sub, ast.Call):
            if (
                isinstance(sub.func, ast.Attribute)
                and sub.func.attr == "astype"
                and sub.args
                and _is_f32_dtype(sub.args[0])
            ):
                return True
            for kw in sub.keywords:
                if kw.arg == "dtype" and _is_f32_dtype(kw.value):
                    return True
        if _is_f32_dtype(sub):  # positional dtype arg / bare reference
            return True
    return False


def _f32_safe(
    expr: Optional[ast.AST], env: Env, line: int, depth: int = 4
) -> bool:
    """Can the AST PROVE this expression is f32 (or wider)? Constants
    and unknowns are NOT safe — in a bf16 forward they inherit bf16."""
    if depth <= 0 or expr is None:
        return False
    if _has_f32_marker(expr):
        return True
    if isinstance(expr, ast.Name):
        prev = _reaching(env, expr.id, line)
        return prev is not None and _f32_safe(
            prev[1], env, prev[0], depth - 1
        )
    if isinstance(expr, (ast.Subscript, ast.Attribute, ast.Starred)):
        return _f32_safe(expr.value, env, line, depth - 1)
    if isinstance(expr, ast.UnaryOp):
        return _f32_safe(expr.operand, env, line, depth - 1)
    if isinstance(expr, ast.BinOp):
        return _f32_safe(expr.left, env, line, depth - 1) or _f32_safe(
            expr.right, env, line, depth - 1
        )
    if isinstance(expr, ast.Call):
        tail = _call_tail(expr)
        if tail == "where" and len(expr.args) >= 3:
            return _f32_safe(
                expr.args[1], env, line, depth - 1
            ) or _f32_safe(expr.args[2], env, line, depth - 1)
        if tail in (
            "reshape", "transpose", "squeeze", "sum", "mean",
        ) and isinstance(expr.func, ast.Attribute):
            return _f32_safe(expr.func.value, env, line, depth - 1)
    return False


# ---- guard-expression helpers ---------------------------------------------


def _contains_call_tail(expr: ast.AST, tails: Set[str]) -> bool:
    for sub in ast.walk(expr):
        if isinstance(sub, ast.Call) and _call_tail(sub) in tails:
            return True
    return False


def _contains_add_const(expr: ast.AST) -> bool:
    """``x + 1.0``-style eps offsets — the additive guard idiom."""
    for sub in ast.walk(expr):
        if isinstance(sub, ast.BinOp) and isinstance(sub.op, ast.Add):
            for side in (sub.left, sub.right):
                if (
                    isinstance(side, ast.Constant)
                    and isinstance(side.value, (int, float))
                    and side.value > 0
                ):
                    return True
    return False


def _names_mention(expr: ast.AST, fragment: str) -> bool:
    for sub in ast.walk(expr):
        ident = ""
        if isinstance(sub, ast.Name):
            ident = sub.id
        elif isinstance(sub, ast.Attribute):
            ident = sub.attr
        if fragment in ident.lower():
            return True
    return False


_CLAMP_TAILS = {"maximum", "clip", "clamp"}

_COUNT_FRAGMENTS = (
    "mask", "valid", "n_node", "n_edge", "deg", "count", "cnt",
    "length", "size",
)


def _is_count_operand(expr: ast.AST) -> bool:
    """Bool masks and integer counts — their reductions accumulate in
    int, never bf16. Unwraps trailing subscripts/attribute chains."""
    while isinstance(expr, (ast.Subscript,)):
        expr = expr.value
    ident = ""
    if isinstance(expr, ast.Name):
        ident = expr.id
    elif isinstance(expr, ast.Attribute):
        ident = expr.attr
    low = ident.lower()
    return any(f in low for f in _COUNT_FRAGMENTS)


# ---- rule 1: low-precision accumulation -----------------------------------


@register
class LowPrecisionAccum(Rule):
    name = "low-precision-accum"
    suite = "numerics"
    description = (
        "segment_sum/cumsum/matmul/long-axis .sum whose operand can be "
        "bf16 without an f32 upcast or preferred_element_type — a "
        "K-neighbor accumulation in bf16 loses ~3 decimal digits; "
        "upcast the masked operand (.astype(jnp.float32)) and cast the "
        "result back, like ops/dense_agg.py"
    )

    def applies_to(self, module: ModuleInfo) -> bool:
        return matches_any(module.rel_path, _NUMERIC_PATTERNS)

    def check(self, module: ModuleInfo) -> Iterable[Finding]:
        in_ops = matches_any(module.rel_path, _OPS_PATTERNS)
        findings: List[Finding] = []
        for scope, env, kernel in _scopes(module):
            if kernel:
                continue  # the wrapper's visible upcast is the contract
            for node in walk_no_nested_functions(scope):
                if not isinstance(node, ast.Call):
                    continue
                callee = dotted_name(node.func)
                tail = _call_tail(node)
                if tail == "segment_sum" and "." in callee:
                    # bare-name segment_sum is graph/segment.py's
                    # upcasting wrapper — only raw jax.ops dispatch
                    # needs its operand proven f32
                    data = node.args[0] if node.args else None
                    if data is not None and not _f32_safe(
                        data, env, node.lineno
                    ):
                        findings.append(
                            module.finding(
                                self.name,
                                node,
                                f"{callee} accumulates its data operand "
                                "at the operand's dtype — under the "
                                "bf16 policy that is a bf16 scatter-"
                                "add; upcast (.astype(jnp.float32)) "
                                "before the segment op (or call the "
                                "graph.segment wrapper, which does)",
                            )
                        )
                elif tail == "cumsum":
                    if any(kw.arg == "dtype" for kw in node.keywords):
                        continue
                    if callee.startswith(("np.", "numpy.")):
                        continue  # host-side numpy (f64 accumulators)
                    operand = (
                        node.func.value
                        if isinstance(node.func, ast.Attribute)
                        and callee not in ("jnp.cumsum",)
                        else (node.args[0] if node.args else None)
                    )
                    if operand is not None and _is_count_operand(operand):
                        continue  # integer offset/count prefix sums
                    if operand is not None and not _f32_safe(
                        operand, env, node.lineno
                    ):
                        findings.append(
                            module.finding(
                                self.name,
                                node,
                                "cumsum without dtype= runs the prefix "
                                "sum at the operand dtype — pass "
                                "dtype=jnp.float32 (bf16 prefix sums "
                                "drift with length)",
                            )
                        )
                elif in_ops and tail in ("dot", "matmul", "dot_general"):
                    if any(
                        kw.arg == "preferred_element_type"
                        for kw in node.keywords
                    ):
                        continue
                    if all(
                        _f32_safe(a, env, node.lineno) for a in node.args
                    ) and node.args:
                        continue
                    findings.append(
                        module.finding(
                            self.name,
                            node,
                            f"{callee} without preferred_element_type "
                            "accumulates at the operand dtype — on the "
                            "MXU a bf16 contraction should accumulate "
                            "f32; pass preferred_element_type="
                            "jnp.float32",
                        )
                    )
                elif in_ops and tail == "sum":
                    axis = None
                    for kw in node.keywords:
                        if kw.arg == "axis":
                            axis = kw.value
                    if axis is None and node.args and not isinstance(
                        node.func, ast.Attribute
                    ):
                        pass  # jnp.sum(x) full reduce — skip
                    if axis is None and isinstance(
                        node.func, ast.Attribute
                    ) and node.args:
                        axis = node.args[0]
                    elif axis is None and not isinstance(
                        node.func, ast.Attribute
                    ) and len(node.args) >= 2:
                        axis = node.args[1]
                    # only leading/neighbor axes: axis=-1 is the short
                    # feature axis (cheap, error-bounded); no axis is a
                    # scalar reduce outside the hot aggregation shape
                    if not (
                        isinstance(axis, ast.Constant)
                        and axis.value in (0, 1)
                    ):
                        continue
                    operand = (
                        node.func.value
                        if isinstance(node.func, ast.Attribute)
                        else (node.args[0] if node.args else None)
                    )
                    if operand is not None and _is_count_operand(operand):
                        continue  # bool-mask/count sums reduce to int
                    if operand is not None and not _f32_safe(
                        operand, env, node.lineno
                    ):
                        findings.append(
                            module.finding(
                                self.name,
                                node,
                                ".sum over the neighbor axis at the "
                                "operand dtype — in the dense bf16 "
                                "path this is a K-length bf16 "
                                "accumulation; upcast the masked "
                                "operand to f32 and cast the result "
                                "back to the input dtype",
                            )
                        )
        return findings


# ---- rule 2: precision-policy bypass --------------------------------------


@register
class PrecisionPolicyBypass(Rule):
    name = "precision-policy-bypass"
    suite = "numerics"
    description = (
        "bf16/f16 dtype literal in a cast/creation outside the "
        "sanctioned precision sites (models/create.resolve_precision "
        "decides, train/steps.py applies) — a stray low-precision cast "
        "silently overrides the policy the MFU ledger accounts against"
    )

    def applies_to(self, module: ModuleInfo) -> bool:
        return not matches_any(module.rel_path, _PRECISION_SANCTIONED)

    def check(self, module: ModuleInfo) -> Iterable[Finding]:
        findings: List[Finding] = []
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Call):
                continue
            hit = None
            if (
                isinstance(node.func, ast.Attribute)
                and node.func.attr == "astype"
                and node.args
                and _is_low_dtype(node.args[0])
            ):
                hit = "astype cast"
            else:
                for kw in node.keywords:
                    if kw.arg == "dtype" and _is_low_dtype(kw.value):
                        hit = "dtype= argument"
                        break
                if hit is None and _call_tail(node) in _CREATION_TAILS:
                    for arg in node.args:
                        if _is_low_dtype(arg):
                            hit = "creation dtype"
                            break
            if hit is not None:
                findings.append(
                    module.finding(
                        self.name,
                        node,
                        f"low-precision {hit} outside the precision "
                        "policy — models/create.resolve_precision is "
                        "the ONE decision point and train/steps.py the "
                        "one application site; route through the "
                        "policy (or justify with a numlint suppression)",
                    )
                )
        return findings


# ---- rule 3: unguarded exp/log/sqrt/division ------------------------------


def _exp_guarded(arg: ast.AST, env: Env, line: int) -> bool:
    if isinstance(arg, ast.Constant):
        return True
    if _contains_call_tail(arg, _CLAMP_TAILS | {"minimum", "where"}):
        return True
    # max-shifted softmax idiom: exp(logits - seg_max[...]) / exp(a - amax)
    for sub in ast.walk(arg):
        if isinstance(sub, ast.BinOp) and isinstance(sub.op, ast.Sub):
            if _names_mention(sub.right, "max"):
                return True
    # exp(-x) where x is provably nonnegative-ish (clamped/abs/squared)
    if isinstance(arg, ast.UnaryOp) and isinstance(arg.op, ast.USub):
        inner = arg.operand
        if _contains_call_tail(
            inner, _CLAMP_TAILS | {"abs", "square", "softplus"}
        ):
            return True
        if isinstance(inner, ast.Name):
            prev = _reaching(env, inner.id, line)
            if prev is not None and _contains_call_tail(
                prev[1], _CLAMP_TAILS | {"abs", "square", "softplus"}
            ):
                return True
    return False


def _log_guarded(arg: ast.AST, env: Env, line: int) -> bool:
    if isinstance(arg, ast.Constant):
        return True
    if _contains_call_tail(
        arg, _CLAMP_TAILS | {"abs", "exp", "where", "finfo"}
    ):
        return True
    if _contains_add_const(arg) or _names_mention(arg, "eps"):
        return True
    if isinstance(arg, ast.Name):
        prev = _reaching(env, arg.id, line)
        if prev is not None:
            return _log_guarded(prev[1], env, prev[0])
    return False


def _reduction_like(expr: ast.AST) -> bool:
    """A computed ARRAY reduction that can legitimately hit exactly
    zero — masked sums, segment scatters, padded counts. The Python
    builtin ``sum(...)`` (host-side config math) does not count."""
    for sub in ast.walk(expr):
        if not isinstance(sub, ast.Call):
            continue
        tail = _call_tail(sub)
        if tail in ("segment_sum", "segment_count", "count_nonzero"):
            return True
        if tail == "sum" and (
            isinstance(sub.func, ast.Attribute)
            or "." in dotted_name(sub.func)
        ):
            return True
    return False


def _div_guarded(expr: ast.AST) -> bool:
    return (
        _contains_call_tail(expr, _CLAMP_TAILS)
        or _contains_add_const(expr)
        or _names_mention(expr, "eps")
    )


def _sqrt_trigger(expr: ast.AST) -> bool:
    """sqrt args that can reach zero/negative: differences, ratios,
    powers-of-differences, reductions. Plain widths/fan-ins (init
    bounds) never trigger."""
    for sub in ast.walk(expr):
        if isinstance(sub, ast.BinOp) and isinstance(
            sub.op, (ast.Sub, ast.Pow, ast.Div)
        ):
            return True
    return _reduction_like(expr)


def _sqrt_guarded(expr: ast.AST) -> bool:
    return (
        _contains_call_tail(expr, _CLAMP_TAILS | {"abs", "where"})
        or _contains_add_const(expr)
        or _names_mention(expr, "eps")
    )


@register
class UnguardedExpLogDiv(Rule):
    name = "unguarded-exp-log-div"
    suite = "numerics"
    description = (
        "exp/log/sqrt/division on an unbounded computed input in model/"
        "kernel code without a clamp/eps — exp overflows bf16 at ~88, "
        "log(0)/x÷0 poison the loss, sqrt(0) has an infinite gradient; "
        "clamp the argument (jnp.maximum/minimum/+eps) or use the "
        "double-where _safe_sqrt idiom"
    )

    def applies_to(self, module: ModuleInfo) -> bool:
        return matches_any(module.rel_path, _NUMERIC_PATTERNS)

    def check(self, module: ModuleInfo) -> Iterable[Finding]:
        findings: List[Finding] = []
        seen: Set[Tuple[int, int]] = set()

        def flag(node, msg):
            key = (node.lineno, node.col_offset)
            if key in seen:
                return
            seen.add(key)
            findings.append(module.finding(self.name, node, msg))

        for scope, env, _kernel in _scopes(module):
            for node in walk_no_nested_functions(scope):
                if isinstance(node, ast.Call):
                    tail = _call_tail(node)
                    arg = node.args[0] if node.args else None
                    if arg is None:
                        continue
                    if tail == "exp" and not _exp_guarded(
                        arg, env, node.lineno
                    ):
                        flag(
                            node,
                            "exp of an unbounded argument — overflows "
                            "to inf (bf16 at ~88); clamp with "
                            "jnp.minimum(arg, 0.0)/max-shift before "
                            "exponentiating",
                        )
                    elif tail in ("log", "log2", "log10") and (
                        not _log_guarded(arg, env, node.lineno)
                    ):
                        flag(
                            node,
                            "log of an unclamped argument — log(0) is "
                            "-inf and poisons every reduction it "
                            "touches; add an eps (jnp.log(x + eps) / "
                            "jnp.maximum(x, eps))",
                        )
                    elif tail == "sqrt":
                        expr = arg
                        if isinstance(arg, ast.Name):
                            prev = _reaching(env, arg.id, node.lineno)
                            if prev is None:
                                continue
                            expr = prev[1]
                        if _sqrt_trigger(expr) and not (
                            _sqrt_guarded(arg) or _sqrt_guarded(expr)
                        ):
                            flag(
                                node,
                                "sqrt of a difference/reduction that "
                                "can reach exactly zero — the gradient "
                                "is inf at 0 and NaNs the backward "
                                "pass; use the double-where _safe_sqrt "
                                "idiom (models/schnet.py) or add an eps",
                            )
                elif isinstance(node, ast.BinOp) and isinstance(
                    node.op, ast.Div
                ):
                    den = node.right
                    if _div_guarded(den):
                        continue
                    expr = den
                    if isinstance(den, ast.Name):
                        prev = _reaching(env, den.id, node.lineno)
                        if prev is None:
                            continue
                        expr = prev[1]
                        if _div_guarded(expr):
                            continue
                    if _reduction_like(expr):
                        flag(
                            node,
                            "division by a computed reduction — masked "
                            "sums/segment counts hit exactly zero on "
                            "padded slots; guard the denominator "
                            "(jnp.maximum(den, 1.0) or + eps)",
                        )
        return findings


# ---- rule 4: the jnp.where grad-NaN trap ----------------------------------

_TRAP_TAILS = {"sqrt", "rsqrt", "log", "log1p", "log2", "log10"}


def _branch_guarded(inner: ast.AST, env: Env, line: int) -> bool:
    if isinstance(inner, ast.Constant):
        return True
    if _contains_call_tail(inner, _CLAMP_TAILS | {"abs", "where"}):
        return True
    if _contains_add_const(inner) or _names_mention(inner, "eps"):
        return True
    if isinstance(inner, ast.Name):
        prev = _reaching(env, inner.id, line)
        if prev is not None:
            return _branch_guarded(prev[1], env, prev[0])
    return False


@register
class NanUnsafeWhere(Rule):
    name = "nan-unsafe-where"
    suite = "numerics"
    description = (
        "jnp.where selecting away from a NaN-producing branch — BOTH "
        "branches are evaluated AND differentiated, so sqrt/log/÷0 in "
        "the unselected branch still NaNs the gradient; sanitize the "
        "argument with an INNER where first (double-where idiom)"
    )

    def applies_to(self, module: ModuleInfo) -> bool:
        return matches_any(module.rel_path, _NUMERIC_PATTERNS)

    def check(self, module: ModuleInfo) -> Iterable[Finding]:
        findings: List[Finding] = []
        for scope, env, _kernel in _scopes(module):
            for node in walk_no_nested_functions(scope):
                if not (
                    isinstance(node, ast.Call)
                    and _call_tail(node) == "where"
                    and len(node.args) >= 3
                ):
                    continue
                hit = None
                for branch in (node.args[1], node.args[2]):
                    for sub in ast.walk(branch):
                        if (
                            isinstance(sub, ast.Call)
                            and _call_tail(sub) in _TRAP_TAILS
                            and sub.args
                            and not _branch_guarded(
                                sub.args[0], env, node.lineno
                            )
                        ):
                            hit = _call_tail(sub)
                            break
                        if (
                            isinstance(sub, ast.BinOp)
                            and isinstance(sub.op, ast.Div)
                            and _reduction_like(sub.right)
                            and not _div_guarded(sub.right)
                        ):
                            hit = "division"
                            break
                    if hit:
                        break
                if hit:
                    findings.append(
                        module.finding(
                            self.name,
                            node,
                            f"where branch computes {hit} on an "
                            "unsanitized argument — jnp.where "
                            "evaluates (and differentiates) BOTH "
                            "branches, so the masked-out NaN still "
                            "reaches the gradient; wrap the argument "
                            "in an inner where (double-where idiom)",
                        )
                    )
        return findings


# ---- rule 5: unmasked gather ids in the padded-edge kernels ---------------

_ID_HINTS = ("idx", "ids", "snd", "rcv", "gid", "seg", "nbr")
_SANCTIONED_PRODUCERS = {
    "_pad_edges", "_pad_ids", "_safe_gather", "clip", "where",
    "minimum", "mod", "arange", "clamp",
}
_SEGMENT_TAILS = {
    "segment_sum", "segment_max", "segment_min", "segment_prod",
}


def _index_name(sub: ast.Subscript) -> Optional[str]:
    s = sub.slice
    if isinstance(s, ast.Name):
        low = s.id.lower()
        if any(h in low for h in _ID_HINTS):
            return s.id
    return None


@register
class UnmaskedGatherId(Rule):
    name = "unmasked-gather-id"
    suite = "numerics"
    description = (
        "gather/segment op in ops/ whose index operand is not provably "
        "routed through the padded-edge masking contract (fused_mp's "
        "_safe_gather / clip+where) — a padded or stale id reads (or "
        "scatters) out of contract silently; mask the ids or the result"
    )

    def applies_to(self, module: ModuleInfo) -> bool:
        return matches_any(module.rel_path, _OPS_PATTERNS)

    def check(self, module: ModuleInfo) -> Iterable[Finding]:
        findings: List[Finding] = []
        for scope, env, kernel in _scopes(module):
            if kernel:
                continue  # kernels see pre-masked refs by contract
            # names that flow through ANY where() in this scope count
            # as mask-consumed (the gather result is neutralized there)
            masked_names: Set[str] = set()
            for node in walk_no_nested_functions(scope):
                if (
                    isinstance(node, ast.Call)
                    and _call_tail(node) == "where"
                ):
                    for sub in ast.walk(node):
                        if isinstance(sub, ast.Name):
                            masked_names.add(sub.id)
            for stmt in walk_no_nested_functions(scope):
                if not isinstance(stmt, (ast.Assign, ast.Return)):
                    continue
                value = stmt.value
                if value is None:
                    continue
                # where-wrapped inline gathers are mask-consumed
                wrapped: Set[int] = set()
                for sub in ast.walk(value):
                    if (
                        isinstance(sub, ast.Call)
                        and _call_tail(sub) == "where"
                    ):
                        wrapped.update(id(s) for s in ast.walk(sub))
                targets: Set[str] = set()
                if isinstance(stmt, ast.Assign):
                    for t in stmt.targets:
                        if isinstance(t, ast.Name):
                            targets.add(t.id)
                # a gather passed to a callee ALONGSIDE a mask arg is
                # mask-consumed there (dense_sum(x[nbr], nmask))
                for sub in ast.walk(value):
                    if not isinstance(sub, ast.Call):
                        continue
                    if any(
                        _names_mention(a, "mask")
                        for a in [*sub.args,
                                  *[k.value for k in sub.keywords]]
                    ):
                        wrapped.update(id(s) for s in ast.walk(sub))
                for sub in ast.walk(value):
                    if not isinstance(sub, ast.Subscript):
                        continue
                    idx = _index_name(sub)
                    if idx is None or id(sub) in wrapped:
                        continue
                    prev = _reaching(env, idx, stmt.lineno)
                    if prev is not None and _contains_call_tail(
                        prev[1], _SANCTIONED_PRODUCERS
                    ):
                        continue
                    if targets and targets <= masked_names:
                        continue  # result is masked downstream
                    findings.append(
                        module.finding(
                            self.name,
                            sub,
                            f"gather by {idx!r} with no visible "
                            "masking contract — ids must come from "
                            "_pad_edges/_safe_gather/clip, or the "
                            "gathered rows must be neutralized in a "
                            "jnp.where before accumulation",
                        )
                    )
            for node in walk_no_nested_functions(scope):
                if (
                    isinstance(node, ast.Call)
                    and _call_tail(node) in _SEGMENT_TAILS
                    and "." in dotted_name(node.func)
                    and not any(
                        kw.arg == "num_segments" for kw in node.keywords
                    )
                    and len(node.args) < 3
                ):
                    findings.append(
                        module.finding(
                            self.name,
                            node,
                            "segment op without num_segments — the "
                            "output length becomes data-dependent "
                            "(max(ids)+1), so a padded id silently "
                            "grows the output; pass num_segments "
                            "explicitly",
                        )
                    )
        return findings


# ---- rule 6: Pallas calls outside a VMEM-budget gate ----------------------


@register
class PallasVmemUnbounded(Rule):
    name = "pallas-vmem-unbounded"
    suite = "numerics"
    description = (
        "pl.pallas_call in a module with no *_enabled VMEM-budget gate "
        "— fused_mp.fused_mp_enabled sizes the working set against "
        "_VMEM_BUDGET before fusing; an ungated kernel OOMs VMEM at a "
        "shape the CPU tests never see"
    )

    def check(self, module: ModuleInfo) -> Iterable[Finding]:
        calls = [
            n
            for n in ast.walk(module.tree)
            if isinstance(n, ast.Call)
            and _call_tail(n) == "pallas_call"
        ]
        if not calls:
            return []
        for node in module.tree.body:
            if not (
                isinstance(node, ast.FunctionDef)
                and node.name.endswith("_enabled")
            ):
                continue
            for sub in ast.walk(node):
                ident = ""
                if isinstance(sub, ast.Name):
                    ident = sub.id
                elif isinstance(sub, ast.Attribute):
                    ident = sub.attr
                up = ident.upper()
                if "VMEM" in up or "BUDGET" in up:
                    return []  # the module carries a budget gate
        return [
            module.finding(
                self.name,
                node,
                "pallas_call with no module-level *_enabled gate "
                "referencing a VMEM/BUDGET constant — size the "
                "kernel's working set against a budget (see "
                "ops/fused_mp.fused_mp_enabled) before dispatching",
            )
            for node in calls
        ]
