"""Output formats: human text, machine JSON, GitHub annotations, --stats.

``github`` emits workflow commands (``::error file=...``) that the Actions
runner renders as inline PR annotations — the lint gate's findings land on
the diff line that introduced them, not in a log nobody scrolls.
"""

import json
from typing import Dict, List, Optional, Set

from hydragnn_tpu.analysis.core import AnalysisResult, Finding, all_rules


def render_text(
    new: List[Finding], baselined: List[Finding], result: AnalysisResult
) -> str:
    lines: List[str] = []
    for f in new:
        lines.append(f"{f.path}:{f.line}:{f.col}: {f.rule}: {f.message}")
    if baselined:
        lines.append(
            f"({len(baselined)} pre-existing finding(s) carried in the "
            "baseline — fix and remove, never add)"
        )
    if result.suppressed:
        lines.append(
            f"({result.suppressed} finding(s) suppressed inline)"
        )
    for err in result.parse_errors:
        lines.append(f"parse error: {err}")
    summary = (
        f"jaxlint: {len(new)} new finding(s), "
        f"{result.files_checked} file(s) checked"
    )
    lines.append(summary)
    return "\n".join(lines)


def render_json(
    new: List[Finding], baselined: List[Finding], result: AnalysisResult
) -> str:
    return json.dumps(
        {
            "new": [f.to_dict() for f in new],
            "baselined": [f.to_dict() for f in baselined],
            "suppressed": result.suppressed,
            "files_checked": result.files_checked,
            "parse_errors": result.parse_errors,
        },
        indent=2,
    )


def render_github(
    new: List[Finding], baselined: List[Finding], result: AnalysisResult
) -> str:
    """GitHub Actions workflow-command annotations, one per new finding
    (and one per unparseable file — a syntax error fails the gate and
    must say so on the PR, not exit 1 claiming zero findings)."""
    lines: List[str] = []
    for f in new:
        # workflow commands terminate at newline; messages are single-line
        msg = f.message.replace("\n", " ")
        lines.append(
            f"::error file={f.path},line={f.line},col={f.col},"
            f"title=jaxlint {f.rule}::{msg}"
        )
    for err in result.parse_errors:
        path = err.split(":", 1)[0]
        lines.append(
            f"::error file={path},title=jaxlint parse-error::"
            f"{err.replace(chr(10), ' ')}"
        )
    lines.append(
        f"jaxlint: {len(new)} new finding(s) "
        f"({len(baselined)} baselined, {result.suppressed} suppressed, "
        f"{len(result.parse_errors)} parse error(s), "
        f"{result.files_checked} files)"
    )
    return "\n".join(lines)


def render_stats(
    new: List[Finding],
    baselined: List[Finding],
    result: AnalysisResult,
    rules: Optional[Set[str]] = None,
) -> str:
    """Per-rule counts — the ratchet numbers CHANGES.md and CI logs cite.
    ``rules`` restricts the table to the rules that actually ran (a
    ``--suite``/``--select`` invocation should not list the other
    suite's rules as zero-count noise)."""
    per_rule: Dict[str, Dict[str, int]] = {
        name: {"new": 0, "baselined": 0}
        for name in sorted(rules if rules is not None else all_rules())
    }
    for f in new:
        per_rule.setdefault(f.rule, {"new": 0, "baselined": 0})["new"] += 1
    for f in baselined:
        per_rule.setdefault(f.rule, {"new": 0, "baselined": 0})[
            "baselined"
        ] += 1
    width = max((len(n) for n in per_rule), default=10) + 2
    lines = [f"{'rule':<{width}}{'new':>6}{'baselined':>11}"]
    for name, c in per_rule.items():
        lines.append(f"{name:<{width}}{c['new']:>6}{c['baselined']:>11}")
    lines.append(
        f"{'total':<{width}}{len(new):>6}{len(baselined):>11}"
        f"   (suppressed inline: {result.suppressed})"
    )
    return "\n".join(lines)
