"""prng-key-reuse: a JAX PRNG key consumed twice.

JAX's functional RNG makes correlated randomness a *silent* bug: passing
the same key to two consumers (or using a key again after splitting it)
yields identical draws — correlated dropout masks, identical shuffles —
with no error anywhere. The hand-threaded ``rng, sub = jax.random.split
(rng)`` chains in the trainer are one typo away from exactly this.

The analysis is intraprocedural and linear: per function it tracks which
names hold keys (assigned from ``jax.random.PRNGKey``/``split``/
``fold_in`` or derived from a key by subscript/reshape, plus parameters
named like keys) and marks a key *consumed* when it is passed to any
call. A consumed key passed to another call before being rebound is
flagged. Control flow is approximated: branches union their consumed
sets; a loop body is analyzed once, and a key consumed in the body but
never rebound anywhere in it is flagged as reused across iterations.
"""

import ast
import re
from typing import Iterable, List, Optional, Set, Tuple

from hydragnn_tpu.analysis.core import (
    Finding,
    ModuleInfo,
    Rule,
    assigned_names,
    dotted_name,
    register,
)

_KEY_SOURCES = {
    "jax.random.PRNGKey",
    "jax.random.key",
    "jax.random.split",
    "jax.random.fold_in",
    "random.PRNGKey",
    "random.split",
    "random.fold_in",
}
_KEY_PARAM_NAMES = {"rng", "key", "prng", "subkey", "rng_key", "prng_key"}

# callees that READ a key (serialize, move, inspect) without drawing from
# it — checkpoint meta building and asarray round-trips pass keys around
# legitimately
_NON_CONSUMING = re.compile(
    r"(asarray|array|device_put|device_get|tree_map|save|meta|state_dict"
    r"|emit|print|log|debug|repr|str|len|append|copy|shape)"
)


def _is_key_source(call: ast.Call) -> bool:
    return dotted_name(call.func) in _KEY_SOURCES


class _FunctionScan:
    def __init__(self, module: ModuleInfo, rule_name: str,
                 fn: ast.FunctionDef):
        self.module = module
        self.rule_name = rule_name
        self.fn = fn
        self.keys: Set[str] = {
            a.arg
            for a in [*fn.args.args, *fn.args.kwonlyargs]
            if a.arg.lower() in _KEY_PARAM_NAMES
        }
        self.consumed: Set[str] = set()
        self.findings: List[Finding] = []
        self._loop_consumptions: Optional[
            List[Tuple[ast.Call, str]]
        ] = None

    # ---- statement interpreter ----------------------------------------
    def run(self):
        self.block(self.fn.body)
        return self.findings

    def block(self, stmts):
        for stmt in stmts:
            self.statement(stmt)

    def statement(self, stmt: ast.stmt):
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            return  # nested defs get their own scan
        if isinstance(stmt, ast.If):
            self.expression(stmt.test)
            self._branch([stmt.body, stmt.orelse])
            return
        if isinstance(stmt, (ast.For, ast.AsyncFor)):
            self._loop(stmt, header_exprs=[stmt.iter],
                       bound=assigned_names(stmt))
            return
        if isinstance(stmt, ast.While):
            self._loop(stmt, header_exprs=[stmt.test], bound=set())
            return
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            for item in stmt.items:
                self.expression(item.context_expr)
            self.block(stmt.body)
            return
        if isinstance(stmt, ast.Try):
            branches = [stmt.body]
            branches.extend(h.body for h in stmt.handlers)
            if stmt.orelse:
                branches.append(stmt.orelse)
            self._branch(branches)
            if stmt.finalbody:
                self.block(stmt.finalbody)
            return
        # plain statement: evaluate RHS expressions (consumption), then
        # apply bindings — `rng, sub = split(rng)` consumes and rebinds
        # in one step, which is the CORRECT chain pattern
        for node in ast.walk(stmt):
            if isinstance(node, ast.Call):
                self.call(node)
        bound = assigned_names(stmt)
        if bound:
            self.bind(bound, getattr(stmt, "value", None))

    def _branch(self, bodies):
        before = set(self.consumed)
        before_keys = set(self.keys)
        after: Set[str] = set()
        for body in bodies:
            self.consumed = set(before)
            self.block(body)
            after |= self.consumed
        self.keys |= before_keys
        self.consumed = after  # union: consumed on ANY path counts

    def _loop(self, stmt, header_exprs, bound: Set[str]):
        for e in header_exprs:
            self.expression(e)
        if bound:
            self.bind(bound, getattr(stmt, "iter", None))
        body_consumed: List[Tuple[ast.Call, str]] = []
        outer = self._loop_consumptions
        self._loop_consumptions = body_consumed
        self.block(stmt.body)
        if stmt.orelse:
            self.block(stmt.orelse)
        self._loop_consumptions = outer
        # keys consumed in the body and never rebound in it: iteration 2
        # reuses the spent key
        rebound: Set[str] = set()
        for s in ast.walk(stmt):
            if isinstance(s, ast.stmt):
                rebound |= assigned_names(s)
        reported: Set[str] = set()
        for call, name in body_consumed:
            if name in rebound or name in reported:
                continue
            reported.add(name)
            self.findings.append(
                self.module.finding(
                    self.rule_name,
                    call,
                    f"key `{name}` is consumed inside the loop in "
                    f"`{self.fn.name}` but never re-split/rebound in the "
                    "body — every iteration reuses the same randomness",
                )
            )

    def expression(self, expr: Optional[ast.AST]):
        if expr is None:
            return
        for node in ast.walk(expr):
            if isinstance(node, ast.Call):
                self.call(node)

    def call(self, call: ast.Call):
        """Record consumption of key-typed names passed to this call."""
        callee = dotted_name(call.func)
        if not _is_key_source(call) and _NON_CONSUMING.search(callee or ""):
            return
        args = list(call.args) + [kw.value for kw in call.keywords]
        for arg in args:
            if not isinstance(arg, ast.Name) or arg.id not in self.keys:
                continue
            name = arg.id
            if name in self.consumed:
                self.findings.append(
                    self.module.finding(
                        self.rule_name,
                        call,
                        f"key `{name}` was already consumed (split or "
                        "passed to a consumer) and is used again here — "
                        "split first and pass the fresh subkey",
                    )
                )
            self.consumed.add(name)
            if self._loop_consumptions is not None:
                self._loop_consumptions.append((call, name))

    def _derives_key(self, node: ast.AST) -> bool:
        """RHS shapes that yield key values: a key-source call, a key
        name, or a subscript / method chain hanging off one
        (``subs[0]``, ``subs[1:].reshape(...)``) — NOT any expression
        that merely mentions a key somewhere (a step call taking `sub`
        returns state, not keys)."""
        if isinstance(node, ast.Call):
            if _is_key_source(node):
                return True
            if isinstance(node.func, ast.Attribute):
                return self._derives_key(node.func.value)
            return False
        if isinstance(node, (ast.Subscript, ast.Attribute)):
            return self._derives_key(node.value)
        if isinstance(node, ast.Name):
            return node.id in self.keys
        if isinstance(node, ast.IfExp):
            return self._derives_key(node.body) or self._derives_key(
                node.orelse
            )
        return False

    def bind(self, names: Set[str], value: Optional[ast.AST]):
        derives = value is not None and self._derives_key(value)
        for n in names:
            if derives:
                self.keys.add(n)
            self.consumed.discard(n)


@register
class PrngKeyReuse(Rule):
    name = "prng-key-reuse"
    description = (
        "A JAX PRNG key consumed twice (passed to two consumers, or used "
        "after being split) — correlated randomness, silently"
    )

    def applies_to(self, module: ModuleInfo) -> bool:
        # cheap pre-filter: no jax.random anywhere -> nothing to track
        return "random" in module.source

    def check(self, module: ModuleInfo) -> Iterable[Finding]:
        findings: List[Finding] = []
        for node in ast.walk(module.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                scan = _FunctionScan(module, self.name, node)
                # only bother when the function touches jax.random or has
                # key-named params — keeps noise out of numpy-random code
                touches = bool(scan.keys) or any(
                    isinstance(n, ast.Call) and _is_key_source(n)
                    for n in ast.walk(node)
                )
                if touches:
                    findings.extend(scan.run())
        return findings
