"""``jax.jit`` lifecycle rules: jit-in-loop, missing-donate,
recompile-hazard.

The persistent XLA compile cache (``utils/compile_cache.py``) makes
*repeat* compilations of the SAME program cheap across processes — but it
keys on the traced program, and none of the bugs below ever reach it with
a stable key:

- a fresh ``jax.jit(lambda ...)`` wrapper per call re-traces every time
  (the jit-level cache keys on function object identity);
- a jit missing ``donate_argnums`` on a state-threading step doubles the
  optimizer-state HBM footprint and costs a device-to-device copy per
  step;
- ``static_argnums`` pointing at per-batch data recompiles per batch.
"""

import ast
from typing import Iterable, List, Optional

from hydragnn_tpu.analysis.core import (
    Finding,
    ModuleInfo,
    Rule,
    dotted_name,
    register,
    walk_no_nested_functions,
)

_JIT_NAMES = {"jax.jit", "jit", "pjit", "jax.pjit"}


def _is_jit_call(node: ast.AST) -> bool:
    return isinstance(node, ast.Call) and dotted_name(node.func) in _JIT_NAMES


def _jit_kwarg_names(call: ast.Call):
    return {kw.arg for kw in call.keywords if kw.arg}


@register
class JitInLoop(Rule):
    name = "jit-in-loop"
    description = (
        "jax.jit created inside a loop or invoked immediately "
        "(jax.jit(f)(x)) — the wrapper must be cached at setup or the "
        "jit-level cache misses on every call and re-traces"
    )

    def check(self, module: ModuleInfo) -> Iterable[Finding]:
        findings: List[Finding] = []
        # (a) jit constructed inside a loop body
        for node in ast.walk(module.tree):
            if not isinstance(node, (ast.For, ast.While)):
                continue
            for stmt in node.body + node.orelse:
                for sub in [stmt, *walk_no_nested_functions(stmt)]:
                    if _is_jit_call(sub):
                        findings.append(
                            module.finding(
                                self.name,
                                sub,
                                "jax.jit inside a loop builds a fresh "
                                "wrapper per iteration — hoist it to "
                                "setup (the persistent compile cache in "
                                "utils/compile_cache.py cannot rescue an "
                                "unstable function identity)",
                            )
                        )
        # (b) immediate invocation: jax.jit(f)(args)
        for node in ast.walk(module.tree):
            if (
                isinstance(node, ast.Call)
                and _is_jit_call(node.func)
            ):
                findings.append(
                    module.finding(
                        self.name,
                        node,
                        "jax.jit(f)(...) builds and discards the wrapper "
                        "in one expression — every evaluation re-traces; "
                        "bind the jitted callable once at setup",
                    )
                )
        # dedupe (a loop-hosted immediate call matches both patterns)
        uniq, out = set(), []
        for f in findings:
            key = (f.line, f.col, f.message)
            if key not in uniq:
                uniq.add(key)
                out.append(f)
        return out


# names that clearly do NOT thread donated state back out
_EXEMPT_SUBSTRINGS = ("eval", "predict", "infer", "loss", "forward", "copy")
# names that look like state-threading compiled programs
_STATEFUL_SUBSTRINGS = ("train", "fit", "update")
_STATEFUL_EXACT = {"step", "epoch_scan"}
_STATEFUL_SUFFIXES = ("_scan",)


def _wrapped_fn_name(call: ast.Call) -> Optional[str]:
    if not call.args:
        return None
    first = call.args[0]
    if isinstance(first, ast.Name):
        return first.id
    if isinstance(first, ast.Attribute):
        return first.attr
    return None


def _looks_stateful(name: str) -> bool:
    low = name.lower()
    if any(s in low for s in _EXEMPT_SUBSTRINGS):
        return False
    if any(s in low for s in _STATEFUL_SUBSTRINGS):
        return True
    if low in _STATEFUL_EXACT:
        return True
    return any(low.endswith(s) for s in _STATEFUL_SUFFIXES)


@register
class MissingDonate(Rule):
    name = "missing-donate"
    description = (
        "jax.jit of a state-threading step (train*/fit*/update*/step/"
        "*_scan) without donate_argnums — the un-donated input state "
        "doubles its HBM footprint and copies every step"
    )

    def check(self, module: ModuleInfo) -> Iterable[Finding]:
        findings: List[Finding] = []
        for node in ast.walk(module.tree):
            if not _is_jit_call(node):
                continue
            fn_name = _wrapped_fn_name(node)
            if fn_name is None or not _looks_stateful(fn_name):
                continue
            kwargs = _jit_kwarg_names(node)
            if kwargs & {"donate_argnums", "donate_argnames"}:
                continue
            findings.append(
                module.finding(
                    self.name,
                    node,
                    f"jax.jit({fn_name}) threads state but does not "
                    "donate it — pass donate_argnums for the state "
                    "argument (see train/steps.py:train_step) so XLA "
                    "reuses the input buffers in place",
                )
            )
        return findings


# parameter names that are per-batch data: marking them static recompiles
# once per novel value (and unhashable values fail outright)
_DATA_PARAM_NAMES = {
    "batch",
    "batches",
    "data",
    "x",
    "inputs",
    "arr",
    "array",
    "graph",
    "graphs",
    "state",
    "params",
}


@register
class RecompileHazard(Rule):
    name = "recompile-hazard"
    description = (
        "static_argnums/static_argnames pointing at per-batch data (or a "
        "parameter with an unhashable default) — every novel value "
        "compiles a fresh executable"
    )

    def check(self, module: ModuleInfo) -> Iterable[Finding]:
        defs = {}
        for node in ast.walk(module.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                defs.setdefault(node.name, node)
        findings: List[Finding] = []
        for node in ast.walk(module.tree):
            if not _is_jit_call(node):
                continue
            static_names = self._static_param_names(node, defs)
            for pname in static_names:
                if pname in _DATA_PARAM_NAMES:
                    findings.append(
                        module.finding(
                            self.name,
                            node,
                            f"static arg `{pname}` looks like per-batch "
                            "data — every distinct value recompiles; "
                            "static args must be small, hashable "
                            "configuration",
                        )
                    )
        return findings

    @staticmethod
    def _static_param_names(call: ast.Call, defs) -> List[str]:
        """Resolve static_argnums positions / static_argnames strings to
        parameter names where possible (same-file function or lambda)."""
        params: List[str] = []
        fn = call.args[0] if call.args else None
        if isinstance(fn, ast.Lambda):
            params = [a.arg for a in fn.args.args]
        elif isinstance(fn, ast.Name) and fn.id in defs:
            params = [a.arg for a in defs[fn.id].args.args]
        out: List[str] = []
        for kw in call.keywords:
            if kw.arg == "static_argnames":
                for v in ast.walk(kw.value):
                    if isinstance(v, ast.Constant) and isinstance(
                        v.value, str
                    ):
                        out.append(v.value)
            elif kw.arg == "static_argnums" and params:
                nums: List[int] = [
                    v.value
                    for v in ast.walk(kw.value)
                    if isinstance(v, ast.Constant)
                    and isinstance(v.value, int)
                ]
                for n in nums:
                    if 0 <= n < len(params):
                        out.append(params[n])
        return out
