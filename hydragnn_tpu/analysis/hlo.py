"""shardlint's compiled-HLO ratchet — the post-compile half of the suite.

The AST rules (``rules_sharding.py``) prove the sharding contract is
*written*; nothing static can prove what XLA *does* with it. An implicit
resharding — a partition rule regressed, a ``with_sharding_constraint``
dropped, a batch dim reshaped — shows up in the compiled module as a new
all-gather long before it shows up as step time on a small config. So
this module fingerprints each of the eight ``train/steps.py`` programs'
compiled HLO on a canonical CPU mesh:

* the **collective set** — one ``(op kind, mesh axis, result bytes)``
  record per all-reduce/all-gather/all-to-all/reduce-scatter, attributed
  via ``parallel/collectives.py``'s replica-group parsing;
* **host-transfer ops** (infeed/outfeed/host custom-calls) — zero today,
  and a future host round-trip inside a step program must fail loudly;
* **bf16 -> f32 converts** — a silent upcast doubles matmul cost on the
  precision-policy paths.

The fingerprints ratchet against a committed ``.shardlint-hlo.json``
(the ``.perf-baseline.json`` pattern at compile time): CI re-compiles the
programs on the forced-8-device CPU backend and fails with a diff naming
the program, the collective and the bytes when anything new appears or
grows past tolerance. ``--prove-injection`` demonstrates the failing
case by injecting a synthetic all-gather and asserting it is caught.

CLI::

    python -m hydragnn_tpu.analysis.hlo --check .shardlint-hlo.json
    python -m hydragnn_tpu.analysis.hlo --write .shardlint-hlo.json
    python -m hydragnn_tpu.analysis.hlo --check ... --prove-injection

Exit status: 0 clean, 1 budget violations (or a failed injection proof),
2 usage errors. Unlike the AST pass this half NEEDS jax — it compiles
the real programs; the budget is the CPU-compiled canon (per-device
result bytes are backend-independent; TPU-only fusion differences are
the introspection gauges' job, not this gate's).
"""

import argparse
import json
import os
import re
import sys
from typing import Dict, List, Optional, Sequence, Tuple

BUDGET_VERSION = 1
DEFAULT_BUDGET = ".shardlint-hlo.json"
DEFAULT_TOLERANCE = 0.25
# the canonical harness mesh: 4x2 exercises BOTH axes' collectives
DEFAULT_MESH = (4, 2)

# ---- pure-text analyzers --------------------------------------------------

_HOST_TRANSFER_RE = re.compile(
    r"\b(?:infeed|outfeed)(?:-start|-done)?\(|is_host_transfer=true|"
    r'custom_call_target="(?:MoveToHost|MoveToDevice|[^"]*[Hh]ost[^"]*)"'
)
_DEF_RE = re.compile(
    r"^\s*(?:ROOT\s+)?%?(?P<name>[\w.\-]+)\s*=\s*(?P<dtype>[a-z]+[0-9]+)\["
)
_CONVERT_RE = re.compile(
    r"=\s*f32\[[0-9,]*\](?:\{[0-9,]*\})?\s*convert\((?P<args>[^)]*)\)"
)
_OPERAND_NAME_RE = re.compile(r"%?([\w.\-]+)\s*$")


def count_host_transfers(hlo_text: str) -> int:
    """Host-transfer ops in one compiled module: infeed/outfeed, sends
    and receives marked ``is_host_transfer=true``, and host-placement
    custom calls. A step program should have NONE — each occurrence is a
    synchronous hop off the device."""
    return sum(
        1 for line in hlo_text.splitlines() if _HOST_TRANSFER_RE.search(line)
    )


def count_bf16_upcasts(hlo_text: str) -> int:
    """``bf16 -> f32`` convert ops. Handles both operand spellings the
    HLO printer emits: the inline-typed ``convert(bf16[...] %x)`` and the
    bare ``convert(%x)`` (resolved through a first pass over instruction
    result dtypes)."""
    dtypes: Dict[str, str] = {}
    for line in hlo_text.splitlines():
        m = _DEF_RE.match(line)
        if m:
            dtypes[m.group("name")] = m.group("dtype")
    count = 0
    for line in hlo_text.splitlines():
        m = _CONVERT_RE.search(line)
        if m is None:
            continue
        args = m.group("args")
        if "bf16[" in args:
            count += 1
            continue
        om = _OPERAND_NAME_RE.search(args.strip())
        if om and dtypes.get(om.group(1)) == "bf16":
            count += 1
    return count


def fingerprint_hlo(
    hlo_text: str, axes: Sequence[str], shape: Sequence[int]
) -> Dict:
    """One program's budgetable fingerprint. Collectives are aggregated
    by ``(op, axis)`` with summed result bytes — stable under
    instruction reordering, sensitive to any NEW collective kind/axis
    and to byte growth."""
    from hydragnn_tpu.parallel.collectives import parse_collectives

    agg: Dict[Tuple[str, str], float] = {}
    for rec in parse_collectives(hlo_text, axes, shape):
        key = (rec["op"], rec["axis"])
        agg[key] = agg.get(key, 0.0) + rec["bytes"]
    return {
        "collectives": [
            {"op": op, "axis": axis, "bytes": int(nbytes)}
            for (op, axis), nbytes in sorted(agg.items())
        ],
        "host_transfers": count_host_transfers(hlo_text),
        "bf16_to_f32_converts": count_bf16_upcasts(hlo_text),
    }


# ---- the budget (the ratchet file) ----------------------------------------


def save_budget(
    path: str,
    programs: Dict[str, Dict],
    axes: Sequence[str],
    shape: Sequence[int],
    tolerance: float = DEFAULT_TOLERANCE,
):
    payload = {
        "version": BUDGET_VERSION,
        "mesh": {"axes": list(axes), "shape": [int(s) for s in shape]},
        "tolerance": tolerance,
        "programs": {k: programs[k] for k in sorted(programs)},
    }
    with open(path, "w", encoding="utf-8") as f:
        json.dump(payload, f, indent=2, sort_keys=True)
        f.write("\n")


def load_budget(path: str) -> Dict:
    with open(path, "r", encoding="utf-8") as f:
        payload = json.load(f)
    version = payload.get("version")
    if version != BUDGET_VERSION:
        raise ValueError(
            f"HLO budget {path} has version {version!r}; this analyzer "
            f"writes version {BUDGET_VERSION} — regenerate with --write"
        )
    return payload


def check_fingerprints(
    current: Dict[str, Dict],
    budget_programs: Dict[str, Dict],
    tolerance: float = DEFAULT_TOLERANCE,
) -> Tuple[List[str], List[str]]:
    """``(violations, notes)`` of the current fingerprints vs the budget.

    Violations (gate-failing): a program absent from the budget, a NEW
    ``(collective, axis)`` pair, collective bytes grown past
    ``tolerance``, more host transfers or bf16->f32 converts than
    budgeted. Notes (stderr, non-failing): budgeted collectives that
    disappeared and stale budgeted programs — the ratchet only tightens,
    so these are prune-the-budget reminders."""
    violations: List[str] = []
    notes: List[str] = []
    for prog in sorted(current):
        fp = current[prog]
        b = budget_programs.get(prog)
        if b is None:
            violations.append(
                f"{prog}: program not in the budget — a new compiled "
                "step program must be budgeted deliberately (--write)"
            )
            continue
        budgeted = {
            (c["op"], c["axis"]): float(c["bytes"])
            for c in b.get("collectives", [])
        }
        seen = set()
        for c in fp["collectives"]:
            key = (c["op"], c["axis"])
            seen.add(key)
            if key not in budgeted:
                violations.append(
                    f"{prog}: NEW collective {c['op']} over axis "
                    f"'{c['axis']}' ({int(c['bytes'])} result bytes/"
                    "dispatch) — an implicit resharding XLA inserted "
                    "that the budget never agreed to"
                )
            elif float(c["bytes"]) > budgeted[key] * (1.0 + tolerance):
                violations.append(
                    f"{prog}: {c['op']}@{c['axis']} grew "
                    f"{int(budgeted[key])} -> {int(c['bytes'])} result "
                    f"bytes (> {tolerance:.0%} tolerance)"
                )
        for (op, axis), nbytes in sorted(budgeted.items()):
            if (op, axis) not in seen:
                notes.append(
                    f"{prog}: budgeted {op}@{axis} ({int(nbytes)} B) no "
                    "longer emitted — tighten the budget with --write"
                )
        for field, label in (
            ("host_transfers", "host-transfer op(s)"),
            ("bf16_to_f32_converts", "bf16->f32 convert(s)"),
        ):
            if int(fp.get(field, 0)) > int(b.get(field, 0)):
                violations.append(
                    f"{prog}: {fp[field]} {label}, budget allows "
                    f"{b.get(field, 0)}"
                )
    for prog in sorted(set(budget_programs) - set(current)):
        notes.append(
            f"{prog}: budgeted but not compiled here — stale entry, "
            "prune with --write"
        )
    return violations, notes


# ---- the canonical program harness ----------------------------------------


def _make_samples(num: int = 24, seed: int = 11):
    import numpy as np

    from hydragnn_tpu.data.dataobj import GraphData

    rng = np.random.default_rng(seed)
    out = []
    for _ in range(num):
        n = 6
        g = GraphData()
        g.x = rng.random((n, 1)).astype(np.float32)
        g.pos = rng.random((n, 3)).astype(np.float32)
        src = np.arange(n)
        dst = (src + 1) % n
        g.edge_index = np.stack(
            [np.concatenate([src, dst]), np.concatenate([dst, src])]
        ).astype(np.int64)
        g.edge_attr = None
        g.targets = [np.array([g.x.sum()], np.float32), g.x.copy()]
        g.target_types = ["graph", "node"]
        out.append(g)
    return out


_CANON_ARCH = {
    "model_type": "GIN",
    "input_dim": 1,
    "hidden_dim": 8,
    "num_conv_layers": 2,
    "output_dim": [1, 1],
    "output_type": ["graph", "node"],
    "output_heads": {
        "graph": {
            "num_sharedlayers": 1,
            "dim_sharedlayers": 8,
            "num_headlayers": 1,
            "dim_headlayers": [8],
        },
        "node": {"num_headlayers": 1, "dim_headlayers": [8], "type": "mlp"},
    },
    "task_weights": [1.0, 1.0],
}


def build_canonical_trainer(mesh_shape: Tuple[int, int] = DEFAULT_MESH):
    """The fixed tiny GIN training the budget is derived from — one
    deterministic config (same shape as the 2-D mesh CI smoke's), so a
    fingerprint diff is a CODE change, never a config drift. Returns
    ``(trainer, state, dev_batch, stacked, mesh)``."""
    import jax

    from hydragnn_tpu.data.loaders import GraphLoader, compute_layout
    from hydragnn_tpu.models.create import create_model_config
    from hydragnn_tpu.parallel.mesh import make_mesh2d, set_active_mesh
    from hydragnn_tpu.train.trainer import Trainer

    d, m = int(mesh_shape[0]), int(mesh_shape[1])
    mesh = make_mesh2d(d, m)
    set_active_mesh(mesh)
    training = {
        "num_epoch": 1,
        "Optimizer": {"type": "AdamW", "learning_rate": 1e-2},
        "model_parallel": m,
    }
    samples = _make_samples()
    layout = compute_layout([samples], batch_size=4, need_triplets=False)
    loader = GraphLoader(samples[:16], 4, layout, shuffle=False)
    model = create_model_config(_CANON_ARCH)
    trainer = Trainer(model, training, mesh=mesh)
    batches = list(loader)
    state = trainer.init_state(batches[0], seed=0)
    dev_batch = trainer.put_batch(batches[0])
    stacked = trainer.stage_batches(batches[:2])
    return trainer, state, dev_batch, stacked, mesh


def compile_step_programs(
    mesh_shape: Tuple[int, int] = DEFAULT_MESH,
    programs: Optional[Sequence[str]] = None,
) -> Tuple[Dict[str, str], Tuple, Tuple, Dict]:
    """Compile the step programs on the canonical harness and return
    ``({name: optimized_hlo_text}, axes, shape, context)``. ``programs``
    restricts the set (the unit tests compile two, CI compiles all 8).
    ``context`` carries the live trainer/state/batch for the runtime
    sharding-sentinel check."""
    import jax
    import jax.numpy as jnp

    from hydragnn_tpu.parallel.mesh import active_mesh, set_active_mesh
    from hydragnn_tpu.train.common import SchedState
    from hydragnn_tpu.train.trainer import _copy_tree

    prev_mesh = active_mesh()
    try:
        trainer, state, dev_batch, stacked, mesh = build_canonical_trainer(
            mesh_shape
        )
    finally:
        # the harness mesh must not leak as ambient context (padding
        # multiples, collective attribution) into the calling process —
        # placement is already baked into the built programs/arrays
        set_active_mesh(prev_mesh)
    steps = trainer._steps
    nb = 2
    step_rng, multi_rng, scan_rng, fit_rng, sentinel_rng = jax.random.split(
        jax.random.PRNGKey(0), 5
    )
    rngs = jax.random.split(multi_rng, nb)
    scan_rngs = jax.random.split(scan_rng, nb)
    perm = jnp.arange(nb)
    sched = jax.tree_util.tree_map(jnp.asarray, SchedState.init())
    best_state = _copy_tree(state)
    perms = jnp.tile(jnp.arange(nb), (1, 1))
    erngs = jax.random.split(fit_rng, nb).reshape(1, nb, -1)
    active = jnp.arange(1) < 1
    params, bs = state.params, state.batch_stats
    lowerings = {
        "train_step": lambda: steps.train_step.lower(
            state, dev_batch, step_rng
        ),
        "train_multi": lambda: steps.train_multi.lower(state, stacked, rngs),
        "epoch_scan": lambda: steps.epoch_scan.lower(
            state, stacked, perm, scan_rngs
        ),
        "eval_epoch": lambda: steps.eval_epoch.lower(params, bs, stacked),
        "predict_scan": lambda: steps.predict_scan.lower(
            params, bs, stacked
        ),
        "fit_scan": lambda: steps.fit_scan.lower(
            state, best_state, sched, stacked, stacked, stacked,
            perms, erngs, active,
        ),
        "eval_step": lambda: steps.eval_step.lower(params, bs, dev_batch),
        "eval_multi": lambda: steps.eval_multi.lower(params, bs, stacked),
    }
    if programs is not None:
        lowerings = {k: lowerings[k] for k in programs}
    # compile ONCE per program: the texts feed the collective ratchet,
    # the executables feed the memory ratchet (analysis/mem.py) via
    # context["compiled"] — recompiling for each consumer would double
    # the multi-minute CI cost
    compiled = {name: low().compile() for name, low in lowerings.items()}
    texts = {name: c.as_text() for name, c in compiled.items()}
    context = {
        "trainer": trainer,
        "state": state,
        "dev_batch": dev_batch,
        "rng": sentinel_rng,
        "mesh": mesh,
        "compiled": compiled,
    }
    return (
        texts,
        tuple(mesh.axis_names),
        tuple(mesh.devices.shape),
        context,
    )


def run_sharding_sentinel(context) -> None:
    """Execute one real train step and assert its outputs LAND at the
    declared shardings (state at the rule-engine placement, metrics
    replicated) — the runtime complement of the compile-time budget.
    Raises :class:`~hydragnn_tpu.analysis.guards.ShardingViolation`."""
    import jax

    from jax.sharding import NamedSharding, PartitionSpec

    from hydragnn_tpu.analysis.guards import sharding_sentinel

    trainer = context["trainer"]
    # the step donates its input state: snapshot-free is fine, the
    # harness state is not reused after this
    new_state, metrics = trainer._train_step(
        context["state"], context["dev_batch"], context["rng"]
    )
    rep = NamedSharding(context["mesh"], PartitionSpec())
    with sharding_sentinel() as sen:
        sen.check(
            new_state,
            trainer._state_shardings,
            what="train_step state",
            defer=True,
        )
        sen.check(
            metrics,
            jax.tree_util.tree_map(lambda _: rep, metrics),
            what="train_step metrics",
            defer=True,
        )


# a synthetic full-mesh all-gather: the exact signature of an implicit
# resharding (e.g. a parameter table gathered at every use) — appended to
# a program's HLO text by --prove-injection to demonstrate the gate fires
INJECTED_ALL_GATHER = (
    "  %shardlint.injected = f32[65536]{0} all-gather("
    "f32[8192]{0} %shardlint.operand), replica_groups={{0,1,2,3,4,5,6,7}}, "
    "dimensions={0}\n"
)


def prove_injection(
    texts: Dict[str, str],
    budget_programs: Dict[str, Dict],
    axes: Sequence[str],
    shape: Sequence[int],
    tolerance: float,
) -> bool:
    """Append a synthetic all-gather to one program and assert the
    budget check CATCHES it — the ratchet's reintroduction regression,
    run in CI so 'the gate would fire' is demonstrated, not assumed."""
    prog = sorted(texts)[0]
    doctored = dict(texts)
    doctored[prog] = texts[prog] + INJECTED_ALL_GATHER
    current = {
        name: fingerprint_hlo(text, axes, shape)
        for name, text in doctored.items()
    }
    violations, _ = check_fingerprints(
        current, budget_programs, tolerance=tolerance
    )
    return any("all-gather" in v and prog in v for v in violations)


# ---- CLI ------------------------------------------------------------------


def _force_cpu_devices(n: int):
    """The canonical budget compiles on the forced-N-device CPU backend;
    set that up before the backend initializes (the jax module may
    already be imported — only backend init reads these)."""
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={n}"
        ).strip()
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import jax

    jax.config.update("jax_platforms", "cpu")


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m hydragnn_tpu.analysis.hlo",
        description=(
            "shardlint compiled-HLO ratchet: fingerprint the step "
            "programs' collective set against the committed budget "
            "(docs/static-analysis.md)"
        ),
    )
    p.add_argument(
        "--check",
        metavar="FILE",
        help=f"check fingerprints against a budget (e.g. {DEFAULT_BUDGET})",
    )
    p.add_argument(
        "--write",
        metavar="FILE",
        help="compile and write the current fingerprints as the budget",
    )
    p.add_argument(
        "--tolerance",
        type=float,
        default=None,
        help="collective-bytes growth tolerance (default: the budget's, "
        f"else {DEFAULT_TOLERANCE})",
    )
    p.add_argument(
        "--mesh",
        default=f"{DEFAULT_MESH[0]},{DEFAULT_MESH[1]}",
        help='harness mesh "d,m" (default 4,2 — the canonical budget)',
    )
    p.add_argument(
        "--prove-injection",
        action="store_true",
        help="after checking, inject a synthetic all-gather and assert "
        "the gate catches it (the CI reintroduction proof)",
    )
    p.add_argument(
        "--skip-sentinel",
        action="store_true",
        help="skip the runtime sharding-sentinel step execution",
    )
    args = p.parse_args(argv)

    if not args.check and not args.write:
        print(
            "hlo-ratchet: one of --check/--write is required",
            file=sys.stderr,
        )
        return 2
    try:
        d, m = (int(v) for v in args.mesh.split(","))
    except ValueError:
        print(
            f'hlo-ratchet: --mesh {args.mesh!r} is not "d,m"',
            file=sys.stderr,
        )
        return 2

    # validate the budget BEFORE the multi-minute 8-program compile: a
    # missing/mismatched budget is answerable from the JSON alone
    budget = None
    tolerance = (
        args.tolerance if args.tolerance is not None else DEFAULT_TOLERANCE
    )
    if args.check and not args.write:
        try:
            budget = load_budget(args.check)
        except FileNotFoundError:
            print(
                f"hlo-ratchet: budget {args.check} not found — derive it "
                "with --write",
                file=sys.stderr,
            )
            return 2
        except ValueError as e:
            print(f"hlo-ratchet: {e}", file=sys.stderr)
            return 2
        if args.tolerance is None:
            tolerance = float(budget.get("tolerance", DEFAULT_TOLERANCE))
        bmesh = budget.get("mesh", {})
        if list(bmesh.get("shape", [])) != [d, m]:
            print(
                f"hlo-ratchet: budget was derived on mesh "
                f"{bmesh.get('shape')} but this run uses [{d}, {m}] — "
                "fingerprints are not comparable (pass the matching "
                "--mesh)",
                file=sys.stderr,
            )
            return 2

    # the canonical environment: forced CPU devices, no ambient
    # HYDRAGNN_MESH leaking into the harness resolution
    os.environ.pop("HYDRAGNN_MESH", None)
    _force_cpu_devices(max(d * m, 8))

    print(f"hlo-ratchet: compiling 8 step programs on a {d}x{m} CPU mesh")
    texts, axes, shape, context = compile_step_programs((d, m))
    current = {
        name: fingerprint_hlo(text, axes, shape)
        for name, text in texts.items()
    }

    if not args.skip_sentinel:
        run_sharding_sentinel(context)
        print("hlo-ratchet: sharding sentinel OK (outputs landed as declared)")

    if args.write:
        save_budget(
            args.write,
            current,
            axes,
            shape,
            tolerance=(
                args.tolerance
                if args.tolerance is not None
                else DEFAULT_TOLERANCE
            ),
        )
        ncol = sum(len(fp["collectives"]) for fp in current.values())
        print(
            f"hlo-ratchet: wrote {len(current)} program fingerprint(s) "
            f"({ncol} collective record(s)) to {args.write}"
        )
        return 0

    violations, notes = check_fingerprints(
        current, budget.get("programs", {}), tolerance=tolerance
    )
    for note in notes:
        print(f"note: {note}", file=sys.stderr)
    for v in violations:
        print(f"VIOLATION: {v}")
    ok = not violations
    print(
        f"hlo-ratchet: {len(violations)} violation(s) across "
        f"{len(current)} program(s) (tolerance {tolerance:.0%})"
    )
    if ok and args.prove_injection:
        if prove_injection(
            texts, budget.get("programs", {}), axes, shape, tolerance
        ):
            print(
                "hlo-ratchet: injection proof OK — a synthetic "
                "all-gather IS caught by this budget"
            )
        else:
            print(
                "hlo-ratchet: injection proof FAILED — the gate did not "
                "catch a synthetic all-gather",
                file=sys.stderr,
            )
            return 1
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
