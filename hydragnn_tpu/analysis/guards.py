"""Runtime correctness guards — what the static pass cannot prove.

Three harnesses, all designed for tests (cheap, no-op-safe, CPU-friendly):

- :class:`CompileSentinel` asserts the XLA compile counter stays FLAT
  across a region: warm a step function up, enter the sentinel, run an
  epoch (or a serve burst) — any recompile means a shape leaked past the
  bucketing/layout machinery, which is this stack's #1 silent perf
  regression. Counts come from the same ``jax.monitoring``
  backend-compile events the ``/metrics`` endpoint exports
  (``obs/runtime.py``), plus each tracked jitted function's own cache
  size as a second, API-stable signal.

- :func:`no_host_syncs` turns IMPLICIT device->host transfers into hard
  errors via ``jax.transfer_guard_device_to_host("disallow")``. The hot
  paths fetch results exactly once per epoch through explicit
  ``jax.device_get`` — which the guard permits — so a reintroduced
  per-batch ``float(metrics[...])`` fails the wrapped test instead of
  silently serializing the dispatch pipeline. :func:`no_implicit_transfers`
  is the stricter all-directions variant for regions that should move no
  data implicitly at all (a fully staged dispatch, a serve batch whose
  inputs are packed host-side).

- :func:`lock_sanitizer` / :class:`InstrumentedLock` — the runtime half
  of the threadlint concurrency suite (``rules_concurrency.py``). The
  static pass sees lock orders the SOURCE nests; only execution sees the
  orders call graphs compose at runtime. Instrumented locks track each
  thread's held-lock set, build the global acquisition-order graph, and
  record a :class:`LockOrderViolation` the moment any thread acquires
  against an order another thread has already established — the deadlock
  is caught on the first interleaving that could EVER deadlock, not the
  unlucky run that does. Per-lock wait/hold-time histograms export
  through a :class:`~hydragnn_tpu.obs.metrics.MetricsRegistry`, and a
  deadlock watchdog dumps every thread's stack + held locks and emits a
  ``deadlock_suspect`` event (``events.jsonl`` schema,
  ``obs/events.py``) when an acquisition blocks past its threshold.

- :func:`nan_sentinel` / :func:`nan_origin` — the runtime half of the
  numlint numerics suite (``rules_numerics.py``). Wraps a step or
  dispatch and, on any non-finite output, localizes the FIRST offending
  leaf to a named head/param subtree, emits a schema-gated
  ``nan_origin`` event, and (in raise mode) fails with the subtree
  named. Opt-in on the train path via ``HYDRAGNN_NAN_SENTINEL``; the
  canary controller's NaN hard-veto uses the report mode so every
  rejection carries an origin.
"""

import contextlib
import re
import sys
import threading
import time
import traceback
from typing import Dict, Iterable, List, Optional, Tuple

from hydragnn_tpu.obs import runtime as _obs_runtime


class RecompileError(AssertionError):
    """A tracked region compiled after its warmup promised it would not."""


class CompileSentinel:
    """Assert zero new XLA compilations across a ``with`` region.

    ``fns``: optional jitted callables; their jit-cache entry counts are
    snapshotted too, catching re-traces even where the monitoring API is
    unavailable (a re-trace that hits the persistent compile cache never
    reaches the backend, but it still inserts a fresh cache entry).

    Usage::

        warmup()                      # compile everything first
        with CompileSentinel(fns=[trainer._train_step]) as sentinel:
            run_two_epochs()
        # exiting asserts flatness; or call sentinel.assert_flat() to
        # check mid-region
    """

    def __init__(self, fns: Iterable = (), check_on_exit: bool = True):
        self.fns = list(fns)
        self.check_on_exit = check_on_exit
        self._events0: Optional[int] = None
        self._cache0: Dict[int, int] = {}

    # ---- signals -------------------------------------------------------
    @staticmethod
    def _cache_size(fn) -> Optional[int]:
        get = getattr(fn, "_cache_size", None)
        if callable(get):
            try:
                return int(get())
            except Exception:
                return None
        return None

    def __enter__(self):
        _obs_runtime.install_compile_listener()
        self._events0 = _obs_runtime.compile_events()
        self._cache0 = {}
        for i, fn in enumerate(self.fns):
            size = self._cache_size(fn)
            if size is not None:
                self._cache0[i] = size
        return self

    def new_compiles(self) -> int:
        """Backend compilations observed since ``__enter__``."""
        if self._events0 is None:
            raise RuntimeError("CompileSentinel used outside its context")
        return _obs_runtime.compile_events() - self._events0

    def new_cache_entries(self) -> int:
        """Fresh jit-cache entries on the tracked fns since entry."""
        grown = 0
        for i, fn in enumerate(self.fns):
            if i not in self._cache0:
                continue
            size = self._cache_size(fn)
            if size is not None:
                grown += max(0, size - self._cache0[i])
        return grown

    def assert_flat(self, what: str = "region"):
        compiles = self.new_compiles()
        entries = self.new_cache_entries()
        if compiles or entries:
            raise RecompileError(
                f"{what}: expected zero recompiles after warmup, saw "
                f"{compiles} backend compilation(s) and {entries} new "
                "jit-cache entr(ies) — a shape or function identity "
                "leaked past setup"
            )

    def __exit__(self, exc_type, exc, tb):
        if exc_type is None and self.check_on_exit:
            self.assert_flat()
        return False


# ---- transfer guards ------------------------------------------------------

def transfer_guard_available() -> bool:
    import jax

    return hasattr(jax, "transfer_guard_device_to_host") and hasattr(
        jax, "transfer_guard"
    )


@contextlib.contextmanager
def no_host_syncs():
    """Hard-error any IMPLICIT device->host transfer in the region.

    Explicit fetches (``jax.device_get``) pass — they are the documented
    once-per-epoch readback. Host->device input transfers are unaffected,
    so a whole ``train_epoch`` (puts included) runs under this guard.
    Degrades to a no-op on jax builds without the transfer-guard API
    (tests should skip via :func:`transfer_guard_available`).
    """
    import jax

    if not transfer_guard_available():
        yield
        return
    with jax.transfer_guard_device_to_host("disallow"):
        yield


@contextlib.contextmanager
def no_implicit_transfers():
    """Hard-error implicit transfers in EVERY direction — for regions
    whose inputs are already device-resident (staged epochs) or packed
    host-side (a serve dispatch)."""
    import jax

    if not transfer_guard_available():
        yield
        return
    with jax.transfer_guard("disallow"):
        yield


# ---- sharding sentinel ----------------------------------------------------


class ShardingViolation(AssertionError):
    """A program output landed at a different sharding than declared."""


def _norm_spec(spec) -> tuple:
    """Canonical PartitionSpec tuple: trailing Nones stripped, so
    ``P('data')`` and ``P('data', None)`` (and a fully-replicated
    ``P()`` vs a spec-less single-device sharding) compare equal."""
    dims = list(tuple(spec))
    while dims and dims[-1] is None:
        dims.pop()
    return tuple(dims)


def _expected_spec(expected):
    """Spec tuple of one expected placement: a NamedSharding, a raw
    PartitionSpec, or anything exposing ``.spec``."""
    spec = getattr(expected, "spec", expected)
    try:
        return _norm_spec(spec)
    except TypeError:
        return None


def tree_sharding_mismatches(tree, expected) -> List[str]:
    """Human-readable mismatches between where ``tree``'s leaves LANDED
    (``leaf.sharding``) and where ``expected`` (a congruent pytree of
    ``NamedSharding``/``PartitionSpec``) declared they should.

    Leaves without a ``.sharding`` (host values) and expected entries of
    None are skipped; a single-device/spec-less sharding reads as
    replicated — declaring ``P()`` on a meshless run passes, declaring
    ``P('data')`` there correctly reports the shard that never happened.
    """
    import jax

    mismatches: List[str] = []

    def chk(path, leaf, exp):
        sh = getattr(leaf, "sharding", None)
        if sh is None or exp is None:
            return leaf
        want = _expected_spec(exp)
        if want is None:
            return leaf
        got = _norm_spec(getattr(sh, "spec", ()))
        if got != want:
            name = jax.tree_util.keystr(path)
            mismatches.append(
                f"{name}: landed at {got or 'replicated'}, "
                f"declared {want or 'replicated'}"
            )
        return leaf

    jax.tree_util.tree_map_with_path(chk, tree, expected)
    return mismatches


class ShardingSentinel:
    """Assert program outputs LAND at their declared shardings — the
    runtime sibling of :class:`CompileSentinel` for the 2-D mesh era and
    of the static ``jit-missing-shardings`` rule: the lint proves the
    contract is *written*, this proves execution *honors* it (a
    ``with_sharding_constraint`` dropped in a refactor still compiles
    and still converges — it just reshards on every consumer).

    Usage::

        state, metrics = trainer._train_step(state, batch, rng)
        with sharding_sentinel() as sen:
            sen.check(state, trainer._state_shardings, what="train_step")
        # or standalone: ShardingSentinel().check(...) raises directly
    """

    def __init__(self):
        self.violations: List[str] = []

    def check(self, tree, expected, what: str = "outputs", defer=False):
        """Compare ``tree``'s landed shardings against ``expected``;
        raises :class:`ShardingViolation` (or records, with
        ``defer=True``, for :meth:`assert_clean` at context exit)."""
        mism = [
            f"{what}: {m}" for m in tree_sharding_mismatches(tree, expected)
        ]
        if not mism:
            return
        self.violations.extend(mism)
        if not defer:
            self._raise()

    def _raise(self):
        raise ShardingViolation(
            f"{len(self.violations)} output(s) landed off their declared "
            "sharding — an implicit reshard every consumer pays for:\n  "
            + "\n  ".join(self.violations)
        )

    def assert_clean(self):
        if self.violations:
            self._raise()


@contextlib.contextmanager
def sharding_sentinel(check_on_exit: bool = True):
    """Context harness: ``check(..., defer=True)`` inside the region,
    one :class:`ShardingViolation` listing everything at exit."""
    sen = ShardingSentinel()
    yield sen
    if check_on_exit:
        sen.assert_clean()


# ---- lock sanitizer -------------------------------------------------------

# lock waits/holds live well below the serving-latency bounds: critical
# sections are microseconds when healthy, and the interesting tail is
# "someone slept under a lock" (ms) through "deadlock suspect" (s)
LOCK_LATENCY_BOUNDS = (
    1e-6, 5e-6, 1e-5, 5e-5, 1e-4, 5e-4, 1e-3, 5e-3,
    0.01, 0.05, 0.1, 0.5, 1.0, 5.0,
)

_METRIC_SAFE_RE = re.compile(r"[^A-Za-z0-9_]")


class LockOrderViolation(AssertionError):
    """Two locks were acquired in opposite orders by live code paths."""


def _call_site() -> str:
    """'file.py:123 in fn' for the first frame outside this module."""
    for frame in reversed(traceback.extract_stack()):
        if frame.filename != __file__:
            return f"{frame.filename}:{frame.lineno} in {frame.name}"
    return "<unknown>"


def _thread_dump(held: Dict[int, List[str]]) -> List[Dict]:
    """One JSON-able record per live thread: name, held locks, stack."""
    frames = sys._current_frames()
    threads = []
    for t in threading.enumerate():
        frame = frames.get(t.ident)
        stack = (
            [
                f"{f.filename}:{f.lineno} in {f.name}"
                for f in traceback.extract_stack(frame)
            ]
            if frame is not None
            else []
        )
        threads.append(
            {
                "name": t.name,
                "ident": t.ident,
                "daemon": t.daemon,
                "held_locks": list(held.get(t.ident, ())),
                "stack": stack,
            }
        )
    return threads


class InstrumentedLock:
    """Drop-in ``threading.Lock``/``RLock`` wrapper reporting to a
    :class:`LockSanitizer`. Same surface as the stdlib lock (``with``,
    ``acquire(blocking=, timeout=)``, ``release``, ``locked``), so
    production classes can take a lock *factory* and tests can inject
    ``sanitizer.lock`` without touching the code under test."""

    def __init__(self, sanitizer: "LockSanitizer", name: str, inner):
        self._san = sanitizer
        self.name = name
        self._inner = inner

    def acquire(self, blocking: bool = True, timeout: float = -1):
        self._san._note_wait(self.name, blocking)
        t0 = time.monotonic()
        if not blocking:
            ok = self._inner.acquire(False)
        else:
            ok = self._acquire_watched(timeout, t0)
        if ok:
            self._san._note_acquired(
                self.name, time.monotonic() - t0, blocking
            )
        return ok

    def _acquire_watched(self, timeout: float, t0: float) -> bool:
        wd = self._san.watchdog_s
        if wd is None:
            return self._inner.acquire(True, timeout)
        # first try inside the watchdog window; on expiry dump + emit,
        # then keep blocking for the remainder — the watchdog REPORTS a
        # suspected deadlock, it does not turn one into a TimeoutError.
        # A caller timeout SHORTER than the threshold can never reach
        # it: timing out there is the caller's normal control flow, not
        # a deadlock suspect
        first = wd if timeout < 0 else min(wd, timeout)
        if self._inner.acquire(True, first):
            return True
        waited = time.monotonic() - t0
        if timeout < 0 or timeout >= wd:
            self._san._fire_watchdog(self.name, waited)
        if timeout < 0:
            return self._inner.acquire(True, -1)
        remaining = timeout - waited
        if remaining <= 0:
            return False
        return self._inner.acquire(True, remaining)

    def release(self):
        self._san._note_release(self.name)
        self._inner.release()

    def locked(self) -> bool:
        return self._inner.locked()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, exc_type, exc, tb):
        self.release()
        return False


class LockSanitizer:
    """Tracks per-thread held-lock sets across every
    :class:`InstrumentedLock` it issued.

    - **order graph**: first acquisition of B while holding A records the
      edge A->B (with its call site). Acquiring A while ANY path B->..->A
      already exists in the graph is an order inversion: two threads
      running the two paths concurrently can deadlock. Recorded into
      :attr:`violations` (and raised on :func:`lock_sanitizer` exit).
    - **metrics**: per-lock wait/hold-time histograms into ``registry``
      (``lock_wait_seconds_<name>`` / ``lock_hold_seconds_<name>``).
    - **watchdog**: an acquisition blocked past ``watchdog_s`` dumps all
      thread stacks + held locks into :attr:`deadlock_suspects` and
      emits a ``deadlock_suspect`` event to ``event_log``.
    """

    def __init__(
        self,
        registry=None,
        watchdog_s: Optional[float] = None,
        event_log=None,
    ):
        self.registry = registry
        self.watchdog_s = watchdog_s
        self.event_log = event_log
        self.violations: List[Dict] = []
        self.deadlock_suspects: List[Dict] = []
        self._mu = threading.Lock()
        self._edges: Dict[Tuple[str, str], str] = {}  # (a, b) -> site
        self._succ: Dict[str, List[str]] = {}  # edge adjacency, cached
        self._held: Dict[int, List[str]] = {}  # ident -> acquisition order
        self._acquired_at: Dict[Tuple[int, str], float] = {}

    # ---- lock factories ------------------------------------------------
    def lock(self, name: str) -> InstrumentedLock:
        return InstrumentedLock(self, name, threading.Lock())

    def rlock(self, name: str) -> InstrumentedLock:
        return InstrumentedLock(self, name, threading.RLock())

    def wrap(self, name: str, inner) -> InstrumentedLock:
        """Instrument an existing lock object (e.g. swap a server's
        ``_pending_lock`` in a test without rebuilding the server)."""
        return InstrumentedLock(self, name, inner)

    # ---- recording (called by InstrumentedLock) ------------------------
    def _note_wait(self, name: str, blocking: bool):
        """Pre-acquire inversion check. Non-blocking attempts are exempt
        by construction: a trylock never waits, so it can never be the
        blocked edge of a deadlock cycle — flagging the standard
        trylock-avoidance idiom would be a false positive. The call site
        is only captured when a violation is actually appended (stack
        extraction is too expensive for every acquire)."""
        if not blocking:
            return
        ident = threading.get_ident()
        with self._mu:
            held = self._held.get(ident, [])
            for h in held:
                if h == name:  # reentrant re-acquire: no new ordering
                    return
            for h in held:
                path = self._find_path(name, h)
                if path is not None:
                    chain = " -> ".join(path)
                    first_site = self._edges.get(
                        (path[0], path[1]), "<unknown>"
                    )
                    self.violations.append(
                        {
                            "thread": threading.current_thread().name,
                            "holding": h,
                            "acquiring": name,
                            "reverse_chain": chain,
                            "site": _call_site(),
                            "first_seen_site": first_site,
                        }
                    )

    def _note_acquired(self, name: str, waited_s: float, blocking: bool):
        """Post-acquire bookkeeping. Order edges are recorded HERE, not
        pre-wait: a timed-out acquire must leave no phantom edge behind,
        and only a blocking nest establishes an ordering another thread
        could deadlock against (trylocks join the held set for dump and
        later-edge purposes, but record no edge of their own)."""
        ident = threading.get_ident()
        with self._mu:
            held = self._held.setdefault(ident, [])
            first_hold = name not in held
            if blocking and first_hold:
                new = [h for h in held if (h, name) not in self._edges]
                if new:
                    site = _call_site()
                    for h in new:
                        self._edges[(h, name)] = site
                        self._succ.setdefault(h, []).append(name)
            held.append(name)
            if first_hold:
                # reentrant re-acquires must NOT reset the clock: the
                # hold histogram measures the OUTERMOST hold
                self._acquired_at[(ident, name)] = time.monotonic()
        self._observe(f"lock_wait_seconds_{self._safe(name)}", waited_s)

    def _note_release(self, name: str):
        ident = threading.get_ident()
        held_s = None
        with self._mu:
            held = self._held.get(ident, [])
            if name in held:
                # remove the LAST occurrence (reentrant locks nest)
                held.reverse()
                held.remove(name)
                held.reverse()
                if name not in held:
                    t0 = self._acquired_at.pop((ident, name), None)
                    if t0 is not None:
                        held_s = time.monotonic() - t0
                if not held:
                    self._held.pop(ident, None)
        if held_s is not None:
            self._observe(
                f"lock_hold_seconds_{self._safe(name)}", held_s
            )

    def _fire_watchdog(self, name: str, waited_s: float):
        with self._mu:
            held_snapshot = {k: list(v) for k, v in self._held.items()}
        payload = {
            "lock": name,
            "waited_s": round(waited_s, 6),
            "threads": _thread_dump(held_snapshot),
        }
        with self._mu:
            self.deadlock_suspects.append(payload)
        if self.event_log is not None:
            self.event_log.emit("deadlock_suspect", **payload)

    # ---- helpers -------------------------------------------------------
    def _find_path(self, src: str, dst: str) -> Optional[List[str]]:
        """BFS path src -> dst through recorded edges (caller holds
        ``_mu``; ``_succ`` is maintained on edge insert)."""
        if src == dst:
            return [src]
        succ = self._succ
        frontier = [[src]]
        seen = {src}
        while frontier:
            path = frontier.pop(0)
            for nxt in succ.get(path[-1], ()):
                if nxt == dst:
                    return path + [nxt]
                if nxt not in seen:
                    seen.add(nxt)
                    frontier.append(path + [nxt])
        return None

    @staticmethod
    def _safe(name: str) -> str:
        return _METRIC_SAFE_RE.sub("_", name)

    def _observe(self, metric: str, seconds: float):
        if self.registry is None:
            return
        try:
            self.registry.observe(metric, seconds)
        except KeyError:
            try:
                self.registry.histogram(
                    metric,
                    "lock sanitizer latency",
                    bounds=LOCK_LATENCY_BOUNDS,
                )
            except ValueError:
                pass  # lost a declare race — the metric exists now
            self.registry.observe(metric, seconds)

    def assert_clean(self):
        """Raise :class:`LockOrderViolation` if any inversion was seen."""
        with self._mu:
            violations = list(self.violations)
        if violations:
            v = violations[0]
            raise LockOrderViolation(
                f"{len(violations)} lock order inversion(s): thread "
                f"{v['thread']!r} acquired `{v['acquiring']}` while "
                f"holding `{v['holding']}` at {v['site']}, but the "
                f"reverse order ({v['reverse_chain']}) was established "
                f"at {v['first_seen_site']}"
            )


# ---- NaN sentinel ---------------------------------------------------------
#
# The runtime half of the numerics suite (rules_numerics.py): the static
# rules prove exp/log/div/gather sites are *written* guarded; this
# localizes the first non-finite value an execution actually produces to
# a named head/param subtree, so a canary NaN veto or a diverged step
# says "pos_MAE head" instead of "somewhere in a 2000-leaf tree".


class NonFiniteError(FloatingPointError):
    """A sentinel-wrapped region produced NaN/Inf; the message and the
    attached :attr:`origin` payload localize the first offending leaf."""

    def __init__(self, message: str, origin: Dict):
        super().__init__(message)
        self.origin = origin


def nonfinite_report(tree) -> List[Tuple[str, int]]:
    """``(keystr_path, nonfinite_count)`` for every leaf of ``tree``
    holding at least one NaN/Inf, in deterministic tree order. Host
    scalars and non-numeric leaves count as finite."""
    import jax
    import numpy as np

    bad: List[Tuple[str, int]] = []

    def visit(path, leaf):
        try:
            arr = np.asarray(leaf)
        except Exception:
            return leaf
        if not np.issubdtype(arr.dtype, np.floating) and not np.issubdtype(
            arr.dtype, np.complexfloating
        ):
            return leaf
        n = int(np.size(arr) - np.sum(np.isfinite(arr)))
        if n:
            bad.append((jax.tree_util.keystr(path) or "<root>", n))
        return leaf

    jax.tree_util.tree_map_with_path(visit, tree)
    return bad


def _subtree_of(keystr_path: str) -> str:
    """First NAMED path component — the head/param group to blame.
    Bare sequence indices (a step's ``(state, metrics)`` tuple) and the
    generic ``params``/``opt_state`` containers are skipped so
    ``"[0].params['encoder_conv_0']['bias']"`` blames ``encoder_conv_0``,
    not ``0``; ``".loss['energy']"`` -> ``loss``."""
    parts = [
        part
        for part in re.split(r"[\[\].']+", keystr_path)
        if part and part != "<root>" and not part.isdigit()
    ]
    for part in parts:
        if part not in ("params", "opt_state", "state"):
            return part
    return parts[0] if parts else keystr_path


def nan_origin(tree, scope: str) -> Optional[Dict]:
    """Localize non-finite leaves of ``tree`` to a ``nan_origin`` event
    payload (``obs/events.py`` schema), or None when all-finite.

    ``origin`` is the FIRST offending leaf's keystr path, ``subtree``
    its leading component, ``leaves``/``total`` the non-finite/total
    leaf counts. Forces a device sync — diagnosis-path only, never on
    the hot path."""
    import jax

    bad = nonfinite_report(tree)
    if not bad:
        return None
    first_path, _ = bad[0]
    return {
        "scope": scope,
        "origin": first_path,
        "subtree": _subtree_of(first_path),
        "leaves": len(bad),
        "total": len(jax.tree_util.tree_leaves(tree)),
    }


def nan_sentinel(fn, *, scope: str, events=None, mode: str = "raise"):
    """Wrap a step/dispatch: when its output tree contains NaN/Inf,
    build the :func:`nan_origin` payload, emit a schema-gated
    ``nan_origin`` event to ``events`` (a
    :class:`~hydragnn_tpu.obs.events.RunEventLog`, optional) and — in
    ``mode="raise"`` — raise :class:`NonFiniteError` naming the subtree.
    ``mode="report"`` returns the output untouched after emitting, for
    paths with their own rejection machinery (the canary veto).

    The finiteness check is a host readback of the outputs, so only wrap
    opt-in (``HYDRAGNN_NAN_SENTINEL=1`` in ``train/steps.py``) or on
    already-host-bound paths."""
    if mode not in ("raise", "report"):
        raise ValueError(f"nan_sentinel mode {mode!r}: raise|report")

    def wrapped(*args, **kwargs):
        out = fn(*args, **kwargs)
        origin = nan_origin(out, scope)
        if origin is not None:
            if events is not None:
                events.emit("nan_origin", **origin)
            if mode == "raise":
                raise NonFiniteError(
                    f"{scope}: non-finite output at {origin['origin']} "
                    f"(subtree `{origin['subtree']}`, "
                    f"{origin['leaves']}/{origin['total']} leaf/leaves "
                    "affected)",
                    origin,
                )
        return out

    wrapped.__name__ = getattr(fn, "__name__", "wrapped")
    # forward the jit surface (lowering/ratchet harnesses, compile
    # sentinel cache signal) so wrapping a jitted step stays transparent
    for attr in ("lower", "_cache_size"):
        inner = getattr(fn, attr, None)
        if inner is not None:
            setattr(wrapped, attr, inner)
    return wrapped


@contextlib.contextmanager
def lock_sanitizer(
    registry=None,
    watchdog_s: Optional[float] = None,
    event_log=None,
    check_on_exit: bool = True,
):
    """Context harness for tests::

        with lock_sanitizer(watchdog_s=0.5) as san:
            server._pending_lock = san.wrap("pending", threading.Lock())
            ... drive the server from several threads ...
        # exit raises LockOrderViolation on any inversion seen

    ``registry`` (a :class:`~hydragnn_tpu.obs.metrics.MetricsRegistry`)
    receives per-lock wait/hold histograms; ``event_log`` (a
    :class:`~hydragnn_tpu.obs.events.RunEventLog`) receives
    ``deadlock_suspect`` events from the watchdog."""
    san = LockSanitizer(
        registry=registry, watchdog_s=watchdog_s, event_log=event_log
    )
    yield san
    if check_on_exit:
        san.assert_clean()
