"""Runtime correctness guards — what the static pass cannot prove.

Two harnesses, both designed for tests (cheap, no-op-safe, CPU-friendly):

- :class:`CompileSentinel` asserts the XLA compile counter stays FLAT
  across a region: warm a step function up, enter the sentinel, run an
  epoch (or a serve burst) — any recompile means a shape leaked past the
  bucketing/layout machinery, which is this stack's #1 silent perf
  regression. Counts come from the same ``jax.monitoring``
  backend-compile events the ``/metrics`` endpoint exports
  (``obs/runtime.py``), plus each tracked jitted function's own cache
  size as a second, API-stable signal.

- :func:`no_host_syncs` turns IMPLICIT device->host transfers into hard
  errors via ``jax.transfer_guard_device_to_host("disallow")``. The hot
  paths fetch results exactly once per epoch through explicit
  ``jax.device_get`` — which the guard permits — so a reintroduced
  per-batch ``float(metrics[...])`` fails the wrapped test instead of
  silently serializing the dispatch pipeline. :func:`no_implicit_transfers`
  is the stricter all-directions variant for regions that should move no
  data implicitly at all (a fully staged dispatch, a serve batch whose
  inputs are packed host-side).
"""

import contextlib
from typing import Dict, Iterable, Optional

from hydragnn_tpu.obs import runtime as _obs_runtime


class RecompileError(AssertionError):
    """A tracked region compiled after its warmup promised it would not."""


class CompileSentinel:
    """Assert zero new XLA compilations across a ``with`` region.

    ``fns``: optional jitted callables; their jit-cache entry counts are
    snapshotted too, catching re-traces even where the monitoring API is
    unavailable (a re-trace that hits the persistent compile cache never
    reaches the backend, but it still inserts a fresh cache entry).

    Usage::

        warmup()                      # compile everything first
        with CompileSentinel(fns=[trainer._train_step]) as sentinel:
            run_two_epochs()
        # exiting asserts flatness; or call sentinel.assert_flat() to
        # check mid-region
    """

    def __init__(self, fns: Iterable = (), check_on_exit: bool = True):
        self.fns = list(fns)
        self.check_on_exit = check_on_exit
        self._events0: Optional[int] = None
        self._cache0: Dict[int, int] = {}

    # ---- signals -------------------------------------------------------
    @staticmethod
    def _cache_size(fn) -> Optional[int]:
        get = getattr(fn, "_cache_size", None)
        if callable(get):
            try:
                return int(get())
            except Exception:
                return None
        return None

    def __enter__(self):
        _obs_runtime.install_compile_listener()
        self._events0 = _obs_runtime.compile_events()
        self._cache0 = {}
        for i, fn in enumerate(self.fns):
            size = self._cache_size(fn)
            if size is not None:
                self._cache0[i] = size
        return self

    def new_compiles(self) -> int:
        """Backend compilations observed since ``__enter__``."""
        if self._events0 is None:
            raise RuntimeError("CompileSentinel used outside its context")
        return _obs_runtime.compile_events() - self._events0

    def new_cache_entries(self) -> int:
        """Fresh jit-cache entries on the tracked fns since entry."""
        grown = 0
        for i, fn in enumerate(self.fns):
            if i not in self._cache0:
                continue
            size = self._cache_size(fn)
            if size is not None:
                grown += max(0, size - self._cache0[i])
        return grown

    def assert_flat(self, what: str = "region"):
        compiles = self.new_compiles()
        entries = self.new_cache_entries()
        if compiles or entries:
            raise RecompileError(
                f"{what}: expected zero recompiles after warmup, saw "
                f"{compiles} backend compilation(s) and {entries} new "
                "jit-cache entr(ies) — a shape or function identity "
                "leaked past setup"
            )

    def __exit__(self, exc_type, exc, tb):
        if exc_type is None and self.check_on_exit:
            self.assert_flat()
        return False


# ---- transfer guards ------------------------------------------------------

def transfer_guard_available() -> bool:
    import jax

    return hasattr(jax, "transfer_guard_device_to_host") and hasattr(
        jax, "transfer_guard"
    )


@contextlib.contextmanager
def no_host_syncs():
    """Hard-error any IMPLICIT device->host transfer in the region.

    Explicit fetches (``jax.device_get``) pass — they are the documented
    once-per-epoch readback. Host->device input transfers are unaffected,
    so a whole ``train_epoch`` (puts included) runs under this guard.
    Degrades to a no-op on jax builds without the transfer-guard API
    (tests should skip via :func:`transfer_guard_available`).
    """
    import jax

    if not transfer_guard_available():
        yield
        return
    with jax.transfer_guard_device_to_host("disallow"):
        yield


@contextlib.contextmanager
def no_implicit_transfers():
    """Hard-error implicit transfers in EVERY direction — for regions
    whose inputs are already device-resident (staged epochs) or packed
    host-side (a serve dispatch)."""
    import jax

    if not transfer_guard_available():
        yield
        return
    with jax.transfer_guard("disallow"):
        yield
