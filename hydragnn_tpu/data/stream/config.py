"""Config-driven construction of the streaming data plane.

A run opts in with a ``Dataset.streaming`` section::

    "Dataset": {
      "streaming": {
        "sources": [
          {"format": "shard_store", "train": "dataset/qm9_trainset",
           "validate": "dataset/qm9_valset", "test": "dataset/qm9_testset",
           "weight": 2.0},
          {"format": "extxyz", "train": "oc20/train_xyz",
           "validate": "oc20/val_xyz", "test": "oc20/test_xyz",
           "weight": 1.0, "radius": 6.0, "max_neighbours": 50}
        ],
        "window_shards": 2,        // shard window per source (host RAM bound)
        "num_buckets": 4,          // auto-tuned bucket plan size
        "samples_per_epoch": null, // default: ceil(total / world)
        "seed": 42
      }
    }

The TRAIN split streams (weighted mix + window shuffle + auto bucket
plan); validate/test splits are materialized into regular
``GraphLoader``\\ s over the plan's layout — eval sets are the small end
of the pipeline and the epoch driver evaluates them every epoch.

``probe_loader`` (returned fourth) is a cursor-neutral materialized
loader over the first window's samples: ``update_config`` derives output
dims/PNA degrees from it, and the trainer's ``init_state`` takes its
example batch — neither may consume the stream.
"""

from typing import Optional

from hydragnn_tpu.data.stream.loader import StreamLoader
from hydragnn_tpu.data.stream.mix import WeightedMix
from hydragnn_tpu.data.stream.planner import BucketPlanner
from hydragnn_tpu.data.stream.source import (
    ExtxyzSource,
    ShardStoreSource,
    StreamSource,
)
from hydragnn_tpu.utils.envparse import env_int


def streaming_requested(config: dict) -> bool:
    return bool(config.get("Dataset", {}).get("streaming"))


def _train_source(spec: dict) -> StreamSource:
    fmt = spec.get("format", "shard_store")
    name = spec.get("name")
    if fmt == "shard_store":
        return ShardStoreSource(spec["train"], name=name)
    if fmt == "extxyz":
        return ExtxyzSource(
            dirpath=spec["train"],
            radius=float(spec.get("radius", 6.0)),
            max_neighbours=int(spec.get("max_neighbours", 50)),
            energy_per_atom=bool(spec.get("energy_per_atom", True)),
            name=name,
        )
    raise ValueError(
        f"streaming source format {fmt!r} has no config mapping; build "
        "MPTrjSource/QM9RawSource through the API "
        "(hydragnn_tpu.data.stream) instead"
    )


def _eval_dataset(spec: dict, split: str):
    fmt = spec.get("format", "shard_store")
    path = spec.get(split)
    if path is None:
        return []
    if fmt == "shard_store":
        from hydragnn_tpu.data.shard_store import ShardDataset

        return ShardDataset(path)
    if fmt == "extxyz":
        from hydragnn_tpu.data.extxyz import load_extxyz_dir

        return load_extxyz_dir(
            path,
            radius=float(spec.get("radius", 6.0)),
            max_neighbours=int(spec.get("max_neighbours", 50)),
            energy_per_atom=bool(spec.get("energy_per_atom", True)),
        )
    raise ValueError(f"streaming source format {fmt!r} has no config mapping")


def assemble_stream_loaders(
    sources, weights, batch_size: int, scfg: dict, valset, testset,
    num_buckets: Optional[int] = None,
):
    """The ONE streaming-pipeline assembly (the config driver and
    ``examples/common.train_with_stream`` both route through here — env
    precedence and plan coverage must not drift between entry points):
    weighted mix, bucket plan over the train histogram PLUS the
    materialized eval splits (an eval graph larger than anything the
    train scan saw still needs a bucket), StreamLoader, eval
    GraphLoaders, cursor-neutral probe loader. The plan's
    ``bucket_plan`` payload rides on ``train_loader.plan_event`` for the
    caller to emit once telemetry is active (the driver builds loaders
    BEFORE ``init_run_telemetry``)."""
    from hydragnn_tpu.data.loaders import GraphLoader

    window = env_int(
        "HYDRAGNN_STREAM_WINDOW",
        int(scfg.get("window_shards", 2)),
        minimum=1,
    )
    mix = WeightedMix(
        sources,
        weights,
        seed=int(scfg.get("seed", 42)),
        samples_per_epoch=scfg.get("samples_per_epoch"),
        window=window,
    )
    planner = BucketPlanner(
        sources,
        batch_size,
        num_buckets=int(
            scfg.get("num_buckets", num_buckets or 4)
        ),
        extra_datasets=[valset, testset],
    )
    layout = planner.plan(emit=False)
    train_loader = StreamLoader(mix, batch_size, layout)
    train_loader.plan_event = planner.plan_payload(layout)
    val_loader = GraphLoader(valset, batch_size, layout, shuffle=False)
    test_loader = GraphLoader(testset, batch_size, layout, shuffle=False)
    probe_loader = GraphLoader(
        mix.probe_samples(limit=max(batch_size * 4, 64)),
        batch_size,
        layout,
        shuffle=False,
        num_shards=1,
        shard_id=0,
    )
    return train_loader, val_loader, test_loader, probe_loader


def build_stream_loaders(config: dict):
    """(train StreamLoader, val GraphLoader, test GraphLoader,
    probe GraphLoader) from the ``Dataset.streaming`` section."""
    from hydragnn_tpu.data.loaders import ConcatDataset

    scfg = config["Dataset"]["streaming"]
    if config["NeuralNetwork"]["Architecture"].get("partition_axis"):
        raise ValueError(
            "streaming ingestion and graph partitioning are mutually "
            "exclusive (the partitioner needs whole-dataset budgets)"
        )
    specs = scfg.get("sources") or []
    if not specs:
        raise ValueError("Dataset.streaming.sources is empty")
    training = config["NeuralNetwork"]["Training"]
    sources = [_train_source(s) for s in specs]
    weights = [float(s.get("weight", 1.0)) for s in specs]
    vals = [_eval_dataset(s, "validate") for s in specs]
    tests = [_eval_dataset(s, "test") for s in specs]
    return assemble_stream_loaders(
        sources,
        weights,
        int(training["batch_size"]),
        scfg,
        ConcatDataset([d for d in vals if len(d)]),
        ConcatDataset([d for d in tests if len(d)]),
        num_buckets=training.get("batch_buckets"),
    )
