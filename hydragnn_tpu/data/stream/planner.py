"""Auto-tuned bucket plans from streamed size histograms.

Closes the loop ROADMAP names: the padding-waste stats the obs layer has
collected since PR 3 (``epoch_padding_stats`` -> ``padding_waste_ratio``)
exist so bucket tables stop being hand-written. :class:`BucketPlanner`
runs a cheap size-histogram pass over the stream sources (index-only on
GraphPack stores — no payload decode), picks bucket boundaries with the
same exact-DP the materialized path uses
(:func:`~hydragnn_tpu.data.loaders._partition_node_bounds`), sizes each
bucket with the SAME budget rule
(:func:`~hydragnn_tpu.data.loaders.budget_bucket_layout`), estimates the
plan's padding waste by simulating the loader's own greedy packing, and
emits one schema-valid ``bucket_plan`` event recording all of it.

One sizing rule shared with ``compute_layout`` means an auto plan can be
compared number-for-number against a hand table through the existing
``epoch_padding_stats`` accounting — the acceptance check.
"""

from typing import Dict, List, Optional, Sequence, Union

import numpy as np

from hydragnn_tpu.data.loaders import (
    BatchLayout,
    BucketedLayout,
    _lcm,
    _pack_indices,
    _partition_node_bounds,
    budget_bucket_layout,
)
from hydragnn_tpu.data.stream.source import StreamSource
from hydragnn_tpu.utils.envparse import env_int


class BucketPlanner:
    """Builds a :class:`BucketedLayout` from streamed size statistics.

    ``plan_shards`` caps the histogram pass per source (default: the
    ``HYDRAGNN_STREAM_PLAN_SHARDS`` env knob, 0 = scan everything —
    index-backed sources scan everything cheaply regardless via their
    no-payload ``size_scan``). DimeNet triplet tables and dense neighbor
    lists need per-sample structure a size pass does not see — those
    layouts stay on the materialized ``compute_layout`` path.
    """

    def __init__(
        self,
        sources: Sequence[StreamSource],
        batch_size: int,
        num_buckets: int = 4,
        plan_shards: Optional[int] = None,
        device_multiple: Optional[int] = None,
        extra_datasets: Sequence = (),
    ):
        if not sources:
            raise ValueError("BucketPlanner needs at least one source")
        self.sources = list(sources)
        self.batch_size = int(batch_size)
        self.num_buckets = max(int(num_buckets), 1)
        # materialized splits (val/test) that will be served through the
        # SAME layout: their sizes join the histogram so an eval graph
        # larger than anything the train scan saw still has a bucket —
        # the materialized compute_layout covers all splits for exactly
        # this reason
        self.extra_datasets = list(extra_datasets)
        if plan_shards is None:
            plan_shards = env_int("HYDRAGNN_STREAM_PLAN_SHARDS", 0)
        self.plan_shards = plan_shards
        if device_multiple is None:
            try:
                import jax

                device_multiple = jax.device_count()
            except Exception:
                device_multiple = 1
        self.device_multiple = max(int(device_multiple), 1)
        self._scan: Optional[Dict] = None

    # ---- histogram pass --------------------------------------------------
    def scan(self) -> Dict:
        if self._scan is not None:
            return self._scan
        nodes_all, edges_all = [], []
        per_source = {}
        cap = None if self.plan_shards <= 0 else self.plan_shards
        for s in self.sources:
            nodes, edges = s.size_scan(max_shards=cap)
            if nodes.size == 0:
                raise ValueError(
                    f"stream source {s.name!r} produced no samples in "
                    "the size scan"
                )
            per_source[s.name] = int(nodes.size)
            nodes_all.append(nodes)
            edges_all.append(edges)
        for ds in self.extra_datasets:
            n = [d.num_nodes for d in ds]
            if n:
                nodes_all.append(np.asarray(n, np.int64))
                edges_all.append(
                    np.asarray([d.num_edges for d in ds], np.int64)
                )
        probe = self.sources[0].probe_samples(limit=1)
        if not probe:
            raise ValueError("cannot probe head schema: empty first shard")
        first = probe[0]
        head_types = tuple(first.target_types)
        head_dims = tuple(
            t.shape[-1] if t.ndim > 1 else t.shape[0] for t in first.targets
        )
        self._scan = {
            "nodes": np.concatenate(nodes_all),
            "edges": np.concatenate(edges_all),
            "per_source": per_source,
            "head_types": head_types,
            "head_dims": head_dims,
        }
        return self._scan

    # ---- plan ------------------------------------------------------------
    def plan(self, emit: bool = True) -> Union[BatchLayout, BucketedLayout]:
        scan = self.scan()
        nodes, edges = scan["nodes"], scan["edges"]
        mult = _lcm(8, self.device_multiple)
        bounds = _partition_node_bounds(nodes, self.num_buckets)
        layouts: List[BatchLayout] = []
        lo = 0
        kept_bounds: List[int] = []
        for hi in bounds:
            mask = (nodes > lo) & (nodes <= hi)
            lo = hi
            if not mask.any():
                continue
            kept_bounds.append(int(hi))
            layouts.append(
                budget_bucket_layout(
                    nodes[mask], edges[mask], np.zeros(int(mask.sum())),
                    self.batch_size, mult, self.device_multiple,
                    scan["head_types"], scan["head_dims"],
                )
            )
        layout = BucketedLayout(layouts=layouts, node_bounds=kept_bounds)
        if emit:
            from hydragnn_tpu.obs import runtime as obs

            obs.emit("bucket_plan", **self.plan_payload(layout))
        return layout

    def plan_payload(self, layout: BucketedLayout) -> Dict:
        """The ``bucket_plan`` event's payload for a plan this planner
        built — separable from :meth:`plan` because the driver builds
        loaders BEFORE telemetry activates and must emit the record
        afterwards (an emit into inactive telemetry is a silent no-op)."""
        scan = self.scan()
        return {
            "num_buckets": len(layout.layouts),
            "bounds": list(layout.node_bounds),
            "samples_scanned": int(scan["nodes"].size),
            "est_waste": round(float(self.estimate_waste(layout)), 6),
            "batch_size": self.batch_size,
            "per_source": scan["per_source"],
            "buckets": [
                {
                    "bound": b,
                    "n_pad": lay.n_pad,
                    "e_pad": lay.e_pad,
                    "g_pad": lay.g_pad,
                }
                for b, lay in zip(layout.node_bounds, layout.layouts)
            ],
        }

    def estimate_waste(
        self, layout: Union[BatchLayout, BucketedLayout]
    ) -> float:
        """Expected padding-waste ratio (1 - real/padded node rows) of
        ``layout`` over the scanned histogram, simulating the loader's
        own greedy packing — the same integrals
        ``GraphLoader.epoch_padding_stats`` reports live, so the planner's
        estimate and the measured epoch waste are directly comparable."""
        scan = self.scan()
        nodes, edges = scan["nodes"], scan["edges"]
        trips = np.zeros(len(nodes), np.int64)
        real = padded = 0
        if isinstance(layout, BucketedLayout):
            assign = np.asarray(
                [layout.bucket_for(int(n)) for n in nodes], np.int64
            )
            for b in range(len(layout.layouts)):
                idx = np.nonzero(assign == b)[0]
                if not len(idx):
                    continue
                lay = layout.layouts[b]
                batches = _pack_indices(
                    idx, nodes, edges, trips, lay,
                    batch_size=self.batch_size,
                )
                real += int(nodes[idx].sum())
                padded += len(batches) * int(lay.n_pad)
        else:
            nb = -(-len(nodes) // self.batch_size)
            real = int(nodes.sum())
            padded = nb * int(layout.n_pad)
        return 1.0 - real / max(padded, 1)
