"""Shard-granular lazy dataset sources — the streaming data plane's
read layer.

A :class:`StreamSource` exposes a dataset as an ordered list of SHARDS
(the unit of I/O, shuffling, and host-RAM residency): ``read_shard(i)``
materializes one shard's samples and nothing else, so a pipeline holding
a window of W shards never has more than W shards' samples resident no
matter how large the dataset is — the role ADIOS2 spans + DDStore chunk
fetches play in the reference's data plane (PAPER.md L3).

Sources over the existing backends:

- :class:`ShardStoreSource` — one GraphPack ``shard.*.gpk`` file per
  shard (the native store; index-only size scans, decode shared with
  ``ShardDataset`` via :func:`~hydragnn_tpu.data.shard_store.
  read_pack_sample`).
- :class:`ExtxyzSource` — one ``.extxyz`` file per shard; frames parse
  WITHOUT graph construction, the radius graph (PBC-aware) is attached
  as a per-sample pipeline stage (:attr:`StreamSource.graph_builder`) so
  neighbor search overlaps the device step instead of gating startup.
- :class:`MPTrjSource` / :class:`QM9RawSource` — sequential-format
  backends (one growing JSON / one SDF): shards are fixed-size record
  ranges; ``seekable=False`` keeps the per-pass shard order sequential
  (re-scanning a tens-of-GB JSON per random access would thrash), while
  window shuffling still decorrelates samples.
- :class:`ListSource` — in-memory list chunked into synthetic shards
  (tests, benchmarks, small datasets entering a mixed run).

``graph_builder`` (None = samples are complete) is applied per sample by
the stream pipeline AFTER the shard read — on-the-fly construction is a
stage, not a property of the reader.
"""

import glob
import os
from typing import Callable, List, Optional, Sequence

import numpy as np

from hydragnn_tpu.data.dataobj import GraphData
from hydragnn_tpu.utils.retry import retry_io


def sample_nbytes(d: GraphData) -> int:
    """Host bytes one sample pins while buffered (the window-residency
    accounting's unit)."""
    total = 0
    for a in (d.x, d.pos, d.y, d.edge_index, d.edge_attr, d.supercell_size):
        if a is not None:
            total += np.asarray(a).nbytes
    for t in d.targets:
        total += np.asarray(t).nbytes
    return total


class StreamSource:
    """Protocol base. Subclasses set ``name``/``seekable`` and implement
    :meth:`num_shards` / :meth:`read_shard`; the optional cheap paths
    (:meth:`num_samples`, :meth:`size_scan`) have scanning defaults."""

    name: str = "source"
    #: seekable sources support random shard access at no extra cost, so
    #: the per-pass shard permutation applies; sequential formats keep
    #: file order (window shuffle still randomizes within the window)
    seekable: bool = True
    #: applied per sample by the pipeline (None = samples arrive complete)
    graph_builder: Optional[Callable[[GraphData], GraphData]] = None

    def num_shards(self) -> int:
        raise NotImplementedError

    def read_shard(self, i: int) -> List[GraphData]:
        raise NotImplementedError

    def num_samples(self) -> int:
        """Total samples (drives the default epoch budget). Default: one
        counting pass over all shards — override where an index makes it
        cheap."""
        if not hasattr(self, "_num_samples_cache"):
            self._num_samples_cache = sum(
                len(self.read_shard(i)) for i in range(self.num_shards())
            )
        return self._num_samples_cache

    def size_scan(self, max_shards: Optional[int] = None):
        """(node_counts, edge_counts) over up to ``max_shards`` shards —
        the :class:`~hydragnn_tpu.data.stream.planner.BucketPlanner`'s
        histogram feed. The default materializes the sampled shards (and
        runs ``graph_builder`` so edge counts are real); index-backed
        sources override with a no-payload scan."""
        n_shards = self.num_shards()
        take = n_shards if max_shards is None else min(max_shards, n_shards)
        nodes, edges = [], []
        for i in range(take):
            for d in self.read_shard(i):
                if self.graph_builder is not None:
                    d = self.graph_builder(d)
                nodes.append(d.num_nodes)
                edges.append(d.num_edges)
        return np.asarray(nodes, np.int64), np.asarray(edges, np.int64)

    def probe_samples(self, limit: int = 64) -> List[GraphData]:
        """First-shard samples with graphs built — head-schema probes and
        example batches, WITHOUT touching any stream cursor."""
        out = []
        for d in self.read_shard(0)[:limit]:
            if self.graph_builder is not None:
                d = self.graph_builder(d)
            out.append(d)
        return out

    def close(self):
        pass


class ListSource(StreamSource):
    """In-memory samples chunked into synthetic shards."""

    def __init__(self, samples: Sequence[GraphData], shard_size: int = 64,
                 name: str = "list"):
        if shard_size < 1:
            raise ValueError("shard_size must be >= 1")
        self.samples = list(samples)
        self.shard_size = int(shard_size)
        self.name = name

    def num_shards(self) -> int:
        return max(-(-len(self.samples) // self.shard_size), 1)

    def read_shard(self, i: int) -> List[GraphData]:
        lo = i * self.shard_size
        return self.samples[lo : lo + self.shard_size]

    def num_samples(self) -> int:
        return len(self.samples)


class ShardStoreSource(StreamSource):
    """GraphPack shard store (``<label>/shard.*.gpk``), one file = one
    shard. Readers open on demand in :meth:`read_shard` and close after
    decoding — at no point does the source pin more than the shard being
    read (vs ``ShardDataset``, which opens every shard's mmap up front
    for O(1) global indexing)."""

    def __init__(self, label: str, name: Optional[str] = None):
        self.label = label
        self.paths = sorted(glob.glob(os.path.join(label, "shard.*.gpk")))
        if not self.paths:
            raise FileNotFoundError(f"no GraphPack shards under {label}")
        self.name = name or os.path.basename(os.path.normpath(label))
        self._counts: Optional[List[int]] = None

    def num_shards(self) -> int:
        return len(self.paths)

    def _open(self, i: int):
        from hydragnn_tpu.native.graphpack import PackReader

        path = self.paths[i]
        return retry_io(lambda: PackReader(path), what=path)

    def read_shard(self, i: int) -> List[GraphData]:
        from hydragnn_tpu.data.shard_store import read_pack_sample

        r = self._open(i)
        try:
            return [read_pack_sample(r, k) for k in range(r.num_samples)]
        finally:
            r.close()

    def _shard_counts(self) -> List[int]:
        if self._counts is None:
            counts = []
            for i in range(len(self.paths)):
                r = self._open(i)
                try:
                    counts.append(int(r.num_samples))
                finally:
                    r.close()
            self._counts = counts
        return self._counts

    def num_samples(self) -> int:
        return sum(self._shard_counts())

    def size_scan(self, max_shards: Optional[int] = None):
        """Index-only: row counts come from the pack's count tables, no
        sample payload is decoded — a full-store scan stays cheap at
        millions of samples."""
        n_shards = len(self.paths)
        take = n_shards if max_shards is None else min(max_shards, n_shards)
        nodes, edges = [], []
        for i in range(take):
            r = self._open(i)
            try:
                for k in range(r.num_samples):
                    nodes.append(r.sample_rows("x", k))
                    edges.append(r.sample_rows("edge_index", k))
            finally:
                r.close()
        return np.asarray(nodes, np.int64), np.asarray(edges, np.int64)


class ExtxyzSource(StreamSource):
    """Extended-XYZ files, one file = one shard. Frames parse into
    edge-LESS samples (z/pos/cell + energy/forces targets); the radius
    graph attaches via :attr:`graph_builder` as a pipeline stage — the
    first streaming run pays neighbor search per window, overlapped with
    training, instead of as a startup pass over the whole dataset."""

    def __init__(
        self,
        dirpath: Optional[str] = None,
        files: Optional[List[str]] = None,
        radius: float = 6.0,
        max_neighbours: int = 50,
        energy_per_atom: bool = True,
        energy_key: str = "energy",
        forces_norm_threshold: Optional[float] = 100.0,
        name: Optional[str] = None,
    ):
        if files is None:
            if dirpath is None:
                raise ValueError("need dirpath or files")
            files = [
                os.path.join(dirpath, fn)
                for fn in sorted(os.listdir(dirpath))
                if fn.endswith(".extxyz") or fn.endswith(".xyz")
            ]
        if not files:
            raise FileNotFoundError(f"no extxyz files under {dirpath!r}")
        self.files = files
        self.radius = float(radius)
        self.max_neighbours = int(max_neighbours)
        self.energy_per_atom = bool(energy_per_atom)
        self.energy_key = energy_key
        self.forces_norm_threshold = forces_norm_threshold
        self.name = name or (
            os.path.basename(os.path.normpath(dirpath)) if dirpath
            else "extxyz"
        )
        self.graph_builder = self._build_graph
        self._counts: Optional[List[int]] = None

    def num_shards(self) -> int:
        return len(self.files)

    def read_shard(self, i: int) -> List[GraphData]:
        from hydragnn_tpu.data.extxyz import iter_extxyz

        out = []
        for frame in iter_extxyz(self.files[i]):
            forces = frame["arrays"].get("forces")
            if (
                self.forces_norm_threshold is not None
                and forces is not None
                and len(forces)
                and np.linalg.norm(forces, axis=1).max()
                > self.forces_norm_threshold
            ):
                continue
            if self.energy_key not in frame["info"]:
                raise KeyError(
                    f"{self.files[i]}: frame has no "
                    f"{self.energy_key!r} in its comment line"
                )
            d = GraphData(
                x=frame["z"].astype(np.float32).reshape(-1, 1),
                pos=frame["pos"].astype(np.float32),
                supercell_size=None
                if frame.get("cell") is None
                else np.asarray(frame["cell"], np.float32),
            )
            energy = float(frame["info"][self.energy_key])
            if self.energy_per_atom:
                energy /= max(d.num_nodes, 1)
            d.targets = [np.asarray([energy], np.float32)]
            d.target_types = ["graph"]
            if forces is not None and len(forces):
                d.targets.append(np.asarray(forces, np.float32))
                d.target_types.append("node")
            # the builder stage needs the per-axis pbc mask AND the
            # full-precision cell: frame_to_graph runs neighbor search on
            # the f64 lattice, and the streamed path must produce
            # bit-identical edge lengths (supercell_size is the f32 model
            # input, not the search geometry)
            d.extras["pbc"] = np.asarray(frame["pbc"], bool)
            if frame.get("cell") is not None:
                d.extras["cell"] = np.asarray(frame["cell"], np.float64)
            out.append(d)
        return out

    def _build_graph(self, d: GraphData) -> GraphData:
        """On-the-fly radius graph (PBC-aware), matching
        ``extxyz.frame_to_graph``'s edge construction exactly — the
        materialized and streamed paths must produce identical neighbor
        lists (regression-locked by the PBC shard-boundary tests)."""
        from hydragnn_tpu.data.radius_graph import (
            radius_graph,
            radius_graph_pbc,
        )

        pbc = d.extras.get("pbc")
        cell = d.extras.get("cell")
        if cell is not None and pbc is not None and bool(np.any(pbc)):
            edge_index, lengths = radius_graph_pbc(
                d.pos.astype(np.float64),
                cell,
                self.radius,
                self.max_neighbours,
                pbc=pbc,
            )
        else:
            edge_index = radius_graph(d.pos, self.radius, self.max_neighbours)
            lengths = np.linalg.norm(
                d.pos[edge_index[0]] - d.pos[edge_index[1]], axis=1
            )
        d.edge_index = edge_index
        d.edge_attr = np.asarray(lengths, np.float32).reshape(-1, 1)
        return d

    def num_samples(self) -> int:
        # frame-count scan (headers only advance the parse; frames are
        # small text blocks) — done once, cached
        if self._counts is None:
            from hydragnn_tpu.data.extxyz import iter_extxyz

            self._counts = [
                sum(1 for _ in iter_extxyz(p)) for p in self.files
            ]
        return sum(self._counts)


class MPTrjSource(StreamSource):
    """MPtrj JSON: shards are fixed-size runs of mp_id entries in file
    order. The format is one sequential JSON object (no random access
    without an offset index), so ``seekable=False``: passes walk entries
    in order and ``read_shard`` streams to its range — each shard read is
    O(prefix), which the sequential consumption pattern keeps amortized
    (the window advances monotonically within a pass)."""

    seekable = False

    def __init__(
        self,
        path: str,
        entries_per_shard: int = 16,
        radius: float = 5.0,
        max_neighbours: int = 50,
        energy_per_atom: bool = True,
        forces_norm_threshold: Optional[float] = 100.0,
        name: Optional[str] = None,
    ):
        self.path = path
        self.entries_per_shard = max(int(entries_per_shard), 1)
        self.radius = float(radius)
        self.max_neighbours = int(max_neighbours)
        self.energy_per_atom = bool(energy_per_atom)
        self.forces_norm_threshold = forces_norm_threshold
        self.name = name or os.path.basename(path)
        self.graph_builder = self._build_graph
        self._num_entries: Optional[int] = None
        self._num_samples_scan: Optional[int] = None

    def _count_entries(self) -> int:
        from hydragnn_tpu.data.mptrj import iter_mptrj_entries

        if self._num_entries is None:
            n_e = n_s = 0
            for _, frames in iter_mptrj_entries(self.path):
                n_e += 1
                n_s += len(frames)
            self._num_entries = n_e
            self._num_samples_scan = n_s
        return self._num_entries

    def num_shards(self) -> int:
        return max(-(-self._count_entries() // self.entries_per_shard), 1)

    def num_samples(self) -> int:
        self._count_entries()
        return int(self._num_samples_scan or 0)

    def read_shard(self, i: int) -> List[GraphData]:
        from hydragnn_tpu.data.mptrj import (
            iter_mptrj_entries,
            structure_from_dict,
        )

        lo = i * self.entries_per_shard
        hi = lo + self.entries_per_shard
        out: List[GraphData] = []
        for k, (mp_id, frames) in enumerate(iter_mptrj_entries(self.path)):
            if k < lo:
                continue
            if k >= hi:
                break
            for frame_id, rec in frames.items():
                z, pos, _lattice = structure_from_dict(rec["structure"])
                forces = np.asarray(rec.get("force", []), np.float64)
                if (
                    self.forces_norm_threshold is not None
                    and forces.size
                    and np.linalg.norm(forces, axis=1).max()
                    > self.forces_norm_threshold
                ):
                    continue
                if self.energy_per_atom:
                    energy = rec.get("energy_per_atom")
                    if energy is None:
                        energy = rec["corrected_total_energy"] / len(z)
                else:
                    energy = rec.get("corrected_total_energy")
                    if energy is None:
                        energy = rec["energy_per_atom"] * len(z)
                posf = pos.astype(np.float32)
                d = GraphData(
                    x=np.concatenate(
                        [
                            z.astype(np.float32).reshape(-1, 1),
                            posf - posf.mean(axis=0, keepdims=True),
                        ],
                        axis=1,
                    ),
                    pos=posf,
                )
                d.targets = [np.asarray([float(energy)], np.float32)]
                d.target_types = ["graph"]
                if forces.size:
                    d.targets.append(forces.astype(np.float32))
                    d.target_types.append("node")
                out.append(d)
        return out

    def _build_graph(self, d: GraphData) -> GraphData:
        from hydragnn_tpu.data.radius_graph import radius_graph

        # non-periodic at 5 A / 50 neighbors by default — the reference's
        # deliberate choice on MPtrj bulk frames (data/mptrj.py docstring)
        d.edge_index = radius_graph(d.pos, self.radius, self.max_neighbours)
        lengths = np.linalg.norm(
            d.pos[d.edge_index[0]] - d.pos[d.edge_index[1]], axis=1
        )
        d.edge_attr = lengths.astype(np.float32).reshape(-1, 1)
        return d


class QM9RawSource(StreamSource):
    """QM9 PyG raw layout (``gdb9.sdf`` + csv + uncharacterized list):
    shards are fixed-size molecule ranges; the SDF streams block by block
    (``$$$$`` delimited) so only the shard's molecules materialize.
    Sequential format -> ``seekable=False``."""

    seekable = False

    def __init__(
        self,
        root: str,
        molecules_per_shard: int = 256,
        target_index: int = 10,
        per_atom: bool = True,
        radius: float = 7.0,
        max_neighbours: int = 5,
        name: Optional[str] = None,
    ):
        self.root = root
        self.sdf = os.path.join(root, "gdb9.sdf")
        if not os.path.exists(self.sdf):
            raise FileNotFoundError(
                f"QM9RawSource streams the sdf layout; no gdb9.sdf "
                f"under {root!r}"
            )
        self.molecules_per_shard = max(int(molecules_per_shard), 1)
        self.target_index = int(target_index)
        self.per_atom = bool(per_atom)
        self.radius = float(radius)
        self.max_neighbours = int(max_neighbours)
        self.name = name or "qm9"
        self.graph_builder = self._build_graph
        from hydragnn_tpu.data.qm9_raw import (
            read_gdb9_csv,
            read_uncharacterized,
        )

        self._targets = read_gdb9_csv(self.sdf + ".csv")
        skip_path = os.path.join(root, "uncharacterized.txt")
        self._skips = set(
            read_uncharacterized(skip_path)
            if os.path.exists(skip_path)
            else []
        )

    def _iter_blocks(self):
        """Stream ``$$$$``-delimited molecule blocks without reading the
        whole SDF into memory."""
        buf: List[str] = []
        with open(self.sdf) as f:
            for line in f:
                if line.strip() == "$$$$":
                    yield "".join(buf)
                    buf = []
                else:
                    buf.append(line)
        if any(ln.strip() for ln in buf):
            yield "".join(buf)

    def num_molecules(self) -> int:
        return int(self._targets.shape[0])

    def num_shards(self) -> int:
        return max(
            -(-self.num_molecules() // self.molecules_per_shard), 1
        )

    def num_samples(self) -> int:
        n = self.num_molecules()
        return n - sum(1 for s in self._skips if s < n)

    def read_shard(self, i: int) -> List[GraphData]:
        from hydragnn_tpu.data.elements import atomic_number
        from hydragnn_tpu.data.qm9_raw import parse_sdf_v2000

        lo = i * self.molecules_per_shard
        hi = lo + self.molecules_per_shard
        out: List[GraphData] = []
        for mi, block in enumerate(self._iter_blocks()):
            if mi < lo:
                continue
            if mi >= hi:
                break
            if mi in self._skips:
                continue
            parsed = parse_sdf_v2000(block + "$$$$\n")
            if not parsed:
                continue
            syms, pos, _bonds = parsed[0]
            z = np.asarray(
                [atomic_number(s) for s in syms], dtype=np.float32
            )
            y = self._targets[mi]
            d = GraphData(
                x=z.reshape(-1, 1), pos=pos, y=y.astype(np.float32)
            )
            t = float(y[self.target_index])
            if self.per_atom:
                t /= len(z)
            d.targets = [np.asarray([t], np.float32)]
            d.target_types = ["graph"]
            out.append(d)
        return out

    def _build_graph(self, d: GraphData) -> GraphData:
        from hydragnn_tpu.data.radius_graph import radius_graph

        d.edge_index = radius_graph(d.pos, self.radius, self.max_neighbours)
        return d
