"""Deterministic weighted multi-dataset interleave with checkpointable
cursors — the streaming plane's sampling layer.

Three deterministic ingredients, all pure functions of ``(seed, ...)``
integers so every host derives the same plan with no communication and a
resumed run replays bitwise-identically:

- **per-pass shard permutation** (seekable sources): pass ``p`` of source
  ``k`` visits shards in ``default_rng(SeedSequence([seed, k, p, 1]))``
  order, partitioned by rank (``perm[rank::world]``, wrap-padded so every
  rank holds the same shard count — DistributedSampler's rule at shard
  granularity). An elastic ``world_resize`` re-derives the partition from
  the new ``(world, rank)`` exactly like PR 8 re-derives data shards.
- **window shuffle**: each rank reads ``window`` shards, shuffles the
  concatenated samples with ``SeedSequence([seed, k, p, 2, ptr])``, and
  releases the buffer when drained — at most one window of shards per
  source is ever resident in host RAM.
- **epoch interleave**: epoch ``e`` draws its source-choice sequence from
  ``SeedSequence([seed, e, 3])`` against the cumulative weights. Because
  the choice sequence depends only on ``(seed, epoch)``, resuming from an
  epoch-boundary cursor replays the interrupted epoch exactly.

The cursor (:meth:`WeightedMix.state_dict`) is a few integers per source
(pass index, shard pointer, within-window offset) — it rides in the
checkpoint's ``train_meta`` (PR 1 format v2) and restores in O(window)
shard reads.
"""

from typing import Dict, List, Optional, Sequence

import numpy as np

from hydragnn_tpu.data.stream.source import StreamSource, sample_nbytes


def _rng(*ints) -> np.random.Generator:
    return np.random.default_rng(np.random.SeedSequence([int(i) for i in ints]))


class _SourceStream:
    """One source's infinite deterministic sample stream for one rank:
    pass-permuted shards -> rank partition -> window shuffle -> samples.
    Holds at most ``window`` shards' samples; cursor = (passno, ptr,
    offset) where ``ptr`` indexes this rank's shard sequence at the
    current window's start and ``offset`` counts samples already yielded
    from it."""

    def __init__(self, source: StreamSource, seed: int, index: int,
                 rank: int, world: int, window: int):
        self.source = source
        self.seed = int(seed)
        self.index = int(index)
        self.rank = int(rank)
        self.world = max(int(world), 1)
        self.window = max(int(window), 1)
        self.passno = 0
        self.ptr = 0  # shards consumed of this rank's current-pass list
        self._buffer: Optional[List] = None
        self._buf_start = 0
        self._offset = 0
        # residency accounting (the "bounded by the shard window"
        # acceptance assertion reads these)
        self.open_shards_peak = 0
        self.resident_bytes = 0
        self.resident_bytes_peak = 0
        self.bytes_read = 0

    def _mine(self, passno: int) -> np.ndarray:
        s = self.source.num_shards()
        if self.source.seekable:
            perm = _rng(self.seed, self.index, passno, 1).permutation(s)
        else:
            perm = np.arange(s)
        if self.world > 1:
            total = -(-s // self.world) * self.world
            perm = np.resize(perm, total)  # wrap-pad: equal count per rank
            perm = perm[self.rank :: self.world]
        return perm

    def _load_window(self):
        guard = 0
        while True:
            mine = self._mine(self.passno)
            if self.ptr >= len(mine):
                self.passno += 1
                self.ptr = 0
                continue
            ids = mine[self.ptr : self.ptr + self.window]
            samples: List = []
            for sid in ids:
                samples.extend(self.source.read_shard(int(sid)))
            self._buf_start = self.ptr
            self.ptr += len(ids)
            if samples:
                order = _rng(
                    self.seed, self.index, self.passno, 2, self._buf_start
                ).permutation(len(samples))
                self._buffer = [samples[i] for i in order]
                self._offset = 0
                self.open_shards_peak = max(
                    self.open_shards_peak, len(ids)
                )
                self.resident_bytes = sum(
                    sample_nbytes(d) for d in self._buffer
                )
                self.bytes_read += self.resident_bytes
                self.resident_bytes_peak = max(
                    self.resident_bytes_peak, self.resident_bytes
                )
                return
            guard += 1
            if guard > self.source.num_shards() + 1:
                raise ValueError(
                    f"stream source {self.source.name!r} yields no samples"
                )

    def next_sample(self):
        if self._buffer is None:
            self._load_window()
        d = self._buffer[self._offset]
        self._offset += 1
        if self._offset >= len(self._buffer):
            # eager release: the window bound is a RESIDENCY bound, not a
            # high-water mark that only GC enforces
            self._buffer = None
            self.resident_bytes = 0
        return d

    def state_dict(self) -> Dict[str, int]:
        if self._buffer is None:
            return {"passno": int(self.passno), "ptr": int(self.ptr),
                    "offset": 0}
        return {
            "passno": int(self.passno),
            "ptr": int(self._buf_start),
            "offset": int(self._offset),
        }

    def load_state_dict(self, sd):
        self.passno = int(np.asarray(sd["passno"]))
        self.ptr = int(np.asarray(sd["ptr"]))
        offset = int(np.asarray(sd["offset"]))
        self._buffer = None
        self.resident_bytes = 0
        self._offset = 0
        if offset > 0:
            self._load_window()
            self._offset = offset


class WeightedMix:
    """Deterministic PRNG-driven interleave of several
    :class:`StreamSource`\\ s with per-source weights.

    One epoch = ``samples_per_epoch`` draws per rank (default
    ``ceil(total_samples / world)``); each draw picks a source by weight
    and takes its stream's next sample. Sources cycle independently
    across epochs — a 10%-weight source takes many epochs to cover, a
    150%-effective-weight source repeats within one — which is exactly
    the GFM multi-dataset semantics (QM9 + OC20 + MPTrj in one run).

    Head schemas must match across sources (asserted at first draw);
    the collator cannot mix graph/node target layouts.
    """

    def __init__(
        self,
        sources: Sequence[StreamSource],
        weights: Optional[Sequence[float]] = None,
        seed: int = 42,
        samples_per_epoch: Optional[int] = None,
        window: Optional[int] = None,
        num_shards: Optional[int] = None,
        shard_id: Optional[int] = None,
    ):
        from hydragnn_tpu.utils.envparse import env_int

        if not sources:
            raise ValueError("WeightedMix needs at least one source")
        if weights is None:
            weights = [1.0] * len(sources)
        if len(weights) != len(sources):
            raise ValueError(
                f"{len(sources)} sources but {len(weights)} weights"
            )
        w = np.asarray(weights, np.float64)
        if not np.all(w > 0):
            raise ValueError(f"weights must be positive, got {list(w)}")
        self.weights = w / w.sum()
        self._cum = np.cumsum(self.weights)
        self.sources = list(sources)
        self.seed = int(seed)
        self.epoch = 0
        if window is None:
            window = env_int("HYDRAGNN_STREAM_WINDOW", 2, minimum=1)
        self.window = window
        from hydragnn_tpu.parallel.distributed import get_comm_size_and_rank

        world, rank = get_comm_size_and_rank()
        self.world = world if num_shards is None else int(num_shards)
        self.rank = rank if shard_id is None else int(shard_id)
        self.streams = [
            _SourceStream(s, self.seed, i, self.rank, self.world, self.window)
            for i, s in enumerate(self.sources)
        ]
        self._samples_per_epoch = samples_per_epoch
        self._schema_checked = False
        # per-epoch draw counts by source (the stream_source_mix gauges)
        self.epoch_draws = np.zeros(len(self.sources), np.int64)

    def samples_per_epoch(self) -> int:
        if self._samples_per_epoch is not None:
            return int(self._samples_per_epoch)
        total = sum(s.num_samples() for s in self.sources)
        return max(-(-total // self.world), 1)

    def set_epoch(self, epoch: int):
        self.epoch = int(epoch)

    def _check_schema(self, first):
        if self._schema_checked:
            return
        self._schema_checked = True
        want = tuple(first.target_types)
        for s in self.sources:
            probe = s.probe_samples(limit=1)
            if probe and tuple(probe[0].target_types) != want:
                raise ValueError(
                    f"source {s.name!r} head schema "
                    f"{tuple(probe[0].target_types)} != {want}; mixed "
                    "sources must share one head layout"
                )

    def __iter__(self):
        """Yield ``(source_index, sample)`` for one epoch's draws. The
        per-sample ``graph_builder`` stage is applied here, so downstream
        stages always see complete graphs."""
        rng = _rng(self.seed, self.epoch, 3)
        self.epoch_draws = np.zeros(len(self.sources), np.int64)
        n = self.samples_per_epoch()
        for _ in range(n):
            u = float(rng.random())
            k = int(np.searchsorted(self._cum, u, side="right"))
            k = min(k, len(self.sources) - 1)
            d = self.streams[k].next_sample()
            builder = self.sources[k].graph_builder
            if builder is not None:
                d = builder(d)
            if not self._schema_checked:
                self._check_schema(d)
            self.epoch_draws[k] += 1
            yield k, d

    # ---- checkpointable cursor ------------------------------------------
    def state_dict(self) -> Dict:
        """The resume cursor: seed/epoch plus each stream's position.
        Plain ints in nested string-keyed dicts — rides through the
        checkpoint's msgpack ``train_meta`` unchanged."""
        return {
            "seed": int(self.seed),
            "epoch": int(self.epoch),
            "world": int(self.world),
            "window": int(self.window),
            "sources": {
                str(i): st.state_dict()
                for i, st in enumerate(self.streams)
            },
        }

    def load_state_dict(self, sd: Dict):
        saved_seed = int(np.asarray(sd["seed"]))
        if saved_seed != self.seed:
            raise ValueError(
                f"stream cursor was saved with seed {saved_seed}, this "
                f"run uses {self.seed} — refusing a silently different "
                "data order"
            )
        # the cursor's (ptr, offset) are positions in a WINDOW-strided
        # walk: a different window silently replays a different order —
        # the same failure mode the seed check refuses
        saved_window = int(np.asarray(sd.get("window", self.window)))
        if saved_window != self.window:
            raise ValueError(
                f"stream cursor was saved with window {saved_window}, "
                f"this run uses {self.window} — refusing a silently "
                "different data order"
            )
        self.epoch = int(np.asarray(sd["epoch"]))
        saved_world = int(np.asarray(sd.get("world", self.world)))
        if saved_world != self.world:
            # elastic world resize: the rank partition the cursors index
            # no longer exists — re-derive from the new layout (fresh
            # per-source positions), exactly how PR 8 re-derives data
            # shards. The post-resize trajectory matches a clean restart
            # at the new world, not the old world's continuation.
            import warnings

            warnings.warn(
                f"stream cursor was saved at world {saved_world}, now "
                f"{self.world}: per-source positions re-derived from the "
                "new rank layout"
            )
            return
        for i, st in enumerate(self.streams):
            key = str(i)
            if key in sd.get("sources", {}):
                st.load_state_dict(sd["sources"][key])

    # ---- residency/telemetry accounting ---------------------------------
    def residency_stats(self) -> Dict[str, float]:
        return {
            "open_shards_peak": max(
                (st.open_shards_peak for st in self.streams), default=0
            ),
            "resident_bytes_peak": sum(
                st.resident_bytes_peak for st in self.streams
            ),
            "bytes_read": sum(st.bytes_read for st in self.streams),
        }

    def probe_samples(self, limit: int = 64):
        """Cursor-neutral schema/example probe across sources."""
        out = []
        for s in self.sources:
            out.extend(s.probe_samples(limit=limit))
            if len(out) >= limit:
                break
        return out[:limit]

    def close(self):
        for s in self.sources:
            s.close()
