"""The streaming batch loader: mix -> bucket-pack -> collate -> prefetch.

Duck-types the :class:`~hydragnn_tpu.data.loaders.GraphLoader` surface
the trainer and epoch driver consume (``set_epoch`` / ``__iter__`` /
``__len__`` / ``epoch_padding_stats``) while never materializing the
dataset: samples arrive one at a time from the
:class:`~hydragnn_tpu.data.stream.mix.WeightedMix` (itself bounded by
the shard window), are routed to their size bucket, packed greedily
under the bucket's budgets (the same rule as ``_pack_indices``), and
collated through the one shared ``collate_for_layout`` path. With
``prefetch > 0`` the whole pipeline — shard I/O, on-the-fly radius
graphs, packing, collation — runs on the background ``prefetch_iter``
thread, bounded by the queue; the consumer only ever blocks on the
queue, which is the ``stream_stall_seconds`` gauge.

``state_dict()``/``load_state_dict()`` expose the mix cursor; the epoch
driver threads it through the checkpoint's ``train_meta`` so a killed
run resumes mid-stream bitwise-identically (PR 1/PR 8 machinery).
"""

import time
from typing import Dict, List, Optional, Union

from hydragnn_tpu.data.loaders import (
    BatchLayout,
    BucketedLayout,
    collate_for_layout,
    prefetch_iter,
)
from hydragnn_tpu.data.stream.mix import WeightedMix
from hydragnn_tpu.utils.envparse import env_int


class StreamLoader:
    """Streaming epoch loader over a :class:`WeightedMix`.

    ``__iter__`` is one-shot per epoch and ADVANCES the mix cursors —
    probes must use :meth:`example_batch` (cursor-neutral). ``len()`` is
    an UPPER bound (every batch holds >= 1 graph); the trainer treats it
    as a cap, so iteration simply ends at the true batch count.
    """

    # this loader measures its own consumer-side stalls and reports them
    # through obs.stream_epoch_stats — the trainer's data-wait accounting
    # (goodput ledger) must not time the same waits a second time
    reports_stream_stats = True

    def __init__(
        self,
        mix: WeightedMix,
        batch_size: int,
        layout: Union[BatchLayout, BucketedLayout],
        prefetch: Optional[int] = None,
    ):
        self.mix = mix
        self.batch_size = int(batch_size)
        self.layout = layout
        if prefetch is None:
            prefetch = env_int(
                "HYDRAGNN_STREAM_QUEUE",
                env_int("HYDRAGNN_PREFETCH", 0),
            )
        self.prefetch = prefetch
        self.epoch = 0
        # the epoch driver probes len(train_loader.dataset) inside a
        # try/TypeError — None keeps its graphs/sec derivation off rather
        # than wrong (the mix's own counters feed the stream gauges)
        self.dataset = None
        self._epoch_stats: Optional[Dict] = None
        self._stats_epoch = -1
        # the builder parks the plan's bucket_plan payload here when the
        # emit must wait for telemetry activation (driver startup order)
        self.plan_event: Optional[Dict] = None

    # ---- GraphLoader surface --------------------------------------------
    def set_epoch(self, epoch: int):
        self.epoch = int(epoch)
        self.mix.set_epoch(epoch)

    def __len__(self) -> int:
        return self.mix.samples_per_epoch()

    def state_dict(self) -> Dict:
        return {"epoch": int(self.epoch), "mix": self.mix.state_dict()}

    def load_state_dict(self, sd: Dict):
        self.mix.load_state_dict(sd["mix"])

    def example_batch(self):
        """A collated batch from cursor-neutral probe samples — feeds
        ``Trainer.init_state`` without consuming the stream."""
        probe = self.mix.probe_samples(limit=self.batch_size)
        if not probe:
            raise ValueError("stream sources yielded no probe samples")
        if isinstance(self.layout, BucketedLayout):
            b = self.layout.bucket_for(probe[0].num_nodes)
            lay = self.layout.layouts[b]
        else:
            lay = self.layout
        # greedy fill under the SAME budgets the epoch packer honors — a
        # probe batch must be a shape the compiled programs will see
        take, n, e = [], 0, 0
        for d in probe:
            ni, ei = d.num_nodes, d.num_edges
            if ni > lay.n_pad - 1 or ei > lay.e_pad:
                continue
            if take and (
                n + ni > lay.n_pad - 1
                or e + ei > lay.e_pad
                or len(take) >= min(self.batch_size, lay.g_pad - 1)
            ):
                break
            take.append(d)
            n += ni
            e += ei
        if not take:
            raise ValueError(
                "no probe sample fits the first bucket's layout"
            )
        return collate_for_layout(take, lay)

    # ---- pipeline --------------------------------------------------------
    def _layout_for(self, num_nodes: int):
        if isinstance(self.layout, BucketedLayout):
            b = self.layout.bucket_for(num_nodes)
            return b, self.layout.layouts[b]
        return 0, self.layout

    def _batches(self, stats: Dict):
        """One epoch's (bucket, samples) stream, packed greedily under
        each bucket's budgets. Deterministic in (seed, epoch, cursor):
        the flush order of end-of-epoch partials is bucket index."""
        n_buckets = (
            len(self.layout.layouts)
            if isinstance(self.layout, BucketedLayout)
            else 1
        )
        open_batches: List[List] = [[] for _ in range(n_buckets)]
        open_n = [0] * n_buckets
        open_e = [0] * n_buckets

        def emit(b):
            lay = (
                self.layout.layouts[b]
                if isinstance(self.layout, BucketedLayout)
                else self.layout
            )
            batch = collate_for_layout(open_batches[b], lay)
            stats["real_nodes"] += open_n[b]
            stats["padded_nodes"] += int(lay.n_pad)
            stats["batches"] += 1
            open_batches[b] = []
            open_n[b] = 0
            open_e[b] = 0
            return batch

        for k, d in self.mix:
            stats["samples"] += 1
            b, lay = self._layout_for(d.num_nodes)
            ni, ei = d.num_nodes, d.num_edges
            if ni > lay.n_pad - 1 or ei > lay.e_pad:
                # a sample no bucket can hold (planner scanned a subset):
                # drop loudly-countable rather than crash the epoch
                stats["oversize_dropped"] += 1
                continue
            if open_batches[b] and (
                open_n[b] + ni > lay.n_pad - 1
                or open_e[b] + ei > lay.e_pad
                or len(open_batches[b]) >= min(
                    self.batch_size, lay.g_pad - 1
                )
            ):
                yield emit(b)
            open_batches[b].append(d)
            open_n[b] += ni
            open_e[b] += ei
        for b in range(n_buckets):
            if open_batches[b]:
                yield emit(b)

    def __iter__(self):
        from hydragnn_tpu.obs import runtime as obs

        stats = {
            "samples": 0,
            "batches": 0,
            "real_nodes": 0,
            "padded_nodes": 0,
            "oversize_dropped": 0,
            "stall_s": 0.0,
            "queue_depth": 0,
            "bytes": 0,
        }
        self._epoch_stats = stats
        self._stats_epoch = self.epoch
        bytes_before = self.mix.residency_stats()["bytes_read"]
        t_start = time.perf_counter()

        def probe(depth):
            stats["queue_depth"] = depth

        if self.prefetch > 0:
            it = prefetch_iter(
                self._batches(stats), self.prefetch,
                name="hydragnn-stream-collate", probe=probe,
            )
        else:
            it = self._batches(stats)
        t0 = time.perf_counter()
        for batch in it:
            # time blocked on the data plane (queue wait with prefetch on,
            # whole-pipeline time with it off)
            stats["stall_s"] += time.perf_counter() - t0
            yield batch
            t0 = time.perf_counter()
        wall = max(time.perf_counter() - t_start, 1e-9)
        res = self.mix.residency_stats()
        stats["bytes"] = res["bytes_read"] - bytes_before
        source_counts = {
            s.name: int(n)
            for s, n in zip(self.mix.sources, self.mix.epoch_draws)
        }
        obs.stream_epoch_stats(
            queue_depth=stats["queue_depth"],
            stall_s=stats["stall_s"],
            bytes_per_sec=stats["bytes"] / wall,
            open_shards_peak=res["open_shards_peak"],
            resident_bytes_peak=res["resident_bytes_peak"],
            samples=stats["samples"],
            oversize_dropped=stats["oversize_dropped"],
            source_counts=source_counts,
        )
        if stats["oversize_dropped"]:
            # size-biased data loss must be operator-visible even with
            # telemetry off — the capped plan scan missed these sizes
            import warnings

            warnings.warn(
                f"stream epoch {self.epoch}: {stats['oversize_dropped']} "
                "sample(s) fit no bucket of the plan and were dropped — "
                "raise HYDRAGNN_STREAM_PLAN_SHARDS (0 scans everything) "
                "or num_buckets"
            )

    def epoch_padding_stats(self):
        """(real, padded) node rows of the LAST iterated epoch — streamed
        accounting is exact (counted as batches emit), unlike the
        materialized loader's plan simulation. None before any epoch
        has run."""
        s = self._epoch_stats
        if s is None or not s["padded_nodes"]:
            return None
        return s["real_nodes"], s["padded_nodes"]

    def close(self):
        self.mix.close()
