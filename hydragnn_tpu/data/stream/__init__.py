"""Streaming data plane: shard-native ingestion for datasets that fit
neither in host RAM nor HBM (docs/data.md).

Pipeline:  sources (lazy shard readers)  ->  WeightedMix (deterministic
interleave + distributed window shuffle, checkpointable cursor)  ->
BucketPlanner (auto-tuned bucket plan from streamed size histograms)  ->
StreamLoader (greedy bucket packing + collation + bounded prefetch).
"""

from hydragnn_tpu.data.stream.loader import StreamLoader
from hydragnn_tpu.data.stream.mix import WeightedMix
from hydragnn_tpu.data.stream.planner import BucketPlanner
from hydragnn_tpu.data.stream.source import (
    ExtxyzSource,
    ListSource,
    MPTrjSource,
    QM9RawSource,
    ShardStoreSource,
    StreamSource,
    sample_nbytes,
)
from hydragnn_tpu.data.stream.config import (
    assemble_stream_loaders,
    build_stream_loaders,
    streaming_requested,
)

__all__ = [
    "BucketPlanner",
    "assemble_stream_loaders",
    "ExtxyzSource",
    "ListSource",
    "MPTrjSource",
    "QM9RawSource",
    "ShardStoreSource",
    "StreamLoader",
    "StreamSource",
    "WeightedMix",
    "build_stream_loaders",
    "sample_nbytes",
    "streaming_requested",
]
