"""Host-side neighbor search: radius graph with and without periodic
boundary conditions.

Replaces torch_cluster's ``radius_graph`` (``preprocess/utils.py:102-131``)
and ase.neighborlist's PBC path (``RadiusGraphPBC``,
``preprocess/utils.py:134-174``) with numpy implementations — graph
construction is dataset preprocessing, it runs once on the host, not on TPU.

Edge convention: (senders=j, receivers=i), every ordered pair within the
cutoff (radius graphs are symmetric). ``max_neighbors`` caps incoming edges
per receiver in index order, matching torch-cluster's behavior.
"""

from typing import Optional, Tuple

import numpy as np


def radius_graph(
    pos: np.ndarray,
    radius: float,
    max_neighbors: int = 32,
    loop: bool = False,
) -> np.ndarray:
    n = pos.shape[0]
    if n == 0:
        return np.zeros((2, 0), dtype=np.int64)
    diff = pos[None, :, :] - pos[:, None, :]  # [i, j]
    dist = np.sqrt((diff * diff).sum(-1))
    within = dist <= radius
    if not loop:
        np.fill_diagonal(within, False)
    senders, receivers = [], []
    for i in range(n):
        js = np.nonzero(within[i])[0][:max_neighbors]
        senders.append(js)
        receivers.append(np.full(js.shape, i, dtype=np.int64))
    return np.stack(
        [np.concatenate(senders), np.concatenate(receivers)]
    ).astype(np.int64)


def radius_graph_pbc(
    pos: np.ndarray,
    cell: np.ndarray,
    radius: float,
    max_neighbors: int = 32,
    loop: bool = False,
) -> Tuple[np.ndarray, np.ndarray]:
    """Periodic radius graph over the 27 minimum-image shifts.

    Returns (edge_index, edge_length). Raises if a pair is connected through
    more than one image — the same "duplicate edges" guard as the reference
    (``preprocess/utils.py:162-167``): reduce the cutoff or grow the cell.
    """
    cell = np.asarray(cell, dtype=np.float64)
    if cell.ndim == 1:
        cell = np.diag(cell)
    n = pos.shape[0]
    shifts = np.array(
        [[i, j, k] for i in (-1, 0, 1) for j in (-1, 0, 1) for k in (-1, 0, 1)]
    )
    shift_vecs = shifts @ cell  # [27, 3]
    senders, receivers, lengths = [], [], []
    seen = set()
    for s in shift_vecs:
        diff = (pos[None, :, :] + s[None, None, :]) - pos[:, None, :]  # [i, j]
        dist = np.sqrt((diff * diff).sum(-1))
        within = dist <= radius
        # self-interaction excluded only for the zero shift; a node's own
        # periodic image is a legitimate neighbor (ase semantics)
        if not loop and np.abs(s).sum() <= 1e-12:
            np.fill_diagonal(within, False)
        ii, jj = np.nonzero(within)
        for i, j in zip(ii, jj):
            key = (int(j), int(i))
            if key in seen:
                raise ValueError(
                    "Adding periodic boundary conditions would result in "
                    "duplicate edges. Cutoff radius must be reduced or "
                    "system size increased."
                )
            seen.add(key)
            senders.append(j)
            receivers.append(i)
            lengths.append(dist[i, j])
    if not senders:
        return np.zeros((2, 0), dtype=np.int64), np.zeros((0,), dtype=np.float32)
    senders = np.asarray(senders, dtype=np.int64)
    receivers = np.asarray(receivers, dtype=np.int64)
    lengths = np.asarray(lengths, dtype=np.float32)
    # cap incoming neighbors per receiver in insertion order
    order = np.argsort(receivers, kind="stable")
    senders, receivers, lengths = senders[order], receivers[order], lengths[order]
    keep = np.ones(senders.shape[0], dtype=bool)
    count = {}
    for idx, r in enumerate(receivers):
        c = count.get(int(r), 0)
        if c >= max_neighbors:
            keep[idx] = False
        count[int(r)] = c + 1
    return (
        np.stack([senders[keep], receivers[keep]]),
        lengths[keep],
    )
