"""Host-side neighbor search: radius graph with and without periodic
boundary conditions.

Replaces torch_cluster's ``radius_graph`` (``preprocess/utils.py:102-131``)
and ase.neighborlist's PBC path (``RadiusGraphPBC``,
``preprocess/utils.py:134-174``) with numpy implementations — graph
construction is dataset preprocessing, it runs once on the host, not on TPU.

Edge convention: (senders=j, receivers=i), every ordered pair within the
cutoff (radius graphs are symmetric). ``max_neighbors`` caps incoming edges
per receiver in index order, matching torch-cluster's behavior.
"""

from typing import Optional, Tuple

import numpy as np


def radius_graph(
    pos: np.ndarray,
    radius: float,
    max_neighbors: int = 32,
    loop: bool = False,
) -> np.ndarray:
    """Radius graph; O(n^2) dense for small systems, cell-list (O(n) memory,
    ~O(n) time for bounded density) above — giant single graphs (the
    graph-partition workload) need the latter: 16k atoms would otherwise
    materialize a 3 GB distance matrix. Both paths produce identical edges:
    every ordered (j -> i) pair with dist <= radius, capped per receiver at
    ``max_neighbors`` in ascending-j order."""
    n = pos.shape[0]
    if n == 0:
        return np.zeros((2, 0), dtype=np.int64)
    pos = np.asarray(pos, dtype=np.float64)
    if n <= 1024:
        diff = pos[None, :, :] - pos[:, None, :]  # [i, j]
        dist = np.sqrt((diff * diff).sum(-1))
        within = dist <= radius
        if not loop:
            np.fill_diagonal(within, False)
        senders, receivers = [], []
        for i in range(n):
            js = np.nonzero(within[i])[0][:max_neighbors]
            senders.append(js)
            receivers.append(np.full(js.shape, i, dtype=np.int64))
        return np.stack(
            [np.concatenate(senders), np.concatenate(receivers)]
        ).astype(np.int64)

    # ---- cell list ------------------------------------------------------
    grid = np.floor((pos - pos.min(axis=0)) / radius).astype(np.int64)
    dims = grid.max(axis=0) + 1
    cid = (grid[:, 0] * dims[1] + grid[:, 1]) * dims[2] + grid[:, 2]
    order = np.argsort(cid, kind="stable")  # points grouped by cell
    sorted_cid = cid[order]
    uniq, start = np.unique(sorted_cid, return_index=True)
    counts = np.diff(np.append(start, n))

    recv_all, send_all = [], []
    offsets = np.array(
        [[a, b, c] for a in (-1, 0, 1) for b in (-1, 0, 1) for c in (-1, 0, 1)]
    )
    for off in offsets:
        ng = grid + off
        ok = np.all((ng >= 0) & (ng < dims), axis=1)
        pts = np.nonzero(ok)[0]
        ncid = (ng[pts, 0] * dims[1] + ng[pts, 1]) * dims[2] + ng[pts, 2]
        slot = np.searchsorted(uniq, ncid)
        hit = (slot < uniq.shape[0]) & (uniq[np.minimum(slot, uniq.shape[0] - 1)] == ncid)
        pts, slot = pts[hit], slot[hit]
        c = counts[slot]
        total = int(c.sum())
        if total == 0:
            continue
        recv = np.repeat(pts, c)
        within_cell = np.arange(total) - np.repeat(np.cumsum(c) - c, c)
        send = order[np.repeat(start[slot], c) + within_cell]
        recv_all.append(recv)
        send_all.append(send)
    if not recv_all:
        return np.zeros((2, 0), dtype=np.int64)
    recv = np.concatenate(recv_all)
    send = np.concatenate(send_all)
    d = np.linalg.norm(pos[send] - pos[recv], axis=1)
    keep = d <= radius
    if not loop:
        keep &= send != recv
    recv, send = recv[keep], send[keep]
    # per-receiver cap in ascending-j order (dense-path semantics)
    so = np.lexsort((send, recv))
    recv, send = recv[so], send[so]
    change = np.r_[True, recv[1:] != recv[:-1]]
    group_start = np.nonzero(change)[0]
    rank = np.arange(recv.shape[0]) - np.repeat(
        group_start, np.diff(np.append(group_start, recv.shape[0]))
    )
    keep = rank < max_neighbors
    return np.stack([send[keep], recv[keep]]).astype(np.int64)


def radius_graph_pbc(
    pos: np.ndarray,
    cell: np.ndarray,
    radius: float,
    max_neighbors: int = 32,
    loop: bool = False,
    pbc: Optional[np.ndarray] = None,
) -> Tuple[np.ndarray, np.ndarray]:
    """Periodic radius graph over the 27 minimum-image shifts.

    ``pbc`` is a per-axis [3] bool mask (default fully periodic): image
    shifts along a non-periodic axis are excluded, so a slab with
    pbc="T T F" never forms edges across the vacuum axis.

    Returns (edge_index, edge_length). Raises if a pair is connected through
    more than one image — the same "duplicate edges" guard as the reference
    (``preprocess/utils.py:162-167``): reduce the cutoff or grow the cell.
    """
    cell = np.asarray(cell, dtype=np.float64)
    if cell.ndim == 1:
        cell = np.diag(cell)
    n = pos.shape[0]
    shifts = np.array(
        [[i, j, k] for i in (-1, 0, 1) for j in (-1, 0, 1) for k in (-1, 0, 1)]
    )
    if pbc is not None:
        pbc = np.asarray(pbc, dtype=bool)
        shifts = shifts[np.all((shifts == 0) | pbc[None, :], axis=1)]
    shift_vecs = shifts @ cell  # [27, 3]
    senders, receivers, lengths = [], [], []
    seen = set()
    for s in shift_vecs:
        diff = (pos[None, :, :] + s[None, None, :]) - pos[:, None, :]  # [i, j]
        dist = np.sqrt((diff * diff).sum(-1))
        within = dist <= radius
        # self-interaction excluded only for the zero shift; a node's own
        # periodic image is a legitimate neighbor (ase semantics)
        if not loop and np.abs(s).sum() <= 1e-12:
            np.fill_diagonal(within, False)
        ii, jj = np.nonzero(within)
        for i, j in zip(ii, jj):
            key = (int(j), int(i))
            if key in seen:
                raise ValueError(
                    "Adding periodic boundary conditions would result in "
                    "duplicate edges. Cutoff radius must be reduced or "
                    "system size increased."
                )
            seen.add(key)
            senders.append(j)
            receivers.append(i)
            lengths.append(dist[i, j])
    if not senders:
        return np.zeros((2, 0), dtype=np.int64), np.zeros((0,), dtype=np.float32)
    senders = np.asarray(senders, dtype=np.int64)
    receivers = np.asarray(receivers, dtype=np.int64)
    lengths = np.asarray(lengths, dtype=np.float32)
    # cap incoming neighbors per receiver in insertion order
    order = np.argsort(receivers, kind="stable")
    senders, receivers, lengths = senders[order], receivers[order], lengths[order]
    keep = np.ones(senders.shape[0], dtype=bool)
    count = {}
    for idx, r in enumerate(receivers):
        c = count.get(int(r), 0)
        if c >= max_neighbors:
            keep[idx] = False
        count[int(r)] = c + 1
    return (
        np.stack([senders[keep], receivers[keep]]),
        lengths[keep],
    )
