"""CFG (extended AtomEye) raw loader.

Parity with ``hydragnn/preprocess/cfg_raw_dataset_loader.py:26-107``, but
parsed directly (no ase dependency): reads particle count, H0 supercell
matrix, and per-atom rows (mass / symbol lines followed by scaled
coordinates + auxiliary columns). Positions are unscaled via the H0 cell;
graph features come from the first line of the sibling ``.bulk`` file
(``cfg_raw_dataset_loader.py:92-100``), zeros when absent.
"""

import os

import numpy as np

from hydragnn_tpu.data.dataobj import GraphData
from hydragnn_tpu.data.raw import AbstractRawDataset

# minimal symbol -> Z table for the alloys the reference examples use;
# extend as needed
_SYMBOLS = {
    "H": 1, "He": 2, "Li": 3, "Be": 4, "B": 5, "C": 6, "N": 7, "O": 8,
    "F": 9, "Ne": 10, "Na": 11, "Mg": 12, "Al": 13, "Si": 14, "P": 15,
    "S": 16, "Cl": 17, "Ar": 18, "K": 19, "Ca": 20, "Ti": 22, "V": 23,
    "Cr": 24, "Mn": 25, "Fe": 26, "Co": 27, "Ni": 28, "Cu": 29, "Zn": 30,
    "Nb": 41, "Mo": 42, "Ta": 73, "W": 74, "Re": 75, "Pt": 78, "Au": 79,
}


class CFGDataset(AbstractRawDataset):
    def transform_input_to_data_object_base(self, filepath: str):
        if not filepath.endswith(".cfg"):
            return None
        num_particles = 0
        cell = np.zeros((3, 3), dtype=np.float64)
        entry_count = 3
        rows = []
        types = []
        current_z = None
        with open(filepath, "r", encoding="utf-8") as f:
            lines = [ln.strip() for ln in f if ln.strip()]
        i = 0
        while i < len(lines):
            ln = lines[i]
            if ln.startswith("Number of particles"):
                num_particles = int(ln.split("=")[1])
            elif ln.startswith("H0("):
                # H0(i,j) = value A
                key = ln.split("=")[0].strip()
                val = float(ln.split("=")[1].split()[0])
                r = int(key[3]) - 1
                c = int(key[5]) - 1
                cell[r, c] = val
            elif ln.startswith("entry_count"):
                entry_count = int(ln.split("=")[1])
            elif ln.startswith(("A =", ".NO_VELOCITY.", "R =", "aux")):
                pass
            else:
                fields = ln.split()
                if len(fields) == 1 and fields[0].replace(".", "").isdigit():
                    pass  # mass line
                elif len(fields) == 1:
                    current_z = _SYMBOLS.get(fields[0], 0)  # symbol line
                elif len(fields) >= 3:
                    rows.append([float(v) for v in fields])
                    types.append(current_z if current_z is not None else 0)
            i += 1

        if not rows:
            return None
        arr = np.asarray(rows, dtype=np.float64)
        scaled = arr[:, :3]
        pos = (scaled @ cell).astype(np.float32)
        aux = arr[:, 3:]
        z = np.asarray(types, dtype=np.float32)[:, None]
        full = np.concatenate([z, pos, aux], axis=1).astype(np.float32)

        node_features = []
        for item in range(len(self.node_feature_dim)):
            for icomp in range(self.node_feature_dim[item]):
                col = self.node_feature_col[item] + icomp
                node_features.append(full[:, col])
        x = np.stack(node_features, axis=1) if node_features else z

        # graph features live in a sibling ".bulk" file, first line
        # (``cfg_raw_dataset_loader.py:92-100``)
        y = np.zeros((sum(self.graph_feature_dim),), dtype=np.float32)
        bulk = os.path.splitext(filepath)[0] + ".bulk"
        if os.path.exists(bulk):
            with open(bulk, "r", encoding="utf-8") as f:
                graph_feat = f.readline().split()
            vals = []
            for item in range(len(self.graph_feature_dim)):
                for icomp in range(self.graph_feature_dim[item]):
                    col = self.graph_feature_col[item] + icomp
                    vals.append(float(graph_feat[col]))
            y = np.asarray(vals, dtype=np.float32)

        data = GraphData(
            x=x.astype(np.float32),
            pos=pos,
            y=y,
            supercell_size=cell,
        )
        return data
