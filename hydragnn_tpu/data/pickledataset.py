"""Per-sample pickle dataset (SimplePickleDataset/Writer analog).

Parity with ``hydragnn/utils/pickledataset.py:15-183``: one ``.pkl`` file
per sample named ``<label>-<k>.pkl`` with a ``<label>-meta.pkl`` manifest,
optional subdirectory bucketing (``k // nmax_persubdir``,
``pickledataset.py:78-90``) so huge datasets don't melt the filesystem,
and rank-offset naming on multi-process writes (global index = local index
+ sum of earlier ranks' counts, ``pickledataset.py:145-183``) so every
process writes its own share without coordination beyond one counts
exchange.

Differences from the reference (deliberate): the meta file is a single
versioned dict (schema evolution + corruption detection) instead of six
sequential pickle records, and the cross-process counts exchange rides the
framework's host collective (``host_allgather_int``) instead of mpi4py.
Most workloads should prefer the GraphPack shard store
(``data/shard_store.py``) — mmap'd, zero-copy, one file per writer rank —
but this format matches the reference's on-disk granularity for
migrations that expect file-per-sample layouts.
"""

import os
import pickle
from typing import List, Optional, Sequence

from hydragnn_tpu.data.dataobj import GraphData
from hydragnn_tpu.data.serialized import (
    extract_targets,
    select_input_node_features,
)
from hydragnn_tpu.parallel.distributed import (
    get_comm_size_and_rank,
    host_allgather_int,
)
from hydragnn_tpu.utils import faults
from hydragnn_tpu.utils.retry import retry_io

_META_VERSION = 1


class SimplePickleWriter:
    """Write a locally-owned list of samples as per-sample pickle files.

    Rank 0 writes the meta manifest; every rank writes its own samples at
    the global offset derived from an allgather of local counts.
    """

    def __init__(
        self,
        dataset: Sequence,
        basedir: str,
        label: str = "total",
        minmax_node_feature=None,
        minmax_graph_feature=None,
        use_subdir: bool = False,
        nmax_persubdir: int = 10_000,
        attrs: Optional[dict] = None,
    ):
        if not isinstance(dataset, list):
            raise TypeError("SimplePickleWriter expects a list of samples")
        world, rank = get_comm_size_and_rank()
        counts = host_allgather_int(len(dataset))
        noffset = int(sum(counts[:rank]))
        ntotal = int(sum(counts))

        if rank == 0:
            os.makedirs(basedir, exist_ok=True)
            meta = {
                "version": _META_VERSION,
                "ntotal": ntotal,
                "use_subdir": bool(use_subdir),
                "nmax_persubdir": int(nmax_persubdir),
                "minmax_node_feature": minmax_node_feature,
                "minmax_graph_feature": minmax_graph_feature,
                "attrs": dict(attrs or {}),
            }
            with open(os.path.join(basedir, f"{label}-meta.pkl"), "wb") as f:
                pickle.dump(meta, f)
        # rank 0 created basedir; other ranks may race ahead of it
        os.makedirs(basedir, exist_ok=True)

        for i, data in enumerate(dataset):
            k = noffset + i
            path = _sample_path(basedir, label, k, use_subdir, nmax_persubdir)
            os.makedirs(os.path.dirname(path), exist_ok=True)
            with open(path, "wb") as f:
                pickle.dump(data, f)
        # barrier: no rank may start reading until every rank has finished
        # writing its share (readers fetch samples owned by other ranks)
        host_allgather_int(1)


class SimplePickleDataset:
    """Lazy (or preloaded) per-sample pickle reader with subset views.

    ``var_config`` (the config's ``Variables_of_interest``) applies the
    same on-read target extraction / input-column selection as the
    reference's ``update_data_object`` (``pickledataset.py:92-103``).
    """

    def __init__(
        self,
        basedir: str,
        label: str = "total",
        subset: Optional[List[int]] = None,
        preload: bool = False,
        var_config: Optional[dict] = None,
    ):
        self.basedir = basedir
        self.label = label
        self.var_config = var_config
        meta_path = os.path.join(basedir, f"{label}-meta.pkl")

        def _read_meta():
            faults.flaky_read(meta_path)
            with open(meta_path, "rb") as f:
                return pickle.load(f)

        meta = retry_io(_read_meta, what=meta_path)
        if not isinstance(meta, dict) or "version" not in meta:
            raise ValueError(
                f"{label}-meta.pkl is not a hydragnn_tpu pickle-dataset "
                "manifest (or predates the versioned format)"
            )
        self.ntotal = int(meta["ntotal"])
        self.use_subdir = bool(meta["use_subdir"])
        self.nmax_persubdir = int(meta["nmax_persubdir"])
        self.minmax_node_feature = meta.get("minmax_node_feature")
        self.minmax_graph_feature = meta.get("minmax_graph_feature")
        self.attrs = dict(meta.get("attrs", {}))
        self.subset = list(range(self.ntotal)) if subset is None else list(subset)
        self._cache = None
        if preload:
            self._cache = [self.read(k) for k in range(self.ntotal)]

    def setsubset(self, subset: List[int]):
        self.subset = list(subset)

    def read(self, k: int) -> GraphData:
        path = _sample_path(
            self.basedir, self.label, k, self.use_subdir, self.nmax_persubdir
        )

        def _read():
            faults.flaky_read(path)
            with open(path, "rb") as f:
                return pickle.load(f)

        # per-sample reads hit the filesystem once per __getitem__; on
        # flaky shared mounts that's the hottest transient-OSError surface
        return self._update(retry_io(_read, what=path))

    def _update(self, data: GraphData) -> GraphData:
        if self.var_config is not None:
            extract_targets(
                self.var_config["type"],
                self.var_config["output_index"],
                self.var_config["graph_feature_dims"],
                self.var_config["node_feature_dims"],
                data,
            )
            select_input_node_features(
                self.var_config["input_node_features"], data
            )
        return data

    def __len__(self):
        return len(self.subset)

    def __getitem__(self, i: int) -> GraphData:
        k = self.subset[i]
        if self._cache is not None:
            return self._cache[k]
        return self.read(k)

    def __iter__(self):
        for i in range(len(self)):
            yield self[i]


def _sample_path(basedir, label, k, use_subdir, nmax_persubdir):
    fname = f"{label}-{k}.pkl"
    if use_subdir:
        return os.path.join(basedir, str(k // nmax_persubdir), fname)
    return os.path.join(basedir, fname)
