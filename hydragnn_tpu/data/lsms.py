"""LSMS text-format raw loader.

Parity with ``hydragnn/preprocess/lsms_raw_dataset_loader.py:20-106``. Format
(also used by the synthetic "unit_test" fixture,
``tests/deterministic_graph_data.py:80-105``):

    line 0:  graph-level features (whitespace separated)
    line i:  feature  node_index  x  y  z  output1  output2  ...

Graph/node feature blocks are selected via the Dataset config's
``column_index``/``dim`` tables. The LSMS "charge density" correction
subtracts the proton count (column 0 of the selected node features) from
column 1 (``lsms_raw_dataset_loader.py:90-106``).
"""

import numpy as np

from hydragnn_tpu.data.dataobj import GraphData
from hydragnn_tpu.data.raw import AbstractRawDataset


class LSMSDataset(AbstractRawDataset):
    def transform_input_to_data_object_base(self, filepath: str):
        with open(filepath, "r", encoding="utf-8") as f:
            lines = f.readlines()
        graph_feat = lines[0].split()
        g_feature = []
        for item in range(len(self.graph_feature_dim)):
            for icomp in range(self.graph_feature_dim[item]):
                col = self.graph_feature_col[item] + icomp
                g_feature.append(float(graph_feat[col]))

        node_features = []
        positions = []
        for line in lines[1:]:
            fields = line.split()
            if not fields:
                continue
            positions.append(
                [float(fields[2]), float(fields[3]), float(fields[4])]
            )
            row = []
            for item in range(len(self.node_feature_dim)):
                for icomp in range(self.node_feature_dim[item]):
                    col = self.node_feature_col[item] + icomp
                    row.append(float(fields[col]))
            node_features.append(row)

        data = GraphData(
            x=np.asarray(node_features, dtype=np.float32),
            pos=np.asarray(positions, dtype=np.float32),
            y=np.asarray(g_feature, dtype=np.float32),
        )
        # charge density correction: x[:,1] -= x[:,0]
        if data.x.shape[1] >= 2:
            data.x[:, 1] = data.x[:, 1] - data.x[:, 0]
        return data
