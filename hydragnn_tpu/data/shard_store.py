"""Sharded GraphPack dataset — the ADIOS2 data-plane replacement.

API parity with ``AdiosWriter``/``AdiosDataset``
(``hydragnn/utils/adiosdataset.py:77-278, 281-789``): a writer that each
process calls with its local samples (``add``), plus global attributes; a
dataset that presents the union of all shards with O(1) ``get(i)`` by global
index. Differences by design (TPU-native):

- Each process writes its OWN shard file (``<label>/shard.<rank>.gpk``) — no
  MPI-collective global write; the "global shape/offset" bookkeeping the
  reference assembles with allgathers (``adiosdataset.py:207-270``) is
  recovered at open time from the per-shard count/offset indexes.
- The reference's node-local SharedMemory mode (``:458-506``) is free here:
  shard files are mmap'd MAP_SHARED, so all trainer processes on one host
  share the same page-cache pages. ``preload=True`` copies into RAM instead
  (slow remote filesystems).
"""

import glob
import json
import os
from typing import Dict, List, Optional

import numpy as np

from hydragnn_tpu.data.dataobj import GraphData
from hydragnn_tpu.native.graphpack import PackReader, PackWriter
from hydragnn_tpu.utils import faults
from hydragnn_tpu.utils.retry import retry_io


class ShardWriter:
    """Per-process shard writer.

    >>> w = ShardWriter("dataset/trainset", rank=rank)
    >>> w.add(samples)           # list[GraphData], this process's share
    >>> w.add_global("pna_deg", deg_hist)
    >>> w.save()
    """

    def __init__(self, label: str, rank: int = 0):
        self.label = label
        self.rank = rank
        self.samples: List[GraphData] = []
        self.attrs: Dict[str, object] = {}

    def add(self, samples):
        if isinstance(samples, GraphData):
            self.samples.append(samples)
        else:
            self.samples.extend(samples)

    def add_global(self, name: str, value):
        if isinstance(value, np.ndarray):
            value = value.tolist()
        self.attrs[name] = value

    def save(self):
        os.makedirs(self.label, exist_ok=True)
        n = len(self.samples)
        path = os.path.join(self.label, f"shard.{self.rank:05d}.gpk")
        tmp = path + ".partial"
        w = PackWriter(tmp, n)
        try:
            self._pack(w)
            w.finish()
            os.replace(tmp, path)
        except Exception:
            w.abort()
            if os.path.exists(tmp):
                os.remove(tmp)
            raise
        if self.rank == 0:
            meta = dict(self.attrs)
            s0 = self.samples[0] if self.samples else None
            if s0 is not None:
                meta.setdefault("target_types", list(s0.target_types))
                meta.setdefault(
                    "target_dims",
                    [int(np.atleast_2d(t).shape[-1]) for t in s0.targets],
                )
            with open(os.path.join(self.label, "meta.json"), "w") as f:
                json.dump(meta, f, indent=1)

    def _pack(self, w: PackWriter):
        ss = self.samples
        n = len(ss)
        nodes = np.array([s.num_nodes for s in ss], dtype=np.int64)
        edges = np.array([s.num_edges for s in ss], dtype=np.int64)
        w.add(
            "x",
            np.concatenate([s.x for s in ss]).astype(np.float32)
            if n
            else np.zeros((0, 1), np.float32),
            counts=nodes,
        )
        if n and all(s.pos is not None for s in ss):
            w.add(
                "pos",
                np.concatenate([s.pos for s in ss]).astype(np.float32),
                counts=nodes,
            )
        # edge_index stored edge-major [E, 2] so samples are contiguous
        w.add(
            "edge_index",
            np.concatenate([s.edge_index.T for s in ss]).astype(np.int64)
            if n
            else np.zeros((0, 2), np.int64),
            counts=edges,
        )
        if n and all(s.edge_attr is not None for s in ss):
            w.add(
                "edge_attr",
                np.concatenate([s.edge_attr for s in ss]).astype(np.float32),
                counts=edges,
            )
        if all(s.y is not None for s in ss) and n:
            w.add(
                "y",
                np.stack([np.ravel(s.y) for s in ss]).astype(np.float32),
            )
        if all(s.supercell_size is not None for s in ss) and n:
            w.add(
                "supercell_size",
                np.stack(
                    [np.asarray(s.supercell_size, np.float32) for s in ss]
                ),
            )
        num_heads = len(ss[0].targets) if n else 0
        for ih in range(num_heads):
            ttype = ss[0].target_types[ih]
            if ttype == "graph":
                w.add(
                    f"target{ih}",
                    np.stack(
                        [np.ravel(s.targets[ih]) for s in ss]
                    ).astype(np.float32),
                )
            else:
                w.add(
                    f"target{ih}",
                    np.concatenate(
                        [
                            np.asarray(s.targets[ih], np.float32).reshape(
                                s.num_nodes, -1
                            )
                            for s in ss
                        ]
                    ),
                    counts=nodes,
                )


def read_pack_sample(r: PackReader, i: int) -> GraphData:
    """Decode one sample out of an open :class:`PackReader` — THE gpk
    sample wire format, shared by :class:`ShardDataset` and the streaming
    shard source (``hydragnn_tpu/data/stream/source.py``) so the two
    paths cannot diverge."""
    d = GraphData()
    d.x = r.read("x", i)
    if "pos" in r.vars:
        d.pos = r.read("pos", i)
    d.edge_index = r.read("edge_index", i).T
    if "edge_attr" in r.vars:
        d.edge_attr = r.read("edge_attr", i)
    if "y" in r.vars:
        d.y = r.read("y", i).ravel()
    if "supercell_size" in r.vars:
        d.supercell_size = r.read("supercell_size", i).reshape(3, 3)
    ih = 0
    d.target_types = []
    while f"target{ih}" in r.vars:
        t = r.read(f"target{ih}", i)
        # variable-dim target vars (dims[0] == -1) are node heads
        is_node = r.vars[f"target{ih}"][2][0] == -1
        d.targets.append(t if is_node else t.reshape(-1))
        d.target_types.append("node" if is_node else "graph")
        ih += 1
    return d


class ShardDataset:
    """Reads every shard under ``label/``; presents a flat global index.

    ``get(i)`` is two array slices out of the mmap per variable — no pickle,
    no per-sample files, no remote fetch needed on a single host.
    """

    def __init__(self, label: str, preload: bool = False, subset=None):
        """``subset``: optional sequence of global sample indices that this
        dataset view exposes (the reference's AdiosDataset subset support,
        ``utils/adiosdataset.py:610-636``) — ``len``/``[i]`` then run over
        the subset while ``get`` keeps taking global indices."""
        self.label = label
        paths = sorted(glob.glob(os.path.join(label, "shard.*.gpk")))
        if not paths:
            raise FileNotFoundError(f"no GraphPack shards under {label}")
        # shared-filesystem opens are the reads most likely to hiccup at
        # job start (thousands of ranks hitting GPFS/NFS at once) — retry
        # with jittered backoff instead of dying on a transient EIO
        self.readers = [
            retry_io(
                lambda p=p: PackReader(p, preload=preload), what=p
            )
            for p in paths
        ]
        self._cum = np.cumsum([r.num_samples for r in self.readers])
        meta_path = os.path.join(label, "meta.json")
        self.meta: Dict[str, object] = {}
        if os.path.exists(meta_path):
            def _read_meta():
                faults.flaky_read(meta_path)
                with open(meta_path) as f:
                    return json.load(f)

            self.meta = retry_io(_read_meta, what=meta_path)
        self.target_types = list(self.meta.get("target_types", []))

        self.subset = None if subset is None else [int(i) for i in subset]

    def num_samples_total(self) -> int:
        return int(self._cum[-1]) if len(self._cum) else 0

    def graph_sizes(self) -> np.ndarray:
        """Per-sample node counts from the shard count indexes alone — no
        sample payloads are read, so dataset-wide size scans (layout
        maxima) stay cheap at millions of samples."""
        sizes = np.concatenate(
            [
                np.array(
                    [r.sample_rows("x", i) for i in range(r.num_samples)],
                    dtype=np.int64,
                )
                for r in self.readers
            ]
        ) if self.readers else np.zeros(0, np.int64)
        if self.subset is not None:
            sizes = sizes[np.asarray(self.subset, np.int64)]
        return sizes

    def __len__(self) -> int:
        if self.subset is not None:
            return len(self.subset)
        return self.num_samples_total()

    def _locate(self, idx: int):
        total = self.num_samples_total()
        if idx < 0:
            idx += total
        if not 0 <= idx < total:
            raise IndexError(idx)
        shard = int(np.searchsorted(self._cum, idx, side="right"))
        local = idx - (int(self._cum[shard - 1]) if shard else 0)
        return self.readers[shard], local

    def get(self, idx: int) -> GraphData:
        # mmap'd page faults can surface transient OSError on remote
        # filesystems; one sample read is cheap, so retry the whole thing
        return retry_io(lambda: self._get_once(idx), what=f"sample {idx}")

    def _get_once(self, idx: int) -> GraphData:
        faults.flaky_read(f"{self.label}[{idx}]")
        r, i = self._locate(idx)
        return read_pack_sample(r, i)

    def __getitem__(self, idx: int) -> GraphData:
        if self.subset is not None:
            idx = self.subset[idx]
        return self.get(idx)

    def __iter__(self):
        for i in range(len(self)):
            yield self[i]  # subset-relative: __getitem__ translates

    def close(self):
        for r in self.readers:
            r.close()
        self.readers = []
