"""Raw -> serialized dataset pipeline.

Behavioral parity with ``hydragnn/preprocess/raw_dataset_loader.py:27-279``:
walk the per-split directories, parse each file into a ``GraphData``, scale
``*_scaled_num_nodes`` features by node count, compute GLOBAL min-max over all
splits, normalize every feature block to [0, 1], and pickle
``(minmax_node_feature, minmax_graph_feature, dataset)`` per split under
``$SERIALIZED_DATA_PATH/serialized_dataset``.
"""

import os
import pickle
import random
from typing import Dict, List

import numpy as np

from hydragnn_tpu.data.dataobj import GraphData


def _tensor_divide(num, den):
    return np.divide(num, den, out=np.zeros_like(num), where=den != 0)


class AbstractRawDataset:
    def __init__(self, config: dict, dist: bool = False, comm=None):
        self.node_feature_name = config["node_features"]["name"]
        self.node_feature_dim = config["node_features"]["dim"]
        self.node_feature_col = config["node_features"]["column_index"]
        self.graph_feature_name = config["graph_features"]["name"]
        self.graph_feature_dim = config["graph_features"]["dim"]
        self.graph_feature_col = config["graph_features"]["column_index"]
        self.raw_dataset_name = config["name"]
        self.data_format = config["format"]
        self.path_dictionary = config["path"]

        assert len(self.node_feature_name) == len(self.node_feature_dim)
        assert len(self.node_feature_name) == len(self.node_feature_col)
        assert len(self.graph_feature_name) == len(self.graph_feature_dim)
        assert len(self.graph_feature_name) == len(self.graph_feature_col)

        self.dist = dist
        self.comm = comm
        self.dataset_list: List[List[GraphData]] = []
        self.serial_data_name_list: List[str] = []
        self.minmax_node_feature = None
        self.minmax_graph_feature = None

    # ---- subclass hook: parse one file ---------------------------------
    def transform_input_to_data_object_base(self, filepath: str):
        raise NotImplementedError

    # ---- sequence protocol over the loaded samples ---------------------
    # (reference AbstractBaseDataset semantics: ``len(ds)`` / ``ds[i]`` /
    # iteration work on the constructed dataset,
    # ``utils/abstractbasedataset.py:6-46``). Loads lazily on first use;
    # the flat view is built once and cached.
    def _all_samples(self) -> List[GraphData]:
        flat = getattr(self, "_flat_samples", None)
        if flat is None:
            if not self.dataset_list:
                self.load_raw_data()
            flat = [d for split in self.dataset_list for d in split]
            self._flat_samples = flat
        return flat

    def __len__(self):
        return len(self._all_samples())

    def __getitem__(self, i: int) -> GraphData:
        return self._all_samples()[i]

    def __iter__(self):
        return iter(self._all_samples())

    def load_raw_data(self):
        serialized_dir = os.path.join(
            os.environ.get("SERIALIZED_DATA_PATH", os.getcwd()),
            "serialized_dataset",
        )
        os.makedirs(serialized_dir, exist_ok=True)

        for dataset_type, raw_path in self.path_dictionary.items():
            if not os.path.isabs(raw_path):
                raw_path = os.path.join(os.getcwd(), raw_path)
            if not os.path.exists(raw_path):
                raise ValueError(f"Folder not found: {raw_path}")
            filelist = sorted(os.listdir(raw_path))
            assert len(filelist) > 0, f"No data files provided in {raw_path}!"
            if self.dist:
                # shuffle deterministically then shard across hosts
                random.seed(43)
                random.shuffle(filelist)
                from hydragnn_tpu.parallel.distributed import (
                    get_comm_size_and_rank,
                    nsplit,
                )

                world, rank = get_comm_size_and_rank()
                filelist = list(nsplit(filelist, world))[rank]

            dataset = []
            for name in filelist:
                if name == ".DS_Store":
                    continue
                full = os.path.join(raw_path, name)
                if os.path.isfile(full):
                    obj = self.transform_input_to_data_object_base(full)
                    if obj is not None:
                        dataset.append(obj)
                elif os.path.isdir(full):
                    for sub in sorted(os.listdir(full)):
                        subfull = os.path.join(full, sub)
                        if os.path.isfile(subfull):
                            obj = self.transform_input_to_data_object_base(subfull)
                            if obj is not None:
                                dataset.append(obj)

            dataset = self.scale_features_by_num_nodes(dataset)
            if dataset_type == "total":
                serial_name = self.raw_dataset_name + ".pkl"
            else:
                serial_name = f"{self.raw_dataset_name}_{dataset_type}.pkl"
            self.dataset_list.append(dataset)
            self.serial_data_name_list.append(serial_name)

        self.normalize_dataset()

        for serial_name, dataset in zip(
            self.serial_data_name_list, self.dataset_list
        ):
            with open(os.path.join(serialized_dir, serial_name), "wb") as f:
                pickle.dump(self.minmax_node_feature, f)
                pickle.dump(self.minmax_graph_feature, f)
                pickle.dump(dataset, f)

    def scale_features_by_num_nodes(self, dataset):
        """Divide ``*_scaled_num_nodes`` feature blocks by node count
        (``raw_dataset_loader.py:169-192``)."""
        g_idx = [
            i
            for i, name in enumerate(self.graph_feature_name)
            if "_scaled_num_nodes" in name
        ]
        n_idx = [
            i
            for i, name in enumerate(self.node_feature_name)
            if "_scaled_num_nodes" in name
        ]
        for data in dataset:
            if data.y is not None and g_idx:
                data.y[g_idx] = data.y[g_idx] / data.num_nodes
            if data.x is not None and n_idx:
                data.x[:, n_idx] = data.x[:, n_idx] / data.num_nodes
        return dataset

    def normalize_dataset(self):
        """Global min-max over every split, then normalize each feature block
        to [0, 1] (``raw_dataset_loader.py:194-279``)."""
        num_nf = len(self.node_feature_dim)
        num_gf = len(self.graph_feature_dim)
        self.minmax_graph_feature = np.full((2, num_gf), np.inf)
        self.minmax_node_feature = np.full((2, num_nf), np.inf)
        self.minmax_graph_feature[1, :] *= -1
        self.minmax_node_feature[1, :] *= -1

        for dataset in self.dataset_list:
            for data in dataset:
                g_start = 0
                for ifeat in range(num_gf):
                    g_end = g_start + self.graph_feature_dim[ifeat]
                    block = data.y[g_start:g_end]
                    self.minmax_graph_feature[0, ifeat] = min(
                        block.min(), self.minmax_graph_feature[0, ifeat]
                    )
                    self.minmax_graph_feature[1, ifeat] = max(
                        block.max(), self.minmax_graph_feature[1, ifeat]
                    )
                    g_start = g_end
                n_start = 0
                for ifeat in range(num_nf):
                    n_end = n_start + self.node_feature_dim[ifeat]
                    block = data.x[:, n_start:n_end]
                    self.minmax_node_feature[0, ifeat] = min(
                        block.min(), self.minmax_node_feature[0, ifeat]
                    )
                    self.minmax_node_feature[1, ifeat] = max(
                        block.max(), self.minmax_node_feature[1, ifeat]
                    )
                    n_start = n_end

        if self.dist:
            from hydragnn_tpu.parallel.distributed import host_allreduce

            self.minmax_graph_feature[0] = host_allreduce(
                self.minmax_graph_feature[0], op="min"
            )
            self.minmax_graph_feature[1] = host_allreduce(
                self.minmax_graph_feature[1], op="max"
            )
            self.minmax_node_feature[0] = host_allreduce(
                self.minmax_node_feature[0], op="min"
            )
            self.minmax_node_feature[1] = host_allreduce(
                self.minmax_node_feature[1], op="max"
            )

        for dataset in self.dataset_list:
            for data in dataset:
                g_start = 0
                for ifeat in range(num_gf):
                    g_end = g_start + self.graph_feature_dim[ifeat]
                    lo = self.minmax_graph_feature[0, ifeat]
                    hi = self.minmax_graph_feature[1, ifeat]
                    data.y[g_start:g_end] = _tensor_divide(
                        data.y[g_start:g_end] - lo, hi - lo
                    )
                    g_start = g_end
                n_start = 0
                for ifeat in range(num_nf):
                    n_end = n_start + self.node_feature_dim[ifeat]
                    lo = self.minmax_node_feature[0, ifeat]
                    hi = self.minmax_node_feature[1, ifeat]
                    data.x[:, n_start:n_end] = _tensor_divide(
                        data.x[:, n_start:n_end] - lo, hi - lo
                    )
                    n_start = n_end
