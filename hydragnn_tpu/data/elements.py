"""Full periodic-table symbol <-> atomic-number mapping.

The embedded descriptor table (``utils/periodic_table.py``) carries rich
per-element data for the 62 elements the descriptor featurizer needs; raw
dataset parsers (QM9 sdf, OC20 extxyz, MPtrj JSON) only need symbol -> Z but
for *every* element (MPtrj spans H..Pu). One canonical table, no deps.
"""

_SYMBOL_LIST = [
    "H", "He", "Li", "Be", "B", "C", "N", "O", "F", "Ne",
    "Na", "Mg", "Al", "Si", "P", "S", "Cl", "Ar", "K", "Ca",
    "Sc", "Ti", "V", "Cr", "Mn", "Fe", "Co", "Ni", "Cu", "Zn",
    "Ga", "Ge", "As", "Se", "Br", "Kr", "Rb", "Sr", "Y", "Zr",
    "Nb", "Mo", "Tc", "Ru", "Rh", "Pd", "Ag", "Cd", "In", "Sn",
    "Sb", "Te", "I", "Xe", "Cs", "Ba", "La", "Ce", "Pr", "Nd",
    "Pm", "Sm", "Eu", "Gd", "Tb", "Dy", "Ho", "Er", "Tm", "Yb",
    "Lu", "Hf", "Ta", "W", "Re", "Os", "Ir", "Pt", "Au", "Hg",
    "Tl", "Pb", "Bi", "Po", "At", "Rn", "Fr", "Ra", "Ac", "Th",
    "Pa", "U", "Np", "Pu", "Am", "Cm", "Bk", "Cf", "Es", "Fm",
    "Md", "No", "Lr", "Rf", "Db", "Sg", "Bh", "Hs", "Mt", "Ds",
    "Rg", "Cn", "Nh", "Fl", "Mc", "Lv", "Ts", "Og",
]

SYMBOL_TO_Z = {s: i + 1 for i, s in enumerate(_SYMBOL_LIST)}
Z_TO_SYMBOL = {i + 1: s for i, s in enumerate(_SYMBOL_LIST)}


def atomic_number(symbol: str) -> int:
    """Symbol -> Z; tolerates case sloppiness ('FE', 'fe')."""
    s = symbol.strip()
    if s in SYMBOL_TO_Z:
        return SYMBOL_TO_Z[s]
    s = s.capitalize()
    if s in SYMBOL_TO_Z:
        return SYMBOL_TO_Z[s]
    raise KeyError(f"unknown element symbol {symbol!r}")


def symbol(z: int) -> str:
    return Z_TO_SYMBOL[int(z)]
