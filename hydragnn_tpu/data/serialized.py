"""Serialized (.pkl) dataset pipeline: edges, descriptors, targets.

Parity with ``hydragnn/preprocess/serialized_dataset_loader.py:33-241``:
load the pickled split, optionally rotate to principal axes, (re)compute the
radius graph (PBC-aware), append edge lengths, normalize them by the GLOBAL
max edge length, apply optional descriptors, extract per-head targets, select
input node-feature columns, optional stratified subsampling.
"""

import pickle
from typing import List

import numpy as np

from hydragnn_tpu.data.dataobj import GraphData
from hydragnn_tpu.data.radius_graph import radius_graph, radius_graph_pbc
from hydragnn_tpu.data.transforms import (
    add_edge_lengths,
    normalize_rotation,
    point_pair_features,
    spherical_descriptor,
)
from hydragnn_tpu.utils import faults
from hydragnn_tpu.utils.retry import retry_io


def extract_targets(
    output_type: List[str],
    output_index: List[int],
    graph_feature_dim: List[int],
    node_feature_dim: List[int],
    data: GraphData,
):
    """Per-head target extraction (analog of ``update_predicted_values``,
    ``preprocess/utils.py:237-278``): one array per head instead of packed
    y/y_loc — graph head [dim], node head [n, dim]."""
    targets = []
    for t, idx in zip(output_type, output_index):
        if t == "graph":
            start = sum(graph_feature_dim[:idx])
            dim = graph_feature_dim[idx]
            targets.append(
                np.asarray(data.y[start : start + dim], dtype=np.float32).reshape(
                    dim
                )
            )
        elif t == "node":
            start = sum(node_feature_dim[:idx])
            dim = node_feature_dim[idx]
            targets.append(
                np.asarray(
                    data.x[:, start : start + dim], dtype=np.float32
                ).reshape(data.num_nodes, dim)
            )
        else:
            raise ValueError(f"Unknown output type: {t}")
    data.targets = targets
    data.target_types = list(output_type)
    return data


def select_input_node_features(input_node_features: List[int], data: GraphData):
    """Column-select the model inputs (``update_atom_features``,
    ``preprocess/utils.py:281-292``)."""
    data.x = data.x[:, input_node_features]
    return data


class SerializedGraphLoader:
    def __init__(self, config: dict, dist: bool = False):
        ds = config["Dataset"]
        arch = config["NeuralNetwork"]["Architecture"]
        voi = config["NeuralNetwork"]["Variables_of_interest"]
        self.verbosity = config.get("Verbosity", {}).get("level", 0)
        self.node_feature_dim = ds["node_features"]["dim"]
        self.graph_feature_dim = ds["graph_features"]["dim"]
        self.rotational_invariance = ds.get("rotational_invariance", False)
        self.periodic = arch.get("periodic_boundary_conditions", False)
        self.radius = arch["radius"]
        self.max_neighbours = arch["max_neighbours"]
        self.variables = voi
        self.output_type = voi["type"]
        self.output_index = voi["output_index"]
        self.input_node_features = voi["input_node_features"]
        self.spherical_coordinates = False
        self.point_pair_features = False
        if "Descriptors" in ds:
            self.spherical_coordinates = ds["Descriptors"].get(
                "SphericalCoordinates", False
            )
            self.point_pair_features = ds["Descriptors"].get(
                "PointPairFeatures", False
            )
        self.dist = dist

    def load_serialized_data(self, dataset_path: str) -> List[GraphData]:
        def _read():
            faults.flaky_read(dataset_path)
            with open(dataset_path, "rb") as f:
                _ = pickle.load(f)  # minmax node
                _ = pickle.load(f)  # minmax graph
                return pickle.load(f)

        # one big read off a shared filesystem: transient OSError gets
        # jittered-backoff retries instead of killing the job at startup
        dataset = retry_io(_read, what=dataset_path)

        if self.rotational_invariance:
            dataset = [normalize_rotation(d) for d in dataset]

        for data in dataset:
            if self.periodic:
                edge_index, lengths = radius_graph_pbc(
                    data.pos,
                    data.supercell_size,
                    self.radius,
                    self.max_neighbours,
                )
                data.edge_index = edge_index
                data.edge_attr = lengths[:, None].astype(np.float32)
            else:
                data.edge_index = radius_graph(
                    data.pos, self.radius, self.max_neighbours
                )
                data.edge_attr = None
                add_edge_lengths(data)

        max_edge_length = 0.0
        for data in dataset:
            if data.edge_attr.size:
                max_edge_length = max(max_edge_length, float(data.edge_attr.max()))
        if self.dist:
            from hydragnn_tpu.parallel.distributed import host_allreduce

            max_edge_length = float(
                host_allreduce(np.asarray([max_edge_length]), op="max")[0]
            )
        max_edge_length = max(max_edge_length, 1e-12)
        for data in dataset:
            data.edge_attr = data.edge_attr / max_edge_length

        if self.spherical_coordinates:
            dataset = [spherical_descriptor(d) for d in dataset]
        if self.point_pair_features:
            dataset = [point_pair_features(d) for d in dataset]

        for data in dataset:
            extract_targets(
                self.output_type,
                self.output_index,
                self.graph_feature_dim,
                self.node_feature_dim,
                data,
            )
            select_input_node_features(self.input_node_features, data)

        if "subsample_percentage" in self.variables:
            from hydragnn_tpu.data.split import stratified_subsample

            return stratified_subsample(
                dataset, self.variables["subsample_percentage"]
            )
        return dataset
