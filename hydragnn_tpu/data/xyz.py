"""XYZ raw loader (parity with ``hydragnn/utils/xyzdataset.py:12``): standard
xyz files — atom count, comment (optionally carrying graph targets), then
``symbol x y z [extra...]`` rows."""

import numpy as np

from hydragnn_tpu.data.dataobj import GraphData
from hydragnn_tpu.data.raw import AbstractRawDataset
from hydragnn_tpu.data.cfg import _SYMBOLS


class XYZDataset(AbstractRawDataset):
    def transform_input_to_data_object_base(self, filepath: str):
        if not filepath.endswith(".xyz"):
            return None
        with open(filepath, "r", encoding="utf-8") as f:
            lines = f.readlines()
        natoms = int(lines[0].split()[0])
        comment = lines[1].split()
        g_feature = []
        for item in range(len(self.graph_feature_dim)):
            for icomp in range(self.graph_feature_dim[item]):
                col = self.graph_feature_col[item] + icomp
                g_feature.append(float(comment[col]) if col < len(comment) else 0.0)
        pos = []
        feats = []
        for ln in lines[2 : 2 + natoms]:
            fields = ln.split()
            z = _SYMBOLS.get(fields[0], 0) if not _is_num(fields[0]) else float(
                fields[0]
            )
            pos.append([float(fields[1]), float(fields[2]), float(fields[3])])
            row_all = [float(z)] + [float(v) for v in fields[1:]]
            row = []
            for item in range(len(self.node_feature_dim)):
                for icomp in range(self.node_feature_dim[item]):
                    col = self.node_feature_col[item] + icomp
                    row.append(row_all[col] if col < len(row_all) else 0.0)
            feats.append(row)
        return GraphData(
            x=np.asarray(feats, dtype=np.float32),
            pos=np.asarray(pos, dtype=np.float32),
            y=np.asarray(g_feature, dtype=np.float32),
        )


def _is_num(s):
    try:
        float(s)
        return True
    except ValueError:
        return False
