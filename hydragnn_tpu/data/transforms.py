"""Host-side geometric transforms.

numpy equivalents of the torch_geometric transforms the reference applies in
its serialized pipeline (``preprocess/serialized_dataset_loader.py:123-171``):
Distance (norm=False, cat=True), NormalizeRotation, Spherical,
PointPairFeatures.
"""

import numpy as np

from hydragnn_tpu.data.dataobj import GraphData


def add_edge_lengths(data: GraphData) -> GraphData:
    """Distance(norm=False, cat=True): append ||pos_j - pos_i|| to edge_attr."""
    src, dst = data.edge_index[0], data.edge_index[1]
    d = np.linalg.norm(data.pos[src] - data.pos[dst], axis=1).astype(np.float32)
    d = d[:, None]
    if data.edge_attr is None:
        data.edge_attr = d
    else:
        data.edge_attr = np.concatenate([data.edge_attr, d], axis=1)
    return data


def normalize_rotation(data: GraphData) -> GraphData:
    """Rotate positions onto their principal components (NormalizeRotation).

    Used for the ``rotational_invariance`` dataset flag
    (``serialized_dataset_loader.py:123-125``).
    """
    pos = data.pos - data.pos.mean(axis=0, keepdims=True)
    _, _, vt = np.linalg.svd(pos, full_matrices=False)
    # sign convention: make the largest-magnitude component of each axis
    # positive so the rotation is deterministic
    signs = np.sign(vt[np.arange(vt.shape[0]), np.abs(vt).argmax(axis=1)])
    signs[signs == 0] = 1.0
    vt = vt * signs[:, None]
    data.pos = (pos @ vt.T).astype(np.float32)
    return data


def spherical_descriptor(data: GraphData) -> GraphData:
    """Append (rho, theta, phi) of each edge vector, normalized like PyG's
    Spherical transform (rho by max, angles to [0, 1])."""
    src, dst = data.edge_index[0], data.edge_index[1]
    cart = data.pos[dst] - data.pos[src]
    rho = np.linalg.norm(cart, axis=1)
    rho_max = max(float(rho.max()), 1e-12) if rho.size else 1.0
    theta = np.arctan2(cart[:, 1], cart[:, 0]) / (2 * np.pi)
    theta = theta + (theta < 0)
    safe_rho = np.maximum(rho, 1e-12)
    phi = np.arccos(np.clip(cart[:, 2] / safe_rho, -1.0, 1.0)) / np.pi
    sph = np.stack([rho / rho_max, theta, phi], axis=1).astype(np.float32)
    if data.edge_attr is None:
        data.edge_attr = sph
    else:
        data.edge_attr = np.concatenate([data.edge_attr, sph], axis=1)
    return data


def point_pair_features(data: GraphData) -> GraphData:
    """PPF descriptor per edge: (||d||, angle(n_i, d), angle(n_j, d),
    angle(n_i, n_j)); requires ``data.extras['normal']``."""
    normal = data.extras.get("normal")
    if normal is None:
        raise ValueError("PointPairFeatures requires node normals")
    src, dst = data.edge_index[0], data.edge_index[1]
    d = data.pos[dst] - data.pos[src]

    def angle(a, b):
        cross = np.linalg.norm(np.cross(a, b), axis=1)
        dot = (a * b).sum(axis=1)
        return np.arctan2(cross, dot)

    feats = np.stack(
        [
            np.linalg.norm(d, axis=1),
            angle(normal[src], d),
            angle(normal[dst], d),
            angle(normal[src], normal[dst]),
        ],
        axis=1,
    ).astype(np.float32)
    if data.edge_attr is None:
        data.edge_attr = feats
    else:
        data.edge_attr = np.concatenate([data.edge_attr, feats], axis=1)
    return data
