from hydragnn_tpu.data.dataobj import GraphData
from hydragnn_tpu.data.radius_graph import radius_graph, radius_graph_pbc
from hydragnn_tpu.data.loaders import (
    BatchLayout,
    BucketedLayout,
    ConcatDataset,
    GraphLoader,
    compute_layout,
    create_dataloaders,
    dataset_loading_and_splitting,
    padding_efficiency,
    total_to_train_val_test_pkls,
    transform_raw_data_to_serialized,
)
from hydragnn_tpu.data.serialized import (
    SerializedGraphLoader,
    extract_targets,
    select_input_node_features,
)
from hydragnn_tpu.data.split import (
    compositional_stratified_splitting,
    split_dataset,
    stratified_subsample,
)
from hydragnn_tpu.data.raw import AbstractRawDataset
from hydragnn_tpu.data.elements import SYMBOL_TO_Z, Z_TO_SYMBOL, atomic_number
from hydragnn_tpu.data.qm9_raw import QM9RawDataset, write_qm9_sdf
from hydragnn_tpu.data.extxyz import (
    frame_to_graph,
    iter_extxyz,
    load_extxyz_dir,
    read_extxyz,
    write_extxyz,
)
from hydragnn_tpu.data.mptrj import load_mptrj, write_mptrj_json
from hydragnn_tpu.data.pickledataset import (
    SimplePickleDataset,
    SimplePickleWriter,
)
from hydragnn_tpu.data.lsms import LSMSDataset
from hydragnn_tpu.data.cfg import CFGDataset
from hydragnn_tpu.data.xyz import XYZDataset
