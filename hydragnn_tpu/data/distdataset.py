"""DistDataset — dataset spread across host RAM with remote fetch.

Parity with the reference's DDStore-backed ``DistDataset``
(``hydragnn/utils/distdataset.py:22-183``): each process contributes its
local shard of samples; the store presents the global index space and
``get(i)`` transparently fetches from the owning process (C++ TCP transport,
``native/diststore.cpp``) inside epoch_begin/epoch_end windows — the same
double-buffered usage the reference drives in its hot loop
(``train/train_validate_test.py:459-536``).
"""

import ctypes
from typing import Dict, List, Optional, Tuple

import numpy as np

from hydragnn_tpu.data.dataobj import GraphData
from hydragnn_tpu.native.build import load_library

_lib = None


def _load():
    global _lib
    if _lib is not None:
        return _lib
    lib = load_library("diststore", ["diststore.cpp"])
    lib.dds_create.restype = ctypes.c_void_p
    lib.dds_create.argtypes = [ctypes.c_int, ctypes.c_int, ctypes.c_char_p]
    lib.dds_set_partition.restype = ctypes.c_int
    lib.dds_set_partition.argtypes = [
        ctypes.c_void_p,
        ctypes.POINTER(ctypes.c_int64),
    ]
    lib.dds_add_var.restype = ctypes.c_int
    lib.dds_add_var.argtypes = [
        ctypes.c_void_p,
        ctypes.c_char_p,
        ctypes.c_uint64,
        ctypes.POINTER(ctypes.c_int64),
        ctypes.c_void_p,
        ctypes.c_uint64,
    ]
    lib.dds_epoch_begin.restype = ctypes.c_int
    lib.dds_epoch_begin.argtypes = [ctypes.c_void_p]
    lib.dds_epoch_end.restype = ctypes.c_int
    lib.dds_epoch_end.argtypes = [ctypes.c_void_p]
    lib.dds_get.restype = ctypes.c_int64
    lib.dds_get.argtypes = [
        ctypes.c_void_p,
        ctypes.c_uint32,
        ctypes.c_uint64,
        ctypes.c_void_p,
        ctypes.c_uint64,
        ctypes.POINTER(ctypes.c_uint64),
    ]
    lib.dds_total_samples.restype = ctypes.c_int64
    lib.dds_total_samples.argtypes = [ctypes.c_void_p]
    lib.dds_local_max_bytes.restype = ctypes.c_uint64
    lib.dds_local_max_bytes.argtypes = [ctypes.c_void_p, ctypes.c_uint32]
    lib.dds_destroy.argtypes = [ctypes.c_void_p]
    _lib = lib
    return lib


# Each store instance needs its own port block (one port per rank). The
# counter is deterministic, so SPMD processes creating stores in the same
# order (train/val/test) agree on every instance's ports.
_PORT_BLOCKS = iter(range(10_000))


def subgroup_of(rank: int, world: int, width: Optional[int]):
    """(group, group_rank, group_size, group_start) for a ``ddstore_width``
    style split: consecutive blocks of ``width`` ranks form replication
    subgroups (reference: ``hydragnn/utils/distdataset.py:43-46`` splits the
    MPI world by ``rank // ddstore_width``). The trailing group may be
    smaller when ``world % width != 0``."""
    if width is None or width <= 0 or width >= world:
        return 0, rank, world, 0
    group = rank // width
    start = group * width
    return group, rank - start, min(width, world - start), start


def subgroup_local_indices(
    n_total: int, rank: int, world: int, width: Optional[int] = None
) -> range:
    """Global sample indices THIS rank loads so every subgroup of ``width``
    ranks collectively holds the FULL dataset (samples replicate across
    subgroups; each subgroup partitions them contiguously). With no width
    this is the plain contiguous world partition."""
    _, grank, gsize, _ = subgroup_of(rank, world, width)
    base, rem = divmod(n_total, gsize)
    start = grank * base + min(grank, rem)
    return range(start, start + base + (1 if grank < rem else 0))


class DistSampleStore:
    """Low-level variable-oriented store (pyddstore.PyDDStore parity).

    ``subgroup_width`` is the ``ddstore_width`` analog: the world splits
    into consecutive blocks of that many ranks, each block serving a full
    replica of the dataset partitioned among its members, so every get()
    resolves within the caller's block (node-local at pod scale). The C++
    core is simply instantiated with the subgroup as its world — ranks
    outside the block are not even in its address list, making
    cross-subgroup traffic impossible by construction."""

    def __init__(
        self,
        rank: int,
        world: int,
        addresses: Optional[List[str]] = None,
        base_port: Optional[int] = None,
        subgroup_width: Optional[int] = None,
    ):
        self._lib = _load()
        self.global_rank = rank
        self.global_world = world
        if base_port is None:
            base_port = 23450 + next(_PORT_BLOCKS) * world
        if addresses is None:
            addresses = [f"127.0.0.1:{base_port + r}" for r in range(world)]
        if len(addresses) != world:
            raise ValueError(
                f"need {world} addresses (one per GLOBAL rank), got "
                f"{len(addresses)}"
            )
        group, grank, gsize, gstart = subgroup_of(rank, world, subgroup_width)
        self.group_index = group
        self.group_start = gstart
        addresses = addresses[gstart : gstart + gsize]
        rank, world = grank, gsize
        self.rank = rank
        self.world = world
        self._h = self._lib.dds_create(
            rank, world, ",".join(addresses).encode()
        )
        if not self._h:
            raise RuntimeError("dds_create failed (bad address list?)")
        self._vars: Dict[str, Tuple[int, np.dtype, Tuple[int, ...], int]] = {}
        self._partitioned = False

    def set_partition(self, samples_per_rank: List[int]):
        arr = (ctypes.c_int64 * self.world)(*samples_per_rank)
        self._lib.dds_set_partition(self._h, arr)
        self._partitioned = True

    def add(
        self,
        name: str,
        data: np.ndarray,
        counts: np.ndarray,
        max_row_count: Optional[int] = None,
    ):
        """Add the LOCAL partition of variable ``name``: ``data`` is the
        concatenation along dim 0, ``counts[i]`` the per-local-sample extent.
        ``max_row_count`` must be the GLOBAL max (host-allgathered by the
        caller when world > 1); defaults to the local max."""
        assert self._partitioned, "call set_partition first"
        data = np.ascontiguousarray(data)
        counts = np.ascontiguousarray(counts, dtype=np.int64)
        row_bytes = data.dtype.itemsize * int(
            np.prod(data.shape[1:], dtype=np.int64)
        )
        vid = self._lib.dds_add_var(
            self._h,
            name.encode(),
            row_bytes,
            counts.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            data.ctypes.data_as(ctypes.c_void_p),
            data.nbytes,
        )
        if vid < 0:
            raise ValueError(f"dds_add_var({name}) failed: {vid}")
        gmax = int(max_row_count if max_row_count is not None
                   else (counts.max() if counts.size else 0))
        self._vars[name] = (vid, data.dtype, tuple(data.shape[1:]), gmax)

    def epoch_begin(self):
        rc = self._lib.dds_epoch_begin(self._h)
        if rc != 0:
            raise RuntimeError(f"dds_epoch_begin failed: {rc}")

    def epoch_end(self):
        self._lib.dds_epoch_end(self._h)

    def get(self, name: str, gidx: int) -> np.ndarray:
        vid, dtype, trailing, gmax = self._vars[name]
        row_bytes = dtype.itemsize * int(np.prod(trailing, dtype=np.int64))
        cap = max(1, gmax * row_bytes)
        out = np.empty(cap, dtype=np.uint8)
        nbytes = ctypes.c_uint64()
        rows = self._lib.dds_get(
            self._h,
            vid,
            gidx,
            out.ctypes.data_as(ctypes.c_void_p),
            cap,
            ctypes.byref(nbytes),
        )
        if rows < 0:
            raise RuntimeError(f"dds_get({name}, {gidx}) failed: {rows}")
        return (
            out[: nbytes.value]
            .view(dtype)
            .reshape((int(rows),) + trailing)
            .copy()
        )

    def __len__(self) -> int:
        return int(self._lib.dds_total_samples(self._h))

    def close(self):
        if self._h:
            self._lib.dds_destroy(self._h)
            self._h = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


def _gather_partition(local_count: int, world: int) -> List[int]:
    """All-processes sample counts. Multi-host: host-side allgather via
    jax multihost utils; single process: trivial."""
    if world == 1:
        return [local_count]
    from hydragnn_tpu.parallel.distributed import host_allgather_int

    return host_allgather_int(local_count)


def _multiprocess() -> bool:
    import jax

    try:
        return jax.process_count() > 1
    except Exception:
        return False


def _reduce_max(value: int) -> int:
    if not _multiprocess():
        return int(value)
    from hydragnn_tpu.parallel.distributed import host_allreduce

    return int(host_allreduce(np.asarray([value], np.int64), "max")[0])


def _resolve_schema(ss: List[GraphData]) -> Dict[str, object]:
    """Globally-consistent variable schema so every process registers the
    SAME var-id sequence (the wire protocol ships ordinal ids). A process
    with zero local samples adopts the schema the others agree on; presence
    flags are AND-reduced across processes, dims/num_heads MAX-reduced."""
    n = len(ss)
    num_heads_local = len(ss[0].targets) if n else 0
    slots = _reduce_max(num_heads_local)
    local = np.zeros(8 + 2 * max(slots, 1), np.int64)
    if n:
        local[0] = int(all(s.pos is not None for s in ss))
        local[1] = int(all(s.edge_attr is not None for s in ss))
        local[2] = int(all(s.y is not None for s in ss))
        local[3] = len(ss[0].targets)
        local[4] = ss[0].x.shape[1]
        local[5] = (
            ss[0].edge_attr.shape[1] if ss[0].edge_attr is not None else 0
        )
        local[6] = np.ravel(ss[0].y).shape[0] if ss[0].y is not None else 0
        for ih in range(num_heads_local):
            local[8 + 2 * ih] = int(ss[0].target_types[ih] == "node")
            local[8 + 2 * ih + 1] = int(
                np.atleast_2d(ss[0].targets[ih]).shape[-1]
            )
    else:
        local[0] = local[1] = local[2] = 1  # neutral for the AND-reduce
    if _multiprocess():
        from hydragnn_tpu.parallel.distributed import host_allreduce

        flags = host_allreduce(local[:3], "min")
        rest = host_allreduce(local[3:], "max")
        local = np.concatenate([flags, rest])
    elif n == 0:
        local[:3] = 0  # nothing to serve, nothing to agree with
    return {
        "has_pos": bool(local[0]),
        "has_edge_attr": bool(local[1]),
        "has_y": bool(local[2]),
        "num_heads": int(local[3]),
        "x_dim": max(int(local[4]), 1),
        "edge_dim": max(int(local[5]), 1),
        "y_dim": max(int(local[6]), 1),
        "target_types": [
            "node" if local[8 + 2 * ih] else "graph"
            for ih in range(int(local[3]))
        ],
        "target_dims": [
            max(int(local[8 + 2 * ih + 1]), 1)
            for ih in range(int(local[3]))
        ],
    }


class DistDataset:
    """GraphData-level distributed dataset over ``DistSampleStore``.

    Each process passes its LOCAL samples; ``len()`` is global and
    ``get(i)`` works for any global index during an epoch window.
    """

    FIELDS = ("x", "pos", "edge_index", "edge_attr")

    def __init__(
        self,
        local_samples: List[GraphData],
        rank: int = 0,
        world: int = 1,
        addresses: Optional[List[str]] = None,
        samples_per_rank: Optional[List[int]] = None,
        base_port: Optional[int] = None,
        max_counts: Optional[Dict[str, int]] = None,
        subgroup_width: Optional[int] = None,
    ):
        """``subgroup_width``: replicate the dataset across blocks of that
        many ranks (``ddstore_width`` analog) — pass ``local_samples``
        sharded by :func:`subgroup_local_indices` so each block holds a
        full replica; ``samples_per_rank`` / the gathered partition then
        describe the caller's OWN subgroup."""
        self.store = DistSampleStore(
            rank, world, addresses, base_port, subgroup_width=subgroup_width
        )
        if samples_per_rank is None:
            per_global_rank = _gather_partition(len(local_samples), world)
            g0 = self.store.group_start
            samples_per_rank = per_global_rank[g0 : g0 + self.store.world]
        elif len(samples_per_rank) != self.store.world:
            raise ValueError(
                f"samples_per_rank must cover the subgroup "
                f"({self.store.world} ranks), got {len(samples_per_rank)}"
            )
        self.store.set_partition(samples_per_rank)
        ss = local_samples
        n = len(ss)
        max_counts = max_counts or {}
        schema = _resolve_schema(ss)
        nodes = np.array([s.num_nodes for s in ss], dtype=np.int64)
        edges = np.array([s.num_edges for s in ss], dtype=np.int64)
        ones = np.ones(n, dtype=np.int64)
        # receive buffers must cover the GLOBAL max sample size — reduce the
        # local maxima across processes unless the caller supplied them
        max_nodes = max_counts.get(
            "nodes", _reduce_max(int(nodes.max()) if n else 0)
        )
        max_edges = max_counts.get(
            "edges", _reduce_max(int(edges.max()) if n else 0)
        )

        def _cat(getter, dtype, cols):
            if not n:
                return np.zeros((0, cols), dtype)
            return np.concatenate([getter(s) for s in ss]).astype(dtype)

        self.store.add(
            "x", _cat(lambda s: s.x, np.float32, schema["x_dim"]),
            nodes, max_nodes,
        )
        self._has = {"x": True}
        self._has["pos"] = schema["has_pos"]
        if self._has["pos"]:
            self.store.add(
                "pos", _cat(lambda s: s.pos, np.float32, 3), nodes, max_nodes
            )
        self.store.add(
            "edge_index",
            _cat(lambda s: s.edge_index.T, np.int64, 2),
            edges,
            max_edges,
        )
        self._has["edge_attr"] = schema["has_edge_attr"]
        if self._has["edge_attr"]:
            self.store.add(
                "edge_attr",
                _cat(lambda s: s.edge_attr, np.float32, schema["edge_dim"]),
                edges,
                max_edges,
            )
        self._has["y"] = schema["has_y"]
        if self._has["y"]:
            self.store.add(
                "y",
                np.stack([np.ravel(s.y) for s in ss]).astype(np.float32)
                if n
                else np.zeros((0, schema["y_dim"]), np.float32),
                ones,
                1,
            )
        self.num_heads = schema["num_heads"]
        self.target_types = list(schema["target_types"])
        for ih in range(self.num_heads):
            dim = schema["target_dims"][ih]
            if self.target_types[ih] == "graph":
                self.store.add(
                    f"target{ih}",
                    np.stack([np.ravel(s.targets[ih]) for s in ss]).astype(
                        np.float32
                    )
                    if n
                    else np.zeros((0, dim), np.float32),
                    ones,
                    1,
                )
            else:
                self.store.add(
                    f"target{ih}",
                    np.concatenate(
                        [
                            np.asarray(s.targets[ih], np.float32).reshape(
                                s.num_nodes, -1
                            )
                            for s in ss
                        ]
                    )
                    if n
                    else np.zeros((0, dim), np.float32),
                    nodes,
                    max_nodes,
                )

        self._local_graph_sizes = nodes

    def graph_sizes(self) -> np.ndarray:
        """LOCAL per-sample node counts, index-only (no store traffic).

        Size statistics over a DistDataset must come from here — walking
        global indices would pull the whole dataset over the store
        transport and require an open epoch window. The method's presence
        also marks the dataset as store-backed for config derivation's
        cheap/expensive-scan gates (``utils/config.py``)."""
        return self._local_graph_sizes

    def epoch_begin(self):
        self.store.epoch_begin()

    def epoch_end(self):
        self.store.epoch_end()

    def __len__(self) -> int:
        return len(self.store)

    def get(self, idx: int) -> GraphData:
        n = len(self)
        if idx < 0:
            idx += n
        if not 0 <= idx < n:
            # IndexError (not RuntimeError) so sequence-protocol iteration
            # terminates like any list-ish dataset
            raise IndexError(idx)
        d = GraphData()
        d.x = self.store.get("x", idx)
        if self._has["pos"]:
            d.pos = self.store.get("pos", idx)
        d.edge_index = self.store.get("edge_index", idx).T
        if self._has["edge_attr"]:
            d.edge_attr = self.store.get("edge_attr", idx)
        if self._has["y"]:
            d.y = self.store.get("y", idx).ravel()
        for ih in range(self.num_heads):
            t = self.store.get(f"target{ih}", idx)
            if self.target_types[ih] == "graph":
                t = t.ravel()
            d.targets.append(t)
        d.target_types = list(self.target_types)
        return d

    def __getitem__(self, idx: int) -> GraphData:
        return self.get(idx)

    def close(self):
        self.store.close()
