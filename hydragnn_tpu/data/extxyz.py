"""Extended-XYZ reader/writer without ase.

The OC20 raw S2EF/IS2RE distribution ships periodic structures as
``.extxyz`` frames (plus ``.txt`` sidecars with system metadata); the
reference ingests them through ``ase.io`` + ``AtomsToGraphs``
(``/root/reference/examples/open_catalyst_2020/utils/atoms_to_graphs.py:26``).
This module is the ase-free equivalent, in the same spirit as the in-repo
CFG parser: a comment-line grammar of ``key=value`` pairs (values may be
quoted), a ``Properties=name:type:ncols:...`` column spec for the per-atom
table, and ``Lattice="ax ay az bx ... cz"`` row-major cell vectors.

``frame_to_graph`` then plays the role of ``AtomsToGraphs.convert``:
radius graph (PBC-aware when the frame has a lattice), energy (optionally
per atom), forces, edge lengths as edge_attr.
"""

import os
import re
from typing import Dict, Iterator, List, Optional

import numpy as np

from hydragnn_tpu.data.dataobj import GraphData
from hydragnn_tpu.data.elements import atomic_number, symbol
from hydragnn_tpu.data.radius_graph import radius_graph, radius_graph_pbc

_TOKEN = re.compile(
    r"""([A-Za-z_][A-Za-z0-9_:-]*)         # key
        \s*=\s*
        ("[^"]*"|'[^']*'|\S+)              # quoted or bare value
    """,
    re.VERBOSE,
)

_TYPE = {"S": str, "R": float, "I": int, "L": lambda s: s in ("T", "True", "1")}


def _parse_comment(line: str) -> Dict[str, object]:
    out = {}
    for key, raw in _TOKEN.findall(line):
        v = raw.strip()
        if v and v[0] in "\"'":
            v = v[1:-1]
        out[key] = v
    return out


def _parse_properties(spec: str):
    """``species:S:1:pos:R:3:forces:R:3`` -> [(name, caster, ncols), ...]"""
    fields = spec.split(":")
    cols = []
    for i in range(0, len(fields), 3):
        name, typ, n = fields[i], fields[i + 1], int(fields[i + 2])
        cols.append((name, _TYPE[typ], n))
    return cols


def iter_extxyz(path: str) -> Iterator[dict]:
    """Yield frames as dicts:
    ``symbols`` [n], ``z`` [n], ``pos`` [n,3], ``cell`` [3,3] or None,
    ``pbc`` [3] bool, ``info`` (remaining comment keys, floats where they
    parse), ``arrays`` (extra per-atom columns, e.g. forces)."""
    with open(path) as f:
        iframe = 0
        while True:
            header = f.readline()
            if not header:
                return
            if not header.strip():
                continue
            try:
                yield _parse_frame(f, header)
            except Exception as e:
                raise ValueError(
                    f"{path}: malformed extxyz frame {iframe}: {e}"
                ) from e
            iframe += 1


def _parse_frame(f, header: str) -> dict:
    natoms = int(header.split()[0])
    comment = f.readline()
    kv = _parse_comment(comment)
    spec = kv.pop("Properties", "species:S:1:pos:R:3")
    columns = _parse_properties(str(spec))
    ncols_expected = sum(n for _, _, n in columns)
    cell = None
    if "Lattice" in kv:
        cell = np.fromstring(str(kv.pop("Lattice")), sep=" ").reshape(3, 3)
    pbc = np.array([False] * 3)
    if "pbc" in kv:
        pbc = np.array(
            [t in ("T", "True", "1") for t in str(kv.pop("pbc")).split()]
        )
    elif cell is not None:
        pbc = np.array([True] * 3)
    info = {}
    for k, v in kv.items():
        try:
            info[k] = float(v)  # type: ignore[arg-type]
        except (TypeError, ValueError):
            info[k] = v
    data: Dict[str, list] = {name: [] for name, _, _ in columns}
    for iatom in range(natoms):
        line = f.readline()
        if not line:
            raise ValueError(
                f"file ends inside atom table (atom {iatom} of {natoms})"
            )
        fields = line.split()
        if len(fields) < ncols_expected:
            raise ValueError(
                f"atom {iatom}: {len(fields)} columns, Properties spec "
                f"needs {ncols_expected}"
            )
        at = 0
        for name, caster, n in columns:
            data[name].append([caster(x) for x in fields[at : at + n]])
            at += n
    symbols = [row[0] for row in data.pop("species")]
    pos = np.asarray(data.pop("pos"), dtype=np.float64)
    # numeric columns (R/I/L) become float arrays; string-typed extras (any
    # Properties ...:S:n besides species) stay as object arrays instead of
    # crashing a legitimate file on float64 coercion
    numeric = {name for name, caster, _ in columns if caster is not str}
    arrays = {}
    for k, v in data.items():
        if k in ("species", "pos"):
            continue
        if k in numeric:
            a = np.asarray(v, dtype=np.float64)
            arrays[k] = a.squeeze(-1) if a.shape[-1] == 1 else a
        else:
            a = np.asarray(v, dtype=object)
            arrays[k] = a.squeeze(-1) if a.shape[-1] == 1 else a
    return {
        "symbols": symbols,
        "z": np.asarray([atomic_number(s) for s in symbols], np.int64),
        "pos": pos,
        "cell": cell,
        "pbc": pbc,
        "info": info,
        "arrays": arrays,
    }


def read_extxyz(path: str) -> List[dict]:
    return list(iter_extxyz(path))


def write_extxyz(path: str, frames, append: bool = False):
    """Write frames (dicts shaped like :func:`iter_extxyz` yields, with
    ``z`` or ``symbols``; optional ``cell``, ``info``, ``arrays``)."""
    mode = "a" if append else "w"
    with open(path, mode) as f:
        for fr in frames:
            syms = fr.get("symbols") or [symbol(int(zz)) for zz in fr["z"]]
            pos = np.asarray(fr["pos"], dtype=np.float64)
            n = len(syms)
            parts = []
            if fr.get("cell") is not None:
                cell = np.asarray(fr["cell"], dtype=np.float64).reshape(3, 3)
                parts.append(
                    'Lattice="' + " ".join(f"{v:.8f}" for v in cell.ravel()) + '"'
                )
                pbc = fr.get("pbc")
                flags = (
                    "T T T"
                    if pbc is None
                    else " ".join("T" if b else "F" for b in np.asarray(pbc))
                )
                parts.append(f'pbc="{flags}"')
            props = "species:S:1:pos:R:3"
            arrays = dict(fr.get("arrays", {}))
            col_type = {}
            for k, v in arrays.items():
                v = np.asarray(v)
                ncols = 1 if v.ndim == 1 else v.shape[1]
                if v.dtype == bool:
                    col_type[k] = "L"  # extxyz logical encoding (T/F)
                elif np.issubdtype(v.dtype, np.number):
                    col_type[k] = "R"
                else:
                    col_type[k] = "S"
                props += f":{k}:{col_type[k]}:{ncols}"
            parts.insert(0, f"Properties={props}")
            for k, v in fr.get("info", {}).items():
                s = str(v)
                if any(c.isspace() for c in s):
                    s = f'"{s}"'  # quote so the round-trip survives
                parts.append(f"{k}={s}")
            f.write(f"{n}\n{' '.join(parts)}\n")
            for i in range(n):
                row = f"{syms[i]:<3s} " + " ".join(f"{c:.8f}" for c in pos[i])
                for k, v in arrays.items():
                    v = np.asarray(v)
                    vals = np.atleast_1d(v[i] if v.ndim > 1 else [v[i]])
                    t = col_type[k]
                    if t == "L":
                        row += " " + " ".join("T" if c else "F" for c in vals)
                    elif t == "S":
                        row += " " + " ".join(str(c) for c in vals)
                    else:
                        row += " " + " ".join(f"{float(c):.8f}" for c in vals)
                f.write(row + "\n")


def frame_to_graph(
    frame: dict,
    radius: float = 6.0,
    max_neighbours: int = 50,
    energy_per_atom: bool = True,
    energy_key: str = "energy",
    forces_key: str = "forces",
) -> GraphData:
    """AtomsToGraphs.convert analog: one extxyz frame -> GraphData with
    graph-level (per-atom) energy target and node-level forces target;
    edge_attr = interatomic distance (the reference's ``Distance``
    transform, norm=False)."""
    z = frame["z"].astype(np.float32).reshape(-1, 1)
    pos = frame["pos"].astype(np.float32)
    if frame.get("cell") is not None and bool(np.any(frame["pbc"])):
        # per-axis pbc mask: a slab (pbc="T T F") must not form edges
        # through the vacuum axis
        edge_index, lengths = radius_graph_pbc(
            pos.astype(np.float64), frame["cell"], radius, max_neighbours,
            pbc=frame["pbc"],
        )
    else:
        edge_index = radius_graph(pos, radius, max_neighbours)
        lengths = np.linalg.norm(
            pos[edge_index[0]] - pos[edge_index[1]], axis=1
        )
    d = GraphData(
        x=z,
        pos=pos,
        supercell_size=None
        if frame.get("cell") is None
        else np.asarray(frame["cell"], np.float32),
    )
    d.edge_index = edge_index
    d.edge_attr = np.asarray(lengths, np.float32).reshape(-1, 1)
    if energy_key not in frame["info"]:
        raise KeyError(
            f"frame has no {energy_key!r} in its comment line "
            f"(keys: {sorted(frame['info'])}); pass energy_key= to name "
            "the right one — refusing to train on silent zero labels"
        )
    energy = float(frame["info"][energy_key])
    if energy_per_atom:
        energy /= max(len(z), 1)
    d.targets = [np.asarray([energy], np.float32)]
    d.target_types = ["graph"]
    if forces_key in frame["arrays"]:
        d.targets.append(np.asarray(frame["arrays"][forces_key], np.float32))
        d.target_types.append("node")
    return d


def load_extxyz_dir(
    dirpath: Optional[str] = None,
    radius: float = 6.0,
    max_neighbours: int = 50,
    energy_per_atom: bool = True,
    forces_norm_threshold: Optional[float] = 100.0,
    num_samples: Optional[int] = None,
    files: Optional[List[str]] = None,
) -> List[GraphData]:
    """Extxyz frames -> graphs, dropping frames whose max force norm
    exceeds the threshold (the reference's ``forces_norm_threshold =
    100.0`` eV/A sanity filter, ``open_catalyst_2020/train.py:60``).

    Source is either every ``*.extxyz``/``*.xyz`` under ``dirpath`` or an
    explicit ``files`` list (the parallel-preprocessing case: each rank
    passes its nsplit share)."""
    if files is None:
        if dirpath is None:
            raise ValueError("need dirpath or files")
        files = [
            os.path.join(dirpath, fn)
            for fn in sorted(os.listdir(dirpath))
            if fn.endswith(".extxyz") or fn.endswith(".xyz")
        ]
    out: List[GraphData] = []
    for path in files:
        for frame in iter_extxyz(path):
            if forces_norm_threshold is not None and "forces" in frame["arrays"]:
                norms = np.linalg.norm(frame["arrays"]["forces"], axis=1)
                if norms.size and norms.max() > forces_norm_threshold:
                    continue
            out.append(
                frame_to_graph(
                    frame, radius, max_neighbours, energy_per_atom
                )
            )
            if num_samples is not None and len(out) >= num_samples:
                return out
    return out
