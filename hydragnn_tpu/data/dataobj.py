"""GraphData — the host-side (numpy) sample container.

Plays the role of PyG's ``Data`` in the reference pipeline, but targets are
kept as one array per head (``targets`` + ``target_types``) instead of the
packed ``y``/``y_loc`` layout (``hydragnn/preprocess/utils.py:237-278``) — see
``hydragnn_tpu/graph/batch.py`` for why.
"""

from typing import List, Optional

import numpy as np


class GraphData:
    def __init__(
        self,
        x: Optional[np.ndarray] = None,
        pos: Optional[np.ndarray] = None,
        y: Optional[np.ndarray] = None,
        edge_index: Optional[np.ndarray] = None,
        edge_attr: Optional[np.ndarray] = None,
        supercell_size: Optional[np.ndarray] = None,
    ):
        self.x = x
        self.pos = pos
        self.y = y  # packed graph-level features (pre target extraction)
        self.edge_index = edge_index
        self.edge_attr = edge_attr
        self.supercell_size = supercell_size
        self.targets: List[np.ndarray] = []
        self.target_types: List[str] = []
        self.extras = {}

    @property
    def num_nodes(self) -> int:
        return 0 if self.x is None else int(self.x.shape[0])

    @property
    def num_edges(self) -> int:
        return 0 if self.edge_index is None else int(self.edge_index.shape[1])

    def clone(self) -> "GraphData":
        g = GraphData(
            x=None if self.x is None else self.x.copy(),
            pos=None if self.pos is None else self.pos.copy(),
            y=None if self.y is None else self.y.copy(),
            edge_index=None
            if self.edge_index is None
            else self.edge_index.copy(),
            edge_attr=None if self.edge_attr is None else self.edge_attr.copy(),
            supercell_size=None
            if self.supercell_size is None
            else np.asarray(self.supercell_size).copy(),
        )
        g.targets = [t.copy() for t in self.targets]
        g.target_types = list(self.target_types)
        g.extras = dict(self.extras)
        return g

    def __repr__(self):
        return (
            f"GraphData(num_nodes={self.num_nodes}, num_edges={self.num_edges},"
            f" heads={len(self.targets)})"
        )
