"""Batch loaders: samples -> statically-shaped padded GraphBatch streams.

Replaces PyG's DataLoader + DistributedSampler (``preprocess/load_data.py:
207-297``) with a numpy collator targeting ONE compiled XLA program: pad
sizes (the "layout") are computed once over all splits, every batch of a
split shares the same shapes, and per-epoch shuffling follows
DistributedSampler semantics (seeded by epoch via ``set_epoch``, sharded
evenly across processes with wrap-around padding).
"""

import os
from dataclasses import dataclass
from typing import List, Optional, Tuple

import numpy as np

from hydragnn_tpu.data.dataobj import GraphData
from hydragnn_tpu.graph.batch import collate_graphs, pad_sizes_for


@dataclass
class BatchLayout:
    n_pad: int
    e_pad: int
    g_pad: int
    head_types: Tuple[str, ...]
    head_dims: Tuple[int, ...]
    need_triplets: bool = False
    t_pad: int = 0
    # dense neighbor-list aggregation (scatter-free message passing):
    # fixed in/out-degree widths, computed over all splits
    need_neighbors: bool = False
    k_in: int = 0
    k_out: int = 0
    # per-edge incoming-triplet list width (DimeNet dense path)
    kt: int = 0


def _sample_triplets(data: GraphData):
    if "triplets" not in data.extras:
        from hydragnn_tpu.models.dimenet import compute_triplets

        data.extras["triplets"] = compute_triplets(data.edge_index, data.num_nodes)
    return data.extras["triplets"]


def _lcm(a, b):
    import math

    return a * b // math.gcd(a, b)


def needs_dense_neighbors(arch_config: dict) -> bool:
    """Single opt-in rule for dense scatter-free aggregation in the
    BATCH-collate path: the config flag, except under graph partitioning —
    there the partitioner builds per-shard lists itself
    (``partition_graph(need_neighbors=True)``, wired by the driver)."""
    return bool(arch_config.get("dense_aggregation")) and not arch_config.get(
        "partition_axis"
    )


def compute_layout(
    datasets: List[List[GraphData]],
    batch_size: int,
    need_triplets: bool = False,
    device_multiple: Optional[int] = None,
    need_neighbors: bool = False,
) -> BatchLayout:
    """``device_multiple``: every padded leading axis is made divisible by
    this (the data-parallel axis size) so sharded batches split evenly."""
    if device_multiple is None:
        try:
            import jax

            device_multiple = jax.device_count()
        except Exception:
            device_multiple = 1
    mult = _lcm(8, max(device_multiple, 1))
    max_nodes = 1
    max_edges = 1
    max_trip = 0
    k_in = k_out = 1
    kt = 1
    first = None
    for ds in datasets:
        for d in ds:
            first = first or d
            max_nodes = max(max_nodes, d.num_nodes)
            max_edges = max(max_edges, d.num_edges)
            if need_triplets:
                trips = _sample_triplets(d)
                max_trip = max(max_trip, trips[0].shape[0])
                if need_neighbors and trips[4].size:
                    # widest per-edge incoming-triplet group in the sample
                    kt = max(kt, int(np.bincount(trips[4]).max()))
            if need_neighbors and d.num_edges:
                from hydragnn_tpu.ops.dense_agg import max_degree

                ki, ko = max_degree(d.edge_index[0], d.edge_index[1])
                k_in = max(k_in, ki)
                k_out = max(k_out, ko)
    head_types = tuple(first.target_types)
    head_dims = tuple(
        t.shape[-1] if t.ndim > 1 else t.shape[0] for t in first.targets
    )
    n_pad, e_pad, g_pad = pad_sizes_for(
        max_nodes,
        max_edges,
        batch_size,
        node_multiple=mult,
        edge_multiple=mult,
        graph_multiple=max(device_multiple, 1),
    )
    t_pad = 0
    if need_triplets:
        t_pad = int(-(-(batch_size * max(max_trip, 1)) // mult) * mult)
    return BatchLayout(
        n_pad=n_pad,
        e_pad=e_pad,
        g_pad=g_pad,
        head_types=head_types,
        head_dims=head_dims,
        need_triplets=need_triplets,
        t_pad=t_pad,
        need_neighbors=need_neighbors,
        k_in=k_in,
        k_out=k_out,
        kt=kt,
    )


def _collate_with_extras(samples, layout: BatchLayout):
    batch = collate_graphs(
        samples,
        layout.n_pad,
        layout.e_pad,
        layout.g_pad,
        head_types=layout.head_types,
        head_dims=layout.head_dims,
    )
    if layout.need_triplets:
        from hydragnn_tpu.graph.batch import pack_triplets

        trips = [
            _sample_triplets(s) + (s.num_nodes, s.num_edges) for s in samples
        ]
        batch = batch.replace(
            extras=pack_triplets(trips, layout.n_pad, layout.t_pad)
        )
    if layout.need_neighbors:
        from hydragnn_tpu.ops.dense_agg import (
            build_group_lists,
            build_neighbor_lists,
        )

        nbr = build_neighbor_lists(
            batch.senders,
            batch.receivers,
            batch.edge_mask,
            layout.n_pad,
            layout.k_in,
            layout.k_out,
        )
        merged = dict(batch.extras or {})
        merged.update(nbr)
        if layout.need_triplets:
            # DimeNet dense path: per-edge incoming-triplet member lists
            tl, tm = build_group_lists(
                merged["trip_ji"],
                merged["trip_mask"],
                layout.e_pad,
                layout.kt,
                label="kt",
            )
            merged["tripnbr_idx"] = tl
            merged["tripnbr_mask"] = tm
        batch = batch.replace(extras=merged)
    return batch


class ConcatDataset:
    """Read-only concatenation of list-like datasets (the multi-dataset
    GFM training pattern, ``examples/multidataset/train.py`` in the
    reference). Works over in-memory lists, ShardDatasets, DistDatasets."""

    def __init__(self, datasets):
        self.datasets = list(datasets)
        self._cum = np.cumsum([len(d) for d in self.datasets])

    def __len__(self):
        return int(self._cum[-1]) if len(self._cum) else 0

    def __getitem__(self, idx):
        n = len(self)
        if idx < 0:
            idx += n
        if not 0 <= idx < n:
            raise IndexError(idx)
        which = int(np.searchsorted(self._cum, idx, side="right"))
        local = idx - (int(self._cum[which - 1]) if which else 0)
        return self.datasets[which][local]

    def __iter__(self):
        for ds in self.datasets:
            yield from ds


class GraphLoader:
    """Iterates padded batches; DistributedSampler-style sharding + epoch
    shuffling (``load_data.py:237-245``, ``train_validate_test.py:151-153``).

    ``prefetch > 0`` collates ahead on a background thread (bounded queue) so
    host-side batch assembly overlaps the device step — the role of the
    reference's thread-pool ``HydraDataLoader`` (``load_data.py:94-204``);
    XLA's async dispatch provides the other half of the overlap. The
    ``HYDRAGNN_PREFETCH`` env var sets the default depth.
    """

    def __init__(
        self,
        dataset: List[GraphData],
        batch_size: int,
        layout: BatchLayout,
        shuffle: bool = True,
        seed: int = 42,
        num_shards: Optional[int] = None,
        shard_id: Optional[int] = None,
        prefetch: Optional[int] = None,
    ):
        from hydragnn_tpu.parallel.distributed import get_comm_size_and_rank

        world, rank = get_comm_size_and_rank()
        self.dataset = dataset
        self.batch_size = batch_size
        self.layout = layout
        self.shuffle = shuffle
        self.seed = seed
        self.epoch = 0
        self.num_shards = world if num_shards is None else num_shards
        self.shard_id = rank if shard_id is None else shard_id
        if prefetch is None:
            prefetch = int(os.getenv("HYDRAGNN_PREFETCH", "0"))
        self.prefetch = prefetch

    def set_epoch(self, epoch: int):
        self.epoch = epoch

    def _indices(self):
        n = len(self.dataset)
        if self.shuffle:
            rng = np.random.default_rng(self.seed + self.epoch)
            idx = rng.permutation(n)
        else:
            idx = np.arange(n)
        if self.num_shards > 1:
            # pad to a multiple of num_shards by wrapping (DistributedSampler)
            total = -(-n // self.num_shards) * self.num_shards
            idx = np.concatenate([idx, idx[: total - n]])
            idx = idx[self.shard_id :: self.num_shards]
        return idx

    def __len__(self):
        n = len(self._indices())
        return -(-n // self.batch_size)

    def _batches(self):
        idx = self._indices()
        for start in range(0, len(idx), self.batch_size):
            chunk = [self.dataset[i] for i in idx[start : start + self.batch_size]]
            yield _collate_with_extras(chunk, self.layout)

    def __iter__(self):
        if self.prefetch <= 0:
            yield from self._batches()
            return
        import queue
        import threading

        q: "queue.Queue" = queue.Queue(maxsize=self.prefetch)
        sentinel = object()
        stop = threading.Event()
        err = []

        def worker():
            try:
                for b in self._batches():
                    # bounded put that notices consumer abandonment, so an
                    # early `break` in the epoch loop (HYDRAGNN_MAX_NUM_BATCH
                    # cap) cannot leak a thread pinning collated batches
                    while not stop.is_set():
                        try:
                            q.put(b, timeout=0.1)
                            break
                        except queue.Full:
                            continue
                    if stop.is_set():
                        return
            except BaseException as e:  # surface collate errors on the consumer
                err.append(e)
            finally:
                # stop-aware sentinel delivery: on abandonment nobody reads
                # it and a blocking put could wedge on a full queue
                while not stop.is_set():
                    try:
                        q.put(sentinel, timeout=0.1)
                        break
                    except queue.Full:
                        continue

        t = threading.Thread(target=worker, daemon=True, name="graphloader-prefetch")
        t.start()
        try:
            while True:
                item = q.get()
                if item is sentinel:
                    break
                yield item
        finally:
            stop.set()
            # unblock a worker stuck on a full queue, then reap it
            try:
                while True:
                    q.get_nowait()
            except queue.Empty:
                pass
            t.join()
        if err:
            raise err[0]


def create_dataloaders(
    trainset,
    valset,
    testset,
    batch_size: int,
    need_triplets: bool = False,
    need_neighbors: bool = False,
):
    layout = compute_layout(
        [trainset, valset, testset],
        batch_size,
        need_triplets,
        need_neighbors=need_neighbors,
    )
    return (
        GraphLoader(trainset, batch_size, layout, shuffle=True),
        GraphLoader(valset, batch_size, layout, shuffle=True),
        GraphLoader(testset, batch_size, layout, shuffle=True),
    )


def dataset_loading_and_splitting(config: dict):
    """Parity with ``preprocess/load_data.py:207-223``: raw -> serialized ->
    split pkls -> per-split datasets -> loaders."""
    from hydragnn_tpu.data.serialized import SerializedGraphLoader

    paths = config["Dataset"]["path"]
    if not list(paths.values())[0].endswith(".pkl"):
        transform_raw_data_to_serialized(config["Dataset"])
    if "total" in paths:
        total_to_train_val_test_pkls(config)

    loader = SerializedGraphLoader(config)
    datasets = {}
    for name, p in config["Dataset"]["path"].items():
        if p.endswith(".pkl"):
            files_dir = p
        else:
            files_dir = (
                f"{os.environ.get('SERIALIZED_DATA_PATH', os.getcwd())}"
                f"/serialized_dataset/{config['Dataset']['name']}_{name}.pkl"
            )
        datasets[name] = loader.load_serialized_data(files_dir)

    arch = config["NeuralNetwork"]["Architecture"]
    need_triplets = arch.get("model_type") == "DimeNet"
    need_neighbors = needs_dense_neighbors(arch)
    return create_dataloaders(
        datasets["train"],
        datasets["validate"],
        datasets["test"],
        batch_size=config["NeuralNetwork"]["Training"]["batch_size"],
        need_triplets=need_triplets,
        need_neighbors=need_neighbors,
    )


def transform_raw_data_to_serialized(ds_config: dict):
    """Rank-0 raw parsing + serialization (``load_data.py:349-363``)."""
    from hydragnn_tpu.parallel.distributed import get_comm_size_and_rank

    _, rank = get_comm_size_and_rank()
    if rank == 0:
        fmt = ds_config["format"]
        if fmt in ("LSMS", "unit_test"):
            from hydragnn_tpu.data.lsms import LSMSDataset

            loader = LSMSDataset(ds_config)
        elif fmt == "CFG":
            from hydragnn_tpu.data.cfg import CFGDataset

            loader = CFGDataset(ds_config)
        elif fmt == "XYZ":
            from hydragnn_tpu.data.xyz import XYZDataset

            loader = XYZDataset(ds_config)
        else:
            raise NameError("Data format not recognized for raw data loader")
        loader.load_raw_data()


def total_to_train_val_test_pkls(config: dict, isdist: bool = False):
    """Split a monolithic pkl into train/val/test pkls and point the config at
    them (``load_data.py:366-407``)."""
    import pickle

    from hydragnn_tpu.data.split import split_dataset
    from hydragnn_tpu.parallel.distributed import get_comm_size_and_rank

    _, rank = get_comm_size_and_rank()
    paths = config["Dataset"]["path"]
    if list(paths.values())[0].endswith(".pkl"):
        file_dir = paths["total"]
    else:
        file_dir = (
            f"{os.environ.get('SERIALIZED_DATA_PATH', os.getcwd())}"
            f"/serialized_dataset/{config['Dataset']['name']}.pkl"
        )
    with open(file_dir, "rb") as f:
        minmax_node = pickle.load(f)
        minmax_graph = pickle.load(f)
        total = pickle.load(f)
    trainset, valset, testset = split_dataset(
        total,
        config["NeuralNetwork"]["Training"]["perc_train"],
        config["Dataset"]["compositional_stratified_splitting"],
    )
    serialized_dir = os.path.dirname(file_dir)
    config["Dataset"]["path"] = {}
    for name, ds in zip(
        ["train", "validate", "test"], [trainset, valset, testset]
    ):
        serial_name = f"{config['Dataset']['name']}_{name}.pkl"
        config["Dataset"]["path"][name] = os.path.join(serialized_dir, serial_name)
        if isdist or rank == 0:
            with open(os.path.join(serialized_dir, serial_name), "wb") as f:
                pickle.dump(minmax_node, f)
                pickle.dump(minmax_graph, f)
                pickle.dump(ds, f)
