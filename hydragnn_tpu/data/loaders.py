"""Batch loaders: samples -> statically-shaped padded GraphBatch streams.

Replaces PyG's DataLoader + DistributedSampler (``preprocess/load_data.py:
207-297``) with a numpy collator targeting ONE compiled XLA program: pad
sizes (the "layout") are computed once over all splits, every batch of a
split shares the same shapes, and per-epoch shuffling follows
DistributedSampler semantics (seeded by epoch via ``set_epoch``, sharded
evenly across processes with wrap-around padding).
"""

import bisect
import os
from dataclasses import dataclass, field
from typing import List, Optional, Tuple, Union

import numpy as np

from hydragnn_tpu.data.dataobj import GraphData
from hydragnn_tpu.graph.batch import _round_up, collate_graphs, pad_sizes_for
from hydragnn_tpu.utils.envparse import env_int


@dataclass
class BatchLayout:
    n_pad: int
    e_pad: int
    g_pad: int
    head_types: Tuple[str, ...]
    head_dims: Tuple[int, ...]
    need_triplets: bool = False
    t_pad: int = 0
    # dense neighbor-list aggregation (scatter-free message passing):
    # fixed in/out-degree widths, computed over all splits
    need_neighbors: bool = False
    k_in: int = 0
    k_out: int = 0

    @property
    def packs_triplets(self) -> bool:
        """Whether collation materializes T-axis triplet tables. Dense
        layouts never do: the bmm-triplet path (models/dimenet.py) derives
        every triplet from the neighbor lists, so host-side
        ``compute_triplets`` is skipped entirely."""
        return self.need_triplets and not self.need_neighbors


@dataclass
class BucketedLayout:
    """2-4 size-bucketed :class:`BatchLayout`\\ s per split (round-3 verdict
    item 3): instead of ONE layout sized at the dataset max — which wastes
    most of each batch's FLOPs and HBM on padding when graph sizes are
    heterogeneous (OC20: ~20-250 atoms) — samples are binned by node count
    and each bucket gets a layout sized at ITS max. Compile count stays
    bounded: one XLA program per bucket (<= 4), vs the reference's PyG
    dynamic batching which recompiles nothing because it is eager
    (``preprocess/load_data.py:226-297``).

    ``node_bounds[b]`` is the inclusive node-count upper bound of bucket
    ``b`` (ascending); a sample with ``num_nodes`` goes to the first bucket
    whose bound covers it."""

    layouts: List[BatchLayout] = field(default_factory=list)
    node_bounds: List[int] = field(default_factory=list)

    def bucket_for(self, num_nodes: int) -> int:
        b = bisect.bisect_left(self.node_bounds, num_nodes)
        return min(b, len(self.layouts) - 1)

    # shared head schema (identical across buckets)
    @property
    def head_types(self):
        return self.layouts[0].head_types

    @property
    def head_dims(self):
        return self.layouts[0].head_dims

    @property
    def need_triplets(self):
        return self.layouts[0].need_triplets

    @property
    def need_neighbors(self):
        return self.layouts[0].need_neighbors

    @property
    def packs_triplets(self):
        return self.layouts[0].packs_triplets


def _sample_triplets(data: GraphData):
    if "triplets" not in data.extras:
        from hydragnn_tpu.models.dimenet import compute_triplets

        data.extras["triplets"] = compute_triplets(data.edge_index, data.num_nodes)
    return data.extras["triplets"]


def _lcm(a, b):
    import math

    return a * b // math.gcd(a, b)


# The measured dense/segment crossover tables and the policy function were
# promoted to ops/autotune.py (the per-bucket aggregation autotuner owns
# every choice tier now); the loader keeps the historical import surface.
from hydragnn_tpu.ops.autotune import (  # noqa: F401  (re-exports)
    DENSE_AUTO_MAX_INPUT_DIM as _DENSE_AUTO_MAX_INPUT_DIM,
    DENSE_AUTO_MIN_HIDDEN as _DENSE_AUTO_MIN_HIDDEN,
    auto_dense_aggregation,
)


def arch_for_auto_policy(nn_config: dict) -> dict:
    """Architecture dict enriched with ``input_dim`` (CGCNN's crossover
    key) derived from ``Variables_of_interest.input_node_features`` when
    the config predates ``update_config`` — ONE derivation shared by every
    entry point so their dense/segment decisions cannot diverge."""
    arch = nn_config["Architecture"]
    feats = nn_config.get("Variables_of_interest", {}).get(
        "input_node_features"
    )
    if feats and "input_dim" not in arch:
        return dict(arch, input_dim=len(feats))
    return arch


def needs_dense_neighbors(arch_config: dict) -> bool:
    """Single rule for dense scatter-free aggregation in the BATCH-collate
    path. ``HYDRAGNN_AGG`` (the autotuner's family force) wins over
    everything; then an explicit ``dense_aggregation`` true/false; then
    AUTO (the measured-crossover policy picks the winning path per
    model x width). Off under graph partitioning — there the partitioner
    builds per-shard lists itself (``partition_graph(need_neighbors=True)``,
    wired by the driver)."""
    if arch_config.get("partition_axis"):
        return False
    from hydragnn_tpu.ops.autotune import (
        DENSE_AUTO_MAX_INPUT_DIM,
        cached_model_choice,
        env_force,
    )

    forced = env_force()
    if forced is not None:
        return forced == "dense"
    flag = arch_config.get("dense_aggregation")
    if flag is not None:
        return bool(flag)
    # AUTO: a measured autotuner decision for this model AT THIS WIDTH
    # beats the static crossover tables — this is where a cached "dense"
    # win is actually ENACTED (the layout is where dense happens). The
    # width key mirrors the static policy's: input_dim for the
    # constant-width stacks (CGCNN), hidden_dim for the rest.
    mt = arch_config.get("model_type") or ""
    width = (
        arch_config.get("input_dim")
        if mt in DENSE_AUTO_MAX_INPUT_DIM
        else arch_config.get("hidden_dim")
    )
    if width:
        cached = cached_model_choice(mt, int(width))
        if cached is not None:
            return cached == "dense"
    return auto_dense_aggregation(arch_config)


def _sample_stats(datasets, need_triplets, need_neighbors):
    """One pass over all samples -> per-sample size arrays (nodes, edges,
    triplets, neighbor-list widths) + the head schema from the first.
    Triplet counting is skipped when dense lists are requested — the bmm
    path never packs a T axis, so running ``compute_triplets`` over the
    whole dataset would be pure startup waste."""
    nodes, edges, trips_n, kis, kos = [], [], [], [], []
    first = None
    for ds in datasets:
        for d in ds:
            first = first or d
            nodes.append(d.num_nodes)
            edges.append(d.num_edges)
            t = ki = ko = 0
            if need_triplets and not need_neighbors:
                trips = _sample_triplets(d)
                t = trips[0].shape[0]
            if need_neighbors and d.num_edges:
                from hydragnn_tpu.ops.dense_agg import max_degree

                ki, ko = max_degree(d.edge_index[0], d.edge_index[1])
            trips_n.append(t)
            kis.append(ki)
            kos.append(ko)
    head_types = tuple(first.target_types)
    head_dims = tuple(
        t.shape[-1] if t.ndim > 1 else t.shape[0] for t in first.targets
    )
    return (
        np.asarray(nodes),
        np.asarray(edges),
        np.asarray(trips_n),
        np.asarray(kis),
        np.asarray(kos),
        head_types,
        head_dims,
    )


def _partition_node_bounds(nodes: np.ndarray, num_buckets: int) -> List[int]:
    """Bucket boundaries minimizing total padded node rows: exact DP over
    the distinct node counts (cost of a bucket = its sample count x its max
    node count — exactly the rows the padded layout will allocate)."""
    uniq, counts = np.unique(nodes, return_counts=True)
    m = len(uniq)
    k = min(num_buckets, m)
    if k <= 1:
        return [int(uniq[-1])]
    prefix = np.concatenate([[0], np.cumsum(counts)])
    INF = float("inf")
    # dp[b][j]: min cost covering the first j distinct sizes with b buckets
    dp = np.full((k + 1, m + 1), INF)
    cut = np.zeros((k + 1, m + 1), np.int64)
    dp[0][0] = 0.0
    prefix = prefix.astype(np.float64)
    for b in range(1, k + 1):
        for j in range(1, m + 1):
            # vectorized min over the cut point i (O(k*m) numpy ops total,
            # not an O(k*m^2) Python loop — m can be thousands of distinct
            # sizes at parser-scale datasets)
            cand = dp[b - 1][:j] + (prefix[j] - prefix[:j]) * float(uniq[j - 1])
            i = int(np.argmin(cand))
            dp[b][j] = cand[i]
            cut[b][j] = i
    bounds = []
    j = m
    for b in range(k, 0, -1):
        bounds.append(int(uniq[j - 1]))
        j = int(cut[b][j])
    return bounds[::-1]


def _layout_from_maxima(
    max_nodes, max_edges, max_trip, k_in, k_out,
    batch_size, mult, device_multiple, head_types, head_dims,
    need_triplets, need_neighbors,
) -> BatchLayout:
    n_pad, e_pad, g_pad = pad_sizes_for(
        max_nodes,
        max_edges,
        batch_size,
        node_multiple=mult,
        edge_multiple=mult,
        graph_multiple=max(device_multiple, 1),
    )
    t_pad = 0
    if need_triplets and not need_neighbors:
        t_pad = int(-(-(batch_size * max(max_trip, 1)) // mult) * mult)
    return BatchLayout(
        n_pad=n_pad,
        e_pad=e_pad,
        g_pad=g_pad,
        head_types=head_types,
        head_dims=head_dims,
        need_triplets=need_triplets,
        t_pad=t_pad,
        need_neighbors=need_neighbors,
        k_in=max(int(k_in), 1),
        k_out=max(int(k_out), 1),
    )


def budget_bucket_layout(
    nodes: np.ndarray,
    edges: np.ndarray,
    trips: np.ndarray,
    batch_size: int,
    mult: int,
    device_multiple: int,
    head_types,
    head_dims,
    need_triplets: bool = False,
    need_neighbors: bool = False,
    k_in: int = 1,
    k_out: int = 1,
) -> BatchLayout:
    """One bucket's layout sized at ``batch_size x bucket MEAN`` (not
    max): the loader packs graphs greedily under these budgets, so every
    batch fits by construction and padding waste is the distance from the
    budget to the last graph that did not fit, not max-vs-mean. ``g_pad``
    allows however many of the bucket's smallest graphs fit the node
    budget. Shared by :func:`compute_layout`'s bucketed path and the
    streaming :class:`~hydragnn_tpu.data.stream.planner.BucketPlanner`
    (one sizing rule — the auto-tuned plan cannot drift from the
    materialized path's)."""
    n_budget = int(max(batch_size * float(nodes.mean()), nodes.max()) + 1)
    e_budget = int(max(batch_size * float(edges.mean()), edges.max(), 1))
    n_pad = _round_up(n_budget, mult)
    e_pad = _round_up(e_budget, mult)
    g_cap = max(batch_size, n_pad // max(int(nodes.min()), 1))
    g_pad = _round_up(g_cap + 1, max(device_multiple, 1))
    t_pad = 0
    if need_triplets and not need_neighbors:
        t_budget = int(max(batch_size * float(trips.mean()), trips.max(), 1))
        t_pad = _round_up(t_budget, mult)
    return BatchLayout(
        n_pad=n_pad,
        e_pad=e_pad,
        g_pad=g_pad,
        head_types=head_types,
        head_dims=head_dims,
        need_triplets=need_triplets,
        t_pad=t_pad,
        need_neighbors=need_neighbors,
        k_in=max(int(k_in), 1),
        k_out=max(int(k_out), 1),
    )


def compute_layout(
    datasets: List[List[GraphData]],
    batch_size: int,
    need_triplets: bool = False,
    device_multiple: Optional[int] = None,
    need_neighbors: bool = False,
    num_buckets: int = 1,
) -> Union[BatchLayout, "BucketedLayout"]:
    """``device_multiple``: every padded leading axis is made divisible by
    this (the data-parallel axis size) so sharded batches split evenly.

    ``num_buckets > 1`` returns a :class:`BucketedLayout`: samples are
    binned by node count (boundaries chosen by an exact DP minimizing
    padded node rows) and each bucket is sized at its own maxima — the
    low-waste answer to heterogeneous graph sizes (SURVEY §5's
    padding/bucketing "hard part"). Compiles stay bounded at one program
    per bucket."""
    if device_multiple is None:
        try:
            # the mesh's DATA axis, not the raw device count: on a 2-D
            # ("data", "model") mesh only the data axis shards batch
            # leading dims (and on a best-fit elastic mesh — e.g. (3, 2)
            # on a 7-device world — the device count does not even divide)
            from hydragnn_tpu.parallel.mesh import data_axis_multiple

            device_multiple = data_axis_multiple()
        except Exception:
            device_multiple = 1
    mult = _lcm(8, max(device_multiple, 1))
    nodes, edges, trips_n, kis, kos, head_types, head_dims = (
        _sample_stats(datasets, need_triplets, need_neighbors)
    )

    def build(mask) -> BatchLayout:
        return _layout_from_maxima(
            max(int(nodes[mask].max()), 1),
            max(int(edges[mask].max()), 1),
            int(trips_n[mask].max()) if need_triplets else 0,
            kis[mask].max() if len(kis) else 1,
            kos[mask].max() if len(kos) else 1,
            batch_size, mult, device_multiple, head_types, head_dims,
            need_triplets, need_neighbors,
        )

    def build_budget(mask) -> BatchLayout:
        return budget_bucket_layout(
            nodes[mask], edges[mask], trips_n[mask],
            batch_size, mult, device_multiple, head_types, head_dims,
            need_triplets, need_neighbors,
            k_in=int(kis[mask].max()) if len(kis) else 1,
            k_out=int(kos[mask].max()) if len(kos) else 1,
        )

    everything = np.ones(len(nodes), bool)
    if num_buckets <= 1:
        return build(everything)
    bounds = _partition_node_bounds(nodes, num_buckets)
    layouts = []
    lo = 0
    for hi in bounds:
        mask = (nodes > lo) & (nodes <= hi)
        layouts.append(build_budget(mask))
        lo = hi
    return BucketedLayout(layouts=layouts, node_bounds=bounds)


def _pack_indices(
    idx: np.ndarray,
    nodes: np.ndarray,
    edges: np.ndarray,
    trips: np.ndarray,
    layout: BatchLayout,
    batch_size: Optional[int] = None,
) -> List[np.ndarray]:
    """Greedy budget packing: fill a batch until the next graph would
    overflow the bucket's node/edge/triplet budget or the graph cap.
    Every batch fits its layout by construction.

    ``batch_size`` caps the GRAPH count per batch at the configured value
    (reference DataLoader semantics: a step is batch_size graphs). Without
    it the node budget alone governs and small-graph buckets pack far
    past the nominal batch size — higher device throughput per epoch but
    a DIFFERENT optimization trajectory (fewer, larger steps): measured
    on QM9-at-scale round 4, budget-only packing trained to val ~6-8
    where batch-capped packing matches the reference-semantics ~3
    (BASELINE.md). Throughput mode stays available via
    ``Training.bucket_graph_cap: "budget"``."""
    cap = layout.g_pad - 1  # the padding-graph slot stays reserved
    if batch_size is not None:
        cap = min(cap, int(batch_size))
    batches, cur = [], []
    n = e = t = 0
    for i in idx:
        ni, ei, ti = int(nodes[i]), int(edges[i]), int(trips[i])
        if cur and (
            n + ni > layout.n_pad - 1
            or e + ei > layout.e_pad
            or (layout.packs_triplets and t + ti > layout.t_pad)
            or len(cur) >= cap
        ):
            batches.append(np.asarray(cur, np.int64))
            cur, n, e, t = [], 0, 0, 0
        cur.append(int(i))
        n += ni
        e += ei
        t += ti
    if cur:
        batches.append(np.asarray(cur, np.int64))
    return batches


def padding_efficiency(datasets, layout, batch_size: int) -> float:
    """Real node rows / padded node rows over one epoch's worth of batches
    — the round-3 verdict's acceptance metric for bucketed layouts.
    Simulates the loader's own packing (shuffle off, one shard) through
    the SAME accounting the telemetry layer reports per epoch
    (:meth:`GraphLoader.epoch_padding_stats`), so the two can't diverge."""
    samples = [d for ds in datasets for d in ds]
    loader = GraphLoader(
        samples, batch_size, layout, shuffle=False, num_shards=1, shard_id=0,
    )
    real, padded = loader.epoch_padding_stats()
    return real / max(padded, 1)


def collate_for_layout(samples, layout: BatchLayout, with_targets: bool = True):
    """Collate ``samples`` into the static shapes of ``layout``, including
    any model-specific extras (DimeNet triplet tables, dense neighbor
    lists). The ONE layout-aware collation path — the training loader and
    the serving request packer (``hydragnn_tpu/serve``) both route through
    here. ``with_targets=False`` packs inputs only (inference requests
    carry no labels)."""
    batch = collate_graphs(
        samples,
        layout.n_pad,
        layout.e_pad,
        layout.g_pad,
        head_types=layout.head_types if with_targets else (),
        head_dims=layout.head_dims if with_targets else (),
    )
    if layout.packs_triplets:
        from hydragnn_tpu.graph.batch import pack_triplets

        trips = [
            _sample_triplets(s) + (s.num_nodes, s.num_edges) for s in samples
        ]
        batch = batch.replace(
            extras=pack_triplets(trips, layout.n_pad, layout.t_pad)
        )
    if layout.need_neighbors:
        from hydragnn_tpu.ops.dense_agg import build_neighbor_lists

        nbr = build_neighbor_lists(
            batch.senders,
            batch.receivers,
            batch.edge_mask,
            layout.n_pad,
            layout.k_in,
            layout.k_out,
            with_slot_tables=layout.need_triplets,
        )
        merged = dict(batch.extras or {})
        merged.update(nbr)
        batch = batch.replace(extras=merged)
    return batch


_collate_with_extras = collate_for_layout


class ConcatDataset:
    """Read-only concatenation of list-like datasets (the multi-dataset
    GFM training pattern, ``examples/multidataset/train.py`` in the
    reference). Works over in-memory lists, ShardDatasets, DistDatasets."""

    def __init__(self, datasets):
        self.datasets = list(datasets)
        self._cum = np.cumsum([len(d) for d in self.datasets])

    def __len__(self):
        return int(self._cum[-1]) if len(self._cum) else 0

    def __getitem__(self, idx):
        n = len(self)
        if idx < 0:
            idx += n
        if not 0 <= idx < n:
            raise IndexError(idx)
        which = int(np.searchsorted(self._cum, idx, side="right"))
        local = idx - (int(self._cum[which - 1]) if which else 0)
        return self.datasets[which][local]

    def __iter__(self):
        for ds in self.datasets:
            yield from ds


class GraphLoader:
    """Iterates padded batches; DistributedSampler-style sharding + epoch
    shuffling (``load_data.py:237-245``, ``train_validate_test.py:151-153``).

    ``prefetch > 0`` collates ahead on a background thread (bounded queue) so
    host-side batch assembly overlaps the device step — the role of the
    reference's thread-pool ``HydraDataLoader`` (``load_data.py:94-204``);
    XLA's async dispatch provides the other half of the overlap. The
    ``HYDRAGNN_PREFETCH`` env var sets the default depth.
    """

    def __init__(
        self,
        dataset: List[GraphData],
        batch_size: int,
        layout: Union[BatchLayout, BucketedLayout],
        shuffle: bool = True,
        seed: int = 42,
        num_shards: Optional[int] = None,
        shard_id: Optional[int] = None,
        prefetch: Optional[int] = None,
        contiguous_buckets: Optional[bool] = None,
        bucket_graph_cap: str = "batch",
    ):
        from hydragnn_tpu.parallel.distributed import get_comm_size_and_rank

        world, rank = get_comm_size_and_rank()
        self.dataset = dataset
        self.batch_size = batch_size
        self.layout = layout
        self.shuffle = shuffle
        self.seed = seed
        self.epoch = 0
        self.num_shards = world if num_shards is None else num_shards
        self.shard_id = rank if shard_id is None else shard_id
        if prefetch is None:
            # validated parse: a typo'd HYDRAGNN_PREFETCH must name the
            # variable, not raise a bare int() ValueError mid-construction
            prefetch = env_int("HYDRAGNN_PREFETCH", 0)
        self.prefetch = prefetch
        self._plan_cache = None  # (epoch, plan) — packing is O(dataset)
        # contiguous_buckets: shuffle samples within buckets and the ORDER
        # of bucket segments, but keep same-bucket batches adjacent — runs
        # of identical shapes let steps_per_dispatch stack K batches into
        # one XLA program on dispatch-latency-bound hosts.
        # HYDRAGNN_BUCKET_CONTIGUOUS overrides whatever the caller passed
        # (the ONE parse site for the env var); absent both, off.
        env_contig = os.getenv("HYDRAGNN_BUCKET_CONTIGUOUS")
        if env_contig is not None:
            contiguous_buckets = env_contig.strip().lower() not in (
                "", "0", "false", "no", "off",
            )
        self.contiguous_buckets = bool(contiguous_buckets)
        # "batch" = at most batch_size graphs per packed batch (reference
        # step semantics); "budget" = fill to the node/edge budget (pure
        # throughput; changes the optimization trajectory — see
        # _pack_indices)
        if bucket_graph_cap not in ("batch", "budget"):
            raise ValueError(
                f"bucket_graph_cap must be 'batch' or 'budget', "
                f"got {bucket_graph_cap!r}"
            )
        if bucket_graph_cap == "budget" and not isinstance(
            layout, BucketedLayout
        ):
            # budget packing only exists on the bucketed plan path; a
            # silent no-op would read as "budget mode has no effect"
            raise ValueError(
                "bucket_graph_cap='budget' requires a bucketed layout "
                "(Training.batch_buckets > 1)"
            )
        self.bucket_graph_cap = bucket_graph_cap
        # lazy: one sizes pass over the dataset (bucketed layouts only)
        self._bucket_ids = None
        self._sizes = None
        self._plain_nodes = None  # node counts cache for the plain layout
        self._padding_stats_cache = None  # (epoch, (real, padded))

    def set_epoch(self, epoch: int):
        self.epoch = epoch

    def _graph_cap(self) -> Optional[int]:
        return None if self.bucket_graph_cap == "budget" else self.batch_size

    def _indices(self):
        n = len(self.dataset)
        if self.shuffle:
            rng = np.random.default_rng(self.seed + self.epoch)
            idx = rng.permutation(n)
        else:
            idx = np.arange(n)
        if self.num_shards > 1:
            # pad to a multiple of num_shards by wrapping (DistributedSampler)
            total = -(-n // self.num_shards) * self.num_shards
            idx = np.concatenate([idx, idx[: total - n]])
            idx = idx[self.shard_id :: self.num_shards]
        return idx

    def _bucket_assignments(self):
        """One pass over the dataset caching (bucket id, node/edge/triplet
        counts) per sample — the packer's inputs."""
        if self._bucket_ids is None:
            ids, nodes, edges, trips = [], [], [], []
            for i in range(len(self.dataset)):
                d = self.dataset[i]
                ids.append(self.layout.bucket_for(d.num_nodes))
                nodes.append(d.num_nodes)
                edges.append(d.num_edges)
                trips.append(
                    _sample_triplets(d)[0].shape[0]
                    if self.layout.packs_triplets
                    else 0
                )
            self._bucket_ids = np.asarray(ids, np.int64)
            self._sizes = (
                np.asarray(nodes, np.int64),
                np.asarray(edges, np.int64),
                np.asarray(trips, np.int64),
            )
        return self._bucket_ids

    def _batch_plan(self):
        """Bucketed epoch plan: per-bucket DistributedSampler sharding +
        greedy budget packing, then a global shuffle of batch ORDER across
        buckets. Deterministic in (seed, epoch) — every process derives
        the same plan, including every OTHER shard's packing, so all
        processes emit the same number of batches with identical shapes at
        every step (multi-host lockstep without communication). Cached per
        epoch: ``len(loader)`` + iteration must not pack twice."""
        if self._plan_cache is not None and self._plan_cache[0] == self.epoch:
            return self._plan_cache[1]
        rng = np.random.default_rng(self.seed + self.epoch)
        plan = []
        assignments = self._bucket_assignments()
        nodes, edges, trips = self._sizes
        for b in range(len(self.layout.layouts)):
            lay = self.layout.layouts[b]
            bidx = np.nonzero(assignments == b)[0]
            n = len(bidx)
            if n == 0:
                continue
            if self.shuffle:
                bidx = bidx[rng.permutation(n)]
            if self.num_shards > 1:
                total = -(-n // self.num_shards) * self.num_shards
                bidx = np.concatenate([bidx, bidx[: total - n]])
                # every process packs ALL shards to learn the common batch
                # count; shards short of it wrap their own first batches
                # (sample duplication — DistributedSampler's padding rule
                # applied at batch granularity)
                per_shard = [
                    _pack_indices(
                        bidx[s :: self.num_shards], nodes, edges, trips, lay,
                        batch_size=self._graph_cap(),
                    )
                    for s in range(self.num_shards)
                ]
                m = max(len(p) for p in per_shard)
                mine = list(per_shard[self.shard_id])
                while len(mine) < m:
                    mine.append(mine[len(mine) % len(per_shard[self.shard_id])])
                plan.extend((b, chunk) for chunk in mine)
            else:
                plan.extend(
                    (b, chunk)
                    for chunk in _pack_indices(
                        bidx, nodes, edges, trips, lay,
                        batch_size=self._graph_cap(),
                    )
                )
        if self.shuffle and plan:
            if self.contiguous_buckets:
                # permute within each bucket segment + the segment order,
                # preserving same-shape adjacency for multi-step stacking
                segments = {}
                for item in plan:
                    segments.setdefault(item[0], []).append(item)
                keys = list(segments)
                plan = []
                for k in rng.permutation(len(keys)):
                    seg = segments[keys[k]]
                    plan.extend(seg[i] for i in rng.permutation(len(seg)))
            else:
                order = rng.permutation(len(plan))
                plan = [plan[i] for i in order]
        self._plan_cache = (self.epoch, plan)
        return plan

    def __len__(self):
        if isinstance(self.layout, BucketedLayout):
            return len(self._batch_plan())
        n = len(self._indices())
        return -(-n // self.batch_size)

    def epoch_padding_stats(self):
        """(real_node_rows, padded_node_rows) over THIS epoch's (sharded)
        batch plan, or ``None`` when computing it would cost a dataset
        I/O pass — the training-side padding-waste accounting (the predict
        server tracks the same two integrals per micro-batch, and the
        telemetry layer reports ``1 - real/padded`` per epoch). Reuses the
        cached sizes/plan and is itself cached per epoch — the fit path
        logs a whole chunk of epochs against one unchanged plan."""
        if (
            self._padding_stats_cache is not None
            and self._padding_stats_cache[0] == self.epoch
        ):
            return self._padding_stats_cache[1]
        if isinstance(self.layout, BucketedLayout):
            plan_ready = (
                self._plan_cache is not None
                and self._plan_cache[0] == self.epoch
            )
            if not plan_ready and self._padding_stats_cache is not None:
                # the plan for THIS epoch was never built (device-resident
                # path: the loader is staged once, then only set_epoch
                # advances) — reporting the last computed integrals beats
                # forcing an O(dataset) repack purely for telemetry
                return self._padding_stats_cache[1]
            # the sizes pass is already paid: bucketed planning needs it
            self._bucket_assignments()
            nodes = self._sizes[0]
            plan = self._batch_plan()
            if plan:
                cat = np.concatenate([chunk for _, chunk in plan])
                real = int(nodes[cat].sum())
            else:
                real = 0
            padded = int(
                sum(self.layout.layouts[b].n_pad for b, _ in plan)
            )
        else:
            if self._plain_nodes is None:
                in_memory = isinstance(self.dataset, list) or (
                    isinstance(self.dataset, ConcatDataset)
                    and all(
                        isinstance(d, list) for d in self.dataset.datasets
                    )
                )
                if not in_memory:
                    # disk-backed datasets (ShardDataset, DistDataset)
                    # would deserialize EVERY sample just to read
                    # num_nodes — a full I/O pass stalling the epoch loop;
                    # telemetry simply omits the waste series there
                    return None
                self._plain_nodes = np.fromiter(
                    (d.num_nodes for d in self.dataset),
                    np.int64,
                    count=len(self.dataset),
                )
            idx = np.asarray(self._indices(), np.int64)
            real = int(self._plain_nodes[idx].sum())
            padded = len(self) * int(self.layout.n_pad)
        self._padding_stats_cache = (self.epoch, (real, padded))
        return real, padded

    def _batch_tasks(self):
        """(layout, sample-index chunk) pairs — the cheap plan half of
        iteration, separable from collation so worker pools can fan the
        expensive half out."""
        if isinstance(self.layout, BucketedLayout):
            for b, chunk in self._batch_plan():
                yield (self.layout.layouts[b], chunk)
            return
        idx = self._indices()
        for start in range(0, len(idx), self.batch_size):
            yield (self.layout, idx[start : start + self.batch_size])

    def _collate_task(self, task):
        layout, chunk = task
        return _collate_with_extras([self.dataset[i] for i in chunk], layout)

    def _batches(self):
        for task in self._batch_tasks():
            yield self._collate_task(task)

    def __iter__(self):
        # HYDRAGNN_NUM_WORKERS > 1: fan sample fetch + collation over a
        # worker pool (ordered), optionally core-pinned via OMP_PLACES +
        # HYDRAGNN_AFFINITY — the reference HydraDataLoader's thread-pool
        # + sched_setaffinity design (``load_data.py:94-204``, worker_init
        # ``:118-154``). Matters on many-core TPU-VM hosts feeding
        # multiple processes; pointless on a 1-core box.
        workers = env_int("HYDRAGNN_NUM_WORKERS", 1)
        if workers > 1:
            yield from prefetch_iter(
                self._batch_tasks(),
                max(self.prefetch, workers),
                fn=self._collate_task,
                workers=workers,
                name="graphloader-worker",
            )
            return
        if self.prefetch <= 0:
            yield from self._batches()
            return
        yield from prefetch_iter(
            self._batches(), self.prefetch, name="graphloader-prefetch"
        )


def _parse_omp_places(spec: Optional[str] = None):
    """OMP_PLACES -> list of core sets, one per place. Supports the forms
    the reference's worker_init parses (``load_data.py:118-154``):
    ``{0:4},{4:4}`` (start:len[:stride]) and explicit ``{0,2,4}`` lists.
    Unparseable input -> no places (pinning silently off)."""
    import re

    if spec is None:
        spec = os.environ.get("OMP_PLACES", "")
    places = []
    try:
        for m in re.finditer(r"\{([^}]*)\}", spec):
            cores = []
            for part in m.group(1).split(","):
                part = part.strip()
                if not part:
                    continue
                if ":" in part:
                    bits = [int(x) for x in part.split(":")]
                    start, length = bits[0], bits[1]
                    stride = bits[2] if len(bits) > 2 else 1
                    cores.extend(
                        range(start, start + length * stride, stride)
                    )
                else:
                    cores.append(int(part))
            if cores:
                places.append(cores)
    except ValueError:
        return []
    return places


def _pin_worker(index: int, places) -> None:
    """Pin the CURRENT thread to place ``index % len(places)`` — the
    reference's ``sched_setaffinity`` worker pinning. No-op without
    places, without OS support, or on denial (containers)."""
    if not places or not hasattr(os, "sched_setaffinity"):
        return
    try:
        os.sched_setaffinity(0, set(places[index % len(places)]))
    except OSError:
        pass


def _affinity_places():
    """Core places for worker pinning, when ``HYDRAGNN_AFFINITY`` opts in
    (the reference's HYDRAGNN_AFFINITY family, ``load_data.py:120-126``)."""
    if os.getenv("HYDRAGNN_AFFINITY", "0") != "1":
        return []
    return _parse_omp_places()


def prefetch_iter(
    source, depth: int, fn=None, name: str = "prefetch", workers: int = 1,
    probe=None,
):
    """Bounded background pipeline stage: applies ``fn`` (identity if
    None) to each item of ``source`` on worker thread(s), up to ``depth``
    results in flight ahead of the consumer, yielded in order.

    ``workers > 1`` fans ``fn`` over an ordered thread pool (the
    reference HydraDataLoader's num_workers model); each worker pins to
    its OMP_PLACES place when ``HYDRAGNN_AFFINITY=1``.

    ``probe``, when given, is called with the queue depth (ready items
    ahead of the consumer) at every consumer-side get — the streaming
    telemetry's ``stream_queue_depth`` gauge feed. Single-worker path
    only; the pool path's in-flight window is not a readiness signal.

    Shared by the loader's collation prefetch and the trainer's
    double-buffered device transfers. The shutdown protocol matters: puts
    are stop-aware timed puts, so an abandoned consumer (early ``break``
    on HYDRAGNN_MAX_NUM_BATCH, or an exception while something retains the
    frame chain) cannot leak a thread pinning collated or device-resident
    batches; worker errors surface on the consumer side."""
    import queue
    import threading

    if fn is None:
        fn = lambda x: x  # noqa: E731
    places = _affinity_places()
    if workers > 1:
        yield from _ordered_pool_map(source, fn, workers, depth, name, places)
        return
    q: "queue.Queue" = queue.Queue(maxsize=max(depth, 1))
    sentinel = object()
    stop = threading.Event()
    err = []

    def _put_stop_aware(item) -> bool:
        while not stop.is_set():
            try:
                q.put(item, timeout=0.1)
                return True
            except queue.Full:
                continue
        return False

    def worker():
        # single pipeline threads deliberately do NOT pin: the collation
        # and device-transfer stages would otherwise all land on place 0
        # and time-share one core — only POOL workers (workers > 1) pin
        try:
            for b in source:
                if not _put_stop_aware(fn(b)):
                    return
        except BaseException as e:  # surface on the consumer side
            err.append(e)
        finally:
            # stop-aware sentinel delivery: on abandonment nobody reads it
            # and a blocking put could wedge on a full queue
            _put_stop_aware(sentinel)

    t = threading.Thread(target=worker, daemon=True, name=name)
    t.start()
    try:
        while True:
            if probe is not None:
                probe(q.qsize())
            item = q.get()
            if item is sentinel:
                break
            yield item
    finally:
        stop.set()
        # unblock a worker stuck on a full queue, then reap it — with a
        # BOUNDED join: generator close (an interrupted epoch, a break
        # on HYDRAGNN_MAX_NUM_BATCH) must never inherit a wedged
        # collate's wait, and the daemon flag keeps a pathological
        # worker from pinning interpreter exit
        try:
            while True:
                q.get_nowait()
        except queue.Empty:
            pass
        t.join(timeout=10.0)
        if not t.is_alive():
            # the worker is done but `source` may be suspended mid-yield
            # still referencing a collated (or device-resident) batch;
            # closing it runs its finally blocks and drops that
            # reference now instead of at GC time. Only safe once the
            # worker has exited — close() on an executing generator
            # raises ValueError.
            closer = getattr(source, "close", None)
            if callable(closer):
                try:
                    closer()
                except Exception:
                    pass
    if err:
        raise err[0]


def _ordered_pool_map(source, fn, workers, depth, name, places):
    """Ordered bounded map over a thread pool: at most ``max(depth,
    workers)`` items in flight, results yielded in source order. The
    consumer thread walks ``source`` (cheap plan work); workers run
    ``fn`` (fetch + collate). Abandonment cancels queued futures and the
    pool context join reaps the threads."""
    import itertools
    from concurrent.futures import ThreadPoolExecutor

    counter = itertools.count()

    def _init():
        _pin_worker(next(counter), places)

    window = []
    with ThreadPoolExecutor(
        max_workers=workers, thread_name_prefix=name, initializer=_init
    ) as ex:
        try:
            limit = max(depth, workers)
            for item in source:
                window.append(ex.submit(fn, item))
                if len(window) >= limit:
                    yield window.pop(0).result()
            while window:
                yield window.pop(0).result()
        finally:
            for f in window:
                f.cancel()
            # release the plan generator's suspended frame (iterated by
            # THIS thread, so it is suspended — not executing — whenever
            # this cleanup runs; closing it is race-free)
            closer = getattr(source, "close", None)
            if callable(closer):
                try:
                    closer()
                except Exception:
                    pass


def create_dataloaders(
    trainset,
    valset,
    testset,
    batch_size: int,
    need_triplets: bool = False,
    need_neighbors: bool = False,
    num_buckets: Optional[int] = None,
    contiguous_buckets: Optional[bool] = None,
    bucket_graph_cap: str = "batch",
):
    """``num_buckets`` (the config's ``Training.batch_buckets``):
    size-bucketed layouts — <= num_buckets compiled programs per split,
    padding sized per bucket instead of at the dataset max. Default 1
    (single layout). ``contiguous_buckets`` (the config's
    ``Training.contiguous_buckets``) keeps same-shape batches adjacent so
    ``steps_per_dispatch`` can stack them (env override parsed inside
    ``GraphLoader``). ``HYDRAGNN_BATCH_BUCKETS`` overrides whatever the
    caller passes — the ONE place that env var's precedence lives."""
    num_buckets = env_int("HYDRAGNN_BATCH_BUCKETS", num_buckets or 1, minimum=1)
    layout = compute_layout(
        [trainset, valset, testset],
        batch_size,
        need_triplets,
        need_neighbors=need_neighbors,
        num_buckets=num_buckets,
    )
    return (
        GraphLoader(trainset, batch_size, layout, shuffle=True,
                    contiguous_buckets=contiguous_buckets,
                    bucket_graph_cap=bucket_graph_cap),
        GraphLoader(valset, batch_size, layout, shuffle=True,
                    contiguous_buckets=contiguous_buckets,
                    bucket_graph_cap=bucket_graph_cap),
        GraphLoader(testset, batch_size, layout, shuffle=True,
                    contiguous_buckets=contiguous_buckets,
                    bucket_graph_cap=bucket_graph_cap),
    )


def dataset_loading_and_splitting(config: dict):
    """Parity with ``preprocess/load_data.py:207-223``: raw -> serialized ->
    split pkls -> per-split datasets -> loaders."""
    from hydragnn_tpu.data.serialized import SerializedGraphLoader

    paths = config["Dataset"]["path"]
    if not list(paths.values())[0].endswith(".pkl"):
        transform_raw_data_to_serialized(config["Dataset"])
    if "total" in paths:
        total_to_train_val_test_pkls(config)

    loader = SerializedGraphLoader(config)
    datasets = {}
    for name, p in config["Dataset"]["path"].items():
        if p.endswith(".pkl"):
            files_dir = p
        else:
            files_dir = (
                f"{os.environ.get('SERIALIZED_DATA_PATH', os.getcwd())}"
                f"/serialized_dataset/{config['Dataset']['name']}_{name}.pkl"
            )
        datasets[name] = loader.load_serialized_data(files_dir)

    arch = config["NeuralNetwork"]["Architecture"]
    need_triplets = arch.get("model_type") == "DimeNet"
    need_neighbors = needs_dense_neighbors(
        arch_for_auto_policy(config["NeuralNetwork"])
    )
    training = config["NeuralNetwork"]["Training"]
    return create_dataloaders(
        datasets["train"],
        datasets["validate"],
        datasets["test"],
        batch_size=training["batch_size"],
        need_triplets=need_triplets,
        need_neighbors=need_neighbors,
        num_buckets=training.get("batch_buckets"),
        contiguous_buckets=training.get("contiguous_buckets"),
        bucket_graph_cap=training.get("bucket_graph_cap", "batch"),
    )


def transform_raw_data_to_serialized(ds_config: dict):
    """Rank-0 raw parsing + serialization (``load_data.py:349-363``)."""
    from hydragnn_tpu.parallel.distributed import get_comm_size_and_rank

    _, rank = get_comm_size_and_rank()
    if rank == 0:
        fmt = ds_config["format"]
        if fmt in ("LSMS", "unit_test"):
            from hydragnn_tpu.data.lsms import LSMSDataset

            loader = LSMSDataset(ds_config)
        elif fmt == "CFG":
            from hydragnn_tpu.data.cfg import CFGDataset

            loader = CFGDataset(ds_config)
        elif fmt == "XYZ":
            from hydragnn_tpu.data.xyz import XYZDataset

            loader = XYZDataset(ds_config)
        else:
            raise NameError("Data format not recognized for raw data loader")
        loader.load_raw_data()


def total_to_train_val_test_pkls(config: dict, isdist: bool = False):
    """Split a monolithic pkl into train/val/test pkls and point the config at
    them (``load_data.py:366-407``)."""
    import pickle

    from hydragnn_tpu.data.split import split_dataset
    from hydragnn_tpu.parallel.distributed import get_comm_size_and_rank

    _, rank = get_comm_size_and_rank()
    paths = config["Dataset"]["path"]
    if list(paths.values())[0].endswith(".pkl"):
        file_dir = paths["total"]
    else:
        file_dir = (
            f"{os.environ.get('SERIALIZED_DATA_PATH', os.getcwd())}"
            f"/serialized_dataset/{config['Dataset']['name']}.pkl"
        )
    with open(file_dir, "rb") as f:
        minmax_node = pickle.load(f)
        minmax_graph = pickle.load(f)
        total = pickle.load(f)
    trainset, valset, testset = split_dataset(
        total,
        config["NeuralNetwork"]["Training"]["perc_train"],
        config["Dataset"]["compositional_stratified_splitting"],
    )
    serialized_dir = os.path.dirname(file_dir)
    config["Dataset"]["path"] = {}
    for name, ds in zip(
        ["train", "validate", "test"], [trainset, valset, testset]
    ):
        serial_name = f"{config['Dataset']['name']}_{name}.pkl"
        config["Dataset"]["path"][name] = os.path.join(serialized_dir, serial_name)
        if isdist or rank == 0:
            with open(os.path.join(serialized_dir, serial_name), "wb") as f:
                pickle.dump(minmax_node, f)
                pickle.dump(minmax_graph, f)
                pickle.dump(ds, f)
