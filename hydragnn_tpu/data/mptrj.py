"""Real-format MPtrj ingestion (no pymatgen, no jarvis).

The MPtrj distribution (``MPtrj_2022.9_full.json``) is one JSON object:
``{mp_id: {frame_id: record}}`` where each record carries a pymatgen
``Structure`` dict (lattice matrix + sites with fractional/cartesian
coordinates and species), plus ``energy_per_atom`` /
``corrected_total_energy``, ``force`` [n,3], ``stress`` [3,3], ``magmom``
[n]. The reference parses it with pymatgen + jarvis
(``/root/reference/examples/mptrj/train.py:33-36,100-118``); this module
reads the same schema directly.

Graph construction mirrors the reference: **non-periodic** radius graph at
5.0 A capped at 50 neighbours (``train.py:67`` — the reference deliberately
uses ``RadiusGraph``, not the PBC variant, on these bulk frames), energy as
the graph target, forces as the node target, frames with max force norm
above 100 eV/A dropped (``train.py:74``).
"""

import json
import os
from typing import Iterator, List, Optional, Tuple

import numpy as np

from hydragnn_tpu.data.dataobj import GraphData
from hydragnn_tpu.data.elements import atomic_number
from hydragnn_tpu.data.radius_graph import radius_graph


def structure_from_dict(s: dict) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """pymatgen ``Structure.as_dict()`` -> (z [n], pos_cartesian [n,3],
    lattice [3,3]). Sites may carry ``xyz`` (cartesian) directly; otherwise
    cartesian = frac @ lattice_matrix (pymatgen row-vector convention)."""
    lattice = np.asarray(s["lattice"]["matrix"], dtype=np.float64)
    zs, pos = [], []
    for site in s["sites"]:
        species = site["species"]
        # dominant species on the site (occu-weighted argmax)
        el = max(species, key=lambda sp: sp.get("occu", 1.0))["element"]
        zs.append(atomic_number(el))
        if "xyz" in site:
            pos.append(site["xyz"])
        else:
            pos.append(np.asarray(site["abc"], dtype=np.float64) @ lattice)
    return (
        np.asarray(zs, dtype=np.int64),
        np.asarray(pos, dtype=np.float64),
        lattice,
    )


def iter_mptrj_entries(path: str, chunk: int = 1 << 22) -> Iterator[tuple]:
    """Stream ``(mp_id, frames_dict)`` pairs from the top level of an
    MPtrj JSON WITHOUT loading the whole file (the real
    ``MPtrj_2022.9_full.json`` is tens of GB; ``json.load`` would exhaust
    host RAM). Incremental scan: find each top-level key, then
    ``raw_decode`` just that entry's value from a growing buffer.

    A file that ends before the top-level closing brace raises (a
    truncated download must not silently train on a partial dataset —
    ``json.load`` would have raised too). ``chunk`` is the refill size
    (small values exercise the boundary handling in tests)."""
    decoder = json.JSONDecoder()
    with open(path) as f:
        buf = f.read(chunk)

        def _fill(need_more=True):
            nonlocal buf
            data = f.read(chunk)
            if not data and need_more:
                raise ValueError(f"truncated MPtrj JSON: {path}")
            buf += data
            return bool(data)

        # opening brace
        i = buf.find("{")
        while i < 0:
            _fill()
            i = buf.find("{")
        buf = buf[i + 1 :]
        while True:
            # next key or closing brace
            while True:
                stripped = buf.lstrip(" \t\r\n,")
                if stripped[:1] in ('"', "}"):
                    buf = stripped
                    break
                if not _fill(need_more=False):
                    raise ValueError(
                        f"truncated MPtrj JSON (no closing brace): {path}"
                    )
            if buf[:1] == "}":
                return
            # parse "key":
            key, end = _decode_growing(decoder, lambda: buf, _fill)
            buf = buf[end:].lstrip(" \t\r\n")
            while buf[:1] != ":":
                _fill()
                buf = buf.lstrip(" \t\r\n")
            buf = buf[1:].lstrip(" \t\r\n")
            # parse the value (one mp_id's frames dict)
            value, end = _decode_growing(decoder, lambda: buf, _fill)
            buf = buf[end:]
            yield key, value


def _decode_growing(decoder, get_buf, fill):
    """raw_decode against a growing buffer. Distinguishes an INCOMPLETE
    value (error at/near the end of the buffer, or an unterminated string
    whose closing quote hasn't arrived) from a genuine syntax error —
    the latter re-raises immediately instead of buffering the rest of a
    tens-of-GB file. Refill size doubles per retry so a large entry costs
    O(V) re-parses of geometric prefixes (~2x total), not O(V^2/chunk)."""
    rounds = 1
    at_eof = False
    while True:
        buf = get_buf()
        # strip per attempt: refills can land right after a ':' so the
        # value starts behind fresh whitespace raw_decode won't skip
        stripped = buf.lstrip(" \t\r\n")
        lead = len(buf) - len(stripped)
        try:
            value, end = decoder.raw_decode(stripped)
            return value, lead + end
        except json.JSONDecodeError as e:
            # incomplete if the error sits inside the final (possibly
            # split) token: non-string JSON tokens — numbers, literals,
            # \uXXXX escapes — are < 16 chars, so a failure in the last 16
            # chars means "need more bytes"; split strings report
            # "Unterminated string" at the string's start. Anything
            # earlier is a genuine syntax error: re-raise with position
            # instead of buffering the rest of a tens-of-GB file.
            incomplete = (
                e.pos >= len(stripped) - 16
                or e.msg.startswith("Unterminated string")
            )
            if not incomplete:
                raise
            if at_eof:
                raise ValueError(
                    "truncated MPtrj JSON (value incomplete at EOF)"
                ) from e
            for _ in range(rounds):
                if not fill(need_more=False):
                    # EOF mid-refill: the value may have JUST completed —
                    # one final decode decides truncated vs done
                    at_eof = True
                    break
            rounds = min(rounds * 2, 64)


def iter_mptrj(
    path: str,
    energy_per_atom: bool = True,
) -> Iterator[dict]:
    """Yield flat records: ``z, pos, lattice, energy, forces, stress,
    magmom, mp_id, frame_id`` from the nested two-level JSON (streamed —
    constant memory in the number of mp_ids)."""
    for mp_id, frames in iter_mptrj_entries(path):
        for frame_id, k in frames.items():
            z, pos, lattice = structure_from_dict(k["structure"])
            if energy_per_atom:
                energy = k.get("energy_per_atom")
                if energy is None:
                    total = k.get("corrected_total_energy")
                    if total is None:
                        raise KeyError(
                            f"{mp_id}/{frame_id}: record has neither "
                            "'energy_per_atom' nor 'corrected_total_energy'"
                        )
                    energy = total / len(z)
            else:
                energy = k.get("corrected_total_energy")
                if energy is None:
                    per_atom = k.get("energy_per_atom")
                    if per_atom is None:
                        # loud failure, mirroring extxyz.frame_to_graph —
                        # a malformed record must not train on a 0.0 label
                        raise KeyError(
                            f"{mp_id}/{frame_id}: record has neither "
                            "'corrected_total_energy' nor 'energy_per_atom'"
                        )
                    energy = per_atom * len(z)
            yield {
                "mp_id": mp_id,
                "frame_id": frame_id,
                "z": z,
                "pos": pos,
                "lattice": lattice,
                "energy": float(energy),
                "forces": np.asarray(k.get("force", []), dtype=np.float64),
                "stress": np.asarray(k.get("stress", []), dtype=np.float64),
                "magmom": np.asarray(
                    k.get("magmom") if k.get("magmom") is not None else [],
                    dtype=np.float64,
                ),
            }


def load_mptrj(
    path: str,
    radius: float = 5.0,
    max_neighbours: int = 50,
    energy_per_atom: bool = True,
    forces_norm_threshold: Optional[float] = 100.0,
    num_samples: Optional[int] = None,
) -> List[GraphData]:
    """MPtrj JSON -> [GraphData] with graph energy + node forces targets."""
    out: List[GraphData] = []
    for rec in iter_mptrj(path, energy_per_atom):
        forces = rec["forces"]
        if (
            forces_norm_threshold is not None
            and forces.size
            and np.linalg.norm(forces, axis=1).max() > forces_norm_threshold
        ):
            continue
        pos = rec["pos"].astype(np.float32)
        # node features [z, x, y, z-coord] — the reference's MPtrj pipeline
        # feeds cartesian coordinates as node features alongside the atomic
        # number (/root/reference/examples/mptrj/train.py:143,234-235 with
        # input_node_features [0,1,2,3]): an invariant MLP node head can
        # only learn a force field if directional information reaches it.
        # coordinates are centered per-frame (forces are translation
        # invariant; absolute box offsets only ill-condition the first layer)
        d = GraphData(
            x=np.concatenate(
                [
                    rec["z"].astype(np.float32).reshape(-1, 1),
                    pos - pos.mean(axis=0, keepdims=True),
                ],
                axis=1,
            ),
            pos=pos,
        )
        d.edge_index = radius_graph(pos, radius, max_neighbours)
        lengths = np.linalg.norm(pos[d.edge_index[0]] - pos[d.edge_index[1]], axis=1)
        d.edge_attr = lengths.astype(np.float32).reshape(-1, 1)
        d.targets = [np.asarray([rec["energy"]], np.float32)]
        d.target_types = ["graph"]
        if forces.size:
            d.targets.append(forces.astype(np.float32))
            d.target_types.append("node")
        d.extras["mp_id"] = rec["mp_id"]
        if rec["stress"].size:
            d.extras["stress"] = rec["stress"].astype(np.float32)
        if rec["magmom"].size:
            d.extras["magmom"] = rec["magmom"].astype(np.float32)
        out.append(d)
        if num_samples is not None and len(out) >= num_samples:
            break
    return out


def write_mptrj_json(path: str, records: List[dict]):
    """Serialize flat records (as :func:`iter_mptrj` yields) back into the
    nested MPtrj schema — lets the offline example materialize synthetic
    trajectories in the real format so the real parser is the single
    ingestion path (and gives tests a round-trip)."""
    nested: dict = {}
    for rec in records:
        lattice = np.asarray(rec["lattice"], dtype=np.float64)
        inv = np.linalg.inv(lattice)
        sites = []
        from hydragnn_tpu.data.elements import symbol

        for zz, xyz in zip(rec["z"], np.asarray(rec["pos"], dtype=np.float64)):
            sites.append(
                {
                    "species": [{"element": symbol(int(zz)), "occu": 1.0}],
                    "xyz": [float(v) for v in xyz],
                    "abc": [float(v) for v in xyz @ inv],
                }
            )
        entry = {
            "structure": {
                "lattice": {"matrix": lattice.tolist()},
                "sites": sites,
            },
            "energy_per_atom": float(rec["energy"]) / (
                1 if rec.get("energy_is_per_atom", True) else len(rec["z"])
            ),
            "corrected_total_energy": float(rec["energy"])
            * (len(rec["z"]) if rec.get("energy_is_per_atom", True) else 1),
            "force": np.asarray(rec["forces"], dtype=np.float64).tolist(),
            "stress": np.asarray(rec.get("stress", np.zeros((3, 3)))).tolist(),
            "magmom": np.asarray(
                rec.get("magmom", np.zeros(len(rec["z"])))
            ).tolist(),
        }
        nested.setdefault(rec["mp_id"], {})[rec["frame_id"]] = entry
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    with open(path, "w") as f:
        json.dump(nested, f)
