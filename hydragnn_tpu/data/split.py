"""Dataset splitting.

Parity with ``hydragnn/preprocess/compositional_data_splitting.py:109-155``
(stratified train/val/test preserving element-composition categories) and
``preprocess/load_data.py:300-318`` (plain proportional split).
"""

import collections
import math
from typing import List

import numpy as np
from sklearn.model_selection import StratifiedShuffleSplit

from hydragnn_tpu.data.dataobj import GraphData


def _dataset_categories(dataset: List[GraphData]):
    """Encode each graph's element composition as an integer category
    (``compositional_data_splitting.py:54-71``)."""
    max_graph_size = max(d.num_nodes for d in dataset)
    power_ten = math.ceil(math.log10(max(max_graph_size, 2)))
    elements = sorted(
        set(float(e) for d in dataset for e in np.unique(d.x[:, 0]))
    )
    element_index = {e: i for i, e in enumerate(elements)}
    categories = []
    for d in dataset:
        vals, counts = np.unique(d.x[:, 0], return_counts=True)
        cat = 0
        for v, c in zip(vals, counts):
            cat += int(c) * (10 ** (power_ten * element_index[float(v)]))
        categories.append(cat)
    return categories


def _duplicate_singletons(dataset, categories):
    """Duplicate category-unique samples so stratified splitting can place a
    member on each side (``compositional_data_splitting.py:74-92``)."""
    counter = collections.Counter(categories)
    extra, extra_cat = [], []
    for d, c in zip(dataset, categories):
        if counter[c] == 1:
            extra.append(d.clone())
            extra_cat.append(c)
    return list(dataset) + extra, list(categories) + extra_cat


def _partition(dataset, categories, train_size):
    sss = StratifiedShuffleSplit(n_splits=1, train_size=train_size, random_state=0)
    idx_a, idx_b = next(sss.split(dataset, categories))
    return [dataset[i] for i in idx_a], [dataset[i] for i in idx_b]


def compositional_stratified_splitting(dataset, perc_train):
    categories = _dataset_categories(dataset)
    dataset, categories = _duplicate_singletons(dataset, categories)
    trainset, val_test = _partition(dataset, categories, perc_train)
    vt_categories = _dataset_categories(val_test)
    val_test, vt_categories = _duplicate_singletons(val_test, vt_categories)
    valset, testset = _partition(val_test, vt_categories, 0.5)
    return trainset, valset, testset


def split_dataset(dataset, perc_train: float, stratify_splitting: bool):
    if not stratify_splitting:
        perc_val = (1 - perc_train) / 2
        n = len(dataset)
        a = int(n * perc_train)
        b = int(n * (perc_train + perc_val))
        return dataset[:a], dataset[a:b], dataset[b:]
    return compositional_stratified_splitting(dataset, perc_train)


def stratified_subsample(dataset, subsample_percentage: float, verbosity=0):
    """Stratified subsample (``preprocess/utils.py:295-336``): category is
    the sorted per-type frequency signature in base 100."""
    categories = []
    for d in dataset:
        freqs = np.bincount(d.x[:, 0].astype(np.int64))
        freqs = sorted(int(f) for f in freqs if f > 0)
        cat = 0
        for i, f in enumerate(freqs):
            cat += f * (100 ** i)
        categories.append(cat)
    sss = StratifiedShuffleSplit(
        n_splits=1, train_size=subsample_percentage, random_state=0
    )
    idx, _ = next(sss.split(dataset, categories))
    return [dataset[i] for i in idx]
