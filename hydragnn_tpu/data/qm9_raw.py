"""Real-format QM9 ingestion (no torch_geometric, no rdkit, no network).

Reads the actual QM9 distribution in either of its two public layouts:

1. **PyG raw layout** — ``gdb9.sdf`` (3-D structures, MDL V2000 blocks) +
   ``gdb9.sdf.csv`` (19 properties per molecule) + ``uncharacterized.txt``
   (3054 failed-consistency indices to skip). This is what
   ``torch_geometric.datasets.QM9`` downloads and what the reference's
   ``examples/qm9/qm9.py:55-57`` consumes via its ``pre_transform``
   (``/root/reference/examples/qm9/qm9.py:15-22``).
2. **Original Ramakrishnan layout** — a directory of ``dsgdb9nsd_*.xyz``
   files, properties on the comment line, ``*^`` float exponents.

Targets reproduce PyG's ``y`` exactly — same column order
(mu, alpha, homo, lumo, gap, r2, zpve, U0, U298, H298, G298, Cv,
U0_atom, U298_atom, H298_atom, G298_atom, A, B, C) and same unit
conversions (Hartree -> eV, kcal/mol -> eV) — so ``y[10]`` is the free
energy the reference example trains on and MAEs are comparable number for
number.
"""

import csv
import os
from typing import List, Optional, Sequence

import numpy as np

from hydragnn_tpu.data.dataobj import GraphData
from hydragnn_tpu.data.elements import atomic_number
from hydragnn_tpu.data.radius_graph import radius_graph

HAR2EV = 27.211386246
KCALMOL2EV = 0.04336414

# names for the 19 PyG-ordered targets; index 10 = g298 (free energy)
TARGET_NAMES = [
    "mu", "alpha", "homo", "lumo", "gap", "r2", "zpve",
    "u0", "u298", "h298", "g298", "cv",
    "u0_atom", "u298_atom", "h298_atom", "g298_atom",
    "A", "B", "C",
]

# per-column unit conversion in PyG order (PyG QM9 `conversion` vector)
_CONVERSION = np.array(
    [1.0, 1.0, HAR2EV, HAR2EV, HAR2EV, 1.0, HAR2EV, HAR2EV, HAR2EV,
     HAR2EV, HAR2EV, 1.0, KCALMOL2EV, KCALMOL2EV, KCALMOL2EV,
     KCALMOL2EV, 1.0, 1.0, 1.0],
    dtype=np.float64,
)


def parse_sdf_v2000(text: str):
    """Parse an MDL SDF string into [(symbols, pos[n,3], bonds[m,2])].

    Fixed-width counts line (3+3 chars) with a whitespace fallback; bond
    atom indices returned 0-based. Property blocks between molecules are
    skipped; molecules are delimited by ``$$$$``.
    """
    mols = []
    for block in text.split("$$$$"):
        lines = block.strip("\n").split("\n")
        # skip leading blank lines left by the delimiter
        while lines and not lines[0].strip():
            lines = lines[1:]
        if len(lines) < 4:
            continue
        counts = lines[3]
        try:
            natoms = int(counts[0:3])
            nbonds = int(counts[3:6])
        except ValueError:
            fields = counts.split()
            natoms, nbonds = int(fields[0]), int(fields[1])
        symbols, pos = [], []
        for ln in lines[4 : 4 + natoms]:
            fields = ln.split()
            pos.append([float(fields[0]), float(fields[1]), float(fields[2])])
            symbols.append(fields[3])
        bonds = []
        for ln in lines[4 + natoms : 4 + natoms + nbonds]:
            try:
                a, b = int(ln[0:3]), int(ln[3:6])
            except ValueError:
                fields = ln.split()
                a, b = int(fields[0]), int(fields[1])
            bonds.append([a - 1, b - 1])
        mols.append(
            (
                symbols,
                np.asarray(pos, dtype=np.float32),
                np.asarray(bonds, dtype=np.int64).reshape(-1, 2),
            )
        )
    return mols


def read_gdb9_csv(path: str) -> np.ndarray:
    """``gdb9.sdf.csv`` -> [N, 19] float64 targets in PyG order with PyG
    unit conversions applied. CSV columns are
    mol_id, A, B, C, mu..cv, u0_atom..g298_atom; PyG reorders to put the
    rotational constants last (``y = cat([y[:, 3:], y[:, :3]])``)."""
    rows = []
    with open(path, newline="") as f:
        reader = csv.reader(f)
        header = next(reader)
        if not header[0].lower().startswith("mol"):
            # explicit raise (not assert): must survive python -O, or a
            # wrong-format file parses silently with misaligned targets
            raise ValueError(f"unexpected gdb9 csv header {header[:2]}")
        for rec in reader:
            if not rec:
                continue
            vals = np.asarray([float(v) for v in rec[1:20]], dtype=np.float64)
            rows.append(np.concatenate([vals[3:], vals[:3]]))
    return np.asarray(rows, dtype=np.float64) * _CONVERSION


def read_uncharacterized(path: str) -> List[int]:
    """0-based indices of molecules to skip. The real file is a 9-line
    banner, then ``   <index>  <name> ...`` rows, then a 2-line tail
    (count summary) — PyG slices ``[9:-2]`` and so do we; within that
    window, rows whose first token isn't an integer are ignored."""
    skips = []
    with open(path) as f:
        lines = f.read().split("\n")
    for ln in lines[9:-2]:
        tok = ln.split()
        if tok:
            try:
                skips.append(int(tok[0]) - 1)
            except ValueError:
                continue
    return skips


def _float_fortran(s: str) -> float:
    """QM9 xyz files use Fortran-ish '*^' exponents (1.23*^-4)."""
    return float(s.replace("*^", "e"))


def parse_dsgdb9nsd_xyz(path: str):
    """One ``dsgdb9nsd_*.xyz`` file -> (symbols, pos, y19).

    Comment line: ``gdb <id> A B C mu alpha homo lumo gap r2 zpve U0 U H G
    Cv``. Only 15 properties exist in this layout; the four atomization
    energies are absent and returned as NaN (PyG computes them from the sdf
    csv, which carries them precomputed).
    """
    with open(path) as f:
        lines = f.read().split("\n")
    natoms = int(lines[0].split()[0])
    props = lines[1].split()
    # props[0]='gdb', props[1]=index, props[2:17]=A..Cv
    raw = np.asarray([_float_fortran(v) for v in props[2:17]], dtype=np.float64)
    a_b_c, rest = raw[:3], raw[3:]  # mu..Cv (12 values)
    y = np.full(19, np.nan, dtype=np.float64)
    y[:12] = rest
    y[16:19] = a_b_c
    y[:12] *= _CONVERSION[:12]
    symbols, pos = [], []
    for ln in lines[2 : 2 + natoms]:
        fields = ln.split()
        symbols.append(fields[0])
        pos.append([_float_fortran(v) for v in fields[1:4]])
    return symbols, np.asarray(pos, dtype=np.float32), y


class QM9RawDataset:
    """List-like dataset of GraphData parsed from a real QM9 tree.

    ``root`` may contain ``gdb9.sdf`` (+ ``gdb9.sdf.csv``,
    ``uncharacterized.txt``) or a set of ``dsgdb9nsd_*.xyz`` files.
    ``target_index`` selects one PyG-ordered property as the graph target
    (default 10 = free energy, the reference example's choice);
    ``per_atom=True`` divides it by the atom count
    (``data.y[:, 10] / len(data.x)``, reference ``qm9.py:19``).
    ``edges='radius'`` builds radius graphs (our pipeline recomputes edge
    structure, like the reference's serialized loader); ``'bonds'`` keeps
    the SDF bond list as undirected edges (PyG-QM9 semantics).
    """

    def __init__(
        self,
        root: str,
        target_index: int = 10,
        per_atom: bool = True,
        edges: str = "radius",
        radius: float = 7.0,
        max_neighbours: int = 5,
        num_samples: Optional[int] = None,
    ):
        self.samples: List[GraphData] = []
        sdf = os.path.join(root, "gdb9.sdf")
        if os.path.exists(sdf):
            mols = parse_sdf_v2000(open(sdf).read())
            targets = read_gdb9_csv(sdf + ".csv")
            skip_path = os.path.join(root, "uncharacterized.txt")
            skips = set(
                read_uncharacterized(skip_path)
                if os.path.exists(skip_path)
                else []
            )
            if len(mols) != targets.shape[0]:
                raise ValueError(
                    f"sdf has {len(mols)} molecules but csv has "
                    f"{targets.shape[0]} rows — misaligned inputs"
                )
            it = (
                (i, syms, pos, bonds, targets[i])
                for i, (syms, pos, bonds) in enumerate(mols)
            )
        else:
            files = sorted(
                f for f in os.listdir(root)
                if f.startswith("dsgdb9nsd_") and f.endswith(".xyz")
            )
            if not files:
                raise FileNotFoundError(
                    f"no gdb9.sdf and no dsgdb9nsd_*.xyz under {root!r}"
                )
            skips = set()

            def _gen():
                for i, fn in enumerate(files):
                    syms, pos, y = parse_dsgdb9nsd_xyz(os.path.join(root, fn))
                    yield i, syms, pos, np.zeros((0, 2), np.int64), y

            it = _gen()

        for i, syms, pos, bonds, y in it:
            if i in skips:
                continue
            if num_samples is not None and len(self.samples) >= num_samples:
                break
            z = np.asarray([atomic_number(s) for s in syms], dtype=np.float32)
            d = GraphData(x=z.reshape(-1, 1), pos=pos, y=y.astype(np.float32))
            if edges == "bonds" and bonds.size:
                und = np.concatenate([bonds, bonds[:, ::-1]], axis=0)
                d.edge_index = und.T.astype(np.int64)
            else:
                d.edge_index = radius_graph(pos, radius, max_neighbours)
            t = float(y[target_index])
            if per_atom:
                t /= len(z)
            d.targets = [np.asarray([t], dtype=np.float32)]
            d.target_types = ["graph"]
            self.samples.append(d)

    def __len__(self):
        return len(self.samples)

    def __getitem__(self, i):
        return self.samples[i]

    def __iter__(self):
        return iter(self.samples)


def write_qm9_sdf(
    root: str,
    molecules: Sequence,
    targets: np.ndarray,
    skips: Sequence[int] = (),
):
    """Write (symbols, pos) molecules + a [N,19] RAW-unit target table in
    the exact gdb9 layout (sdf + csv + uncharacterized.txt). Used by the
    offline example to materialize its synthetic molecules in the real
    format so the real parser is the one code path; also handy for tests.
    ``targets`` must be in CSV (raw) units and CSV column order
    (A,B,C,mu..cv,u0_atom..g298_atom) — exactly what the file stores.
    """
    os.makedirs(root, exist_ok=True)
    with open(os.path.join(root, "gdb9.sdf"), "w") as f:
        for mi, (symbols, pos) in enumerate(molecules):
            f.write(f"gdb_{mi + 1}\n  written by hydragnn_tpu\n\n")
            f.write(f"{len(symbols):3d}{0:3d}  0  0  0  0  0  0  0  0999 V2000\n")
            for s, p in zip(symbols, pos):
                f.write(
                    f"{p[0]:10.4f}{p[1]:10.4f}{p[2]:10.4f} {s:<3s}"
                    " 0  0  0  0  0  0  0  0  0  0  0  0\n"
                )
            f.write("M  END\n$$$$\n")
    cols = ["mol_id", "A", "B", "C", "mu", "alpha", "homo", "lumo", "gap",
            "r2", "zpve", "u0", "u298", "h298", "g298", "cv",
            "u0_atom", "u298_atom", "h298_atom", "g298_atom"]
    with open(os.path.join(root, "gdb9.sdf.csv"), "w") as f:
        f.write(",".join(cols) + "\n")
        for mi, row in enumerate(np.asarray(targets, dtype=np.float64)):
            f.write(
                f"gdb_{mi + 1}," + ",".join(f"{v:.8g}" for v in row) + "\n"
            )
    with open(os.path.join(root, "uncharacterized.txt"), "w") as f:
        f.write("\n" * 9)  # banner lines, as in the real file
        for s in skips:
            f.write(f"  {int(s) + 1}  dummy\n")
        # tail line, as in the real file (with the trailing newline it
        # occupies the [-2:] slice read_uncharacterized excludes)
        f.write(f"{len(list(skips))} compounds\n")
