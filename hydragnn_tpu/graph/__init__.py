from hydragnn_tpu.graph.batch import (
    GraphBatch,
    collate_graphs,
    pad_sizes_for,
    stack_batches,
)
from hydragnn_tpu.graph.segment import (
    segment_sum,
    segment_mean,
    segment_max,
    segment_min,
    segment_std,
    segment_softmax,
    segment_softmax_unnorm,
    segment_moments_fused,
    segment_minmax_fused,
    segment_count,
)
