"""Segment reductions — the substrate of message passing on TPU.

The reference's conv stacks lean on torch_scatter/torch_sparse CUDA kernels
(SURVEY.md §2.4). On TPU the idiomatic equivalent is ``jax.ops.segment_sum``
and friends: XLA lowers them to sorted-scatter programs it can fuse with the
surrounding elementwise work, keeping everything in registers/VMEM instead of
bouncing through HBM.

All ops take static ``num_segments`` (XLA needs static output shapes) and are
safe under padding: padded edges must carry zeroed data or be masked by the
caller; padded segments simply produce the reduction identity.
"""

import jax
import jax.numpy as jnp

_BIG = 1e9  # sentinel for min/max identities; float32-safe


def segment_sum(data, segment_ids, num_segments):
    from hydragnn_tpu.ops import pallas_segments_enabled, segment_sum_onehot

    # scatter-adds in sub-f32 dtypes are pathologically slow on TPU (measured
    # 14x on v5e under bf16 mixed precision) AND lose accumulation precision;
    # run the reduction in f32, hand back the caller's dtype. Upcast BEFORE
    # the pallas dispatch — its kernel and custom VJP are f32-only.
    in_dtype = data.dtype
    if in_dtype in (jnp.bfloat16, jnp.float16):
        data = data.astype(jnp.float32)
    if data.ndim == 2 and pallas_segments_enabled(num_segments, data.shape[1]):
        out = segment_sum_onehot(data, segment_ids, num_segments)
    else:
        out = jax.ops.segment_sum(data, segment_ids, num_segments=num_segments)
    return out.astype(in_dtype) if out.dtype != in_dtype else out


def segment_count(segment_ids, num_segments, weights=None):
    """Number of elements per segment (in-degree when ids are edge receivers)."""
    ones = (
        jnp.ones(segment_ids.shape[0], dtype=jnp.float32)
        if weights is None
        else weights
    )
    return jax.ops.segment_sum(ones, segment_ids, num_segments=num_segments)


def segment_mean(data, segment_ids, num_segments):
    total = segment_sum(data, segment_ids, num_segments)
    count = segment_count(segment_ids, num_segments)
    count = jnp.maximum(count, 1.0)
    return total / count.reshape((-1,) + (1,) * (data.ndim - 1))


def segment_max(data, segment_ids, num_segments, fill=0.0, has=None):
    """Max per segment; empty segments get ``fill`` (reference semantics: padded
    nodes should see 0, not -inf, so downstream matmuls stay finite).

    ``has``: optional precomputed [num_segments]-ish non-empty mask — callers
    that already ran a counting scatter (PNA's fused moments pass) supply it
    to avoid a redundant segment_count scatter."""
    out = jax.ops.segment_max(data, segment_ids, num_segments=num_segments)
    if has is None:
        has = segment_count(segment_ids, num_segments) > 0
    has = has.reshape((-1,) + (1,) * (data.ndim - 1))
    return jnp.where(has, jnp.where(jnp.isfinite(out), out, fill), fill)


def segment_min(data, segment_ids, num_segments, fill=0.0, has=None):
    out = jax.ops.segment_min(data, segment_ids, num_segments=num_segments)
    if has is None:
        has = segment_count(segment_ids, num_segments) > 0
    has = has.reshape((-1,) + (1,) * (data.ndim - 1))
    return jnp.where(has, jnp.where(jnp.isfinite(out), out, fill), fill)


def segment_minmax_fused(data, segment_ids, num_segments, fill=0.0, has=None):
    """(min, max) per segment from ONE scatter pass.

    Packs ``[data, -data]`` on the feature axis so a single segment-max
    scatter yields both extremes (max of ``-data`` is ``-min``). At
    small-graph batch shapes the scatter PASS, not the flops, is the cost
    (measured ~0.5 ms/pass on v5e at E=18k, D=64) — PNA runs this instead
    of separate min/max scatters.
    """
    d = data.shape[1]
    packed = jnp.concatenate([data, -data], axis=-1)
    out = jax.ops.segment_max(packed, segment_ids, num_segments=num_segments)
    if has is None:
        has = segment_count(segment_ids, num_segments) > 0
    has = has.reshape((-1,) + (1,) * (data.ndim - 1))
    mx_raw = out[:, :d]
    mn_raw = -out[:, d:]
    mx = jnp.where(has, jnp.where(jnp.isfinite(mx_raw), mx_raw, fill), fill)
    mn = jnp.where(has, jnp.where(jnp.isfinite(mn_raw), mn_raw, fill), fill)
    return mn, mx


def segment_std(data, segment_ids, num_segments, eps=1e-5):
    """Per-segment standard deviation, PNA-style: sqrt(relu(E[x^2]-E[x]^2)+eps).

    Matches PyG PNAConv's ``std`` aggregator numerics (reference uses it via
    ``models/PNAStack.py:28``) so degree-scaler statistics line up.
    """
    mean = segment_mean(data, segment_ids, num_segments)
    mean_sq = segment_mean(data * data, segment_ids, num_segments)
    var = jax.nn.relu(mean_sq - mean * mean)
    return jnp.sqrt(var + eps)


def segment_moments_fused(data, segment_ids, num_segments, weights=None):
    """(sum, count, sum_of_squares) per segment from ONE scatter pass.

    XLA fallback counterpart of the pallas ``segment_moments`` kernel: packs
    data / data^2 / count-weights on the feature axis so a single segment
    scatter produces all three statistics (scatter passes, not flops, are
    the hot cost at small-graph scale — measured on v5e, bench.py).
    ``weights``: optional [E] count weights (e.g. an edge mask).
    """
    d = data.shape[1]
    w = (
        jnp.ones((data.shape[0],), jnp.float32)
        if weights is None
        else weights.astype(jnp.float32)
    )
    packed = jnp.concatenate([data, data * data, w[:, None]], axis=-1)
    s = segment_sum(packed, segment_ids, num_segments)
    return s[:, :d], s[:, -1:], s[:, d : 2 * d]


def segment_softmax_unnorm(logits, segment_ids, num_segments, mask=None):
    """Masked, max-shifted ``exp`` — the stable-softmax numerator terms.

    Shared prologue of :func:`segment_softmax` and fused-attention callers
    (GAT) that fold the normalizer into their aggregation scatter: returns
    ``exp(logits - segmax)`` with padded elements exactly zero, so
    ``segment_sum`` of the result is the softmax denominator.
    """
    if mask is not None:
        m = mask.reshape(mask.shape + (1,) * (logits.ndim - 1))
        logits = jnp.where(m, logits, -_BIG)
    seg_max = jax.ops.segment_max(logits, segment_ids, num_segments=num_segments)
    seg_max = jnp.where(jnp.isfinite(seg_max), seg_max, 0.0)
    unnorm = jnp.exp(logits - seg_max[segment_ids])
    if mask is not None:
        m = mask.reshape(mask.shape + (1,) * (logits.ndim - 1))
        unnorm = jnp.where(m, unnorm, 0.0)
    return unnorm


def segment_softmax(logits, segment_ids, num_segments, mask=None):
    """Numerically-stable softmax within segments (GAT edge attention).

    ``mask`` (bool over elements) zeroes out padded edges so they contribute
    neither to the max nor the normalizer.
    """
    unnorm = segment_softmax_unnorm(logits, segment_ids, num_segments, mask)
    denom = segment_sum(unnorm, segment_ids, num_segments)
    denom = jnp.maximum(denom, 1e-16)
    return unnorm / denom[segment_ids]
