"""Statically-shaped padded graph batches.

The reference batches graphs with torch_geometric's ragged ``Batch`` — shapes
change every step, which is fine for eager CUDA but poison for XLA (every new
shape is a recompile). Here a batch is ONE static shape: node/edge/graph arrays
padded to fixed sizes, with a dedicated trailing *padding graph* that absorbs
all padding nodes and edges (so pooled/graph-level math needs no special
cases — the padding rows simply fall into graph ``G-1`` and are masked out).

This replaces the reference's variable-graph-size machinery
(``hydragnn/preprocess/utils.py:25-80`` detection + PyG dynamic batching) with
the TPU-idiomatic design: pad once, compile once.

Multi-task labels: the reference packs all heads into a flat ``data.y`` plus a
``y_loc`` index table (``hydragnn/preprocess/utils.py:237-278``) and re-slices
it every step (``train/train_validate_test.py:302-365``). We store one target
array per head instead — graph heads ``[G, dim]``, node heads ``[N, dim]`` —
which removes the index gymnastics from the hot loop entirely.
"""

from typing import Optional, Tuple

import numpy as np
import jax.numpy as jnp
from flax import struct


@struct.dataclass
class GraphBatch:
    """A padded multigraph batch (pytree; every field is a device array).

    Shapes: N = padded node count, E = padded edge count, G = padded graph
    count (always >= num real graphs + 1: the last slot is the padding graph).
    """

    x: jnp.ndarray  # [N, F] node input features
    pos: jnp.ndarray  # [N, 3] node positions
    senders: jnp.ndarray  # [E] int32, source node of each edge (j of j->i)
    receivers: jnp.ndarray  # [E] int32, target node of each edge
    edge_attr: Optional[jnp.ndarray]  # [E, De] or None
    node_graph: jnp.ndarray  # [N] int32, graph id of each node
    n_node: jnp.ndarray  # [G] int32
    n_edge: jnp.ndarray  # [G] int32
    node_mask: jnp.ndarray  # [N] bool, True on real nodes
    edge_mask: jnp.ndarray  # [E] bool
    graph_mask: jnp.ndarray  # [G] bool
    targets: Tuple[jnp.ndarray, ...] = ()  # per head: [G, d] or [N, d]
    # model-specific precomputed index arrays (e.g. DimeNet triplets),
    # padded to static budgets host-side
    extras: Optional[dict] = None

    @property
    def num_nodes(self) -> int:
        return self.x.shape[0]

    @property
    def num_edges(self) -> int:
        return self.senders.shape[0]

    @property
    def num_graphs(self) -> int:
        return self.n_node.shape[0]


def _round_up(value: int, multiple: int) -> int:
    return int(-(-value // multiple) * multiple)


def pad_sizes_for(
    max_nodes: int,
    max_edges: int,
    batch_size: int,
    node_multiple: int = 8,
    edge_multiple: int = 8,
    graph_multiple: int = 1,
) -> Tuple[int, int, int]:
    """Static pad sizes for a batch of up to ``batch_size`` graphs.

    Worst-case sizing (every graph maximal) plus one guaranteed padding node
    and one padding graph, rounded up so XLA tiles land on lane boundaries.
    ``graph_multiple``/``node_multiple`` should be divisible by the
    data-parallel axis size so sharded batches split evenly across devices.
    """
    n_pad = _round_up(batch_size * max_nodes + 1, node_multiple)
    e_pad = _round_up(max(batch_size * max_edges, 1), edge_multiple)
    g_pad = _round_up(batch_size + 1, graph_multiple)
    return n_pad, e_pad, g_pad


def pack_triplets(triplets, n_pad: int, t_pad: Optional[int] = None):
    """Pack per-sample DimeNet triplet tables into one padded extras dict.

    ``triplets``: list of ``(t_i, t_j, t_k, t_kj, t_ji, n_nodes, n_edges)``
    per sample, in batch order (node/edge offsets accumulate exactly as
    ``collate_graphs`` lays the samples out). Padded triplet slots point at
    the padding node ``n_pad - 1`` with mask False. ``t_pad`` defaults to
    the total rounded up to 8. The ONE canonical packer — the loader, the
    benches and the driver entry all route through here.
    """
    total = sum(t[0].shape[0] for t in triplets)
    if t_pad is None:
        t_pad = _round_up(max(total, 1), 8)
    if total > t_pad:
        raise ValueError(f"{total} triplets exceed t_pad={t_pad}")
    ti = np.full((t_pad,), n_pad - 1, np.int32)
    tj = np.full((t_pad,), n_pad - 1, np.int32)
    tk = np.full((t_pad,), n_pad - 1, np.int32)
    tkj = np.zeros((t_pad,), np.int32)
    tji = np.zeros((t_pad,), np.int32)
    tmask = np.zeros((t_pad,), bool)
    off_n = off_e = off_t = 0
    for a, b, c, kj, ji, n_nodes, n_edges in triplets:
        t = a.shape[0]
        ti[off_t : off_t + t] = a + off_n
        tj[off_t : off_t + t] = b + off_n
        tk[off_t : off_t + t] = c + off_n
        tkj[off_t : off_t + t] = kj + off_e
        tji[off_t : off_t + t] = ji + off_e
        tmask[off_t : off_t + t] = True
        off_t += t
        off_n += int(n_nodes)
        off_e += int(n_edges)
    return {
        "trip_i": ti,
        "trip_j": tj,
        "trip_k": tk,
        "trip_kj": tkj,
        "trip_ji": tji,
        "trip_mask": tmask,
    }


def stack_batches(batches):
    """Stack K same-shape collated batches along a new leading axis.

    Producer-side counterpart of the trainer's scan-based multi-step
    dispatch: one host->device transfer and ONE XLA dispatch then run K
    optimizer steps on device (``lax.scan``), amortizing per-step dispatch
    latency — the TPU answer to the reference's per-batch eager hot loop
    (``train/train_validate_test.py:463-520``), where each step pays full
    Python + launch overhead.
    """
    import jax

    return jax.tree_util.tree_map(lambda *xs: np.stack(xs), *batches)


def collate_graphs(
    samples,
    n_pad: int,
    e_pad: int,
    g_pad: int,
    head_types: Tuple[str, ...] = (),
    head_dims: Tuple[int, ...] = (),
    to_device: bool = False,
):
    """Collate a list of ``GraphData``-like samples into one padded batch.

    Each sample must expose numpy arrays: ``x [n,F]``, ``pos [n,3]``,
    ``edge_index [2,e]``, optional ``edge_attr [e,De]``, and (if ``head_types``
    given) ``targets`` — a list with one array per head (graph head: ``[d]``,
    node head: ``[n, d]``).

    Runs on the host in numpy: this is the producer side of the input
    pipeline; the arrays are shipped to HBM once per step.
    """
    num_graphs = len(samples)
    total_nodes = int(sum(s.x.shape[0] for s in samples))
    total_edges = int(sum(s.edge_index.shape[1] for s in samples))
    if num_graphs > g_pad - 1:
        raise ValueError(f"batch of {num_graphs} graphs exceeds g_pad-1={g_pad - 1}")
    if total_nodes > n_pad - 1:
        raise ValueError(f"{total_nodes} nodes exceed n_pad-1={n_pad - 1}")
    if total_edges > e_pad:
        raise ValueError(f"{total_edges} edges exceed e_pad={e_pad}")

    feat_dim = samples[0].x.shape[1]
    x = np.zeros((n_pad, feat_dim), dtype=np.float32)
    pos = np.zeros((n_pad, 3), dtype=np.float32)
    # padding edges point at the last node slot (always a padding node since
    # total_nodes <= n_pad - 1) and live in the padding graph.
    senders = np.full((e_pad,), n_pad - 1, dtype=np.int32)
    receivers = np.full((e_pad,), n_pad - 1, dtype=np.int32)
    edge_dim = None
    if samples[0].edge_attr is not None:
        edge_dim = samples[0].edge_attr.shape[1]
        edge_attr = np.zeros((e_pad, edge_dim), dtype=np.float32)
    node_graph = np.full((n_pad,), g_pad - 1, dtype=np.int32)
    n_node = np.zeros((g_pad,), dtype=np.int32)
    n_edge = np.zeros((g_pad,), dtype=np.int32)
    node_mask = np.zeros((n_pad,), dtype=bool)
    edge_mask = np.zeros((e_pad,), dtype=bool)
    graph_mask = np.zeros((g_pad,), dtype=bool)

    targets = []
    for t, d in zip(head_types, head_dims):
        if t == "graph":
            targets.append(np.zeros((g_pad, d), dtype=np.float32))
        else:
            targets.append(np.zeros((n_pad, d), dtype=np.float32))

    node_off = 0
    edge_off = 0
    for g, s in enumerate(samples):
        n = s.x.shape[0]
        e = s.edge_index.shape[1]
        x[node_off : node_off + n] = s.x
        if s.pos is not None:
            pos[node_off : node_off + n] = s.pos
        senders[edge_off : edge_off + e] = s.edge_index[0] + node_off
        receivers[edge_off : edge_off + e] = s.edge_index[1] + node_off
        if edge_dim is not None:
            edge_attr[edge_off : edge_off + e] = s.edge_attr
        node_graph[node_off : node_off + n] = g
        n_node[g] = n
        n_edge[g] = e
        node_mask[node_off : node_off + n] = True
        edge_mask[edge_off : edge_off + e] = True
        graph_mask[g] = True
        for ih, t in enumerate(head_types):
            tgt = np.asarray(s.targets[ih], dtype=np.float32)
            if t == "graph":
                targets[ih][g] = tgt.reshape(-1)
            else:
                targets[ih][node_off : node_off + n] = tgt.reshape(n, -1)
        node_off += n
        edge_off += e

    # padding nodes all sit in the padding graph; record its node count so
    # segment means over the padding graph stay well-defined.
    n_node[g_pad - 1] = n_pad - node_off
    n_edge[g_pad - 1] = e_pad - edge_off

    batch = GraphBatch(
        x=x,
        pos=pos,
        senders=senders,
        receivers=receivers,
        edge_attr=edge_attr if edge_dim is not None else None,
        node_graph=node_graph,
        n_node=n_node,
        n_edge=n_edge,
        node_mask=node_mask,
        edge_mask=edge_mask,
        graph_mask=graph_mask,
        targets=tuple(targets),
    )
    if to_device:
        import jax

        batch = jax.tree_util.tree_map(jnp.asarray, batch)
    return batch
