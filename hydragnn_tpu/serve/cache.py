"""Canonical-graph response cache: bounded LRU in front of dispatch.

Atomistic serving traffic repeats itself — the same relaxed structure is
scored again and again by screening loops, and the same molecule arrives
from many clients under different node orderings. A bucket slot costs a
padded micro-batch dispatch; a cache hit costs a hash. Two pieces:

- :func:`canonical_graph_key` — a **permutation-stable** digest of one
  :class:`~hydragnn_tpu.data.dataobj.GraphData`, computed PRE-collation
  (raw request graph, before any padding/packing). Reordering nodes
  (with edges relabeled accordingly) or reordering edge columns yields
  the SAME key; perturbing any float32 bit of coords/species/edge
  features, or rewiring any edge, yields a different one. GNN forward
  passes are permutation-equivariant, so two graphs with equal keys get
  byte-identical per-node answers up to the same relabeling — but the
  cache never relies on that: it only ever returns a response computed
  for the EXACT submitted byte content (key equality on content digests
  plus the full-stream fallback digest below).
- :class:`ResponseCache` — a thread-safe LRU bounded by entry count AND
  total payload bytes, keyed ``(tenant, model, version, graph_key)``.
  The model VERSION in the key is the staleness proof: a promote or
  rollback changes the active version, so every lookup after the swap
  misses by construction — invalidation (:meth:`invalidate`) is a
  memory-reclaim courtesy, not a correctness requirement.

Hash construction (1-round Weisfeiler–Lehman over content digests)::

    node_i   = H(x[i] bytes, pos[i] bytes)          # content, not index
    refine_i = H(node_i, sorted out-multiset of (node_j, edge_attr),
                         sorted in-multiset  of (node_j, edge_attr))
    edge_k   = H(refine_src, refine_dst, edge_attr[k] bytes)
    key      = H(counts, sorted(refine_*), sorted(edge_*))

Sorting the multisets is what buys permutation invariance; the WL
refinement round is what keeps duplicate-feature nodes from colliding
across non-isomorphic wirings (two identical atoms with different
neighborhoods refine to different digests). Digests are BLAKE2b-128 over
exact float32/int64 bytes — no rounding, so "collision-distinct for
perturbed coords" holds down to one ULP.
"""

import hashlib
import threading
from collections import OrderedDict
from typing import Dict, List, Optional, Tuple

import numpy as np

from hydragnn_tpu.utils.envparse import env_int

_DIGEST_SIZE = 16  # BLAKE2b-128: plenty for a cache key, half the hashing cost


def _h(*parts: bytes) -> bytes:
    h = hashlib.blake2b(digest_size=_DIGEST_SIZE)
    for p in parts:
        h.update(p)
    return h.digest()


def canonical_graph_key(graph) -> str:
    """Permutation-stable content digest of one request graph (hex).

    Invariant under any relabeling of nodes (with ``edge_index`` mapped
    through the same permutation) and any reordering of edge columns;
    sensitive to every float32 bit of ``x``/``pos``/``edge_attr`` and to
    the (directed) wiring itself.
    """
    x = np.ascontiguousarray(np.asarray(graph.x, np.float32))
    n = int(x.shape[0])
    pos = (
        None
        if graph.pos is None
        else np.ascontiguousarray(np.asarray(graph.pos, np.float32))
    )
    ei = (
        np.zeros((2, 0), np.int64)
        if graph.edge_index is None
        else np.ascontiguousarray(np.asarray(graph.edge_index, np.int64))
    )
    ea = (
        None
        if getattr(graph, "edge_attr", None) is None
        else np.ascontiguousarray(np.asarray(graph.edge_attr, np.float32))
    )
    m = int(ei.shape[1])
    # pass 1: per-node content digests (row bytes only — no indices)
    node = [
        _h(x[i].tobytes(), b"" if pos is None else pos[i].tobytes())
        for i in range(n)
    ]
    # pass 2: one WL refinement round, direction-aware, edge-attr-aware
    out_adj: List[List[bytes]] = [[] for _ in range(n)]
    in_adj: List[List[bytes]] = [[] for _ in range(n)]
    for k in range(m):
        s, d = int(ei[0, k]), int(ei[1, k])
        attr = b"" if ea is None else ea[k].tobytes()
        out_adj[s].append(node[d] + attr)
        in_adj[d].append(node[s] + attr)
    refined = [
        _h(
            node[i],
            b"\x00",
            *sorted(out_adj[i]),
            b"\x01",
            *sorted(in_adj[i]),
        )
        for i in range(n)
    ]
    # pass 3: edge digests over refined endpoints, then the sorted roll-up
    edges = sorted(
        _h(
            refined[int(ei[0, k])],
            refined[int(ei[1, k])],
            b"" if ea is None else ea[k].tobytes(),
        )
        for k in range(m)
    )
    return _h(
        np.int64(n).tobytes(),
        np.int64(m).tobytes(),
        *sorted(refined),
        b"\x02",
        *edges,
    ).hex()


def _payload_bytes(heads: List[np.ndarray]) -> int:
    return int(sum(np.asarray(h).nbytes for h in heads))


class ResponseCache:
    """Bounded LRU of per-head response arrays, keyed
    ``(tenant, model, version, graph_key)``.

    Thread-safe; sized by both entry count (``capacity``) and payload
    bytes (``max_bytes``) — whichever bound bites first evicts from the
    LRU tail. Stored arrays are the exact ``jax.device_get`` results a
    dispatch produced; :meth:`get` hands back copies so a caller
    mutating its answer cannot poison later hits.
    """

    def __init__(self, capacity: int = 1024, max_bytes: int = 64 << 20,
                 metrics=None):
        if capacity < 1:
            raise ValueError("cache capacity must be >= 1")
        if max_bytes < 1:
            raise ValueError("cache max_bytes must be >= 1")
        self.capacity = int(capacity)
        self.max_bytes = int(max_bytes)
        self.metrics = metrics  # ServeMetrics (or None): cache_* counters
        self._lock = threading.Lock()
        self._entries: "OrderedDict[Tuple, Tuple[List[np.ndarray], int]]" = (
            OrderedDict()
        )
        self._bytes = 0
        # local counters so the cache is inspectable without a ServeMetrics
        self.hits = 0
        self.misses = 0
        self.evictions = 0

    @classmethod
    def from_env(cls, spec: Optional[Dict] = None, metrics=None,
                 ) -> Optional["ResponseCache"]:
        """Build a cache from a spec section + ``HYDRAGNN_CACHE_*`` env
        knobs (env wins). Returns None when caching is disabled
        (``HYDRAGNN_CACHE=0`` overrides a spec that enables it;
        ``HYDRAGNN_CACHE=1`` enables with defaults when no spec does)."""
        spec = dict(spec or {})
        enabled = env_int(
            "HYDRAGNN_CACHE", 1 if spec.get("enabled", bool(spec)) else 0
        )
        if not enabled:
            return None
        return cls(
            capacity=env_int(
                "HYDRAGNN_CACHE_CAPACITY",
                int(spec.get("capacity", 1024)), minimum=1,
            ),
            max_bytes=env_int(
                "HYDRAGNN_CACHE_MAX_BYTES",
                int(spec.get("max_bytes", 64 << 20)), minimum=1,
            ),
            metrics=metrics,
        )

    @staticmethod
    def key(graph_key: str, model: str, version: int,
            tenant: Optional[str] = None) -> Tuple:
        """The full cache key. Version is load-bearing: it is what makes
        a stale hit after promote/rollback impossible by construction."""
        return (tenant or "", str(model), int(version), graph_key)

    # ---- read/write ----------------------------------------------------
    def get(self, key: Tuple) -> Optional[List[np.ndarray]]:
        with self._lock:
            hit = self._entries.get(key)
            if hit is None:
                self.misses += 1
            else:
                self._entries.move_to_end(key)
                self.hits += 1
                heads = [np.array(h, copy=True) for h in hit[0]]
        if hit is None:
            if self.metrics is not None:
                self.metrics.on_cache_miss()
            return None
        if self.metrics is not None:
            self.metrics.on_cache_hit()
        return heads

    def put(self, key: Tuple, heads: List[np.ndarray]):
        stored = [np.array(h, copy=True) for h in heads]
        size = _payload_bytes(stored)
        if size > self.max_bytes:
            return  # one oversized answer must not wipe the whole cache
        evicted = 0
        freed = 0
        with self._lock:
            old = self._entries.pop(key, None)
            if old is not None:
                self._bytes -= old[1]
            self._entries[key] = (stored, size)
            self._bytes += size
            while (
                len(self._entries) > self.capacity
                or self._bytes > self.max_bytes
            ):
                _, (_, osize) = self._entries.popitem(last=False)
                self._bytes -= osize
                evicted += 1
                freed += osize
            self.evictions += evicted
            total = self._bytes
        if self.metrics is not None:
            if evicted:
                self.metrics.on_cache_evict(evicted)
            self.metrics.set_cache_bytes(total)

    # ---- invalidation --------------------------------------------------
    def invalidate(self, tenant: Optional[str] = None,
                   model: Optional[str] = None,
                   version: Optional[int] = None) -> int:
        """Drop matching entries (all of them with no filter). Returns
        the count dropped. Correctness never depends on this — version
        keys already fence stale reads — but promote/rollback call it so
        a superseded version's answers stop occupying budget."""
        with self._lock:
            doomed = [
                k for k in self._entries
                if (tenant is None or k[0] == tenant)
                and (model is None or k[1] == str(model))
                and (version is None or k[2] == int(version))
            ]
            for k in doomed:
                _, size = self._entries.pop(k)
                self._bytes -= size
            total = self._bytes
        if self.metrics is not None:
            self.metrics.set_cache_bytes(total)
        return len(doomed)

    # ---- introspection -------------------------------------------------
    def __len__(self):
        with self._lock:
            return len(self._entries)

    @property
    def bytes(self) -> int:
        with self._lock:
            return self._bytes

    def stats(self) -> Dict:
        with self._lock:
            return {
                "entries": len(self._entries),
                "bytes": self._bytes,
                "hits": self.hits,
                "misses": self.misses,
                "evictions": self.evictions,
                "hit_ratio": round(
                    self.hits / max(self.hits + self.misses, 1), 6
                ),
            }
