"""Frozen, versioned model registry for the predict server.

A registry entry is everything inference needs, immutable once
registered: the flax module, its restored ``params``/``batch_stats``,
and the head schema. Checkpoints load through the STRICT v2 loader
(``load_state_dict(..., fallback=False)`` — serving must never silently
answer from an older rolling checkpoint; that rule already guards
``run_prediction``, ``train/driver.py``) and any embedded ``train_meta``
is stripped: serving state is weights only.

Multiple models serve side by side (one entry per name); re-registering
a name bumps its version and new requests pick up the new entry at the
next micro-batch — in-flight batches keep the entry they were packed
with (each batch captures the frozen entry, not the name).

**Promote / rollback** (the hot-swap contract, ``serve/fleet.py``):
:meth:`ModelRegistry.promote` pins which version answers version-less
``get(name)`` calls; until the first promote, the latest registered
version serves (the historical behavior, unchanged).
:meth:`ModelRegistry.promote_checkpoint` is the atomic
load-register-promote: a candidate whose checkpoint fails CRC or the
strict v2 load raises BEFORE anything is registered or promoted — the
old version keeps serving and the registry holds no half-registered
state. Double-promoting the already-active version is an idempotent
no-op (no history entry, so a later :meth:`rollback` still reverts to
the genuinely previous version). :meth:`rollback` re-activates the
version that was serving before the last effective promote; both record
nothing but the activation — entries stay frozen and registered, so a
rolled-back candidate remains inspectable.

**Publication channel** (the train -> serve handoff,
``serve/canary.py``): :class:`CandidateChannel` is a file-backed queue
of candidate checkpoint SNAPSHOTS under one root directory. The
training side (rank 0, end-of-epoch cadence, ordered behind the
async-checkpoint writer so a snapshot is only ever taken of a durable
checkpoint) calls :func:`publish_candidate`; the canary controller
consumes ``pending()`` manifests, proves each candidate against live
traffic, and pins the promoted/rollback-base versions so retention GC
(:meth:`CandidateChannel.gc`, the keep-last-K mirror of the PR 1
rolling-checkpoint policy) can never collect a version the fleet might
still need to serve or revert to.
"""

import dataclasses
import glob
import json
import os
import re
import shutil
import threading
import time
from typing import Any, Dict, List, Optional, Tuple


@dataclasses.dataclass(frozen=True)
class ModelEntry:
    """One immutable serveable model version."""

    name: str
    version: int
    model: Any  # flax module (HydraBase subclass)
    params: Any  # restored param pytree
    batch_stats: Any  # restored BN stats pytree ({} when stat-free)
    output_type: Tuple[str, ...]  # per head: "graph" | "node"
    output_dim: Tuple[int, ...]
    source: str = "memory"  # checkpoint path or "memory"

    @property
    def key(self) -> Tuple[str, int]:
        return (self.name, self.version)


class ModelRegistry:
    """Name -> latest :class:`ModelEntry`, with version history."""

    def __init__(self):
        self._lock = threading.Lock()
        self._entries: Dict[str, List[ModelEntry]] = {}
        # activation history per name: [..., previous, ACTIVE]. Empty =
        # never explicitly promoted -> latest registered version serves.
        self._active: Dict[str, List[int]] = {}
        # fired as fn(name, new_active_version) after every EFFECTIVE
        # activation change (promote that moved, rollback) — the
        # response cache hangs its invalidation here. Mutated only at
        # server construction; called outside self._lock.
        self._listeners: List = []

    def add_activation_listener(self, fn):
        """Register ``fn(name, version)`` to run after each effective
        promote/rollback. Listeners run OUTSIDE the registry lock (a
        listener may call back into the registry) and exceptions are
        swallowed — observers must never fail an activation."""
        self._listeners.append(fn)

    def _notify_activation(self, name: str, version: int):
        for fn in list(self._listeners):
            try:
                fn(name, version)
            except Exception:
                pass

    def register(
        self,
        name: str,
        model,
        params,
        batch_stats=None,
        source: str = "memory",
    ) -> ModelEntry:
        """Freeze (model, weights) as the next version of ``name``."""
        with self._lock:
            version = len(self._entries.get(name, ())) + 1
            entry = ModelEntry(
                name=name,
                version=version,
                model=model,
                params=params,
                batch_stats=batch_stats if batch_stats is not None else {},
                output_type=tuple(model.output_type),
                output_dim=tuple(model.output_dim),
                source=source,
            )
            self._entries.setdefault(name, []).append(entry)
            return entry

    def load_checkpoint(
        self,
        checkpoint_name: str,
        arch_config: Optional[dict] = None,
        path: str = "./logs/",
        name: Optional[str] = None,
        verbosity: int = 0,
    ) -> ModelEntry:
        """Load ``<path>/<checkpoint_name>/<checkpoint_name>.pk`` into a
        fresh entry. ``arch_config`` is the derived Architecture section
        (post-``update_config``); when omitted it is read from the
        ``config.json`` the training driver saved next to the checkpoint.
        ``name`` defaults to the checkpoint name."""
        from hydragnn_tpu.models.create import create_model_config
        from hydragnn_tpu.train.checkpoint import (
            load_state_dict,
            pop_train_meta,
        )

        if arch_config is None:
            cfg_path = os.path.join(path, checkpoint_name, "config.json")
            with open(cfg_path, "r") as f:
                arch_config = json.load(f)["NeuralNetwork"]["Architecture"]
        model = create_model_config(dict(arch_config), verbosity)
        # strict: corruption/truncation aborts, no rolling fallback
        restored = load_state_dict(checkpoint_name, path=path, fallback=False)
        pop_train_meta(restored)
        if "params" not in restored:
            raise ValueError(
                f"checkpoint {checkpoint_name} has no 'params' section — "
                "not a model checkpoint"
            )
        return self.register(
            name or checkpoint_name,
            model,
            restored["params"],
            restored.get("batch_stats", {}),
            source=os.path.join(path, checkpoint_name),
        )

    def get(self, name: str, version: Optional[int] = None) -> ModelEntry:
        """The entry that should serve ``name``: the explicit ``version``
        when given, else the ACTIVE version (last promote; latest
        registered when nothing was ever promoted)."""
        with self._lock:
            history = self._entries.get(name)
            if not history:
                raise KeyError(f"no model registered under {name!r}")
            if version is None:
                stack = self._active.get(name)
                version = stack[-1] if stack else history[-1].version
            for entry in history:
                if entry.version == version:
                    return entry
            raise KeyError(f"model {name!r} has no version {version}")

    def active_version(self, name: str) -> int:
        """The version a version-less :meth:`get` would serve right now."""
        return self.get(name).version

    def promote(self, name: str, version: Optional[int] = None) -> ModelEntry:
        """Activate ``version`` of ``name`` (default: latest registered)
        for version-less :meth:`get` calls. In-flight micro-batches keep
        the entry they were packed with, so the swap lands exactly at a
        batch boundary — no response is ever computed by a mix of
        versions within one batch. Promoting the already-active version
        is an idempotent no-op (no activation-history entry). Raises
        ``KeyError`` (registry unchanged) for unknown names/versions."""
        changed = False
        with self._lock:
            history = self._entries.get(name)
            if not history:
                raise KeyError(f"no model registered under {name!r}")
            if version is None:
                version = history[-1].version
            entry = next(
                (e for e in history if e.version == version), None
            )
            if entry is None:
                raise KeyError(f"model {name!r} has no version {version}")
            stack = self._active.setdefault(name, [])
            current = stack[-1] if stack else history[-1].version
            if current == version and stack:
                return entry  # double-promote: idempotent, no notify
            if not stack:
                # seed with the implicit active so the first rollback
                # has a "before" to return to
                stack.append(current)
            if stack[-1] != version:
                stack.append(version)
                changed = True
        if changed:
            # outside the lock: a listener may call back into the
            # registry (and must not block promotes, threadlint-wise)
            self._notify_activation(name, version)
        return entry

    def rollback(self, name: str) -> ModelEntry:
        """Re-activate the version that served before the last effective
        promote. Raises ``ValueError`` when there is nothing to roll back
        to (never promoted, or already rolled back to the original)."""
        with self._lock:
            stack = self._active.get(name)
            if not stack or len(stack) < 2:
                raise ValueError(
                    f"model {name!r} has no previous promoted version to "
                    "roll back to"
                )
            stack.pop()
            version = stack[-1]
            history = self._entries.get(name, ())
            entry = next(
                (e for e in history if e.version == version), None
            )
            if entry is None:  # unreachable: entries are never removed
                raise KeyError(f"model {name!r} has no version {version}")
        self._notify_activation(name, version)
        return entry

    def promote_checkpoint(
        self,
        checkpoint_name: str,
        arch_config: Optional[dict] = None,
        path: str = "./logs/",
        name: Optional[str] = None,
        verbosity: int = 0,
    ) -> ModelEntry:
        """Atomic load + register + promote of a candidate checkpoint.

        The strict v2 load (CRC verification, no rolling fallback) runs
        FIRST: a corrupt or truncated candidate raises here with the
        registry untouched — no version is registered, the activation
        history does not move, and the old version keeps serving every
        request. Only a fully loaded candidate is registered (as the next
        version of ``name``) and promoted, as one registry transition."""
        serving_name = name or checkpoint_name
        try:
            # pin the CURRENT ACTIVE version first (not the latest
            # registered — a previously rolled-back candidate may be
            # newer): registering the candidate must not implicitly flip
            # serving onto it, and a later rollback() must have the
            # genuine pre-promote version to return to
            self.promote(serving_name, self.active_version(serving_name))
        except KeyError:
            pass  # first registration under this name: nothing to pin
        entry = self.load_checkpoint(
            checkpoint_name,
            arch_config=arch_config,
            path=path,
            name=name,
            verbosity=verbosity,
        )
        return self.promote(entry.name, entry.version)

    def names(self) -> List[str]:
        with self._lock:
            return sorted(self._entries)

    def describe(self) -> Dict[str, Dict]:
        """Registry summary for ``/healthz`` — ``version`` is the ACTIVE
        (serving) version, ``latest`` the newest registered one; they
        differ only mid-hot-swap or after a rollback."""
        with self._lock:
            out = {}
            for name, history in self._entries.items():
                stack = self._active.get(name)
                active = stack[-1] if stack else history[-1].version
                serving = next(
                    e for e in history if e.version == active
                )
                out[name] = {
                    "version": active,
                    "latest": history[-1].version,
                    "versions": len(history),
                    "output_type": list(serving.output_type),
                    "output_dim": list(serving.output_dim),
                    "source": serving.source,
                }
            return out

    def __len__(self):
        with self._lock:
            return len(self._entries)


# ---- candidate publication channel -----------------------------------------


class CandidateChannel:
    """File-backed train -> serve candidate queue under one root dir::

        <root>/candidates/cand-<seq:06d>.json   # manifest (commit point)
        <root>/versions/v<seq:06d>/<ck>/<ck>.pk # checkpoint SNAPSHOT
        <root>/promoted.json                    # {active_seq, base_seq}

    ``publish`` COPIES the checkpoint into a per-seq version directory
    before writing the manifest: the training side's rolling saves
    overwrite ``<name>.pk`` in place, so a consumer loading the
    publisher's live path could read a half-written or newer file. The
    snapshot directory keeps the ``<path>/<name>/<name>.pk`` layout the
    strict loader (and ``ServingFleet.promote``) already reads, and the
    atomic manifest write is the commit point — a consumer never sees a
    manifest whose snapshot is incomplete.

    Single publisher (training rank 0), any number of consumers. All
    methods are safe to call concurrently with a consumer's reads.
    """

    def __init__(self, root: str):
        self.root = root
        self._cand_dir = os.path.join(root, "candidates")
        self._ver_dir = os.path.join(root, "versions")

    # -- paths ---------------------------------------------------------------
    def manifest_path(self, seq: int) -> str:
        return os.path.join(self._cand_dir, f"cand-{int(seq):06d}.json")

    def version_dir(self, seq: int) -> str:
        """The snapshot dir for ``seq`` — usable directly as the ``path``
        of a strict checkpoint load or a fleet promote."""
        return os.path.join(self._ver_dir, f"v{int(seq):06d}")

    # -- publisher side ------------------------------------------------------
    def publish(self, checkpoint: str, path: str,
                **meta) -> Dict:
        """Snapshot ``<path>/<checkpoint>/<checkpoint>.pk`` (plus its
        ``config.json`` when present) as the next candidate version and
        commit its manifest. Extra ``meta`` (epoch, val_loss, run name)
        rides along for the controller's event payloads."""
        from hydragnn_tpu import coord

        seq = self.latest_seq() + 1
        src = os.path.join(path, checkpoint)
        src_pk = os.path.join(src, f"{checkpoint}.pk")
        if not os.path.exists(src_pk):
            raise FileNotFoundError(
                f"cannot publish {checkpoint!r}: {src_pk} does not exist"
            )
        dst = os.path.join(self.version_dir(seq), checkpoint)
        # a crashed previous publish may have left a manifest-less
        # version dir under this seq — overwrite it, the manifest never
        # committed so nothing can be reading it
        if os.path.isdir(dst):
            shutil.rmtree(dst)
        os.makedirs(dst, exist_ok=True)
        # copy to a temp name + rename so even the snapshot file itself
        # is never observable half-written
        tmp = os.path.join(dst, f".{checkpoint}.pk.tmp")
        shutil.copyfile(src_pk, tmp)
        os.replace(tmp, os.path.join(dst, f"{checkpoint}.pk"))
        cfg = os.path.join(src, "config.json")
        if os.path.exists(cfg):
            shutil.copyfile(cfg, os.path.join(dst, "config.json"))
        manifest = {
            "seq": seq,
            "checkpoint": checkpoint,
            "path": os.path.abspath(self.version_dir(seq)),
            "source_path": os.path.abspath(path),
            "ts": time.time(),
        }
        manifest.update(meta)
        coord.write_json(self.manifest_path(seq), manifest)
        return manifest

    # -- consumer side -------------------------------------------------------
    def _seqs(self) -> List[int]:
        out = []
        for p in glob.glob(os.path.join(self._cand_dir, "cand-*.json")):
            m = re.search(r"cand-(\d+)\.json$", p)
            if m:
                out.append(int(m.group(1)))
        return sorted(out)

    def latest_seq(self) -> int:
        seqs = self._seqs()
        return seqs[-1] if seqs else 0

    def read(self, seq: int) -> Optional[Dict]:
        from hydragnn_tpu import coord

        return coord.read_json(self.manifest_path(seq))

    def pending(self, after_seq: int = 0) -> List[Dict]:
        """Committed manifests with ``seq > after_seq``, oldest first."""
        out = []
        for seq in self._seqs():
            if seq <= after_seq:
                continue
            man = self.read(seq)
            if man is not None:
                out.append(man)
        return out

    # -- retention -----------------------------------------------------------
    def record_promotion(self, seq: int):
        """Pin ``seq`` as the ACTIVE published version; the previously
        active one becomes the rollback BASE pin. Both survive any GC —
        the fleet may be serving one and reverting onto the other."""
        from hydragnn_tpu import coord

        pins = coord.read_json(
            os.path.join(self.root, "promoted.json")
        ) or {}
        coord.write_json(
            os.path.join(self.root, "promoted.json"),
            {"active_seq": int(seq),
             "base_seq": pins.get("active_seq"),
             "ts": time.time()},
        )

    def pinned(self) -> set:
        from hydragnn_tpu import coord

        pins = coord.read_json(
            os.path.join(self.root, "promoted.json")
        ) or {}
        return {
            int(s) for s in (pins.get("active_seq"), pins.get("base_seq"))
            if s is not None
        }

    def gc(self, keep_last: int) -> List[int]:
        """Collect published versions outside the newest ``keep_last``,
        never touching the pinned active/rollback-base versions — the
        keep-last-K mirror of the training side's rolling-checkpoint
        retention. Manifest goes first (consumers discover through it),
        then the snapshot dir. Returns the collected seqs."""
        if keep_last < 1:
            raise ValueError("keep_last must be >= 1")
        seqs = self._seqs()
        keep = set(seqs[-keep_last:]) | self.pinned()
        removed = []
        for seq in seqs:
            if seq in keep:
                continue
            try:
                os.remove(self.manifest_path(seq))
            except OSError:
                continue  # already collected by a racing GC
            shutil.rmtree(self.version_dir(seq), ignore_errors=True)
            removed.append(seq)
        return removed


def publish_candidate(root: str, checkpoint: str, path: str,
                      keep_last: Optional[int] = None, **meta) -> Dict:
    """One-shot publish into the channel at ``root`` (the training-side
    convenience ``epoch_driver`` calls): snapshot + manifest, then
    retention GC when ``keep_last`` is given. Returns the manifest."""
    channel = CandidateChannel(root)
    manifest = channel.publish(checkpoint, path, **meta)
    if keep_last is not None:
        channel.gc(keep_last)
    return manifest
