"""Frozen, versioned model registry for the predict server.

A registry entry is everything inference needs, immutable once
registered: the flax module, its restored ``params``/``batch_stats``,
and the head schema. Checkpoints load through the STRICT v2 loader
(``load_state_dict(..., fallback=False)`` — serving must never silently
answer from an older rolling checkpoint; that rule already guards
``run_prediction``, ``train/driver.py``) and any embedded ``train_meta``
is stripped: serving state is weights only.

Multiple models serve side by side (one entry per name); re-registering
a name bumps its version and new requests pick up the new entry at the
next micro-batch — in-flight batches keep the entry they were packed
with (each batch captures the frozen entry, not the name).
"""

import dataclasses
import json
import os
import threading
from typing import Any, Dict, List, Optional, Tuple


@dataclasses.dataclass(frozen=True)
class ModelEntry:
    """One immutable serveable model version."""

    name: str
    version: int
    model: Any  # flax module (HydraBase subclass)
    params: Any  # restored param pytree
    batch_stats: Any  # restored BN stats pytree ({} when stat-free)
    output_type: Tuple[str, ...]  # per head: "graph" | "node"
    output_dim: Tuple[int, ...]
    source: str = "memory"  # checkpoint path or "memory"

    @property
    def key(self) -> Tuple[str, int]:
        return (self.name, self.version)


class ModelRegistry:
    """Name -> latest :class:`ModelEntry`, with version history."""

    def __init__(self):
        self._lock = threading.Lock()
        self._entries: Dict[str, List[ModelEntry]] = {}

    def register(
        self,
        name: str,
        model,
        params,
        batch_stats=None,
        source: str = "memory",
    ) -> ModelEntry:
        """Freeze (model, weights) as the next version of ``name``."""
        with self._lock:
            version = len(self._entries.get(name, ())) + 1
            entry = ModelEntry(
                name=name,
                version=version,
                model=model,
                params=params,
                batch_stats=batch_stats if batch_stats is not None else {},
                output_type=tuple(model.output_type),
                output_dim=tuple(model.output_dim),
                source=source,
            )
            self._entries.setdefault(name, []).append(entry)
            return entry

    def load_checkpoint(
        self,
        checkpoint_name: str,
        arch_config: Optional[dict] = None,
        path: str = "./logs/",
        name: Optional[str] = None,
        verbosity: int = 0,
    ) -> ModelEntry:
        """Load ``<path>/<checkpoint_name>/<checkpoint_name>.pk`` into a
        fresh entry. ``arch_config`` is the derived Architecture section
        (post-``update_config``); when omitted it is read from the
        ``config.json`` the training driver saved next to the checkpoint.
        ``name`` defaults to the checkpoint name."""
        from hydragnn_tpu.models.create import create_model_config
        from hydragnn_tpu.train.checkpoint import (
            load_state_dict,
            pop_train_meta,
        )

        if arch_config is None:
            cfg_path = os.path.join(path, checkpoint_name, "config.json")
            with open(cfg_path, "r") as f:
                arch_config = json.load(f)["NeuralNetwork"]["Architecture"]
        model = create_model_config(dict(arch_config), verbosity)
        # strict: corruption/truncation aborts, no rolling fallback
        restored = load_state_dict(checkpoint_name, path=path, fallback=False)
        pop_train_meta(restored)
        if "params" not in restored:
            raise ValueError(
                f"checkpoint {checkpoint_name} has no 'params' section — "
                "not a model checkpoint"
            )
        return self.register(
            name or checkpoint_name,
            model,
            restored["params"],
            restored.get("batch_stats", {}),
            source=os.path.join(path, checkpoint_name),
        )

    def get(self, name: str, version: Optional[int] = None) -> ModelEntry:
        with self._lock:
            history = self._entries.get(name)
            if not history:
                raise KeyError(f"no model registered under {name!r}")
            if version is None:
                return history[-1]
            for entry in history:
                if entry.version == version:
                    return entry
            raise KeyError(f"model {name!r} has no version {version}")

    def names(self) -> List[str]:
        with self._lock:
            return sorted(self._entries)

    def describe(self) -> Dict[str, Dict]:
        """Registry summary for ``/healthz``."""
        with self._lock:
            return {
                name: {
                    "version": history[-1].version,
                    "versions": len(history),
                    "output_type": list(history[-1].output_type),
                    "output_dim": list(history[-1].output_dim),
                    "source": history[-1].source,
                }
                for name, history in self._entries.items()
            }

    def __len__(self):
        with self._lock:
            return len(self._entries)
