"""Serving bucket plans: request -> static padding bucket -> padded batch.

The serving counterpart of the training loader's bucketed layouts
(``data/loaders.py``): a fixed, ascending family of
:class:`~hydragnn_tpu.data.loaders.BatchLayout` paddings, each the shape
signature of ONE pre-compiled predict executable. A request is routed to
the smallest bucket whose PER-GRAPH capacity covers it — node count AND
edge count (and triplet count for DimeNet layouts); a dense graph whose
edges overflow its node-natural bucket falls through to the next larger
one instead of failing. Batch packing is budget-greedy like
``_pack_indices``: requests accumulate until the next one would overflow
the bucket's padded sizes, so every packed batch fits its layout by
construction and never recompiles.

Sizing reuses the loader's own machinery (``_partition_node_bounds``
exact-DP boundaries, ``_layout_from_maxima`` worst-case pads) so a plan
derived from a sample of production graphs gives the same low-waste
shapes training already measured (94% padding efficiency on OC20-shaped
distributions, README).
"""

from dataclasses import dataclass, field
from typing import List, Optional, Sequence, Tuple

import numpy as np

from hydragnn_tpu.data.dataobj import GraphData
from hydragnn_tpu.data.loaders import (
    BatchLayout,
    BucketedLayout,
    _layout_from_maxima,
    _lcm,
    _partition_node_bounds,
    _sample_triplets,
    collate_for_layout,
)


class GraphTooLarge(ValueError):
    """The graph exceeds the largest bucket's per-graph capacity."""


@dataclass(frozen=True)
class BucketCapacity:
    """Per-graph admission limits for one bucket (a single request must
    fit a batch alone: ``n_pad`` reserves one padding node)."""

    max_nodes: int
    max_edges: int
    max_triplets: int = 0

    def admits(self, num_nodes: int, num_edges: int, num_triplets: int = 0):
        return (
            num_nodes <= self.max_nodes
            and num_edges <= self.max_edges
            and (self.max_triplets == 0 or num_triplets <= self.max_triplets)
        )


@dataclass
class ServingBucketPlan:
    """Ascending bucket layouts + per-bucket admission capacities.

    ``warmup_sample`` is a small :class:`GraphData` used to pre-compile
    every bucket's executable at startup (it must fit bucket 0, so it
    fits all)."""

    layouts: List[BatchLayout]
    capacities: List[BucketCapacity]
    warmup_sample: Optional[GraphData] = None
    node_bounds: List[int] = field(default_factory=list)

    def __post_init__(self):
        if not self.layouts:
            raise ValueError("a serving plan needs at least one bucket")
        if len(self.layouts) != len(self.capacities):
            raise ValueError("layouts and capacities must pair up")

    @property
    def num_buckets(self) -> int:
        return len(self.layouts)

    def request_sizes(self, graph: GraphData) -> Tuple[int, int, int]:
        """(nodes, edges, triplets) of one request — triplets computed
        (and cached on the sample) only for triplet-packing layouts."""
        t = 0
        if self.layouts[0].packs_triplets:
            t = int(_sample_triplets(graph)[0].shape[0])
        return int(graph.num_nodes), int(graph.num_edges), t

    def select(self, graph: GraphData) -> int:
        """Smallest admitting bucket, falling through to larger ones when
        edge/triplet counts overflow the node-natural bucket. Raises
        :class:`GraphTooLarge` when nothing admits the graph."""
        return self.admit(graph)[0]

    def admit(self, graph: GraphData) -> Tuple[int, Tuple[int, int, int]]:
        """One-pass admission: ``(bucket, (nodes, edges, triplets))`` —
        what the server's submit path needs, without re-deriving the
        sizes per check. Raises :class:`GraphTooLarge` when nothing
        admits the graph."""
        sizes = self.request_sizes(graph)
        n, e, t = sizes
        for b, cap in enumerate(self.capacities):
            if cap.admits(n, e, t):
                return b, sizes
        raise GraphTooLarge(
            f"graph with {n} nodes / {e} edges exceeds the largest serving "
            f"bucket (max {self.capacities[-1].max_nodes} nodes / "
            f"{self.capacities[-1].max_edges} edges); re-plan with larger "
            "buckets or partition the graph"
        )

    def natural_bucket(self, num_nodes: int) -> int:
        """The bucket the node count alone would pick — ``select`` beyond
        this index means an edge/triplet-overflow fallback."""
        for b, cap in enumerate(self.capacities):
            if num_nodes <= cap.max_nodes:
                return b
        return len(self.capacities) - 1

    def pack(self, graphs: Sequence[GraphData], bucket: int):
        """Collate admitted requests into bucket ``bucket``'s static
        shapes (inputs only — requests carry no targets). Returns the
        padded batch plus per-request (graph-row, node-offset, node-count)
        coordinates for slicing the model outputs back apart."""
        layout = self.layouts[bucket]
        batch = collate_for_layout(list(graphs), layout, with_targets=False)
        coords = []
        off = 0
        for g, sample in enumerate(graphs):
            n = int(sample.num_nodes)
            coords.append((g, off, n))
            off += n
        return batch, coords

    def fits_batch(
        self,
        bucket: int,
        acc_nodes: int,
        acc_edges: int,
        acc_trips: int,
        acc_graphs: int,
        sizes: Tuple[int, int, int],
    ) -> bool:
        """Would adding a request of ``sizes`` keep the accumulating
        batch inside bucket ``bucket``'s padded budgets? (The greedy
        packing rule of ``_pack_indices``, applied online.)"""
        lay = self.layouts[bucket]
        n, e, t = sizes
        return (
            acc_nodes + n <= lay.n_pad - 1
            and acc_edges + e <= lay.e_pad
            and (not lay.packs_triplets or acc_trips + t <= lay.t_pad)
            and acc_graphs + 1 <= lay.g_pad - 1
        )


def plan_from_samples(
    samples: Sequence[GraphData],
    max_batch_graphs: int = 8,
    num_buckets: int = 3,
    need_triplets: bool = False,
    need_neighbors: bool = False,
    headroom: float = 1.0,
) -> ServingBucketPlan:
    """Derive a serving plan from representative graphs (e.g. the
    training set or a traffic sample).

    Buckets are worst-case sized: a batch of ``max_batch_graphs`` graphs
    each at the bucket's observed maxima always fits, so admission is a
    pure per-graph check and packing never re-plans. ``headroom``
    multiplies the observed per-bucket node/edge maxima so production
    graphs slightly larger than the sample still admit (capacity grows
    with the pad)."""
    if not samples:
        raise ValueError("plan_from_samples needs at least one sample")
    if headroom < 1.0:
        raise ValueError("headroom must be >= 1.0")
    nodes = np.asarray([s.num_nodes for s in samples])
    edges = np.asarray([s.num_edges for s in samples])
    trips = np.zeros(len(samples), np.int64)
    kis = kos = np.ones(len(samples), np.int64)
    if need_triplets and not need_neighbors:
        trips = np.asarray(
            [_sample_triplets(s)[0].shape[0] for s in samples]
        )
    if need_neighbors:
        from hydragnn_tpu.ops.dense_agg import max_degree

        deg = [
            max_degree(s.edge_index[0], s.edge_index[1])
            if s.num_edges
            else (1, 1)
            for s in samples
        ]
        kis = np.asarray([d[0] for d in deg])
        kos = np.asarray([d[1] for d in deg])
    try:
        import jax

        device_multiple = jax.device_count()
    except Exception:
        device_multiple = 1
    mult = _lcm(8, max(device_multiple, 1))
    bounds = _partition_node_bounds(nodes, num_buckets)
    layouts, capacities = [], []
    lo = 0
    for hi in bounds:
        mask = (nodes > lo) & (nodes <= hi)
        if not mask.any():
            lo = hi
            continue
        cap_nodes = int(np.ceil(hi * headroom))
        cap_edges = int(np.ceil(int(edges[mask].max()) * headroom))
        cap_trips = int(np.ceil(int(trips[mask].max()) * headroom))
        layouts.append(
            _layout_from_maxima(
                cap_nodes,
                max(cap_edges, 1),
                cap_trips,
                int(kis[mask].max()),
                int(kos[mask].max()),
                max_batch_graphs,
                mult,
                device_multiple,
                (),  # inference batches pack no targets
                (),
                need_triplets,
                need_neighbors,
            )
        )
        capacities.append(
            BucketCapacity(
                max_nodes=cap_nodes,
                max_edges=max(cap_edges, 1),
                max_triplets=cap_trips if need_triplets else 0,
            )
        )
        lo = hi
    smallest = samples[int(np.argmin(nodes))]
    return ServingBucketPlan(
        layouts=layouts,
        capacities=capacities,
        warmup_sample=smallest.clone(),
        node_bounds=[c.max_nodes for c in capacities],
    )


def plan_from_layout(
    layout,
    warmup_sample: GraphData,
    node_bounds: Optional[Sequence[int]] = None,
) -> ServingBucketPlan:
    """Adopt a training-time layout (``compute_layout`` output) as the
    serving plan — the compiled-shape family then matches training's
    exactly, so a warm training compile cache doubles as the serving
    warmup. Budget-sized training buckets guarantee any SINGLE graph of
    the bucket fits (``n_pad - 1``/``e_pad`` floors in
    ``build_budget``), which is exactly the admission rule here."""
    layouts = (
        list(layout.layouts)
        if isinstance(layout, BucketedLayout)
        else [layout]
    )
    bounds = list(
        node_bounds
        if node_bounds is not None
        else getattr(layout, "node_bounds", [])
    )
    capacities = []
    for i, lay in enumerate(layouts):
        cap_nodes = (
            min(bounds[i], lay.n_pad - 1) if i < len(bounds) else lay.n_pad - 1
        )
        capacities.append(
            BucketCapacity(
                max_nodes=cap_nodes,
                max_edges=lay.e_pad,
                max_triplets=lay.t_pad if lay.packs_triplets else 0,
            )
        )
    return ServingBucketPlan(
        layouts=layouts,
        capacities=capacities,
        warmup_sample=warmup_sample.clone(),
        node_bounds=[c.max_nodes for c in capacities],
    )
