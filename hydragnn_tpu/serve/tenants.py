"""Tenant multiplexing: HBM-packed models, quotas, fair scheduling.

One replica process serves MANY tenants: each tenant maps to one
registered model name (a GFM adapter, a per-task head stack), and all of
them are resident in device memory at once — "HBM packing" is simply N
entries in one :class:`~hydragnn_tpu.serve.registry.ModelRegistry`
behind one bucket plan, so every tenant rides the same compile-once
executables (one per (model, bucket), warmed at startup like any other
registered model).

Isolation is two mechanisms, both owned by :class:`TenantManager`:

- **Admission quotas** — each tenant holds at most ``quota`` requests
  in flight (queued + packed) per server. The quota check happens at
  ``submit()`` BEFORE the shared queue: a flooding tenant is shed with
  :class:`TenantOverQuota` (a :class:`ServerOverloaded` carrying the
  tenant name, so the router's backoff is tenant-scoped) while every
  other tenant's path to the queue stays clear. The shared queue's own
  capacity check still runs after — quotas bound each tenant's SHARE,
  the queue bounds the total.
- **Deficit-weighted round robin** — when several tenants have groups
  due, the batcher flushes them in DWRR order: every scheduling round
  credits each backlogged tenant ``weight * quantum`` deficit, the
  fullest credit dispatches first, and served requests debit it. A
  tenant that floods its quota cannot buy more than its weight share of
  the device; an idle tenant's credit does not accumulate (classic DRR:
  deficit resets when the backlog empties).

Tenant model loading composes with the PR 16 publication machinery: a
spec may point a tenant at a checkpoint directory OR at a
:class:`~hydragnn_tpu.serve.registry.CandidateChannel` root, in which
case the channel's PINNED active version (``promoted.json``) is loaded —
the same snapshot the canary controller promoted, never a mid-write
training save.
"""

import threading
from typing import Dict, List, Optional, Tuple

from hydragnn_tpu.serve.server import ServerOverloaded
from hydragnn_tpu.utils.envparse import env_int

DEFAULT_QUOTA = 64
DEFAULT_QUANTUM = 4


class TenantOverQuota(ServerOverloaded):
    """One tenant's admission quota is exhausted — sheds THAT tenant
    only. Subclasses :class:`ServerOverloaded` so every existing caller
    (HTTP 503 mapping, router retry classification) handles it
    unchanged; the ``tenant`` attribute is what lets the router scope
    its backoff to the offender."""

    def __init__(self, tenant: str, quota: int, retry_after_s: float):
        super().__init__(retry_after_s=retry_after_s)
        self.tenant = tenant
        self.quota = quota

    def __str__(self):
        return (
            f"tenant {self.tenant!r} quota ({self.quota} in flight) "
            f"exhausted; retry after {self.retry_after_s:.3f}s"
        )


class TenantSpec:
    """Static config of one tenant (validated eagerly — a typo'd spec
    must fail at registration, not at first request)."""

    def __init__(
        self,
        name: str,
        model: str,
        quota: Optional[int] = None,
        weight: float = 1.0,
        checkpoint: Optional[Dict] = None,
        channel: Optional[str] = None,
    ):
        if not name:
            raise ValueError("tenant name must be non-empty")
        if not model:
            raise ValueError(f"tenant {name!r} needs a model name")
        if quota is not None and int(quota) < 1:
            raise ValueError(f"tenant {name!r} quota must be >= 1")
        if not float(weight) > 0:
            raise ValueError(f"tenant {name!r} weight must be > 0")
        self.name = name
        self.model = model
        self.quota = None if quota is None else int(quota)
        self.weight = float(weight)
        self.checkpoint = checkpoint  # {"name": ..., "path": ..., "arch"?}
        self.channel = channel  # CandidateChannel root (pinned load)

    @classmethod
    def from_dict(cls, d: Dict) -> "TenantSpec":
        return cls(
            name=d.get("name", ""),
            model=d.get("model") or d.get("name", ""),
            quota=d.get("quota"),
            weight=d.get("weight", 1.0),
            checkpoint=d.get("checkpoint"),
            channel=d.get("channel"),
        )


class TenantManager:
    """Tenant registry + admission quotas + DWRR flush scheduling.

    One instance per :class:`~hydragnn_tpu.serve.server.InferenceServer`
    (in-flight counts are per-server state); the SPECS are shared fleet
    config, so ``from_specs`` on each replica of one fleet builds
    identical managers."""

    def __init__(
        self,
        specs: Optional[List[TenantSpec]] = None,
        default_quota: Optional[int] = None,
        quantum: Optional[int] = None,
    ):
        self.default_quota = (
            env_int("HYDRAGNN_TENANT_DEFAULT_QUOTA", DEFAULT_QUOTA,
                    minimum=1)
            if default_quota is None
            else int(default_quota)
        )
        self.quantum = (
            env_int("HYDRAGNN_TENANT_QUANTUM", DEFAULT_QUANTUM, minimum=1)
            if quantum is None
            else int(quantum)
        )
        if self.default_quota < 1:
            raise ValueError("default_quota must be >= 1")
        if self.quantum < 1:
            raise ValueError("quantum must be >= 1")
        self._lock = threading.Lock()
        self._specs: Dict[str, TenantSpec] = {}
        self._in_flight: Dict[str, int] = {}
        self._deficit: Dict[str, float] = {}
        # cost-feedback admission overrides (serve/costs.py): a shaved
        # quota lives HERE, never on the spec — clearing the override
        # restores the spec'd base exactly
        self._quota_override: Dict[str, int] = {}
        self.admitted_total: Dict[str, int] = {}
        self.shed_total: Dict[str, int] = {}
        for spec in specs or ():
            self.register(spec)

    @classmethod
    def from_specs(cls, specs: List[Dict], **kw) -> "TenantManager":
        return cls([TenantSpec.from_dict(d) for d in specs], **kw)

    # ---- registration --------------------------------------------------
    def register(self, spec: TenantSpec) -> TenantSpec:
        with self._lock:
            if spec.name in self._specs:
                raise ValueError(f"tenant {spec.name!r} already registered")
            self._specs[spec.name] = spec
            self._in_flight[spec.name] = 0
        return spec

    def names(self) -> List[str]:
        with self._lock:
            return sorted(self._specs)

    def spec(self, tenant: str) -> TenantSpec:
        with self._lock:
            spec = self._specs.get(tenant)
        if spec is None:
            raise KeyError(
                f"unknown tenant {tenant!r}; registered: {self.names()}"
            )
        return spec

    def model_for(self, tenant: str) -> str:
        return self.spec(tenant).model

    def base_quota_for(self, tenant: str) -> int:
        """The spec'd (or default) quota, ignoring any cost-feedback
        override — what :meth:`set_quota_override` restores to."""
        spec = self.spec(tenant)
        return self.default_quota if spec.quota is None else spec.quota

    def quota_for(self, tenant: str) -> int:
        base = self.base_quota_for(tenant)  # KeyError on unknown tenant
        with self._lock:
            override = self._quota_override.get(tenant)
        return base if override is None else min(override, base)

    def set_quota_override(self, tenant: str, quota: Optional[int]):
        """Install (or with ``None`` clear) a cost-feedback admission
        override for ``tenant``. Overrides only ever SHAVE — an override
        above the base quota is clamped at read time."""
        self.spec(tenant)  # KeyError on unknown tenant
        if quota is not None and int(quota) < 1:
            raise ValueError(
                f"tenant {tenant!r} quota override must be >= 1"
            )
        with self._lock:
            if quota is None:
                self._quota_override.pop(tenant, None)
            else:
                self._quota_override[tenant] = int(quota)

    def quota_override(self, tenant: str) -> Optional[int]:
        with self._lock:
            return self._quota_override.get(tenant)

    def load_models(self, registry) -> Dict[str, int]:
        """HBM-pack every tenant's model into ``registry`` (idempotent
        per name: tenants may share a model). Checkpoint-backed tenants
        load through the strict v2 path; channel-backed tenants load the
        channel's PINNED active snapshot (the canary-promoted version).
        Returns {model name: registered version}."""
        from hydragnn_tpu.serve.registry import CandidateChannel

        versions: Dict[str, int] = {}
        for name in self.names():
            spec = self.spec(name)
            if spec.model in versions or spec.model in registry.names():
                versions.setdefault(
                    spec.model, registry.get(spec.model).version
                )
                continue
            if spec.channel is not None:
                channel = CandidateChannel(spec.channel)
                pinned = channel.pinned()
                seq = max(pinned) if pinned else channel.latest_seq()
                if seq <= 0:
                    raise ValueError(
                        f"tenant {name!r}: channel {spec.channel!r} has "
                        "no published candidate to load"
                    )
                man = channel.read(seq)
                entry = registry.load_checkpoint(
                    man["checkpoint"],
                    path=channel.version_dir(seq),
                    name=spec.model,
                )
            elif spec.checkpoint is not None:
                ck = spec.checkpoint
                entry = registry.load_checkpoint(
                    ck["name"],
                    arch_config=ck.get("arch"),
                    path=ck.get("path", "./logs/"),
                    name=spec.model,
                )
            else:
                raise ValueError(
                    f"tenant {name!r}: model {spec.model!r} is not "
                    "registered and the spec names no checkpoint/channel"
                )
            versions[spec.model] = entry.version
        return versions

    # ---- admission -----------------------------------------------------
    def admit(self, tenant: str, retry_after_s: float = 0.005):
        """Count one request against ``tenant``'s quota or shed it with
        :class:`TenantOverQuota`. Callers MUST pair every successful
        admit with exactly one :meth:`release` (the server wires it to
        the request future's terminal resolution)."""
        quota = self.quota_for(tenant)  # KeyError on unknown tenant
        with self._lock:
            if self._in_flight[tenant] >= quota:
                self.shed_total[tenant] = self.shed_total.get(tenant, 0) + 1
                raise TenantOverQuota(
                    tenant, quota, retry_after_s=max(retry_after_s, 0.001)
                )
            self._in_flight[tenant] += 1
            self.admitted_total[tenant] = (
                self.admitted_total.get(tenant, 0) + 1
            )

    def release(self, tenant: str):
        with self._lock:
            n = self._in_flight.get(tenant, 0)
            self._in_flight[tenant] = max(n - 1, 0)

    def in_flight(self, tenant: str) -> int:
        with self._lock:
            return self._in_flight.get(tenant, 0)

    # ---- DWRR scheduling -----------------------------------------------
    def flush_order(self, backlog: Dict[Optional[str], int],
                    ) -> List[Optional[str]]:
        """Order tenants with due groups for this flush round.

        Deficit-weighted round robin: each backlogged tenant is credited
        ``weight * quantum``, the order is descending credit (ties
        broken by name for determinism), and :meth:`on_served` debits
        what actually dispatched. Tenants absent from the backlog have
        their deficit reset (classic DRR — credit must not accrue while
        idle). ``None`` (untenanted traffic) schedules with weight 1."""
        with self._lock:
            for t in list(self._deficit):
                if t not in backlog:
                    self._deficit.pop(t)
            for t in backlog:
                w = 1.0
                if t is not None and t in self._specs:
                    w = self._specs[t].weight
                self._deficit[t] = self._deficit.get(t, 0.0) + (
                    w * self.quantum
                )
            return sorted(
                backlog,
                key=lambda t: (-self._deficit.get(t, 0.0), t or ""),
            )

    def on_served(self, tenant: Optional[str], n: int):
        with self._lock:
            if tenant in self._deficit:
                self._deficit[tenant] = max(
                    self._deficit[tenant] - float(n), 0.0
                )

    # ---- introspection -------------------------------------------------
    def describe(self) -> Dict[str, Dict]:
        with self._lock:
            return {
                name: {
                    "model": spec.model,
                    "quota": min(
                        self._quota_override.get(name, 1 << 30),
                        self.default_quota
                        if spec.quota is None
                        else spec.quota,
                    ),
                    "quota_override": self._quota_override.get(name),
                    "weight": spec.weight,
                    "in_flight": self._in_flight.get(name, 0),
                    "admitted": self.admitted_total.get(name, 0),
                    "shed": self.shed_total.get(name, 0),
                }
                for name, spec in self._specs.items()
            }
