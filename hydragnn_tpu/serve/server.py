"""The in-process predict server: micro-batched, bucket-compiled inference.

Request lifecycle::

    submit(graph) --admission--> bounded queue --batcher thread-->
      group by (model, bucket) --max-wait / budget-full flush-->
        pack into the bucket's static padding (pad once) -->
          pre-warmed jitted executable (compile once) -->
            split outputs per request --> future resolves

Design rules, in the order they bite:

- **Static shapes are the unit of compilation** (the repo's batching
  thesis, ``graph/batch.py``): every dispatch reuses one of the plan's
  <= num_buckets shape signatures, so after startup warmup steady state
  runs ZERO recompiles — the compile counter on ``/metrics`` is the
  regression alarm.
- **Micro-batching trades a bounded wait for throughput**: requests
  wait at most ``max_wait_s`` for co-riders; a full budget (node/edge/
  graph pads) flushes immediately.
- **Graceful degradation**: a full queue sheds NEW work at submit time
  with a retry-after hint (callers back off; latency of accepted work
  stays bounded) — never silently queues unbounded. Expired deadlines
  resolve with :class:`DeadlineExceeded` before wasting a dispatch.
  Graphs denser than their node-natural bucket fall through to the next
  larger one (``ServingBucketPlan.select``).
- **Failure isolation**: a dispatch error fails only that batch's
  requests; the batcher thread survives and keeps serving.
"""

import queue
import threading
import time
from typing import Dict, List, Optional, Set, Tuple

import numpy as np

from hydragnn_tpu.data.dataobj import GraphData
from hydragnn_tpu.serve.buckets import ServingBucketPlan
from hydragnn_tpu.serve.metrics import ServeMetrics
from hydragnn_tpu.serve.registry import ModelEntry, ModelRegistry


class ServerOverloaded(RuntimeError):
    """Queue full — the request was shed, retry after ``retry_after_s``."""

    def __init__(self, retry_after_s: float):
        super().__init__(
            f"predict queue full; retry after {retry_after_s:.3f}s"
        )
        self.retry_after_s = retry_after_s


class DeadlineExceeded(TimeoutError):
    """The request's deadline expired before its batch dispatched."""


class ServeFuture:
    """Minimal future resolved by the batcher thread.

    ``version`` and ``batch_seq`` are stamped by the dispatching batch
    just before the result lands: which model version computed the
    answer and which micro-batch carried it — the hot-swap tests assert
    every response in one batch_seq shares one version (the registry's
    batch-boundary swap contract made observable). ``model_name`` rides
    along for multi-tenant responses (which packed model answered).

    ``_on_done`` (internal) fires exactly once, on the WINNING
    resolution, outside the future's lock — the tenant quota release
    hook: every admitted request frees its quota slot at its terminal
    outcome, whichever code path resolved it (dispatch, expiry, error,
    shutdown sweep)."""

    def __init__(self):
        self._event = threading.Event()
        self._lock = threading.Lock()
        self._result = None
        self._error: Optional[BaseException] = None
        self.version: Optional[int] = None
        self.model_name: Optional[str] = None
        self.batch_seq: Optional[int] = None
        # per-head predictive-variance scalars when the server scores
        # uncertainty (serve/quality.py); None otherwise (incl. cache
        # hits — a cached answer re-used no device samples)
        self.uncertainty: Optional[List[float]] = None
        self._on_done = None

    def done(self) -> bool:
        return self._event.is_set()

    def _fire_done(self):
        # only the winning resolver reaches here, so the unlocked
        # read-and-clear cannot race another firer
        cb, self._on_done = self._on_done, None
        if cb is not None:
            try:
                cb()
            except Exception:
                pass  # a bookkeeping hook can never fail a resolution

    def set_result(self, result) -> bool:
        # first resolution wins (atomically): a shutdown sweep racing a
        # completed dispatch must not overwrite a result with an error
        with self._lock:
            if self._event.is_set():
                return False
            self._result = result
            self._event.set()
        self._fire_done()
        return True

    def set_exception(self, error: BaseException) -> bool:
        with self._lock:
            if self._event.is_set():
                return False
            self._error = error
            self._event.set()
        self._fire_done()
        return True

    def result(self, timeout: Optional[float] = None):
        if not self._event.wait(timeout):
            raise TimeoutError("prediction not ready")
        if self._error is not None:
            raise self._error
        return self._result


class _Request:
    __slots__ = (
        "graph", "entry", "bucket", "sizes", "future", "enqueued_at",
        "deadline", "fallback", "tenant", "cache_key", "trace",
    )

    def __init__(self, graph, entry, bucket, sizes, deadline, fallback,
                 tenant=None, cache_key=None, trace=None):
        self.graph = graph
        self.entry = entry
        self.bucket = bucket
        self.sizes = sizes  # (nodes, edges, triplets)
        self.future = ServeFuture()
        self.enqueued_at = time.monotonic()
        self.deadline = deadline  # absolute monotonic time or None
        self.fallback = fallback  # served above its node-natural bucket
        self.tenant = tenant  # admission/packing identity (None = untenanted)
        self.cache_key = cache_key  # fill the response cache on dispatch
        self.trace = trace  # armed TraceContext (obs/trace.py) or None

    def expired(self, now: float) -> bool:
        return self.deadline is not None and now >= self.deadline


class InferenceServer:
    """Micro-batching predict server over a :class:`ModelRegistry` and a
    :class:`ServingBucketPlan`.

    In-process and thread-safe: any number of caller threads ``submit``;
    one batcher thread packs and dispatches (single-threaded device use —
    jit dispatch from multiple threads buys nothing and interleaves
    badly). ``/healthz`` + ``/metrics`` come from
    :class:`~hydragnn_tpu.serve.http.ObservabilityServer`, started here
    when ``observability_port`` is not None (0 = ephemeral port)."""

    def __init__(
        self,
        registry: ModelRegistry,
        plan: ServingBucketPlan,
        default_model: Optional[str] = None,
        max_wait_s: float = 0.005,
        queue_capacity: int = 256,
        default_deadline_s: Optional[float] = None,
        observability_port: Optional[int] = None,
        metrics: Optional[ServeMetrics] = None,
        tenants=None,
        cache=None,
        costs=None,
        scorer=None,
    ):
        self.registry = registry
        self.plan = plan
        self.default_model = default_model
        self.max_wait_s = float(max_wait_s)
        self.queue_capacity = int(queue_capacity)
        self.default_deadline_s = default_deadline_s
        self.metrics = metrics or ServeMetrics()
        # multi-tenant serving (serve/tenants.py): quota admission +
        # DWRR flush ordering; None = the historical single-tenant path
        self.tenants = tenants
        if tenants is not None:
            tenants.load_models(registry)  # HBM-pack every tenant model
        # response cache (serve/cache.py): consulted at submit (pre-
        # collation key), filled at dispatch, invalidated on promote/
        # rollback through the registry's activation listeners
        self.cache = cache
        if cache is not None:
            if cache.metrics is None:
                cache.metrics = self.metrics
            registry.add_activation_listener(
                lambda name, version: cache.invalidate(model=name)
            )
        # tenant cost ledger (serve/costs.py): every dispatched batch's
        # device time + compiled FLOPs attributed to its tenant, with
        # the cost->quota feedback tick riding the batcher loop
        self.costs = costs
        # uncertainty scorer (serve/quality.py UncertaintyScorer): when
        # set, every dispatched batch also runs the K-sample scoring
        # program — warmed per bucket like the predict program, so the
        # zero-steady-state-recompiles contract covers it too
        self.scorer = scorer
        self._shape_flops: Dict[Tuple, float] = {}
        self._last_flops = 0.0  # batcher-thread-only scratch
        self._queue: "queue.Queue[_Request]" = queue.Queue(
            maxsize=self.queue_capacity
        )
        # mutated only by the batcher thread; the lock covers the cross-
        # thread reads (_depth from submitters, drain checks from stop)
        self._pending_lock = threading.Lock()
        self._pending: Dict[Tuple[str, int, int], List[_Request]] = {}
        self._predict_fns: Dict[Tuple[str, int], object] = {}
        self._seen_shapes: Set[Tuple] = set()
        self._thread: Optional[threading.Thread] = None
        self._running = threading.Event()
        self._batch_seq = 0  # mutated only by the dispatching thread
        # guards the stopped-check + enqueue pair in submit() against
        # stop(): without it a submit could pass the check, then enqueue
        # AFTER stop()'s sweep — a request no one would ever answer
        self._submit_lock = threading.Lock()
        self._stopped = False  # start() -> stop() happened; submits refuse
        self._warm = False
        self._observability_port = observability_port
        self._http = None

    # ---- lifecycle -----------------------------------------------------
    def start(self, warmup: bool = True):
        """Warm every (registered model, bucket) executable, then start
        the batcher thread (and the observability endpoint, if asked)."""
        if self._running.is_set():
            return self
        with self._submit_lock:
            # the stopped flag is read/written ONLY under this lock
            # (threadlint unguarded-shared-state): a lock-free restart
            # here could race a concurrent stop()'s sweep and revive a
            # queue that sweep already declared dead
            self._stopped = False
        from hydragnn_tpu.utils.compile_cache import enable_compile_cache

        enable_compile_cache()
        if warmup:
            self.warmup()
        self._running.set()
        # daemon=True is the crashed-caller backstop ONLY: the orderly
        # path is stop(), which drains, joins with a bounded timeout and
        # fails anything still queued — never fire-and-forget
        thread = threading.Thread(
            target=self._batcher_loop,
            name="hydragnn-serve-batcher",
            daemon=True,
        )
        thread.start()
        http = None
        if self._observability_port is not None:
            from hydragnn_tpu.serve.http import ObservabilityServer

            http = ObservabilityServer(self, port=self._observability_port)
            http.start()
        # publish the teardown handles under the lock stop() takes them
        # with — a lock-free write here would race stop()'s handoff
        with self._submit_lock:
            self._thread = thread
            self._http = http
        return self

    def stop(self, drain: bool = True, timeout: float = 10.0):
        """Stop the batcher; ``drain=True`` serves already-queued work
        first, otherwise queued requests fail with a shutdown error.
        Also sweeps a never-started server's queue, so requests
        submitted before ``start()`` cannot strand. Idempotent: a second
        ``stop()`` after a completed one is a no-op (unless the batcher
        outlived its join timeout, in which case it retries the join)."""
        with self._submit_lock:
            # after this block no submit can enqueue: any submit holding
            # the lock finished its put before the flag flipped, and the
            # sweep below runs strictly later — nothing slips past it.
            # Taking the teardown handles here hands them to exactly ONE
            # stopper: concurrent stop() calls must not both join (or
            # both null) the same thread/listener
            already_stopped = self._stopped
            self._stopped = True
            thread, self._thread = self._thread, None
            http, self._http = self._http, None
        if already_stopped and thread is None and http is None:
            return
        if self._running.is_set():
            if drain:
                deadline = time.monotonic() + timeout
                while self._depth() and time.monotonic() < deadline:
                    time.sleep(0.005)
            self._running.clear()
        if thread is not None:
            # bounded join — shutdown must terminate even if a dispatch
            # wedges; a still-alive batcher hands its handle back so a
            # retry stop() can join it again instead of silently
            # forgetting it
            thread.join(timeout)
            if thread.is_alive():
                with self._submit_lock:
                    self._thread = thread
        # fail anything still queued — no silent black hole. Counted as
        # errors so the metrics lifecycle invariant (every accepted
        # request ends in responses/timeouts/errors) survives shutdown.
        stranded: List[_Request] = []
        while True:
            try:
                stranded.append(self._queue.get_nowait())
            except queue.Empty:
                break
        with self._pending_lock:
            # a batcher outliving join(timeout) still pops groups under
            # this lock; taking ownership here prevents double-resolution
            for group in self._pending.values():
                stranded.extend(group)
            self._pending.clear()
        failed = sum(
            req.future.set_exception(RuntimeError("server stopped"))
            for req in stranded
        )
        if failed:
            self.metrics.on_error(failed)
        if http is not None:
            http.stop()

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()

    @property
    def observability_address(self) -> Optional[Tuple[str, int]]:
        return None if self._http is None else self._http.address

    # ---- warmup --------------------------------------------------------
    def warmup(self):
        """Compile every (model, bucket) executable before traffic: one
        dispatch of the plan's warmup sample per bucket per model. After
        this, any request the plan admits reuses a cached program."""
        for name in self.registry.names():
            self.warmup_entry(self.registry.get(name))
        self._warm = True

    def warmup_entry(self, entry: ModelEntry):
        """Warm ONE model version across every bucket by direct dispatch
        (startup path — the batcher is not running yet). For warming a
        candidate version on a LIVE server use :meth:`warm_version`,
        which routes through the batcher so traffic keeps being served
        between warmup batches."""
        sample = self._warmup_sample()
        for b in range(self.plan.num_buckets):
            batch, _ = self.plan.pack([sample], b)
            self._dispatch_compiled(entry, b, batch)
            if self.scorer is not None:
                self._dispatch_scored(entry, batch)

    def _warmup_sample(self):
        sample = self.plan.warmup_sample
        if sample is None:
            raise ValueError(
                "plan has no warmup_sample; pass one (a small GraphData) "
                "or build the plan via plan_from_samples/plan_from_layout"
            )
        return sample

    def warm_version(
        self,
        name: str,
        version: Optional[int] = None,
        timeout: float = 120.0,
        passes: int = 2,
    ) -> Dict[str, int]:
        """Warm a (usually freshly registered) model version THROUGH the
        running batcher: one pinned-bucket warmup request per bucket per
        pass, interleaving with live traffic — the zero-downtime half of
        a hot-swap promote. Returns per-pass compile-counter deltas so
        the caller can verify the warm took: pass 1 must compile exactly
        ``num_buckets`` novel shapes (times two with an uncertainty
        scorer — its per-bucket scoring program warms in the same
        dispatch), every later pass ZERO (a non-zero
        later pass means the candidate's executables did not cache — a
        promote gated on this never swaps onto a version that would
        recompile under traffic). Requires a started server."""
        if not self._running.is_set():
            raise RuntimeError(
                "warm_version needs a running batcher; call start() first "
                "(startup warmup uses warmup_entry directly)"
            )
        entry = self.registry.get(name, version)
        sample = self._warmup_sample()
        deltas: List[int] = []
        for _ in range(max(int(passes), 1)):
            before = self.metrics.compiles_total
            futures = []
            for b in range(self.plan.num_buckets):
                futures.append(self._submit_pinned(sample, entry, b))
            for fut in futures:
                fut.result(timeout)  # dispatch errors propagate loudly
            deltas.append(self.metrics.compiles_total - before)
        per_bucket = 1 if self.scorer is None else 2
        return {
            "buckets": self.plan.num_buckets,
            "first_pass_compiles": deltas[0],
            "later_pass_compiles": sum(deltas[1:]),
            "verified": (
                deltas[0] == self.plan.num_buckets * per_bucket
                and sum(deltas[1:]) == 0
            ),
        }

    def _submit_pinned(self, graph, entry: ModelEntry,
                       bucket: int) -> ServeFuture:
        """Enqueue one request pinned to an explicit (entry, bucket) —
        the warm-version path. Same atomic stopped-check/enqueue as
        submit(); counted in the normal metrics lifecycle so the
        accepted == terminal invariant holds for warmup traffic too."""
        sizes = self.plan.request_sizes(graph)
        req = _Request(graph, entry, bucket, sizes, None, fallback=False)
        with self._submit_lock:
            if self._stopped:
                raise RuntimeError("server stopped; submits are refused")
            self._queue.put_nowait(req)  # queue.Full propagates: a warm
            # attempt must not silently evaporate under pressure
        self.metrics.on_submit()
        return req.future

    def is_warm(self) -> bool:
        return self._warm

    # ---- submission ----------------------------------------------------
    def submit(
        self,
        graph: GraphData,
        model: Optional[str] = None,
        deadline_s: Optional[float] = None,
        tenant: Optional[str] = None,
        trace=None,
    ) -> ServeFuture:
        """Enqueue one graph; returns a future resolving to a list of
        per-head numpy outputs (graph head: ``[dim]``, node head:
        ``[num_nodes, dim]``). Raises :class:`ServerOverloaded` when the
        queue is full (or, as its :class:`~hydragnn_tpu.serve.tenants.
        TenantOverQuota` subclass, when ``tenant``'s quota is) and
        :class:`GraphTooLarge` when no bucket admits the graph (all
        BEFORE queueing — shed work fails fast). With a tenant manager
        configured, ``tenant`` resolves the model name and counts
        against that tenant's quota; a cache hit answers before the
        quota check (a cached answer consumes no device time)."""
        name = model or self.default_model
        if tenant is not None:
            if self.tenants is None:
                raise ValueError(
                    f"tenant {tenant!r} given but the server has no "
                    "TenantManager"
                )
            if model is None:
                name = self.tenants.model_for(tenant)  # KeyError: unknown
        if name is None:
            names = self.registry.names()
            if len(names) != 1:
                raise ValueError(
                    "no model= given and no default_model set with "
                    f"{len(names)} models registered"
                )
            name = names[0]
        entry = self.registry.get(name)
        bucket, sizes = self.plan.admit(graph)  # GraphTooLarge propagates
        if deadline_s is None:
            deadline_s = self.default_deadline_s
        deadline = (
            None if deadline_s is None else time.monotonic() + deadline_s
        )
        cache_key = None
        if self.cache is not None:
            from hydragnn_tpu.serve.cache import (
                ResponseCache,
                canonical_graph_key,
            )

            # keyed PRE-collation on the raw request graph; the entry's
            # ACTIVE version in the key is what makes a stale hit after
            # promote/rollback impossible by construction
            cache_key = ResponseCache.key(
                canonical_graph_key(graph), entry.name, entry.version,
                tenant,
            )
            cached = self.cache.get(cache_key)
            if cached is not None:
                if trace is not None:
                    trace.record(
                        "cache_lookup", time.time(), 0.0, hit=True,
                        side="replica", tenant=tenant,
                    )
                fut = ServeFuture()
                fut.version = entry.version
                fut.model_name = entry.name
                fut.set_result(cached)
                self.metrics.on_submit()
                self.metrics.on_response()
                self.metrics.on_response_latency(0.0)
                if deadline is not None:
                    self.metrics.on_deadline(True)
                return fut
        if tenant is not None:
            # quota admission AFTER the cache (hits are free) and BEFORE
            # the shared queue: a flooding tenant sheds here, tenant-
            # tagged, while the queue stays clear for everyone else
            try:
                self.tenants.admit(
                    tenant, retry_after_s=max(self.max_wait_s, 0.001)
                )
            except ServerOverloaded:
                self.metrics.on_shed()
                raise
        req = _Request(
            graph,
            entry,
            bucket,
            sizes,
            deadline,
            fallback=bucket > self.plan.natural_bucket(graph.num_nodes),
            tenant=tenant,
            cache_key=cache_key,
            trace=trace,
        )
        if tenant is not None:
            tenants = self.tenants
            req.future._on_done = lambda t=tenant: tenants.release(t)
        # check-and-enqueue atomically vs stop(): once stop() takes this
        # lock to set _stopped, no request can slip into the dead queue
        # after its sweep
        with self._submit_lock:
            if self._stopped:
                if tenant is not None:
                    self.tenants.release(tenant)
                raise RuntimeError("server stopped; submits are refused")
            try:
                self._queue.put_nowait(req)
            except queue.Full:
                if tenant is not None:
                    self.tenants.release(tenant)
                self.metrics.on_shed()
                # the queue drains one max-wait window per flush round; a
                # full queue clears in about capacity/batch flushes of it
                raise ServerOverloaded(
                    retry_after_s=max(self.max_wait_s, 0.001)
                )
        self.metrics.on_submit()
        self.metrics.set_queue_depth(self._depth())
        return req.future

    def predict(
        self,
        graph: GraphData,
        model: Optional[str] = None,
        timeout: Optional[float] = None,
        tenant: Optional[str] = None,
    ):
        """Synchronous convenience: submit + wait."""
        return self.submit(
            graph, model=model, deadline_s=timeout, tenant=tenant
        ).result(timeout)

    def _depth(self) -> int:
        with self._pending_lock:
            pending = sum(len(g) for g in self._pending.values())
        return self._queue.qsize() + pending

    # ---- batcher -------------------------------------------------------
    def _batcher_loop(self):
        tick = max(self.max_wait_s / 4, 0.0005)
        while self._running.is_set():
            try:
                req = self._queue.get(timeout=tick)
            except queue.Empty:
                req = None
            if req is not None:
                self._admit_pending(req)
                # greedy drain: move everything already queued into its
                # group before checking flush conditions — one wakeup
                # packs the whole burst
                while True:
                    try:
                        more = self._queue.get_nowait()
                    except queue.Empty:
                        break
                    self._admit_pending(more)
            self._flush_due()
            if self.costs is not None:
                # cost->quota feedback tick: a clock read between
                # windows, the share comparison once per window
                self.costs.maybe_adjust_quotas(self.tenants)
            self.metrics.set_queue_depth(self._depth())
        # shutdown flush: serve whatever is pending so stop(drain=True)
        # never strands accepted work
        with self._pending_lock:
            keys = list(self._pending)
        for key in keys:
            self._flush_group(key)

    def _admit_pending(self, req: _Request):
        # per-(tenant, model-version, bucket) groups: one micro-batch
        # never mixes tenants, so a response reaching the wrong tenant
        # is impossible by construction, not by filtering
        key = (req.tenant, req.entry.name, req.entry.version, req.bucket)
        with self._pending_lock:
            self._pending.setdefault(key, []).append(req)

    def _flush_due(self):
        now = time.monotonic()
        with self._pending_lock:
            keys = list(self._pending)
            backlog: Dict[Optional[str], int] = {}
            for key in keys:
                backlog[key[0]] = backlog.get(key[0], 0) + len(
                    self._pending.get(key) or ()
                )
        if self.tenants is not None and len(backlog) > 1:
            # deficit-weighted round robin across tenants: when several
            # tenants have groups due, dispatch order follows earned
            # credit — a flooding tenant cannot buy more than its weight
            # share of consecutive device slots
            rank = {
                t: i
                for i, t in enumerate(self.tenants.flush_order(backlog))
            }
            keys.sort(key=lambda k: (rank.get(k[0], len(rank)), k[3]))
        for key in keys:
            group = self._pending.get(key)
            if not group:
                with self._pending_lock:
                    self._pending.pop(key, None)
                continue
            if self._group_full(key, group) or (
                now - group[0].enqueued_at >= self.max_wait_s
            ):
                served = self._flush_group(key)
                if self.tenants is not None and served:
                    self.tenants.on_served(key[0], served)

    def _group_full(self, key, group) -> bool:
        """Full = the bucket budget cannot take one more request of the
        group's smallest plausible size — approximated by: adding the
        LAST request's sizes again would overflow (cheap, and exact for
        same-size streams; worst case we flush one request early)."""
        bucket = key[3]
        n = sum(r.sizes[0] for r in group)
        e = sum(r.sizes[1] for r in group)
        t = sum(r.sizes[2] for r in group)
        return not self.plan.fits_batch(
            bucket, n, e, t, len(group), group[-1].sizes
        )

    def _flush_group(self, key) -> int:
        """Dispatch one pending group; returns how many requests went to
        the device (the DWRR debit — expiries consumed no device time)."""
        with self._pending_lock:
            group = self._pending.pop(key, None)
        if not group:
            return 0
        now = time.monotonic()
        live: List[_Request] = []
        expired = 0
        for req in group:
            if req.expired(now):
                if req.trace is not None:
                    dur = now - req.enqueued_at
                    req.trace.record(
                        "queue_wait", time.time() - dur, dur,
                        bucket=key[3], tenant=req.tenant, expired=True,
                    )
                req.future.set_exception(
                    DeadlineExceeded(
                        "deadline expired after "
                        f"{now - req.enqueued_at:.3f}s in queue"
                    )
                )
                expired += 1
            else:
                live.append(req)
        if expired:
            self.metrics.on_timeout(expired)
        bucket = key[3]
        served = len(live)
        # budget-greedy split: a group can exceed one batch's budgets
        # (e.g. a burst larger than g_pad-1) — emit as many full batches
        # as needed, every one inside the bucket's static shapes
        while live:
            take: List[_Request] = []
            n = e = t = 0
            for req in live:
                if take and not self.plan.fits_batch(
                    bucket, n, e, t, len(take), req.sizes
                ):
                    break
                take.append(req)
                n += req.sizes[0]
                e += req.sizes[1]
                t += req.sizes[2]
            live = live[len(take):]
            self._dispatch_batch(take, bucket, real_nodes=n)
        return served

    def _dispatch_batch(self, requests: List[_Request], bucket: int,
                        real_nodes: int):
        entry = requests[0].entry
        t0 = time.monotonic()
        traced = [r for r in requests if r.trace is not None]
        w0 = time.time() if traced else 0.0
        try:
            batch, coords = self.plan.pack(
                [r.graph for r in requests], bucket
            )
            t_pack = time.monotonic()
            outputs = self._dispatch_compiled(entry, bucket, batch)
            t_disp = time.monotonic()
            # ONE explicit bulk fetch for the whole batch's heads — the
            # per-head np.asarray() it replaces was an implicit transfer
            # per head, which the transfer-guard test now hard-errors
            import jax

            outputs = [
                np.asarray(o) for o in jax.device_get(list(outputs))
            ]
            variances = None
            if self.scorer is not None:
                # scoring is advisory: a scorer failure degrades the
                # batch to unscored responses, never to errors
                try:
                    v = self._dispatch_scored(entry, batch)
                    variances = [
                        np.asarray(a) for a in jax.device_get(list(v))
                    ]
                except Exception:
                    variances = None
        except Exception as e:  # fail the batch, keep the server alive
            self.metrics.on_error(len(requests))
            for req in requests:
                req.future.set_exception(e)
            return
        now = time.monotonic()
        self._batch_seq += 1
        batch_seq = self._batch_seq
        for req in traced:
            # the batch's phase boundaries, one span set per traced
            # rider: queue_wait ends where packing starts; wall starts
            # derive from w0 (the monotonic t0's wall reading) so spans
            # from this process and the router share one timeline
            queue_s = max(t0 - req.enqueued_at, 0.0)
            req.trace.record(
                "queue_wait", w0 - queue_s, queue_s,
                bucket=bucket, tenant=req.tenant,
            )
            req.trace.record(
                "batch_form", w0, t_pack - t0,
                bucket=bucket, batch_graphs=len(requests),
            )
            req.trace.record(
                "dispatch", w0 + (t_pack - t0), t_disp - t_pack,
                bucket=bucket, batch_seq=batch_seq,
            )
            req.trace.record(
                "readback", w0 + (t_disp - t0), now - t_disp,
                bucket=bucket,
            )
        if self.costs is not None:
            self.costs.note_batch(
                requests[0].tenant, bucket, len(requests),
                batch_seconds=now - t0, flops=self._last_flops,
            )
        for req, (g, off, n) in zip(requests, coords):
            per_head = []
            for ihead, kind in enumerate(entry.output_type):
                if kind == "graph":
                    per_head.append(outputs[ihead][g])
                else:
                    per_head.append(outputs[ihead][off: off + n])
            if variances is not None:
                unc = []
                for ihead, kind in enumerate(entry.output_type):
                    arr = (
                        variances[ihead][g]
                        if kind == "graph"
                        else variances[ihead][off: off + n]
                    )
                    # `variances` was device_get + np.asarray'd above —
                    # this mean runs on host memory, not a device sync
                    unc.append(
                        float(np.mean(arr)) if arr.size else 0.0  # jaxlint: disable=host-sync-in-hot-loop
                    )
                req.future.uncertainty = unc
                self.scorer.observe(req.tenant, bucket, unc)
            # stamped before resolution: a waiter that wakes on
            # set_result reads a consistent (version, batch) pair
            req.future.version = entry.version
            req.future.model_name = entry.name
            req.future.batch_seq = batch_seq
            if self.cache is not None and req.cache_key is not None:
                # fill BEFORE resolving: a waiter that re-submits the
                # same graph right after result() must see the hit
                self.cache.put(req.cache_key, per_head)
            req.future.set_result(per_head)
            self.metrics.on_response_latency(now - req.enqueued_at)
            # SLO accounting: a deadline-carrying request that still got
            # its answer counts met/missed by when the answer LANDED (a
            # result delivered late is a miss even though it resolved;
            # in-queue expiries were already counted by on_timeout).
            # Errored requests are failures, not deadline outcomes.
            if req.deadline is not None:
                self.metrics.on_deadline(now <= req.deadline)
        self.metrics.on_batch(
            bucket,
            len(requests),
            real_nodes=real_nodes,
            padded_nodes=self.plan.layouts[bucket].n_pad,
            batch_seconds=now - t0,
            fallbacks=sum(1 for r in requests if r.fallback),
        )

    # ---- compiled dispatch ---------------------------------------------
    def _predict_fn(self, entry: ModelEntry):
        fn = self._predict_fns.get(entry.key)
        if fn is None:
            import jax

            from hydragnn_tpu.obs.introspect import instrument

            model = entry.model

            def _apply(params, batch_stats, batch):
                variables = {"params": params}
                if batch_stats:
                    variables["batch_stats"] = batch_stats
                return model.apply(variables, batch, train=False)

            # introspection-wrapped (obs/introspect.py): when enabled
            # (live telemetry or HYDRAGNN_INTROSPECT=1), every serving
            # bucket's compiled cost/memory analysis is captured at
            # warmup — introspect.captured() carries it even without a
            # telemetry run. Pure passthrough otherwise. jit_replicated
            # declares the sharding contract (replicated outputs on the
            # active mesh; plain jit without one) instead of inheriting
            # whatever placement the inputs carried — the shardlint
            # jit-missing-shardings contract for serve dispatch.
            from hydragnn_tpu.parallel.mesh import jit_replicated

            fn = instrument(
                f"serve_predict:{entry.name}:v{entry.version}",
                jit_replicated(_apply),
            )
            self._predict_fns[entry.key] = fn
        return fn

    def _dispatch_compiled(self, entry: ModelEntry, bucket: int, batch):
        """Run the bucket's executable; account a compile whenever this
        (model version, shape signature) has not been seen — warmup sees
        every bucket once, so any later increment means a shape leaked
        past the plan (the exact bug class ``/metrics`` must expose)."""
        import jax

        shape_key = (
            entry.key,
            tuple(
                (tuple(a.shape), str(getattr(a, "dtype", type(a))))
                for a in jax.tree_util.tree_leaves(batch)
            ),
        )
        novel = shape_key not in self._seen_shapes
        if novel:
            self._seen_shapes.add(shape_key)
            self.metrics.on_compile()
        dev_batch = jax.tree_util.tree_map(np.asarray, batch)
        out = self._predict_fn(entry)(
            entry.params, entry.batch_stats, dev_batch
        )
        if self.costs is not None:
            if novel:
                # first sight of this (version, shape): introspection
                # (when live) just captured the executable's
                # cost_analysis — resolve its per-dispatch FLOPs once
                self._shape_flops[shape_key] = self._captured_flops(
                    entry, dev_batch
                )
            self._last_flops = self._shape_flops.get(shape_key, 0.0)
        return out

    def _dispatch_scored(self, entry: ModelEntry, batch):
        """Run the bucket's K-sample uncertainty program with the SAME
        seen-shapes/compile accounting as the predict program: warmup
        sees every (scorer signature, shape) once, so the scoring path
        is held to the zero-steady-state-recompiles contract too."""
        import jax

        shape_key = (
            self.scorer.signature(entry),
            tuple(
                (tuple(a.shape), str(getattr(a, "dtype", type(a))))
                for a in jax.tree_util.tree_leaves(batch)
            ),
        )
        if shape_key not in self._seen_shapes:
            self._seen_shapes.add(shape_key)
            self.metrics.on_compile()
        dev_batch = jax.tree_util.tree_map(np.asarray, batch)
        return self.scorer.dispatch(entry, dev_batch)

    def _captured_flops(self, entry: ModelEntry, dev_batch) -> float:
        """This bucket's compiled per-dispatch FLOPs from introspect's
        capture record (0 when introspection is off or the backend has
        no cost model) — the CostLedger's FLOP attribution source."""
        try:
            from hydragnn_tpu.obs import introspect

            name = f"serve_predict:{entry.name}:v{entry.version}"
            label = introspect.bucket_label(
                name,
                introspect.signature_key(
                    (entry.params, entry.batch_stats, dev_batch), {}
                ),
            )
            for rec in introspect.captured(name):
                if rec.get("bucket") == label:
                    return float(
                        (rec.get("cost") or {}).get("flops", 0.0)
                    )
        except Exception:
            pass
        return 0.0

    # ---- multi-tenant conveniences -------------------------------------
    def warm_tenant(self, tenant: str, timeout: float = 120.0,
                    passes: int = 2) -> Dict[str, int]:
        """Warm one tenant's model through the live batcher (same
        compile-counter verification as :meth:`warm_version`)."""
        if self.tenants is None:
            raise ValueError("server has no TenantManager")
        return self.warm_version(
            self.tenants.model_for(tenant), timeout=timeout, passes=passes
        )

    # ---- health --------------------------------------------------------
    def health(self) -> Dict:
        """``/healthz`` payload: liveness + registry + warmup state."""
        out = {
            "status": "ok" if self._running.is_set() else "stopped",
            "warm": self._warm,
            "models": self.registry.describe(),
            "buckets": [
                {
                    "max_nodes": cap.max_nodes,
                    "max_edges": cap.max_edges,
                    "n_pad": lay.n_pad,
                    "e_pad": lay.e_pad,
                    "g_pad": lay.g_pad,
                }
                for cap, lay in zip(self.plan.capacities, self.plan.layouts)
            ],
            "queue_depth": self._depth(),
            "queue_capacity": self.queue_capacity,
            "max_wait_s": self.max_wait_s,
        }
        if self.tenants is not None:
            out["tenants"] = self.tenants.describe()
        if self.cache is not None:
            out["cache"] = self.cache.stats()
        if self.costs is not None:
            out["costs"] = self.costs.bill()
        if self.scorer is not None:
            out["quality"] = self.scorer.stats()
        return out
