"""hydragnn_tpu.serve — online inference: micro-batched, bucket-compiled,
observable prediction serving (docs/serving.md).

The offline path (``run_prediction``) sweeps a whole test split; this
package answers SINGLE ad-hoc graphs at low latency by reusing the two
ingredients the batching layer already provides — static padded shapes
and node-count buckets — as a pad-once/compile-once request server:

    from hydragnn_tpu.serve import (
        InferenceServer, ModelRegistry, plan_from_samples,
    )

    registry = ModelRegistry()
    registry.load_checkpoint("PNA-r-2.0-...-run")        # strict v2 loader
    plan = plan_from_samples(sample_graphs, max_batch_graphs=8)
    with InferenceServer(registry, plan,
                         observability_port=8080) as server:
        heads = server.predict(graph)                    # sync
        fut = server.submit(graph, deadline_s=0.1)       # async
"""

from hydragnn_tpu.serve.autoscale import (
    AutoscalePolicy,
    FleetAutoscaler,
    LoadForecast,
)
from hydragnn_tpu.serve.buckets import (
    BucketCapacity,
    GraphTooLarge,
    ServingBucketPlan,
    plan_from_layout,
    plan_from_samples,
)
from hydragnn_tpu.serve.cache import (
    ResponseCache,
    canonical_graph_key,
)
from hydragnn_tpu.serve.costs import (
    CostLedger,
    merge_bills,
    price_per_million,
)
from hydragnn_tpu.serve.canary import (
    CanaryController,
    CanaryGates,
    CanaryMetrics,
)
from hydragnn_tpu.serve.fleet import (
    FleetMetrics,
    ReplicaServer,
    ServingFleet,
)
from hydragnn_tpu.serve.http import ObservabilityServer
from hydragnn_tpu.serve.metrics import LatencyHistogram, ServeMetrics
from hydragnn_tpu.serve.quality import (
    FeedbackSink,
    UncertaintyScorer,
)
from hydragnn_tpu.serve.registry import (
    CandidateChannel,
    ModelEntry,
    ModelRegistry,
    publish_candidate,
)
from hydragnn_tpu.serve.router import (
    FleetRouter,
    NoLiveReplica,
    RetryBudget,
)
from hydragnn_tpu.serve.server import (
    DeadlineExceeded,
    InferenceServer,
    ServeFuture,
    ServerOverloaded,
)
from hydragnn_tpu.serve.tenants import (
    TenantManager,
    TenantOverQuota,
    TenantSpec,
)

__all__ = [
    "AutoscalePolicy",
    "BucketCapacity",
    "CanaryController",
    "CanaryGates",
    "CanaryMetrics",
    "CandidateChannel",
    "CostLedger",
    "DeadlineExceeded",
    "FeedbackSink",
    "FleetAutoscaler",
    "FleetMetrics",
    "FleetRouter",
    "GraphTooLarge",
    "LoadForecast",
    "InferenceServer",
    "LatencyHistogram",
    "ModelEntry",
    "ModelRegistry",
    "NoLiveReplica",
    "ObservabilityServer",
    "ReplicaServer",
    "ResponseCache",
    "RetryBudget",
    "ServeFuture",
    "ServeMetrics",
    "ServerOverloaded",
    "ServingBucketPlan",
    "ServingFleet",
    "TenantManager",
    "TenantOverQuota",
    "TenantSpec",
    "UncertaintyScorer",
    "canonical_graph_key",
    "merge_bills",
    "plan_from_layout",
    "plan_from_samples",
    "price_per_million",
    "publish_candidate",
]
