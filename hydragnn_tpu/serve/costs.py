"""Per-tenant cost attribution: device time, FLOPs, quota feedback.

The multi-tenant replica (PR 17) isolates tenants at admission and
scheduling but bills nobody: a tenant that floods cheap requests and one
that sends few expensive graphs look identical to quotas counted in
requests. This module prices the device itself:

- **Attribution is per dispatched batch**: micro-batches never mix
  tenants (the batcher groups on ``(tenant, model, version, bucket)``),
  so every batch's device wall-time — and its compiled FLOPs, when
  introspection captured the bucket's ``cost_analysis`` — belongs
  entirely to one tenant. :meth:`CostLedger.note_batch` is called once
  per dispatch from the batcher thread.
- **Replica-seconds close the books**: a replica's total cost is its
  wall-clock lifetime, not just its busy time. :meth:`CostLedger.bill`
  reports per-tenant device seconds plus an explicit ``idle_s``
  residual, so the rows SUM to the integrated replica-seconds exactly —
  the fleet bill is the sum of the replica bills, no double counting,
  no leakage.
- **Cost feedback into quotas** (``HYDRAGNN_TENANT_COST_QUOTAS=1``):
  every cost window, each tenant's share of the window's device time is
  compared against its weight-proportional fair share. A tenant
  persistently over (``patience`` consecutive windows beyond the
  tolerance) gets its admission quota shaved multiplicatively — floored
  so no tenant starves — and a schema-gated ``quota_adjusted`` event
  records the change; persistently-under tenants get their base quota
  restored. The DWRR scheduler already bounds a flooder's share of
  device SLOTS; the feedback bounds its share of device TIME.

Exported gauge families (``hydragnn_tenant_cost_*``): per-tenant device
seconds / FLOPs / requests plus the replica-seconds and idle-seconds
totals — rendered after the serving series on the replica's
``/metrics`` so existing consumers' byte offsets are untouched.
"""

import math
import os
import threading
import time
from typing import Callable, Dict, List, Optional

from hydragnn_tpu.obs.metrics import MetricsRegistry
from hydragnn_tpu.utils.envparse import env_float, env_int

# bill row for device time consumed by requests carrying no tenant
UNTENANTED = "(untenanted)"

_FALSY = ("", "0", "false", "no", "off")


def feedback_enabled() -> bool:
    """Cost->quota feedback armed? (``HYDRAGNN_TENANT_COST_QUOTAS=1``)"""
    return (
        os.getenv("HYDRAGNN_TENANT_COST_QUOTAS", "").strip().lower()
        not in _FALSY
    )


class CostLedger:
    """Per-replica tenant cost accounting + quota feedback loop.

    One instance per :class:`~hydragnn_tpu.serve.server.InferenceServer`
    (batch attribution is per-process state). ``emit`` is a schema-gated
    event emitter for ``quota_adjusted`` records; ``clock`` is
    injectable for deterministic tests."""

    def __init__(self, emit: Optional[Callable] = None,
                 clock: Callable[[], float] = time.monotonic):
        self.emit = emit
        self._clock = clock
        self._start = clock()
        self._lock = threading.Lock()
        self._device_s: Dict[str, float] = {}
        self._flops: Dict[str, float] = {}
        self._requests: Dict[str, int] = {}
        self._batches: Dict[str, int] = {}
        # feedback knobs (env-validated once, at construction)
        self.feedback = feedback_enabled()
        self.window_s = env_float(
            "HYDRAGNN_TENANT_COST_WINDOW_S", 1.0, minimum=0.01
        )
        self.patience = env_int(
            "HYDRAGNN_TENANT_COST_PATIENCE", 2, minimum=1
        )
        self.shave = env_float(
            "HYDRAGNN_TENANT_COST_SHAVE", 0.5, minimum=0.01
        )
        self.floor_fraction = env_float(
            "HYDRAGNN_TENANT_COST_FLOOR", 0.125, minimum=0.0
        )
        self.tolerance = env_float(
            "HYDRAGNN_TENANT_COST_TOLERANCE", 1.25, minimum=1.0
        )
        self._window_start = clock()
        self._window_device: Dict[str, float] = {}
        self._over_streak: Dict[str, int] = {}
        self._under_streak: Dict[str, int] = {}
        self.metrics = MetricsRegistry("hydragnn")
        self.metrics.labeled_gauge(
            "tenant_cost_device_seconds",
            "Device wall-time attributed to this tenant's batches",
        )
        self.metrics.labeled_gauge(
            "tenant_cost_flops",
            "Compiled FLOPs attributed to this tenant's batches",
        )
        self.metrics.labeled_gauge(
            "tenant_cost_requests",
            "Requests dispatched for this tenant",
        )
        self.metrics.gauge(
            "tenant_cost_replica_seconds",
            "Integrated replica lifetime this ledger has billed over",
        )
        self.metrics.gauge(
            "tenant_cost_idle_seconds",
            "Replica-seconds attributed to no tenant (idle residual)",
        )
        self.metrics.counter(
            "tenant_quota_adjustments_total",
            "Cost-feedback quota changes (shaves + restores)",
        )

    # ---- attribution ---------------------------------------------------
    def note_batch(self, tenant: Optional[str], bucket: int,
                   n_requests: int, batch_seconds: float,
                   flops: float = 0.0) -> None:
        """Attribute one dispatched batch (batcher thread, post-
        readback). ``flops`` is the bucket's compiled per-dispatch FLOPs
        (0 when introspection captured nothing for it)."""
        key = tenant if tenant is not None else UNTENANTED
        secs = max(float(batch_seconds), 0.0)
        with self._lock:
            self._device_s[key] = self._device_s.get(key, 0.0) + secs
            self._flops[key] = self._flops.get(key, 0.0) + max(
                float(flops), 0.0
            )
            self._requests[key] = (
                self._requests.get(key, 0) + int(n_requests)
            )
            self._batches[key] = self._batches.get(key, 0) + 1
            self._window_device[key] = (
                self._window_device.get(key, 0.0) + secs
            )

    def replica_seconds(self) -> float:
        return max(self._clock() - self._start, 0.0)

    # ---- billing -------------------------------------------------------
    def bill(self) -> Dict:
        """The replica's cost statement. Per-tenant ``device_s`` rows
        plus the ``idle_s`` residual sum to ``replica_s`` by
        construction (clamped at zero if measurement skew ever puts
        busy time above the lifetime)."""
        replica_s = self.replica_seconds()
        with self._lock:
            device = dict(self._device_s)
            flops = dict(self._flops)
            requests = dict(self._requests)
            batches = dict(self._batches)
        busy = sum(device.values())
        tenants = {
            name: {
                "device_s": round(device[name], 6),
                "flops": flops.get(name, 0.0),
                "requests": requests.get(name, 0),
                "batches": batches.get(name, 0),
                "cost_share": round(
                    device[name] / busy if busy > 0 else 0.0, 6
                ),
            }
            for name in sorted(device)
        }
        out = {
            "replica_s": round(replica_s, 6),
            "busy_s": round(busy, 6),
            "idle_s": round(max(replica_s - busy, 0.0), 6),
            "tenants": tenants,
        }
        self._export_gauges(out)
        return out

    def _export_gauges(self, bill: Dict) -> None:
        self.metrics.set("tenant_cost_replica_seconds", bill["replica_s"])
        self.metrics.set("tenant_cost_idle_seconds", bill["idle_s"])
        for name, row in bill["tenants"].items():
            self.metrics.set_labeled(
                "tenant_cost_device_seconds", row["device_s"], tenant=name
            )
            self.metrics.set_labeled(
                "tenant_cost_flops", row["flops"], tenant=name
            )
            self.metrics.set_labeled(
                "tenant_cost_requests", row["requests"], tenant=name
            )

    def render_prometheus(self) -> str:
        self.bill()  # refresh the gauge families before exposition
        return self.metrics.render_prometheus()

    # ---- quota feedback ------------------------------------------------
    def maybe_adjust_quotas(self, tenants) -> List[Dict]:
        """One feedback tick: no-op until a cost window has elapsed,
        then compare every registered tenant's window cost share against
        its weight-fair share and shave/restore admission quotas.
        Called from the batcher thread after dispatch (cheap: one clock
        read between windows). Returns the adjustments made."""
        if not self.feedback or tenants is None:
            return []
        now = self._clock()
        with self._lock:
            if now - self._window_start < self.window_s:
                return []
            window = dict(self._window_device)
            self._window_device.clear()
            self._window_start = now
        busy = sum(window.values())
        if busy <= 0.0:
            return []
        names = tenants.names()
        if not names:
            return []
        weights = {n: tenants.spec(n).weight for n in names}
        wsum = sum(weights.values())
        adjustments: List[Dict] = []
        for name in names:
            share = window.get(name, 0.0) / busy
            fair = weights[name] / wsum if wsum > 0 else 0.0
            if share > fair * self.tolerance:
                self._under_streak[name] = 0
                streak = self._over_streak.get(name, 0) + 1
                self._over_streak[name] = streak
                if streak < self.patience:
                    continue
                self._over_streak[name] = 0  # re-arm the patience gate
                base = tenants.base_quota_for(name)
                current = tenants.quota_for(name)
                floor = max(
                    int(math.ceil(base * self.floor_fraction)), 1
                )
                shaved = max(floor, int(current * self.shave))
                if shaved >= current:
                    continue  # already at (or below) the floor
                tenants.set_quota_override(name, shaved)
                adjustments.append(self._emit_adjustment(
                    name, current, shaved, "over_cost", share, fair,
                ))
            else:
                self._over_streak[name] = 0
                streak = self._under_streak.get(name, 0) + 1
                self._under_streak[name] = streak
                if (
                    streak < self.patience
                    or tenants.quota_override(name) is None
                ):
                    continue
                self._under_streak[name] = 0
                current = tenants.quota_for(name)
                tenants.set_quota_override(name, None)
                adjustments.append(self._emit_adjustment(
                    name, current, tenants.quota_for(name), "restored",
                    share, fair,
                ))
        return adjustments

    def _emit_adjustment(self, tenant: str, old: int, new: int,
                         reason: str, share: float, fair: float) -> Dict:
        self.metrics.inc("tenant_quota_adjustments_total")
        rec = {
            "tenant": tenant,
            "old_quota": int(old),
            "new_quota": int(new),
            "reason": reason,
            "cost_share": round(share, 6),
            "fair_share": round(fair, 6),
        }
        if self.emit is not None:
            try:
                self.emit("quota_adjusted", **rec)
            except Exception:
                pass  # bookkeeping must never fail the dispatch path
        return rec


# ---- fleet aggregation (bench / smoke helpers) ----------------------------


def merge_bills(bills: List[Dict]) -> Dict:
    """Sum replica bills into one fleet statement (same shape as
    :meth:`CostLedger.bill`; per-tenant rows merge by name)."""
    out: Dict = {"replica_s": 0.0, "busy_s": 0.0, "idle_s": 0.0,
                 "tenants": {}}
    for bill in bills:
        if not bill:
            continue
        out["replica_s"] += float(bill.get("replica_s", 0.0))
        out["busy_s"] += float(bill.get("busy_s", 0.0))
        out["idle_s"] += float(bill.get("idle_s", 0.0))
        for name, row in (bill.get("tenants") or {}).items():
            agg = out["tenants"].setdefault(
                name,
                {"device_s": 0.0, "flops": 0.0, "requests": 0,
                 "batches": 0},
            )
            agg["device_s"] += float(row.get("device_s", 0.0))
            agg["flops"] += float(row.get("flops", 0.0))
            agg["requests"] += int(row.get("requests", 0))
            agg["batches"] += int(row.get("batches", 0))
    busy = out["busy_s"]
    for row in out["tenants"].values():
        row["cost_share"] = round(
            row["device_s"] / busy if busy > 0 else 0.0, 6
        )
        row["device_s"] = round(row["device_s"], 6)
    for k in ("replica_s", "busy_s", "idle_s"):
        out[k] = round(out[k], 6)
    return out


def price_per_million(bill: Dict, succeeded: int) -> Dict:
    """Fleet-global price of a million requests from one merged bill:
    replica-seconds per request scaled up, priced at
    ``HYDRAGNN_COST_PER_REPLICA_HOUR`` (default 1.0 currency units)."""
    rate = env_float("HYDRAGNN_COST_PER_REPLICA_HOUR", 1.0, minimum=0.0)
    replica_s = float(bill.get("replica_s", 0.0))
    per_million_s = (
        replica_s / succeeded * 1e6 if succeeded > 0 else float("inf")
    )
    return {
        "requests": int(succeeded),
        "replica_s": round(replica_s, 6),
        "replica_s_per_million": round(per_million_s, 3),
        "cost_per_replica_hour": rate,
        "cost_per_million": round(per_million_s / 3600.0 * rate, 6),
    }
