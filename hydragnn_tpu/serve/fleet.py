"""Self-healing serving fleet: replica supervision + zero-downtime hot-swap.

PR 2's :class:`~hydragnn_tpu.serve.server.InferenceServer` is one
process: a wedged batcher or a bad promote takes the endpoint down. This
module runs **N replica processes behind one front-end router**
(``serve/router.py``), coordinated through the same shared-directory
lease/tombstone protocol elastic training uses (``hydragnn_tpu.coord``,
extracted from ``train/elastic.py``) — replica loss is detected and
healed the same way host loss is in training.

Three roles:

- :class:`ReplicaServer` — runs INSIDE each replica process: wraps one
  ``InferenceServer`` with a stdlib HTTP ``POST /predict`` endpoint
  (plus ``/healthz``/``/metrics``), writes a heartbeat **lease**
  (``<dir>/replicas/replica-<k>.json`` — state, port, active version,
  request count), and runs a **promote watcher** thread that executes
  hot-swap commands (load candidate -> per-bucket warm through the live
  batcher, compile-counter verified -> ack) and follows the published
  active version.
- :class:`ServingFleet` — the per-host supervisor: spawns/respawns the
  replica processes, declares a replica lost on process exit OR stale
  lease (a wedged replica is killed and respawned at the next
  incarnation; repeat boot failures respawn under exponential backoff),
  prices every transition into the obs stack (``replica_lost`` / ``replica_respawned`` /
  ``fleet_degraded`` events + the ``hydragnn_fleet_*`` gauges), and
  orchestrates **zero-downtime hot-swap**: write a promote command, wait
  for every live replica's warmed ack, then atomically publish the new
  active version — any CRC-bad / warmup-failing / timed-out candidate
  rolls back loudly (``model_rollback``) with the old version still
  serving every request.
- the CLI — ``python -m hydragnn_tpu.serve.fleet --spec spec.json
  --dir <coord> --replicas N`` runs the supervisor; with
  ``HYDRAGNN_FLEET_REPLICA`` set in the environment (the supervisor
  sets it) the same entry point runs one replica instead.

Hot-swap lifecycle (all files under ``<dir>/promote/``)::

    supervisor                      each live replica
    ----------                      -----------------
    cmd-<c>.json  ---------------->  strict v2 load (CRC) of candidate
                                     warm_version through the batcher
                                       pass 1: exactly num_buckets compiles
                                       pass 2: ZERO (verified cached)
    all acks warmed?  <------------  ack-<c>-r<k>.json
      yes: active.json (atomic) -->  registry.promote at the next
           model_promoted            micro-batch boundary (in-flight
      no:  result-<c>.json           batches keep their packed entry —
           model_rollback            no mixed-version micro-batch)

Env set by the supervisor for each replica (presence of
``HYDRAGNN_FLEET_DIR`` + ``HYDRAGNN_FLEET_REPLICA`` is what turns the
replica-side machinery on): ``HYDRAGNN_FLEET_DIR``,
``HYDRAGNN_FLEET_REPLICA``, ``HYDRAGNN_FLEET_GEN`` (incarnation),
``HYDRAGNN_FLEET_HEARTBEAT_S``.

Degradation ladder (documented in docs/serving.md, enforced jointly
with the router): full fleet -> all lanes admitted; degraded (live <
target) -> lanes at/below the shed priority are rejected with
retry-after; zero live replicas -> everything sheds with retry-after
until the supervisor heals the fleet. Shedding always answers — a
request is never silently dropped.
"""

import json
import os
import pickle
import shutil
import signal
import subprocess
import sys
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, List, Optional

import numpy as np

from hydragnn_tpu import coord
from hydragnn_tpu.obs.events import RunEventLog
from hydragnn_tpu.obs.metrics import MetricsRegistry
from hydragnn_tpu.obs.trace import TRACE_HEADER, TraceContext
from hydragnn_tpu.utils import faults

REPLICA = "replica"  # coord kind AND member prefix for fleet leases
# canary replicas lease under a DIFFERENT kind (<dir>/canarys/): the
# router's discovery scan globs replicas/ only, so a canary is invisible
# to routing and capacity math by construction — no filtering logic to
# get wrong (serve/canary.py is the sole consumer of these leases)
CANARY = "canary"

# serving leases turn over much faster than training ones: a replica
# outage is user-visible latency, not a lost epoch
DEFAULT_HEARTBEAT_S = 0.25
DEFAULT_LEASE_S = 2.0


def highest_cmd(promote_dir: str) -> int:
    """Highest promote command id already on disk (written sequentially
    from 1) — the one walk both the supervisor's counter reseed and the
    replica's boot-time history fast-forward use."""
    highest = 0
    while os.path.exists(
        os.path.join(promote_dir, f"cmd-{highest + 1:06d}.json")
    ):
        highest += 1
    return highest


def lease_serving(lease: Optional[Dict], lease_s: float,
                  now: Optional[float] = None) -> bool:
    """THE definition of "this lease represents a live, serving
    replica" — shared by the supervisor's monitor tick, the promote
    quorum, and the router's discovery scan, so all three planes agree
    on liveness."""
    if lease is None or "ts" not in lease:
        return False
    now = time.time() if now is None else now
    return bool(
        lease.get("state") == "serving"
        and not lease.get("done")
        and now - float(lease["ts"]) <= float(lease_s)
    )


# ---- wire format -----------------------------------------------------------


def encode_graph(graph) -> Dict:
    """GraphData -> JSON-able dict (inference inputs only)."""
    payload = {
        "x": np.asarray(graph.x).tolist(),
        "edge_index": np.asarray(graph.edge_index).tolist(),
    }
    if graph.pos is not None:
        payload["pos"] = np.asarray(graph.pos).tolist()
    if graph.edge_attr is not None:
        payload["edge_attr"] = np.asarray(graph.edge_attr).tolist()
    return payload


def decode_graph(payload: Dict):
    from hydragnn_tpu.data.dataobj import GraphData

    g = GraphData(
        x=np.asarray(payload["x"], np.float32),
        pos=(
            np.asarray(payload["pos"], np.float32)
            if payload.get("pos") is not None
            else None
        ),
    )
    g.edge_index = np.asarray(payload["edge_index"], np.int64)
    if payload.get("edge_attr") is not None:
        g.edge_attr = np.asarray(payload["edge_attr"], np.float32)
    return g


# ---- fleet metrics ---------------------------------------------------------


class FleetMetrics:
    """The ``hydragnn_fleet_*`` series. One instance per PROCESS role:
    the supervisor records replica lifecycle, a router its routing /
    shedding side — both expose through the shared
    :class:`~hydragnn_tpu.obs.metrics.MetricsRegistry` machinery."""

    def __init__(self):
        r = MetricsRegistry("hydragnn_fleet")
        r.gauge("target_replicas", "Replica processes the fleet maintains")
        r.gauge("live_replicas", "Replicas currently holding a fresh lease")
        r.gauge(
            "availability",
            "live/target fraction (1.0 = full fleet serving)",
        )
        r.gauge("degraded", "1 while live < target (the shed trigger)")
        r.counter(
            "replica_losses_total",
            "Replica deaths detected (process exit or stale lease)",
        )
        r.counter("replica_respawns_total", "Replicas healed by respawn")
        r.gauge(
            "last_recovery_seconds",
            "Detection-to-serving downtime of the last respawn",
        )
        r.counter("promotes_total", "Hot-swap promotes published")
        r.counter(
            "rollbacks_total",
            "Hot-swap candidates rejected with the old version serving",
        )
        # router-side lanes (serve/router.py records these): cumulative
        # totals as labeled gauges, one series per admission lane
        r.counter("requests_routed_total", "Requests the router accepted")
        r.counter(
            "retries_total", "Routed attempts beyond each request's first"
        )
        r.counter(
            "replica_errors_total",
            "Replica attempts that failed (connection/5xx)",
        )
        r.labeled_gauge(
            "lane_shed_total", "Cumulative shed requests per admission lane"
        )
        r.labeled_gauge(
            "lane_retries_total", "Cumulative retries per admission lane"
        )
        # per-TENANT shed/retry series (multi-tenant serving): a quota
        # shed is the offending tenant's problem, not its lane's — the
        # lane-global gauges alone would blame every tenant in the lane
        r.labeled_gauge(
            "tenant_shed_total", "Cumulative quota-shed requests per tenant"
        )
        r.labeled_gauge(
            "tenant_retries_total", "Cumulative retried requests per tenant"
        )
        self.registry = r
        self._lane_lock = threading.Lock()
        self._lane_shed: Dict[str, int] = {}
        self._lane_retries: Dict[str, int] = {}
        self._tenant_shed: Dict[str, int] = {}
        self._tenant_retries: Dict[str, int] = {}

    def on_lane_shed(self, lane: str):
        with self._lane_lock:
            self._lane_shed[lane] = self._lane_shed.get(lane, 0) + 1
            total = self._lane_shed[lane]
        self.registry.set_labeled("lane_shed_total", total, lane=lane)

    def on_lane_retry(self, lane: str):
        with self._lane_lock:
            self._lane_retries[lane] = self._lane_retries.get(lane, 0) + 1
            total = self._lane_retries[lane]
        self.registry.set_labeled("lane_retries_total", total, lane=lane)

    def on_tenant_shed(self, tenant: str):
        with self._lane_lock:
            self._tenant_shed[tenant] = self._tenant_shed.get(tenant, 0) + 1
            total = self._tenant_shed[tenant]
        self.registry.set_labeled("tenant_shed_total", total, tenant=tenant)

    def on_tenant_retry(self, tenant: str):
        with self._lane_lock:
            self._tenant_retries[tenant] = (
                self._tenant_retries.get(tenant, 0) + 1
            )
            total = self._tenant_retries[tenant]
        self.registry.set_labeled(
            "tenant_retries_total", total, tenant=tenant
        )

    def render_prometheus(self) -> str:
        return self.registry.render_prometheus()

    def snapshot(self) -> Dict:
        return self.registry.snapshot()


# ---- replica-side ----------------------------------------------------------


class _ReplicaListener(ThreadingHTTPServer):
    allow_reuse_address = True
    daemon_threads = True  # a hung in-flight request must not block exit


class ReplicaServer:
    """One serving replica: ``InferenceServer`` + HTTP + lease + promote
    watcher. Usable in-process (tests drive real routing against it) or
    as the body of a supervised replica process (:func:`replica_main`).
    """

    def __init__(
        self,
        server,
        coord_dir: str,
        replica_id: int,
        port: int = 0,
        heartbeat_s: float = DEFAULT_HEARTBEAT_S,
        incarnation: int = 0,
        model_name: Optional[str] = None,
        arch_config: Optional[dict] = None,
        poll_s: float = 0.1,
        role: str = REPLICA,
    ):
        if role not in (REPLICA, CANARY):
            raise ValueError(f"unknown replica role {role!r}")
        self.server = server
        self.coord_dir = coord_dir
        self.replica_id = int(replica_id)
        self.role = role
        self.is_canary = role == CANARY
        self.incarnation = int(incarnation)
        self.model_name = model_name or (
            server.default_model or server.registry.names()[0]
        )
        self.arch_config = arch_config
        self.heartbeat_s = float(heartbeat_s)
        self.poll_s = float(poll_s)
        self._port = int(port)
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._http_thread: Optional[threading.Thread] = None
        self._watch_stop = threading.Event()
        self._watch_thread: Optional[threading.Thread] = None
        self.heartbeat: Optional[coord.Heartbeat] = None
        self._state = "starting"
        self._done = False
        self._lock = threading.Lock()  # guards counters + promote state
        self._served = 0
        # promote bookkeeping: cmd_id -> (name, warmed version); with
        # tenants a replica serves MANY names, each with its own promote
        # stream, so activation sequence and boot-time base version are
        # tracked per name. _warm_versions is the set of (name, version)
        # pairs ACTUALLY compiled per bucket — a switch onto anything
        # outside it must warm first or the batcher pays the compile
        # inline under traffic
        self._warmed: Dict[int, tuple] = {}
        self._warm_versions: set = set()
        self._base_versions: Dict[str, int] = {}
        self._last_cmd_handled = 0
        self._active_seqs: Dict[str, int] = {}
        # model-quality observatory hooks, wired by replica_main (or a
        # test harness) after construction — same pattern as
        # server.costs: None = feature off, zero request-path cost
        self.drift = None  # obs/drift.py DriftDetector
        self.sink = None   # serve/quality.py FeedbackSink

    def serving_names(self) -> List[str]:
        """Every model name this replica serves (the default plus all
        tenant-packed models) — the set a promote command may target."""
        return self.server.registry.names()

    # -- lifecycle -----------------------------------------------------------
    def start(self) -> "ReplicaServer":
        # the version each name BOOTED with is the cmd-0 "base" a fleet
        # rollback() reverts to — capture BEFORE catching up, which
        # registers (and activates) any published candidate as a NEWER
        # version; recording the candidate as base would make a later
        # rollback split serving versions across the fleet
        bases = {
            name: self.server.registry.get(name).version
            for name in self.serving_names()
        }
        with self._lock:
            self._base_versions = bases
        # catch up on an already-published active version BEFORE taking
        # traffic: a replica respawned mid/after a promote must come up
        # serving what the fleet serves, not the stale base checkpoint.
        # A CANARY never catches up: it exists to serve exactly the
        # candidate it booted with, not whatever the fleet promoted
        if not self.is_canary:
            self._catch_up_promotes()
        self.server.start()  # warms every registered model per bucket
        # PIN every currently-active version: without an explicit
        # promote the registry serves the LATEST registered version, so
        # merely registering a candidate mid-hot-swap would flip traffic
        # onto unwarmed weights before the supervisor publishes.
        # Promoting the current version makes activation explicit.
        for name in self.serving_names():
            self.server.registry.promote(
                name, self.server.registry.active_version(name)
            )
        # server.start() warmed the ACTIVE version of every name
        warm_now = {
            (name, self.server.registry.active_version(name))
            for name in self.serving_names()
        }
        with self._lock:
            self._warmed.setdefault(
                0, (self.model_name, bases[self.model_name])
            )
            self._warm_versions.update(warm_now)
        httpd = _ReplicaListener(("127.0.0.1", self._port), self._handler())
        thread = threading.Thread(
            target=httpd.serve_forever,
            name=f"hydragnn-replica-{self.replica_id}",
            daemon=True,
        )
        thread.start()
        with self._lock:
            self._httpd, self._http_thread = httpd, thread
            self._state = "serving"
        self.heartbeat = coord.Heartbeat(
            coord.hb_path(
                self.coord_dir, self.role, self.replica_id,
                prefix=self.role,
            ),
            self._lease_payload,
            self.heartbeat_s,
        ).start()
        if not self.is_canary:
            # a canary runs NO promote watcher: following active.json
            # would flip it off its candidate, and acking the fleet's
            # promote commands would corrupt the all-replica quorum
            watch = threading.Thread(
                target=self._watch_promotes,
                name=f"hydragnn-promote-watch-{self.replica_id}",
                daemon=True,
            )
            watch.start()
            with self._lock:
                self._watch_thread = watch
        return self

    @property
    def address(self):
        with self._lock:
            if self._httpd is None:
                return None
            return self._httpd.server_address[:2]

    def _lease_payload(self) -> Dict:
        with self._lock:
            state = self._state
            served = self._served
            done = self._done
            port = (
                self._httpd.server_address[1]
                if self._httpd is not None
                else 0
            )
        try:
            active = self.server.registry.get(self.model_name)
            active_info = {"name": active.name, "version": active.version,
                           "source": active.source}
        except KeyError:
            active_info = None
        # per-name active versions: the legacy "active" field covers the
        # default serving name only; named (per-tenant) promotes verify
        # propagation against this map
        actives = {}
        for name in self.serving_names():
            try:
                actives[name] = self.server.registry.active_version(name)
            except KeyError:
                pass
        return {
            "replica": self.replica_id,
            "role": self.role,
            "gen": self.incarnation,
            "state": state,
            "port": port,
            "served": served,
            "active": active_info,
            "actives": actives,
            "done": done,
        }

    def shutdown(self, drain: bool = True, timeout: float = 10.0):
        """Fleet-orchestrated (or operator) teardown: stop accepting,
        drain the batcher so every queued/in-flight future resolves with
        a terminal outcome, answer stragglers with 503 + retry-after,
        then release the lease marked done (a drained replica is
        finished, not lost)."""
        with self._lock:
            if self._state == "stopped":
                return
            self._state = "draining"
        self._watch_stop.set()
        with self._lock:
            watch = self._watch_thread
            self._watch_thread = None
        if watch is not None and watch.is_alive():
            watch.join(timeout=max(self.poll_s * 4, 2.0))
        # InferenceServer.stop resolves EVERY accepted future (result or
        # "server stopped") — the PR 6 stop-under-load contract; handler
        # threads waiting on those futures answer their clients from it
        self.server.stop(drain=drain, timeout=timeout)
        # flush partial quality state so no accepted feedback graph or
        # drift sample is lost across a drain (both calls are idempotent)
        if self.sink is not None:
            try:
                self.sink.close()
            except Exception:
                pass
        if self.drift is not None:
            try:
                self.drift.evaluate_window()  # close the partial window
            except Exception:
                pass
        with self._lock:
            httpd, self._httpd = self._httpd, None
            thread, self._http_thread = self._http_thread, None
            self._state = "stopped"
            self._done = True
        if httpd is not None:
            httpd.shutdown()
            httpd.server_close()
        if thread is not None:
            thread.join(timeout=5.0)
        if self.heartbeat is not None:
            self.heartbeat.stop()  # final write carries done=True

    def serve_forever(self):
        """CLI body: serve until SIGTERM/SIGINT, then drain and exit."""
        stop = threading.Event()

        def _sig(_signum, _frame):
            stop.set()

        signal.signal(signal.SIGTERM, _sig)
        signal.signal(signal.SIGINT, _sig)
        self.start()
        while not stop.wait(0.2):
            pass
        self.shutdown()

    # -- request path --------------------------------------------------------
    def _handler(self):
        replica = self

        class Handler(BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802 (stdlib naming)
                if self.path == "/healthz":
                    body = json.dumps(replica.health()).encode()
                    self._reply(200, body, "application/json")
                elif self.path == "/metrics":
                    text = replica.server.metrics.render_prometheus()
                    costs = getattr(replica.server, "costs", None)
                    if costs is not None:
                        # cost families append AFTER the server's stable
                        # series so existing scrape offsets never shift
                        text += costs.render_prometheus()
                    # quality families (uncertainty quantiles + drift
                    # scores) append after costs, same stable-offset rule
                    scorer = getattr(replica.server, "scorer", None)
                    if scorer is not None:
                        text += scorer.render_prometheus()
                    if replica.drift is not None:
                        text += replica.drift.render_prometheus()
                    self._reply(200, text.encode(), "text/plain")
                else:
                    self._reply(404, b"not found\n", "text/plain")

            def do_POST(self):  # noqa: N802
                if self.path != "/predict":
                    self._reply(404, b"not found\n", "text/plain")
                    return
                length = int(self.headers.get("Content-Length", "0"))
                try:
                    payload = json.loads(self.rfile.read(length))
                except (ValueError, OSError):
                    self._json(400, {"error": "unparseable request body"})
                    return
                code, body, headers = replica.handle_predict(
                    payload, trace_header=self.headers.get(TRACE_HEADER)
                )
                self._json(code, body, headers)

            def _json(self, code, obj, headers=None):
                self._reply(
                    code, json.dumps(obj).encode(), "application/json",
                    headers,
                )

            def _reply(self, code, body, ctype, headers=None):
                try:
                    self.send_response(code)
                    self.send_header("Content-Type", ctype)
                    self.send_header("Content-Length", str(len(body)))
                    for k, v in (headers or {}).items():
                        self.send_header(k, v)
                    self.end_headers()
                    self.wfile.write(body)
                except (BrokenPipeError, ConnectionResetError):
                    pass  # client gave up (deadline): nothing to answer

            def log_message(self, *args):  # request spam off stderr
                pass

        return Handler

    def handle_predict(self, payload: Dict,
                       trace_header: Optional[str] = None):
        """One request end to end; returns ``(status, body, headers)``.
        Factored out of the HTTP handler so tests can drive the exact
        request path (fault hooks included) without a socket.

        A well-formed ``X-Hydragnn-Trace`` header arms span capture for
        THIS request: replica-side spans (queue-wait, batch-form,
        dispatch, readback) ride back to the router in the response
        body's ``spans`` field, and EVERY body — success or error —
        echoes the request's ``trace`` id, so a failed attempt is still
        attributable to its end-to-end trace."""
        from hydragnn_tpu.serve.server import (
            DeadlineExceeded,
            ServerOverloaded,
        )
        from hydragnn_tpu.serve.buckets import GraphTooLarge

        ctx = TraceContext.from_header(trace_header)

        def _out(code, body, headers):
            # the router (the trace's single event-stream writer) merges
            # these spans under the attempt span it sent in the header
            if ctx is not None:
                body = dict(body)
                body["trace"] = ctx.trace_id
                body["spans"] = ctx.export()
            return code, body, headers

        # fault hooks fire on ACCEPTED requests, before any work — the
        # SIGKILL-mid-request and slow-replica injections
        faults.kill_replica_at_request()
        with self._lock:
            ordinal = self._served
            self._served += 1
        faults.slow_replica(ordinal)
        if self.is_canary:
            # bad-candidate injections fire ONLY on the canary role —
            # a fleet-wide env can regress the candidate under test but
            # never a live replica's answers or latency
            faults.slow_candidate(ordinal)
        try:
            graph = decode_graph(payload["graph"])
        except (KeyError, ValueError, TypeError):
            return _out(400, {"error": "malformed graph payload"}, {})
        # input-distribution-shift injection (drift-detector testing):
        # scales THIS replica's decoded copy only
        graph = faults.shift_inputs(graph, ordinal)
        deadline_s = payload.get("deadline_s")
        tenant = payload.get("tenant")
        try:
            fut = self.server.submit(
                graph,
                model=payload.get("model"),
                deadline_s=deadline_s,
                tenant=tenant,
                trace=ctx,
            )
        except ServerOverloaded as e:
            # a TenantOverQuota carries the offender's name: the router
            # scopes its backoff to THAT tenant instead of the whole lane
            return _out(
                503,
                {"error": "overloaded",
                 "retry_after_s": e.retry_after_s,
                 "tenant": getattr(e, "tenant", None)},
                {"Retry-After": f"{e.retry_after_s:.3f}"},
            )
        except GraphTooLarge as e:
            return _out(413, {"error": str(e)}, {})
        except (KeyError, ValueError) as e:
            # unknown model name / bad request fields: the request is
            # wrong, not the replica — 400 so the router does NOT retry
            return _out(400, {"error": str(e)}, {})
        except RuntimeError as e:  # server stopped (draining replica)
            retry = max(self.server.max_wait_s, 0.05)
            return _out(
                503,
                {"error": str(e), "retry_after_s": retry},
                {"Retry-After": f"{retry:.3f}"},
            )
        try:
            heads = fut.result(
                deadline_s if deadline_s is not None else 60.0
            )
        except DeadlineExceeded as e:
            return _out(504, {"error": str(e)}, {})
        except TimeoutError:
            return _out(504, {"error": "prediction timed out"}, {})
        except RuntimeError as e:
            # stop-under-load: an accepted future failed at shutdown —
            # terminal, explicit, retryable elsewhere
            retry = max(self.server.max_wait_s, 0.05)
            return _out(
                503,
                {"error": str(e), "retry_after_s": retry},
                {"Retry-After": f"{retry:.3f}"},
            )
        except Exception as e:  # dispatch error: failed, not dropped
            return _out(500, {"error": str(e)}, {})
        if self.is_canary and faults.nan_candidate(ordinal + 1):
            heads = [
                np.full(np.shape(np.asarray(h)), np.nan, np.float32)
                for h in heads
            ]
        # model-quality observatory: fold this request into the drift
        # sketches and offer interesting graphs to the feedback sink.
        # Both hooks are advisory — a broken detector must never turn a
        # successful prediction into an error response.
        unc = getattr(fut, "uncertainty", None)
        drifted = False
        if self.drift is not None:
            try:
                drifted = self.drift.observe(
                    tenant, graph=graph, heads=heads, uncertainty=unc
                )
            except Exception:
                drifted = False
        if self.sink is not None:
            self.sink.offer(graph, uncertainty=unc, drifted=drifted)
        body = {
            "heads": [np.asarray(h).tolist() for h in heads],
            "version": fut.version,
            # which packed model answered: the cross-tenant isolation
            # proof reads this (a tenant's responses must ALL carry
            # its own model), and the router's cache keys put() on it
            "model": fut.model_name,
            "tenant": tenant,
            "batch_seq": fut.batch_seq,
            "replica": self.replica_id,
        }
        if unc is not None:
            body["uncertainty"] = [float(v) for v in unc]
        return _out(200, body, {})

    def health(self) -> Dict:
        h = self.server.health()
        with self._lock:
            h.update(
                replica=self.replica_id,
                incarnation=self.incarnation,
                state=self._state,
                served=self._served,
            )
        return h

    # -- hot-swap ------------------------------------------------------------
    def _promote_dir(self) -> str:
        return os.path.join(self.coord_dir, "promote")

    def _cmd_path(self, cmd_id: int) -> str:
        return os.path.join(self._promote_dir(), f"cmd-{int(cmd_id):06d}.json")

    def _ack_path(self, cmd_id: int) -> str:
        return os.path.join(
            self._promote_dir(),
            f"ack-{int(cmd_id):06d}-r{self.replica_id}.json",
        )

    def _watch_promotes(self):
        warned = False
        wait = self.poll_s
        while not self._watch_stop.wait(wait):
            try:
                self.poll_promotes()
                wait = self.poll_s
            except Exception as e:
                # a torn command file must not kill the watcher — but a
                # replica PERSISTENTLY unable to follow the active
                # version (unreadable candidate) must be diagnosable,
                # and must not re-attempt the full checkpoint load every
                # tick
                if not warned:
                    warned = True
                    import warnings

                    warnings.warn(
                        f"replica {self.replica_id} promote watcher: "
                        f"{type(e).__name__}: {e} (will keep retrying "
                        "at reduced cadence)"
                    )
                wait = self.poll_s * 10

    def poll_promotes(self):
        """One watcher tick (public so in-process tests can step it
        deterministically): handle any new promote command, then follow
        the published active version."""
        pdir = self._promote_dir()
        if not os.path.isdir(pdir):
            return
        with self._lock:
            last = self._last_cmd_handled
        next_cmd = last + 1
        while True:
            cmd = coord.read_json(self._cmd_path(next_cmd))
            if cmd is None:
                break
            self._handle_promote_cmd(cmd)
            with self._lock:
                self._last_cmd_handled = next_cmd
            next_cmd += 1
        for active in self._published_actives():
            self._apply_active(active)

    def _published_actives(self) -> List[Dict]:
        """Every published active-version file: the legacy fleet-wide
        ``active.json`` plus one ``active-byname/<name>.json`` per model
        name a NAMED (per-tenant) promote has targeted. Applying both for
        the same name is safe — the per-name seq makes it idempotent."""
        pdir = self._promote_dir()
        out = []
        legacy = coord.read_json(os.path.join(pdir, "active.json"))
        if legacy is not None:
            out.append(legacy)
        bydir = os.path.join(pdir, "active-byname")
        if os.path.isdir(bydir):
            for fn in sorted(os.listdir(bydir)):
                if not fn.endswith(".json"):
                    continue
                active = coord.read_json(os.path.join(bydir, fn))
                if active is not None:
                    out.append(active)
        return out

    def _handle_promote_cmd(self, cmd: Dict):
        """Load + warm one candidate; ack warmed/failed. The old version
        serves throughout: the load happens off the batcher thread, the
        warmup routes THROUGH the batcher (interleaving with traffic),
        and nothing switches until the supervisor publishes."""
        cmd_id = int(cmd["cmd_id"])
        try:
            entry = self._load_candidate(cmd)
            warm = self.server.warm_version(entry.name, entry.version)
            if not warm["verified"]:
                raise RuntimeError(
                    "candidate warmup not compile-verified: pass 1 "
                    f"compiled {warm['first_pass_compiles']}/"
                    f"{warm['buckets']} buckets, later passes "
                    f"{warm['later_pass_compiles']} (want 0)"
                )
            with self._lock:
                self._warmed[cmd_id] = (entry.name, entry.version)
                self._warm_versions.add((entry.name, entry.version))
            coord.write_json(
                self._ack_path(cmd_id),
                {"cmd_id": cmd_id, "replica": self.replica_id,
                 "status": "warmed", "version": entry.version,
                 "name": entry.name,
                 "compiles": warm["first_pass_compiles"]},
            )
        except Exception as e:
            coord.write_json(
                self._ack_path(cmd_id),
                {"cmd_id": cmd_id, "replica": self.replica_id,
                 "status": "failed", "error": f"{type(e).__name__}: {e}"},
            )

    def _load_candidate(self, cmd: Dict):
        """Strict v2 load of the candidate into the registry (as the
        next INACTIVE version of the serving name). The corrupt-candidate
        fault injection reroutes the read through a byte-flipped copy so
        the real CRC path rejects it."""
        checkpoint = cmd["checkpoint"]
        target = cmd.get("name") or self.model_name
        if target not in self.serving_names():
            # the replica hot-swaps names it SERVES (the default plus
            # every tenant-packed model); a promote labeled with any
            # other name would mislabel the event stream and never be
            # routable — refuse loudly (acked "failed")
            raise ValueError(
                f"promote names {cmd['name']!r} but this replica serves "
                f"{sorted(self.serving_names())}"
            )
        path = cmd["path"]
        real = os.path.join(path, checkpoint, f"{checkpoint}.pk")
        injected = faults.corrupt_candidate(real)
        if injected != real:
            # stage a temp checkpoint layout around the corrupted copy
            # (the loader reads <path>/<name>/<name>.pk)
            stage = os.path.join(
                self.coord_dir,
                f"cand-{int(cmd['cmd_id'])}-r{self.replica_id}",
            )
            os.makedirs(os.path.join(stage, checkpoint), exist_ok=True)
            shutil.copyfile(
                injected, os.path.join(stage, checkpoint, f"{checkpoint}.pk")
            )
            path = stage
        return self.server.registry.load_checkpoint(
            checkpoint,
            arch_config=cmd.get("arch") or self.arch_config,
            path=path,
            name=target,
        )

    def _apply_active(self, active: Dict):
        """Follow the supervisor's published active version for ONE
        model name (the one the active file carries; the default serving
        name when absent). The switch is a registry promote: new submits
        resolve the new entry, batches in flight keep theirs — the
        micro-batch boundary IS the swap."""
        seq = int(active.get("seq", 0))
        target = active.get("name") or self.model_name
        with self._lock:
            if seq <= self._active_seqs.get(target, 0):
                return
            cmd_id = int(active.get("cmd_id", 0))
            if cmd_id == 0:
                # cmd 0 = the fleet rollback target: the base version of
                # the named model this incarnation booted with
                version = self._base_versions.get(target)
            else:
                warmed = self._warmed.get(cmd_id)
                version = (
                    warmed[1]
                    if warmed is not None and warmed[0] == target
                    else None
                )
        if version is None and int(active.get("cmd_id", 0)) != 0:
            # the published active references a candidate this replica
            # never warmed (respawned after the promote resolved, or the
            # startup active.json read raced the publish): adopt it now
            # — load, warm through the live batcher, then switch
            cmd_id = int(active.get("cmd_id", 0))
            cmd = coord.read_json(self._cmd_path(cmd_id))
            if cmd is None:
                return
            entry = self._load_candidate(cmd)
            self.server.warm_version(entry.name, entry.version)
            with self._lock:
                self._warmed[cmd_id] = (entry.name, entry.version)
                self._warm_versions.add((entry.name, entry.version))
            version = entry.version
        if version is None:
            return
        with self._lock:
            warm_needed = (target, version) not in self._warm_versions
        if warm_needed:
            # switching onto a registered-but-never-warmed version (a
            # respawned replica's booted base on a fleet rollback):
            # warm it through the live batcher FIRST, or every bucket's
            # first post-switch request pays a compile inline
            self.server.warm_version(target, version)
            with self._lock:
                self._warm_versions.add((target, version))
        self.server.registry.promote(target, version)
        with self._lock:
            self._active_seqs[target] = seq

    def _existing_cmds(self) -> int:
        return highest_cmd(self._promote_dir())

    def _catch_up_promotes(self):
        """Startup: adopt every published active version (fleet-wide AND
        per-name) before serving. Loads ONLY the active candidates —
        commands already on disk are NEVER replayed (their promotes
        resolved, or are resolving, against quorums that predate this
        incarnation; re-warming a rejected candidate on every respawn
        would burn compiles and overwrite historical acks). Warmup of
        the adopted versions happens in ``server.start()``, which warms
        the active version of every name."""
        existing = self._existing_cmds()
        with self._lock:
            self._last_cmd_handled = existing
        for active in self._published_actives():
            self._catch_up_one(active, existing)

    def _catch_up_one(self, active: Dict, existing: int):
        target = active.get("name") or self.model_name
        cmd_id = int(active.get("cmd_id", 0))
        seq = int(active.get("seq", 0))
        if cmd_id == 0:
            with self._lock:
                self._active_seqs[target] = max(
                    self._active_seqs.get(target, 0), seq
                )
                self._last_cmd_handled = max(
                    self._last_cmd_handled,
                    int(active.get("latest_cmd", 0)),
                )
            return
        cmd = coord.read_json(self._cmd_path(cmd_id))
        if cmd is None:
            # active references a torn/missing command: skip history and
            # let _apply_active's adopt path pick the version up live
            return
        entry = self._load_candidate(cmd)
        self.server.registry.promote(target, entry.version)
        with self._lock:
            self._warmed[cmd_id] = (target, entry.version)
            self._active_seqs[target] = max(
                self._active_seqs.get(target, 0), seq
            )
            # commands at or before the active one are history; later
            # ones (a promote racing our respawn) are handled live
            self._last_cmd_handled = max(
                self._last_cmd_handled, cmd_id,
                int(active.get("latest_cmd", cmd_id)),
            )


# ---- supervisor ------------------------------------------------------------


class _ReplicaHandle:
    """Supervisor-side state for one replica slot."""

    __slots__ = (
        "rid", "proc", "incarnation", "spawned_ts", "detect_ts",
        "was_serving", "fail_streak", "respawn_at",
    )

    def __init__(self, rid: int):
        self.rid = rid
        self.proc: Optional[subprocess.Popen] = None
        self.incarnation = 0
        self.spawned_ts = 0.0
        self.detect_ts: Optional[float] = None  # respawn pending since
        self.was_serving = False
        self.fail_streak = 0  # consecutive deaths without reaching serving
        self.respawn_at: Optional[float] = None  # backoff: spawn not before


class ServingFleet:
    """Supervise N replica processes through one coordination directory.

    The supervisor is also an ObservabilityServer provider (``health()``
    + ``metrics.render_prometheus()``), so ``observability_port`` exposes
    fleet ``/healthz`` + ``/metrics`` like any replica or training run.
    """

    def __init__(
        self,
        coord_dir: str,
        n_replicas: int,
        spec_path: Optional[str] = None,
        worker_cmd: Optional[List[str]] = None,
        env: Optional[Dict[str, str]] = None,
        heartbeat_s: float = DEFAULT_HEARTBEAT_S,
        lease_s: float = DEFAULT_LEASE_S,
        poll_s: float = 0.1,
        boot_timeout_s: float = 180.0,
        log_dir: Optional[str] = None,
        observability_port: Optional[int] = None,
    ):
        if spec_path is None and worker_cmd is None:
            raise ValueError("need spec_path or an explicit worker_cmd")
        self.coord_dir = coord_dir
        self.target = int(n_replicas)
        self.spec_path = spec_path
        self.worker_cmd = worker_cmd or [
            sys.executable, "-m", "hydragnn_tpu.serve.fleet",
            "--spec", spec_path, "--dir", coord_dir,
        ]
        self.extra_env = dict(env or {})
        self.heartbeat_s = float(heartbeat_s)
        self.lease_s = float(lease_s)
        self.poll_s = float(poll_s)
        self.boot_timeout_s = float(boot_timeout_s)
        self.metrics = FleetMetrics()
        self.metrics.registry.set("target_replicas", float(self.target))
        self.events = RunEventLog(
            os.path.join(log_dir or coord_dir, "events.jsonl")
        )
        self._replicas: Dict[int, _ReplicaHandle] = {
            rid: _ReplicaHandle(rid) for rid in range(self.target)
        }
        # slots removed by a scale-down: their processes drain (SIGTERM)
        # off the monitored set, but stop() still owns their teardown
        self._retired: List[_ReplicaHandle] = []
        self._lock = threading.Lock()  # guards _replicas + counters
        self._stop = threading.Event()
        self._monitor: Optional[threading.Thread] = None
        # degraded means LOST capacity: the flag starts True so the boot
        # window (live climbing 0 -> target) emits no fleet_degraded —
        # only a drop from a previously-full fleet does
        self._degraded = True
        self._next_cmd = 0
        self._active_seq = 0
        self._http = None
        self._observability_port = observability_port

    # -- lifecycle -----------------------------------------------------------
    def start(self, wait_serving: bool = True,
              timeout: Optional[float] = None) -> "ServingFleet":
        for sub in (f"{REPLICA}s", "dead", "promote",
                    os.path.join("promote", "active-byname")):
            os.makedirs(os.path.join(self.coord_dir, sub), exist_ok=True)
        self._emit_tenant_admissions()
        # a supervisor RESTARTED on an existing coordination dir must
        # continue the promote sequence, not restart it: reusing cmd id
        # 1 would overwrite history and let stale ack files satisfy the
        # new promote without any replica having warmed it
        pdir = os.path.join(self.coord_dir, "promote")
        seqs = [0]
        active = coord.read_json(os.path.join(pdir, "active.json"))
        if active is not None:
            seqs.append(int(active.get("seq", 0)))
        bydir = os.path.join(pdir, "active-byname")
        if os.path.isdir(bydir):
            # named promotes publish per-name actives: the seq counter
            # must clear THOSE too, or a restarted supervisor's next
            # promote would be ignored as stale by every replica
            for fn in os.listdir(bydir):
                if fn.endswith(".json"):
                    a = coord.read_json(os.path.join(bydir, fn))
                    if a is not None:
                        seqs.append(int(a.get("seq", 0)))
        with self._lock:
            self._next_cmd = max(self._next_cmd, highest_cmd(pdir))
            self._active_seq = max(self._active_seq, *seqs)
        for rid in range(self.target):
            self._spawn(self._replicas[rid])
        monitor = threading.Thread(
            target=self._monitor_loop, name="hydragnn-fleet-monitor",
            daemon=True,
        )
        monitor.start()
        with self._lock:
            self._monitor = monitor
        if self._observability_port is not None:
            from hydragnn_tpu.obs.http import ObservabilityServer

            self._http = ObservabilityServer(
                self, port=self._observability_port
            ).start()
        if wait_serving:
            self.wait_serving(timeout or self.boot_timeout_s)
        return self

    def stop(self, graceful: bool = True, timeout: float = 15.0):
        self._stop.set()
        with self._lock:
            monitor, self._monitor = self._monitor, None
            # snapshot: resize() mutates _replicas from other threads
            handles = list(self._replicas.values()) + list(self._retired)
        if monitor is not None and monitor.is_alive():
            monitor.join(timeout=max(self.poll_s * 8, 5.0))
        for handle in handles:
            proc = handle.proc
            if proc is None or proc.poll() is not None:
                continue
            if graceful:
                proc.terminate()  # replicas drain on SIGTERM
        deadline = time.monotonic() + timeout
        for handle in handles:
            proc = handle.proc
            if proc is None:
                continue
            try:
                proc.wait(timeout=max(deadline - time.monotonic(), 0.1))
            except subprocess.TimeoutExpired:
                proc.kill()
                proc.wait(timeout=10)
        if self._http is not None:
            self._http.stop()
            self._http = None
        self.events.close()

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()

    def emit(self, event: str, **fields):
        """Append one schema-gated event to the fleet stream (public:
        load generators append their ``fleet_report`` here)."""
        self.events.emit(event, **fields)

    def _emit_tenant_admissions(self):
        """One ``tenant_admitted`` per spec'd tenant at fleet start: the
        audit record of who is packed into this fleet with what quota."""
        if self.spec_path is None:
            return
        try:
            with open(self.spec_path) as f:
                spec = json.load(f)
        except (OSError, ValueError):
            return
        from hydragnn_tpu.serve.tenants import DEFAULT_QUOTA

        from hydragnn_tpu.utils.envparse import env_int

        default_quota = env_int(
            "HYDRAGNN_TENANT_DEFAULT_QUOTA", DEFAULT_QUOTA, minimum=1
        )
        for t in spec.get("tenants") or ():
            self.emit(
                "tenant_admitted",
                tenant=t.get("name"),
                model=t.get("model") or t.get("name"),
                quota=int(t.get("quota") or default_quota),
            )

    # -- autoscaling ---------------------------------------------------------
    def resize(self, n_replicas: int, reason: str = "manual") -> int:
        """Grow/shrink the supervised replica set to ``n_replicas``.

        Grow spawns fresh slots at the next rids; shrink SIGTERMs the
        highest rids, which drain (every in-flight future resolves) and
        release their leases marked done — removed from the monitored
        set first, so the monitor never "heals" an intentional retire.
        Emits ``fleet_scaled``; the autoscaler is the main caller."""
        n = int(n_replicas)
        if n < 1:
            raise ValueError(f"n_replicas must be >= 1, got {n}")
        grown: List[_ReplicaHandle] = []
        shrunk: List[_ReplicaHandle] = []
        with self._lock:
            old = self.target
            if n == old:
                return old
            if n > old:
                for rid in range(old, n):
                    handle = self._replicas.get(rid) or _ReplicaHandle(rid)
                    self._replicas[rid] = handle
                    grown.append(handle)
            else:
                for rid in range(n, old):
                    handle = self._replicas.pop(rid, None)
                    if handle is not None:
                        shrunk.append(handle)
                        self._retired.append(handle)
            self.target = n
            if grown:
                # new slots boot live < target for a while: that is
                # GROWTH, not lost capacity — suppress fleet_degraded
                # exactly like the initial boot window does
                self._degraded = True
        self.metrics.registry.set("target_replicas", float(n))
        for handle in grown:
            self._spawn(handle)
        for handle in shrunk:
            proc = handle.proc
            if proc is not None and proc.poll() is None:
                proc.terminate()  # drain, answer stragglers, lease done
        self.emit(
            "fleet_scaled", old_target=old, new_target=n, reason=reason
        )
        return n

    # -- spawning ------------------------------------------------------------
    def _worker_env(self, handle: _ReplicaHandle) -> Dict[str, str]:
        env = dict(os.environ)
        env.update(self.extra_env)
        env.update(
            HYDRAGNN_FLEET_DIR=self.coord_dir,
            HYDRAGNN_FLEET_REPLICA=str(handle.rid),
            HYDRAGNN_FLEET_GEN=str(handle.incarnation),
            HYDRAGNN_FLEET_HEARTBEAT_S=str(self.heartbeat_s),
        )
        return env

    def _spawn(self, handle: _ReplicaHandle):
        handle.proc = subprocess.Popen(
            self.worker_cmd, env=self._worker_env(handle)
        )
        handle.spawned_ts = time.time()
        handle.was_serving = False

    def replica_pid(self, rid: int) -> Optional[int]:
        proc = self._replicas[int(rid)].proc
        return None if proc is None else proc.pid

    def replica_port(self, rid: int) -> Optional[int]:
        lease = coord.read_json(
            coord.hb_path(self.coord_dir, REPLICA, rid, prefix=REPLICA)
        )
        if lease is None:
            return None
        return int(lease.get("port") or 0) or None

    # -- monitoring ----------------------------------------------------------
    def _lease(self, handle: _ReplicaHandle) -> Optional[Dict]:
        lease = coord.read_json(
            coord.hb_path(
                self.coord_dir, REPLICA, handle.rid, prefix=REPLICA
            )
        )
        if lease is None:
            return None
        if int(lease.get("gen", handle.incarnation)) != handle.incarnation:
            return None  # a previous incarnation's lease: booting
        return lease

    def _monitor_loop(self):
        while not self._stop.wait(self.poll_s):
            try:
                self._tick()
            except Exception:
                pass  # monitoring must outlive any single bad read

    def _tick(self, now: Optional[float] = None):
        now = time.time() if now is None else now
        live = 0
        with self._lock:  # resize() mutates the dict concurrently
            handles = list(self._replicas.values())
        for handle in handles:
            if handle.respawn_at is not None:
                # respawn backoff window: the slot is down by decision,
                # not death — spawn once the window closes
                if now >= handle.respawn_at:
                    handle.respawn_at = None
                    self._spawn(handle)
                continue
            lease = self._lease(handle)
            serving = lease_serving(lease, self.lease_s, now)
            if serving:
                live += 1
                if not handle.was_serving:
                    handle.was_serving = True
                    handle.fail_streak = 0  # reached serving: heal worked
                    if handle.detect_ts is not None:
                        downtime = now - handle.detect_ts
                        handle.detect_ts = None
                        self.metrics.registry.inc("replica_respawns_total")
                        self.metrics.registry.set(
                            "last_recovery_seconds", round(downtime, 3)
                        )
                        self.emit(
                            "replica_respawned",
                            replica=handle.rid,
                            downtime_s=round(downtime, 3),
                            incarnation=handle.incarnation,
                        )
                continue
            reason = self._death_reason(handle, lease, now)
            if reason is None:
                continue
            self._heal(handle, reason, now)
        self._publish_status(live)

    def _death_reason(self, handle: _ReplicaHandle, lease: Optional[Dict],
                      now: float) -> Optional[str]:
        proc = handle.proc
        if proc is None:
            return None
        rc = proc.poll()
        if rc is not None:
            return f"exit_{rc}"
        if lease is None:
            # no current-incarnation lease yet: still booting, unless it
            # has been booting implausibly long (wedged before serving)
            if now - handle.spawned_ts > self.boot_timeout_s:
                return "boot_timeout"
            return None
        if lease.get("done"):
            return None  # drained clean: not a loss, not respawned
        if now - float(lease["ts"]) > self.lease_s:
            return "lease_expired"
        return None

    def _heal(self, handle: _ReplicaHandle, reason: str, now: float):
        """One replica death end to end: kill whatever is left of the
        process, emit + count the loss, respawn at the next incarnation.
        (No tombstone: replicas run no peer watchdog and the router
        discovers from leases alone, so the supervisor's SIGKILL is the
        whole eviction.) A slot that keeps dying before ever reaching
        serving respawns under exponential backoff — a persistent boot
        failure (bad spec, missing checkpoint) must not turn the
        supervisor into a fork storm."""
        proc = handle.proc
        if proc is not None and proc.poll() is None:
            proc.kill()  # wedged (stale lease): SIGKILL, not a drain
            try:
                proc.wait(timeout=10)
            except subprocess.TimeoutExpired:
                pass
        age = coord.heartbeat_age(
            self.coord_dir, REPLICA, handle.rid, now=now, prefix=REPLICA
        )
        self.metrics.registry.inc("replica_losses_total")
        self.emit(
            "replica_lost",
            replica=handle.rid,
            reason=reason,
            stale_s=None if age is None else round(float(age), 3),
            incarnation=handle.incarnation,
        )
        handle.detect_ts = handle.detect_ts or now
        handle.incarnation += 1
        streak = handle.fail_streak
        handle.fail_streak += 1
        if streak == 0:
            self._spawn(handle)  # first failure heals immediately
        else:
            handle.respawn_at = now + min(0.5 * (2.0 ** (streak - 1)), 15.0)

    def _publish_status(self, live: int):
        with self._lock:
            # resize() flips _degraded under the same lock (the grow
            # boot-window suppression); the read-modify-write here must
            # not race it into a spurious fleet_degraded
            degraded = live < self.target
            was_degraded = self._degraded
            self._degraded = degraded
        self.metrics.registry.set("live_replicas", float(live))
        self.metrics.registry.set(
            "availability", live / max(self.target, 1)
        )
        self.metrics.registry.set("degraded", float(degraded))
        if degraded and not was_degraded:
            self.emit("fleet_degraded", live=live, target=self.target)
        coord.write_json(
            os.path.join(self.coord_dir, "fleet.json"),
            {"live": live, "target": self.target, "degraded": degraded,
             "ts": time.time()},
        )

    def wait_serving(self, timeout: float = 60.0) -> int:
        """Block until every replica serves (or timeout); returns the
        live count. The monitor keeps healing regardless."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            status = coord.read_json(
                os.path.join(self.coord_dir, "fleet.json")
            )
            if status and status.get("live", 0) >= self.target:
                return int(status["live"])
            time.sleep(self.poll_s)
        status = coord.read_json(
            os.path.join(self.coord_dir, "fleet.json")
        )
        return int(status.get("live", 0)) if status else 0

    # -- hot-swap orchestration ----------------------------------------------
    def promote(
        self,
        checkpoint: str,
        path: str,
        arch_config: Optional[dict] = None,
        name: Optional[str] = None,
        timeout: float = 120.0,
    ) -> Dict:
        """Zero-downtime promote: command every live replica to load +
        warm the candidate; publish the new active version only when ALL
        of them ack warmed. Any failed/timed-out ack rolls back — the
        active version (and every replica's serving state) is untouched
        and the rejection is loud (``model_rollback`` + return value)."""
        with self._lock:
            self._next_cmd += 1
            cmd_id = self._next_cmd
        pdir = os.path.join(self.coord_dir, "promote")
        cmd = {
            "cmd_id": cmd_id,
            "checkpoint": checkpoint,
            "path": os.path.abspath(path),
            "name": name,
            "ts": time.time(),
        }
        if arch_config is not None:
            cmd["arch"] = arch_config
        coord.write_json(
            os.path.join(pdir, f"cmd-{cmd_id:06d}.json"), cmd
        )
        # the ack quorum is the replicas SERVING on a FRESH lease at
        # command time — a stale lease is a death in progress, and
        # waiting on its ack would block the promote for the full
        # timeout. A member that gets respawned mid-promote fails the
        # promote fast instead: its new incarnation never saw the
        # command (boot fast-forwards history) and adopts the candidate
        # from active.json only if the promote resolves without it.
        now = time.time()
        quorum_inc: Dict[int, int] = {}
        with self._lock:
            handles = list(self._replicas.values())
        for h in handles:
            if lease_serving(self._lease(h), self.lease_s, now):
                quorum_inc[h.rid] = h.incarnation
        if not quorum_inc:
            # nobody serving means nobody can warm the candidate — fail
            # NOW with a clear reason rather than blocking the full
            # timeout (replicas booting right now fast-forward past this
            # command and would never ack it)
            reason = "no serving replica to warm the candidate"
            result = {
                "status": "rolled_back",
                "cmd_id": cmd_id,
                "reason": reason,
                "acks": {},
            }
            coord.write_json(
                os.path.join(pdir, f"result-{cmd_id:06d}.json"), result
            )
            self.metrics.registry.inc("rollbacks_total")
            self.emit(
                "model_rollback",
                name=name or checkpoint,
                reason=reason,
                cmd_id=cmd_id,
            )
            return result
        quorum = sorted(quorum_inc)
        acks: Dict[int, Dict] = {}
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline and len(acks) < len(quorum):
            for rid in quorum:
                if rid in acks:
                    continue
                with self._lock:
                    handle = self._replicas.get(rid)
                if handle is None or handle.incarnation != quorum_inc[rid]:
                    acks[rid] = {
                        "status": "failed",
                        "error": "replica lost and respawned mid-promote",
                    }
                    continue
                ack = coord.read_json(
                    os.path.join(pdir, f"ack-{cmd_id:06d}-r{rid}.json")
                )
                if ack is not None:
                    acks[rid] = ack
            time.sleep(self.poll_s)
        failed = {
            rid: ack for rid, ack in acks.items()
            if ack.get("status") != "warmed"
        }
        missing = [rid for rid in quorum if rid not in acks]
        if failed or missing:
            reason = "; ".join(
                [f"replica {rid}: {ack.get('error', 'failed')}"
                 for rid, ack in sorted(failed.items())]
                + [f"replica {rid}: no ack within {timeout:.0f}s"
                   for rid in missing]
            )
            result = {
                "status": "rolled_back",
                "cmd_id": cmd_id,
                "reason": reason,
                "acks": acks,
            }
            coord.write_json(
                os.path.join(pdir, f"result-{cmd_id:06d}.json"), result
            )
            self.metrics.registry.inc("rollbacks_total")
            self.emit(
                "model_rollback",
                name=name or checkpoint,
                reason=reason,
                cmd_id=cmd_id,
                **(
                    {}
                    if not acks
                    else {"version": max(
                        int(a.get("version", 0)) for a in acks.values()
                    )}
                ),
            )
            return result
        with self._lock:
            self._active_seq += 1
            seq = self._active_seq
        versions = {rid: int(ack["version"]) for rid, ack in acks.items()}
        t_publish = time.time()
        active_payload = {
            "seq": seq, "cmd_id": cmd_id, "checkpoint": checkpoint,
            "name": name, "latest_cmd": cmd_id, "ts": t_publish,
        }
        if name is None:
            coord.write_json(
                os.path.join(pdir, "active.json"), active_payload
            )
        else:
            # NAMED promotes (per-tenant hot-swap) publish under
            # active-byname/<name>.json and leave active.json alone:
            # each model name gets its own active pointer, so promotes
            # of different names never overwrite each other's catch-up
            # state for respawning replicas
            os.makedirs(
                os.path.join(pdir, "active-byname"), exist_ok=True
            )
            coord.write_json(
                os.path.join(pdir, "active-byname", f"{name}.json"),
                active_payload,
            )
        # wait (bounded) for every acked replica's lease to REPORT the
        # new active version: when this returns "propagated", the whole
        # fleet answers new submits from the candidate — the swap is
        # done, not merely announced
        prop_deadline = time.monotonic() + max(
            min(timeout, 30.0), self.poll_s * 4
        )

        def _lease_reports(rid: int) -> bool:
            with self._lock:
                handle = self._replicas.get(rid)
            if handle is None:
                return True  # retired by a scale-down mid-propagation
            lease = self._lease(handle)
            if lease is None:
                return False
            if name is not None:
                # named promote: verify against the per-name actives map
                # (the legacy "active" field tracks the DEFAULT name)
                reported = (lease.get("actives") or {}).get(name)
            else:
                reported = (lease.get("active") or {}).get("version")
            return reported == versions[rid]

        propagated = False
        while time.monotonic() < prop_deadline and not propagated:
            propagated = all(_lease_reports(rid) for rid in versions)
            if not propagated:
                time.sleep(self.poll_s)
        result = {
            "status": "promoted",
            "cmd_id": cmd_id,
            "versions": versions,
            "propagated": propagated,
            "acks": acks,
        }
        coord.write_json(
            os.path.join(pdir, f"result-{cmd_id:06d}.json"), result
        )
        self.metrics.registry.inc("promotes_total")
        self.emit(
            "model_promoted",
            name=name or checkpoint,
            version=max(versions.values()),
            cmd_id=cmd_id,
            replicas=sorted(versions),
            propagation_s=round(time.time() - t_publish, 3),
        )
        return result

    def rollback(self, reason: str = "operator",
                 name: Optional[str] = None) -> Dict:
        """Revert the published active version to the base checkpoint
        (cmd 0) — fleet-wide default name, or ONE tenant model when
        ``name`` is given. Replicas re-promote their original entry at
        the next watcher tick — already warm, so the revert is also
        downtime-free."""
        with self._lock:
            self._active_seq += 1
            seq = self._active_seq
            latest = self._next_cmd
        payload = {"seq": seq, "cmd_id": 0, "latest_cmd": latest,
                   "name": name, "ts": time.time()}
        if name is None:
            coord.write_json(
                os.path.join(self.coord_dir, "promote", "active.json"),
                payload,
            )
        else:
            bydir = os.path.join(
                self.coord_dir, "promote", "active-byname"
            )
            os.makedirs(bydir, exist_ok=True)
            coord.write_json(
                os.path.join(bydir, f"{name}.json"), payload
            )
        self.metrics.registry.inc("rollbacks_total")
        self.emit(
            "model_rollback", name=name or "<base>", reason=reason,
            cmd_id=0,
        )
        return {"status": "rolled_back", "cmd_id": 0, "reason": reason}

    # -- provider protocol ---------------------------------------------------
    def health(self) -> Dict:
        status = coord.read_json(
            os.path.join(self.coord_dir, "fleet.json")
        ) or {}
        live = int(status.get("live", 0))
        with self._lock:
            handles = dict(self._replicas)
        return {
            "status": "ok" if live >= self.target else (
                "degraded" if live else "down"
            ),
            "live": live,
            "target": self.target,
            "replicas": {
                rid: {
                    "incarnation": h.incarnation,
                    "pid": None if h.proc is None else h.proc.pid,
                    "port": self.replica_port(rid),
                }
                for rid, h in handles.items()
            },
        }


# ---- spec-driven replica process -------------------------------------------


def build_server_from_spec(spec: Dict):
    """Build (InferenceServer, arch_config, model_name) from a fleet
    spec — the one recipe the CLI replica, tests, and the bench share::

        {
          "checkpoint": {"name": "model", "path": "logs/"},
          "arch": {... Architecture section ...},
          "model_name": "model",          # registry/serving name
          "samples": "samples.pkl",       # list[GraphData] for the plan
          "plan": {"max_batch_graphs": 8, "num_buckets": 3},
          "server": {"max_wait_s": 0.005, "queue_capacity": 256},
          "tenants": [                    # optional: multi-tenant packing
            {"name": "acme", "model": "model", "quota": 32, "weight": 2},
            {"name": "beta", "model": "aux",
             "checkpoint": {"name": "aux_ck", "path": "logs/"}}
          ],
          "cache": {"enabled": true}      # optional: response cache
        }
    """
    from hydragnn_tpu.serve.buckets import plan_from_samples
    from hydragnn_tpu.serve.registry import ModelRegistry
    from hydragnn_tpu.serve.server import InferenceServer

    with open(spec["samples"], "rb") as f:
        samples = pickle.load(f)
    plan_kw = dict(spec.get("plan", {}))
    plan = plan_from_samples(samples, **plan_kw)
    registry = ModelRegistry()
    name = spec.get("model_name") or spec["checkpoint"]["name"]
    registry.load_checkpoint(
        spec["checkpoint"]["name"],
        arch_config=spec.get("arch"),
        path=spec["checkpoint"]["path"],
        name=name,
    )
    tenants = None
    if spec.get("tenants"):
        from hydragnn_tpu.serve.tenants import TenantManager

        # tenant models HBM-pack into the same registry at server
        # construction (InferenceServer calls tenants.load_models);
        # tenants whose model IS the default name share its entry
        tenants = TenantManager.from_specs(spec["tenants"])
    from hydragnn_tpu.serve.cache import ResponseCache

    cache = ResponseCache.from_env(spec.get("cache"))
    from hydragnn_tpu.serve.quality import UncertaintyScorer

    # opt-in K-sample uncertainty path (HYDRAGNN_UNC_SAMPLES=0 → None,
    # zero scoring programs compiled, steady state unchanged)
    scorer = UncertaintyScorer.from_env(registry)
    server_kw = dict(spec.get("server", {}))
    server = InferenceServer(
        registry, plan, default_model=name, tenants=tenants,
        cache=cache, scorer=scorer, **server_kw
    )
    return server, spec.get("arch"), name


def replica_main(spec_path: str) -> int:
    """Body of one supervised replica process (the CLI's --replica-id
    mode): build the server from the spec, serve until SIGTERM."""
    with open(spec_path) as f:
        spec = json.load(f)
    coord_dir = os.environ["HYDRAGNN_FLEET_DIR"]
    rid = int(os.environ["HYDRAGNN_FLEET_REPLICA"])
    server, arch, name = build_server_from_spec(spec)
    # each replica gets its OWN event stream (RunEventLog's per-file seq
    # forbids multi-process writers on one file); the obs CLI and the
    # bench merge events*.jsonl from the coord dir
    from hydragnn_tpu.serve.costs import CostLedger

    cost_events = RunEventLog(
        os.path.join(coord_dir, f"events-replica{rid}.jsonl")
    )
    server.costs = CostLedger(emit=cost_events.emit)
    replica = ReplicaServer(
        server,
        coord_dir,
        rid,
        incarnation=int(os.getenv("HYDRAGNN_FLEET_GEN", "0")),
        heartbeat_s=float(
            os.getenv("HYDRAGNN_FLEET_HEARTBEAT_S",
                      str(DEFAULT_HEARTBEAT_S))
        ),
        model_name=name,
        arch_config=arch,
        # the canary controller spawns this same entry point with
        # HYDRAGNN_FLEET_CANARY=1: same server, canary lease namespace,
        # no promote watcher
        role=CANARY if os.getenv("HYDRAGNN_FLEET_CANARY") else REPLICA,
    )
    # model-quality observatory: drift detector with version-pinned
    # reference windows (snapshotted in the coord dir so promote and
    # rollback can never alias baselines) plus the feedback sink; both
    # are env-gated and None when their knobs are unset
    from hydragnn_tpu.obs.drift import DriftDetector
    from hydragnn_tpu.serve.quality import FeedbackSink

    # reference snapshots and feedback packs are PER-PROCESS state
    # (DriftDetector persists drift-ref-v<N>.json on bootstrap/promote,
    # FeedbackSink's pack ranks count from 0), so each replica gets its
    # own subdir — two replicas sharing one path would overwrite each
    # other's reference file / shard.00000.gpk
    drift = DriftDetector.from_env(
        os.path.join(coord_dir, f"drift-replica{rid}"),
        emit=cost_events.emit,
    )
    replica.drift = drift
    sink = FeedbackSink.from_env(emit=cost_events.emit)
    if sink is not None:
        sink.queue_dir = os.path.join(
            sink.queue_dir, f"replica{rid}"
        )
    replica.sink = sink
    if drift is not None:
        # promote/rollback re-pins the reference to the activated
        # version; the initial call adopts (or loads) v_active's window
        server.registry.add_activation_listener(
            lambda _name, version: drift.on_activate(version)
        )
        drift.on_activate(server.registry.active_version(name))
    replica.serve_forever()
    return 0


def main(argv: Optional[List[str]] = None) -> int:
    import argparse

    parser = argparse.ArgumentParser(
        prog="python -m hydragnn_tpu.serve.fleet",
        description="Serving-fleet supervisor / replica (module docs).",
    )
    parser.add_argument("--spec", required=True, help="fleet spec JSON")
    parser.add_argument("--dir", default=None,
                        help="coordination dir (supervisor mode)")
    parser.add_argument("--replicas", type=int, default=2)
    parser.add_argument("--heartbeat", type=float,
                        default=DEFAULT_HEARTBEAT_S)
    parser.add_argument("--lease", type=float, default=DEFAULT_LEASE_S)
    parser.add_argument("--obs-port", type=int, default=None)
    args = parser.parse_args(argv)
    if os.getenv("HYDRAGNN_FLEET_REPLICA") is not None:
        return replica_main(args.spec)
    if args.dir is None:
        parser.error("supervisor mode needs --dir")
    fleet = ServingFleet(
        args.dir,
        args.replicas,
        spec_path=args.spec,
        heartbeat_s=args.heartbeat,
        lease_s=args.lease,
        observability_port=args.obs_port,
    )
    stop = threading.Event()
    signal.signal(signal.SIGTERM, lambda *_: stop.set())
    signal.signal(signal.SIGINT, lambda *_: stop.set())
    fleet.start()
    while not stop.wait(0.5):
        pass
    fleet.stop()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
