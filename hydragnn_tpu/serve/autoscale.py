"""SLO-driven predictive autoscaling for the serving fleet.

Reactive autoscaling (scale when the SLO is already burning) pays the
replica boot + warmup time (tens of seconds: process spawn, checkpoint
load, per-bucket compile) in USER-VISIBLE misses. This module scales the
:class:`~hydragnn_tpu.serve.fleet.ServingFleet` from two signals so that
capacity usually arrives BEFORE the miss:

- **SLO pressure (reactive floor)** — the PR 11 deadline ledger
  (``slo_miss_ratio`` over the last tick window) plus admission sheds:
  a window over the miss budget, or any shed traffic, forces at least
  one replica of growth regardless of what the forecast says.
- **Short-horizon forecast (predictive)** — an EWMA of request rate
  blended with a diurnal profile: the day is split into fixed phases
  (``period_s / n_phases`` each) and each phase keeps its own EWMA of
  observed load, so a traffic curve that repeats (the diurnal pattern
  every serving fleet has) is anticipated one phase ahead. Desired
  capacity is ``ceil(forecast / per-replica capacity)``.

Hysteresis is what keeps it from fighting the self-healing monitor:

- separate up/down cooldowns (down much longer — growing is cheap to
  undo, shrinking under rising load is not);
- scale-down is REFUSED while the fleet is degraded (live < target):
  a dead replica being respawned is the monitor's job, and shrinking
  target to match a momentary live dip would turn every replica loss
  into a permanent capacity loss;
- min/max bounds are hard clamps.

All knobs route through ``HYDRAGNN_AUTOSCALE_*`` env vars (validated in
:mod:`~hydragnn_tpu.utils.envparse`); every scaling action lands in the
event stream as ``fleet_scaled`` via :meth:`ServingFleet.resize`.
"""

import math
import os
import threading
import time
from typing import Callable, Dict, List, Optional

from hydragnn_tpu.utils.envparse import env_float, env_int


class AutoscalePolicy:
    """Bounds + hysteresis + forecast shape for one autoscaler."""

    def __init__(
        self,
        min_replicas: int = 1,
        max_replicas: int = 8,
        capacity_rps: float = 50.0,
        slo_budget: float = 0.05,
        up_cooldown_s: float = 10.0,
        down_cooldown_s: float = 60.0,
        ewma_alpha: float = 0.3,
        period_s: float = 86400.0,
        n_phases: int = 24,
        headroom: float = 1.2,
    ):
        if min_replicas < 1:
            raise ValueError("min_replicas must be >= 1")
        if max_replicas < min_replicas:
            raise ValueError("max_replicas must be >= min_replicas")
        if capacity_rps <= 0:
            raise ValueError("capacity_rps must be > 0")
        if not 0.0 < ewma_alpha <= 1.0:
            raise ValueError("ewma_alpha must be in (0, 1]")
        if n_phases < 1:
            raise ValueError("n_phases must be >= 1")
        self.min_replicas = int(min_replicas)
        self.max_replicas = int(max_replicas)
        self.capacity_rps = float(capacity_rps)
        self.slo_budget = float(slo_budget)
        self.up_cooldown_s = float(up_cooldown_s)
        self.down_cooldown_s = float(down_cooldown_s)
        self.ewma_alpha = float(ewma_alpha)
        self.period_s = float(period_s)
        self.n_phases = int(n_phases)
        self.headroom = float(headroom)

    @classmethod
    def from_env(cls, **overrides) -> "AutoscalePolicy":
        """Policy from ``HYDRAGNN_AUTOSCALE_*`` knobs; explicit kwargs
        win over env, env wins over defaults."""
        kw = dict(
            min_replicas=env_int("HYDRAGNN_AUTOSCALE_MIN", 1, minimum=1),
            max_replicas=env_int("HYDRAGNN_AUTOSCALE_MAX", 8, minimum=1),
            capacity_rps=env_float(
                "HYDRAGNN_AUTOSCALE_CAPACITY_RPS", 50.0
            ),
            slo_budget=env_float("HYDRAGNN_AUTOSCALE_SLO_BUDGET", 0.05),
            up_cooldown_s=env_float(
                "HYDRAGNN_AUTOSCALE_UP_COOLDOWN_S", 10.0
            ),
            down_cooldown_s=env_float(
                "HYDRAGNN_AUTOSCALE_DOWN_COOLDOWN_S", 60.0
            ),
            period_s=env_float("HYDRAGNN_AUTOSCALE_PERIOD_S", 86400.0),
        )
        kw.update(overrides)
        return cls(**kw)


class LoadForecast:
    """EWMA + diurnal-phase request-rate forecast.

    ``observe(rps, now)`` feeds one measured window; ``forecast(now)``
    returns the expected rate for the phase ``now`` falls into — the
    max of the global EWMA (tracks the current level) and that phase's
    own EWMA from previous periods (anticipates the repeating curve).
    Phases never observed fall back to the global EWMA alone.
    """

    def __init__(self, alpha: float = 0.3, period_s: float = 86400.0,
                 n_phases: int = 24):
        self.alpha = float(alpha)
        self.period_s = float(period_s)
        self.n_phases = int(n_phases)
        self._ewma: Optional[float] = None
        self._phase_ewma: List[Optional[float]] = [None] * self.n_phases

    def _phase(self, now: float) -> int:
        return int((now % self.period_s) / self.period_s * self.n_phases
                   ) % self.n_phases

    def observe(self, rps: float, now: float):
        rps = max(float(rps), 0.0)
        self._ewma = (
            rps if self._ewma is None
            else self.alpha * rps + (1 - self.alpha) * self._ewma
        )
        p = self._phase(now)
        prev = self._phase_ewma[p]
        self._phase_ewma[p] = (
            rps if prev is None
            else self.alpha * rps + (1 - self.alpha) * prev
        )

    def forecast(self, now: float, horizon_s: float = 0.0) -> float:
        """Expected rps at ``now + horizon_s`` (default: the current
        phase). Looking one phase ahead is what buys boot time: capacity
        for the morning ramp starts spawning during the last quiet
        phase."""
        if self._ewma is None:
            return 0.0
        p = self._phase(now + horizon_s)
        phase = self._phase_ewma[p]
        return self._ewma if phase is None else max(self._ewma, phase)


class FleetAutoscaler:
    """Closed loop: signals -> forecast -> :meth:`ServingFleet.resize`.

    ``signals`` is any zero-arg callable returning CUMULATIVE counters —
    the router's ``ServeMetrics.snapshot()`` is accepted as-is
    (``requests_total`` / ``shed_total`` / ``deadline_met_total`` /
    ``deadline_missed_total``), as is a nested
    ``{"slo": {"deadline_met": ..., "deadline_missed": ...}}`` shape.

    The autoscaler diffs consecutive snapshots itself, so wiring it to a
    live router is one lambda. ``tick(now)`` is public and deterministic
    (inject ``now``) — tests drive the whole loop without threads or
    sleeps; ``start()`` runs it on a timer for production.
    """

    def __init__(
        self,
        fleet,
        signals: Callable[[], Dict],
        policy: Optional[AutoscalePolicy] = None,
        interval_s: Optional[float] = None,
        forecast: Optional[LoadForecast] = None,
    ):
        self.fleet = fleet
        self.signals = signals
        self.policy = policy or AutoscalePolicy.from_env()
        self.interval_s = (
            env_float("HYDRAGNN_AUTOSCALE_INTERVAL_S", 5.0)
            if interval_s is None
            else float(interval_s)
        )
        if self.interval_s <= 0:
            raise ValueError("interval_s must be > 0")
        self.forecast = forecast or LoadForecast(
            alpha=self.policy.ewma_alpha,
            period_s=self.policy.period_s,
            n_phases=self.policy.n_phases,
        )
        self._prev: Optional[Dict] = None
        self._prev_ts: Optional[float] = None
        self._last_up = float("-inf")
        self._last_down = float("-inf")
        self.decisions: List[Dict] = []  # bounded audit trail
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- lifecycle -----------------------------------------------------------
    def start(self) -> "FleetAutoscaler":
        if self._thread is not None:
            return self
        self._stop.clear()
        thread = threading.Thread(
            target=self._loop, name="hydragnn-autoscaler", daemon=True
        )
        thread.start()
        self._thread = thread
        return self

    def stop(self):
        self._stop.set()
        thread, self._thread = self._thread, None
        if thread is not None and thread.is_alive():
            thread.join(timeout=max(self.interval_s * 2, 5.0))

    def _loop(self):
        while not self._stop.wait(self.interval_s):
            try:
                self.tick()
            except Exception:
                pass  # scaling must outlive any single bad snapshot

    # -- the control loop ----------------------------------------------------
    @staticmethod
    def _counters(snap: Dict) -> Dict[str, float]:
        slo = snap.get("slo") or {}
        return {
            "requests": float(snap.get("requests_total", 0)),
            "shed": float(snap.get("shed_total", 0)),
            "met": float(
                slo.get("deadline_met", snap.get("deadline_met_total", 0))
            ),
            "missed": float(
                slo.get("deadline_missed",
                        snap.get("deadline_missed_total", 0))
            ),
        }

    def _fleet_degraded(self) -> bool:
        from hydragnn_tpu import coord

        status = coord.read_json(
            os.path.join(self.fleet.coord_dir, "fleet.json")
        )
        return bool(status and status.get("degraded"))

    def tick(self, now: Optional[float] = None) -> Optional[Dict]:
        """One control step; returns the decision record (None on the
        priming tick, which only seeds the counter baseline)."""
        now = time.time() if now is None else now
        cur = self._counters(self.signals())
        prev, self._prev = self._prev, cur
        prev_ts, self._prev_ts = self._prev_ts, now
        if prev is None or prev_ts is None or now <= prev_ts:
            return None
        window = now - prev_ts
        d = {k: max(cur[k] - prev[k], 0.0) for k in cur}
        rps = d["requests"] / window
        self.forecast.observe(rps, now)
        outcomes = d["met"] + d["missed"]
        miss_ratio = d["missed"] / outcomes if outcomes else 0.0
        slo_pressure = (
            miss_ratio > self.policy.slo_budget or d["shed"] > 0
        )
        # predictive demand: next-phase forecast, with headroom so the
        # fleet does not run at exactly 100% of estimated capacity
        phase_s = self.policy.period_s / self.policy.n_phases
        want_rps = self.forecast.forecast(now, horizon_s=phase_s)
        desired = math.ceil(
            (want_rps * self.policy.headroom) / self.policy.capacity_rps
        )
        current = int(self.fleet.target)
        reason = "forecast"
        if slo_pressure:
            # the reactive floor: the SLO is burning NOW, grow at least
            # one replica whatever the forecast believes
            desired = max(desired, current + 1)
            reason = "slo_pressure"
        desired = min(
            max(desired, self.policy.min_replicas),
            self.policy.max_replicas,
        )
        applied = current
        if desired > current:
            if now - self._last_up >= self.policy.up_cooldown_s:
                applied = self.fleet.resize(desired, reason=reason)
                self._last_up = now
        elif desired < current:
            if (
                now - self._last_down >= self.policy.down_cooldown_s
                and now - self._last_up >= self.policy.down_cooldown_s
                and not self._fleet_degraded()
            ):
                # shrink only from a HEALTHY fleet, long after the last
                # grow: a live dip is the monitor's to heal, and a fresh
                # spike may return before the down-cooldown expires
                applied = self.fleet.resize(desired, reason="scale_down")
                self._last_down = now
        decision = {
            "ts": now,
            "rps": round(rps, 3),
            "forecast_rps": round(want_rps, 3),
            "miss_ratio": round(miss_ratio, 6),
            "shed": d["shed"],
            "desired": desired,
            "applied": applied,
            "reason": reason,
        }
        self.decisions.append(decision)
        del self.decisions[:-200]
        return decision
