"""Serving observability endpoint — re-export of the shared listener.

The stdlib ``/healthz`` + ``/metrics`` listener that started here (PR 2)
was promoted to :mod:`hydragnn_tpu.obs.http`: it only ever needed a
provider with ``health()`` and ``metrics.render_prometheus()``, which an
:class:`~hydragnn_tpu.serve.server.InferenceServer` and a training
:class:`~hydragnn_tpu.obs.runtime.RunTelemetry` both satisfy. This module
keeps the historical import path alive with an unchanged public API.
"""

from hydragnn_tpu.obs.http import (  # noqa: F401  (re-exported API)
    ObservabilityServer,
)

__all__ = ["ObservabilityServer"]
