"""Stdlib observability endpoint pair for the predict server.

``GET /healthz`` — JSON liveness/readiness (registry contents, warmup
state, queue depth); non-2xx when the server is stopped, so a load
balancer can eject the replica. ``GET /metrics`` — Prometheus text
exposition of :class:`~hydragnn_tpu.serve.metrics.ServeMetrics`.

``http.server`` only (the container bakes in no web framework); the
listener runs on a daemon thread and ``port=0`` binds an ephemeral port
(tests and multi-replica hosts), readable from ``address`` after
``start()``.
"""

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional, Tuple


class ObservabilityServer:
    """Serves ``/healthz`` + ``/metrics`` for one
    :class:`~hydragnn_tpu.serve.server.InferenceServer`."""

    def __init__(self, inference_server, port: int = 8080,
                 host: str = "127.0.0.1"):
        self._inference = inference_server
        self._host = host
        self._port = port
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None

    def start(self):
        if self._httpd is not None:
            return self
        inference = self._inference

        class Handler(BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802 (stdlib naming)
                if self.path == "/healthz":
                    health = inference.health()
                    body = json.dumps(health).encode()
                    code = 200 if health.get("status") == "ok" else 503
                    ctype = "application/json"
                elif self.path == "/metrics":
                    body = inference.metrics.render_prometheus().encode()
                    code = 200
                    ctype = "text/plain; version=0.0.4"
                else:
                    body = b"not found: serve exposes /healthz and /metrics\n"
                    code = 404
                    ctype = "text/plain"
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *args):  # scrape spam off stderr
                pass

        self._httpd = ThreadingHTTPServer((self._host, self._port), Handler)
        self._thread = threading.Thread(
            target=self._httpd.serve_forever,
            name="hydragnn-serve-observability",
            daemon=True,
        )
        self._thread.start()
        return self

    @property
    def address(self) -> Optional[Tuple[str, int]]:
        """(host, port) actually bound — port 0 resolves here."""
        if self._httpd is None:
            return None
        return self._httpd.server_address[:2]

    def stop(self):
        if self._httpd is None:
            return
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread is not None:
            self._thread.join(5.0)
            self._thread = None
        self._httpd = None
