"""Per-request uncertainty scoring + the labeled-on-demand feedback sink.

The serving half of the model-quality observatory (``obs/drift.py`` is
the scoring half):

- :class:`UncertaintyScorer` — an OPT-IN K-sample scoring path producing
  per-head predictive variance for every dispatched batch. Two modes,
  both the standard recipes: ``dropout`` (MC dropout, Gal & Ghahramani
  2016: K forward passes under K fixed PRNG dropout keys — models
  without dropout layers honestly report zero variance) and ``ensemble``
  (deep-ensemble style, Lakshminarayanan et al. 2017: one pass per
  registered version of the model, up to the last K). Each (model
  version, bucket) gets ONE extra compiled program with a leading sample
  axis — warmed at startup/promote exactly like the predict program, so
  steady state stays recompile-free and the compile counter keeps being
  the regression alarm.
- :class:`FeedbackSink` — high-uncertainty / drifted request graphs,
  deduplicated by ``canonical_graph_key`` (permutation-stable, so the
  same molecule re-sent with shuffled atoms cannot enqueue twice),
  buffered and flushed as bounded shard_store packs under a queue dir.
  The queue dir is a valid ``ShardStoreSource``/``ShardDataset`` input:
  the next active-learning PR points a ``WeightedMix`` at it and trains.
"""

import math
import threading
from typing import Dict, List, Optional, Tuple

import numpy as np

MODES = ("dropout", "ensemble")


class UncertaintyScorer:
    """K-sample predictive-variance scoring over the serving registry.

    ``dispatch(entry, dev_batch)`` runs the (cached, jitted) scoring
    program for the entry and returns one variance array per head,
    shaped exactly like the predict program's outputs — the server
    slices them per request with the same coordinates. ``signature``
    keys the server's seen-shapes accounting so a scorer compile is
    counted (and warmed) like any other bucket program.
    """

    def __init__(
        self,
        mode: str = "dropout",
        samples: int = 4,
        seed: int = 0,
        registry=None,
        metrics=None,
    ):
        if mode not in MODES:
            raise ValueError(
                f"HYDRAGNN_UNC_MODE must be one of {MODES}, got {mode!r}"
            )
        if samples < 2:
            raise ValueError(
                f"uncertainty scoring needs samples >= 2, got {samples}"
            )
        from hydragnn_tpu.obs.metrics import MetricsRegistry

        self.mode = mode
        self.samples = int(samples)
        self.seed = int(seed)
        self.registry = registry
        self.metrics = metrics or MetricsRegistry("hydragnn")
        self.metrics.labeled_gauge(
            "uncertainty",
            "per-tenant/bucket/head predictive-variance quantiles",
        )
        self._fns: Dict[Tuple, object] = {}
        self._stacked: Dict[Tuple, Tuple] = {}
        self._lock = threading.Lock()
        self._quant: Dict[Tuple, Dict] = {}
        self.scored = 0

    # ---- compiled scoring programs -------------------------------------
    def signature(self, entry) -> Tuple:
        """Extra shape-accounting key: the scoring program recompiles
        when (and only when) its member set changes — for dropout never,
        for ensemble on promote (which re-warms anyway)."""
        if self.mode == "ensemble":
            return ("score", "ensemble", entry.name,
                    self._member_versions(entry))
        return ("score", "dropout", entry.key, self.samples, self.seed)

    def dispatch(self, entry, dev_batch):
        """Per-head predictive variance for one packed batch (device
        arrays; the caller device_gets alongside the predict outputs)."""
        if self.mode == "ensemble":
            fn, stacked_params, stacked_bs = self._ensemble_fn(entry)
            return fn(stacked_params, stacked_bs, dev_batch)
        fn = self._dropout_fn(entry)
        return fn(entry.params, entry.batch_stats, dev_batch)

    def _dropout_fn(self, entry):
        key = ("dropout", entry.key)
        fn = self._fns.get(key)
        if fn is not None:
            return fn
        import jax
        import jax.numpy as jnp

        from hydragnn_tpu.obs.introspect import instrument
        from hydragnn_tpu.parallel.mesh import jit_replicated

        model = entry.model
        k, seed = self.samples, self.seed

        def _apply(params, batch_stats, batch):
            variables = {"params": params}
            if batch_stats:
                variables["batch_stats"] = batch_stats

            def one(rng):
                # train=True activates the dropout masks; BatchNorm's
                # batch-stats mutation is computed and DISCARDED — the
                # served running averages never move
                out, _ = model.apply(
                    variables, batch, train=True,
                    rngs={"dropout": rng}, mutable=["batch_stats"],
                )
                return out

            # fixed keys: same sample set every dispatch, so the scored
            # variance is a deterministic function of the input (and the
            # program never sees a novel shape after warmup)
            keys = jax.random.split(jax.random.PRNGKey(seed), k)
            outs = jax.vmap(one)(keys)
            return tuple(jnp.var(o, axis=0) for o in outs)

        fn = instrument(
            f"serve_score:{entry.name}:v{entry.version}",
            jit_replicated(_apply),
        )
        self._fns[key] = fn
        return fn

    def _member_versions(self, entry) -> Tuple[int, ...]:
        """The ensemble member set: the entry's version plus up to K-1
        predecessors still registered (entries are never removed, so
        every promoted version remains available)."""
        versions = [entry.version]
        if self.registry is not None:
            v = entry.version - 1
            while len(versions) < self.samples and v >= 1:
                try:
                    self.registry.get(entry.name, v)
                except KeyError:
                    break
                versions.append(v)
                v -= 1
        return tuple(sorted(versions))

    def _ensemble_fn(self, entry):
        versions = self._member_versions(entry)
        key = ("ensemble", entry.name, versions)
        cached = self._stacked.get(key)
        if cached is None:
            import jax

            members = [
                self.registry.get(entry.name, v) if self.registry
                else entry
                for v in versions
            ]
            stacked_params = jax.tree_util.tree_map(
                lambda *xs: np.stack([np.asarray(x) for x in xs]),
                *[m.params for m in members],
            )
            has_bs = bool(members[0].batch_stats)
            stacked_bs = (
                jax.tree_util.tree_map(
                    lambda *xs: np.stack([np.asarray(x) for x in xs]),
                    *[m.batch_stats for m in members],
                )
                if has_bs
                else {}
            )
            cached = (stacked_params, stacked_bs)
            self._stacked[key] = cached
        fn = self._fns.get(key)
        if fn is None:
            import jax
            import jax.numpy as jnp

            from hydragnn_tpu.obs.introspect import instrument
            from hydragnn_tpu.parallel.mesh import jit_replicated

            model = entry.model
            has_bs = bool(cached[1])

            def _apply(stacked_params, stacked_bs, batch):
                def one(params, batch_stats):
                    variables = {"params": params}
                    if has_bs:
                        variables["batch_stats"] = batch_stats
                    return model.apply(variables, batch, train=False)

                outs = jax.vmap(one)(stacked_params, stacked_bs)
                return tuple(jnp.var(o, axis=0) for o in outs)

            fn = instrument(
                f"serve_score:{entry.name}:"
                f"ens{'-'.join(str(v) for v in versions)}",
                jit_replicated(_apply),
            )
            self._fns[key] = fn
        return fn, cached[0], cached[1]

    # ---- per-tenant/bucket histograms ----------------------------------
    def observe(self, tenant, bucket, head_vars: List[float]):
        """Fold one request's per-head variance scalars into the
        per-(tenant, bucket, head) quantile sketches + gauges."""
        from hydragnn_tpu.obs.drift import P2Quantile

        with self._lock:
            self.scored += 1
            for ihead, v in enumerate(head_vars):
                if v is None or not math.isfinite(float(v)):
                    continue
                key = (tenant or "-", int(bucket), ihead)
                qs = self._quant.get(key)
                if qs is None:
                    qs = self._quant[key] = {
                        "p50": P2Quantile(0.5),
                        "p90": P2Quantile(0.9),
                        "p99": P2Quantile(0.99),
                    }
                for name, sk in qs.items():
                    sk.add(float(v))
                    val = sk.value()
                    if val is not None:
                        self.metrics.set_labeled(
                            "uncertainty", val,
                            tenant=key[0], bucket=key[1],
                            head=ihead, q=name,
                        )

    def stats(self) -> Dict:
        with self._lock:
            quantiles = {
                f"{t}|{b}|{h}": {
                    name: sk.value() for name, sk in qs.items()
                }
                for (t, b, h), qs in sorted(self._quant.items())
            }
            return {
                "mode": self.mode,
                "samples": self.samples,
                "scored": self.scored,
                "quantiles": quantiles,
            }

    def render_prometheus(self) -> str:
        return self.metrics.render_prometheus()

    @classmethod
    def from_env(cls, registry=None) -> Optional["UncertaintyScorer"]:
        """``HYDRAGNN_UNC_SAMPLES`` >= 2 enables scoring (default 0 =
        off); ``HYDRAGNN_UNC_MODE`` picks the recipe,
        ``HYDRAGNN_UNC_SEED`` the dropout sample keys. All parsed via
        ``utils/envparse`` so a bad value names its variable."""
        import os

        from hydragnn_tpu.utils.envparse import env_int

        samples = env_int("HYDRAGNN_UNC_SAMPLES", 0)
        if samples == 0:
            return None
        if samples < 2:
            raise ValueError(
                "HYDRAGNN_UNC_SAMPLES must be 0 (off) or >= 2 "
                f"(K scoring samples), got {samples}"
            )
        mode = os.getenv("HYDRAGNN_UNC_MODE", "dropout")
        return cls(
            mode=mode,
            samples=samples,
            seed=env_int("HYDRAGNN_UNC_SEED", 0),
            registry=registry,
        )


class FeedbackSink:
    """Dedup + bound + persist the graphs worth labeling.

    ``offer`` admits a graph when the request was drifted (detector
    alert active) or its max per-head predictive variance clears
    ``min_unc``; admitted graphs dedup by ``canonical_graph_key`` (an
    LRU seen-set, so permuted duplicates of the same graph never enqueue
    twice), buffer up to ``max_graphs`` and flush as one shard_store
    pack (``shard.<packs:05d>.gpk``) under ``queue_dir`` — which is then
    directly consumable by ``ShardStoreSource``/``ShardDataset``. At
    most ``max_packs`` packs are ever written (bounded disk), after
    which offers count as dropped.
    """

    def __init__(
        self,
        queue_dir: str,
        *,
        max_graphs: int = 256,
        max_packs: int = 16,
        min_unc: float = 0.0,
        emit=None,
    ):
        self.queue_dir = queue_dir
        self.max_graphs = max(int(max_graphs), 1)
        self.max_packs = max(int(max_packs), 1)
        self.min_unc = float(min_unc)
        self.emit = emit
        self._lock = threading.Lock()
        self._buf: List = []
        self._seen: "dict" = {}  # canonical key -> True, LRU-bounded
        self._seen_cap = max(4 * self.max_graphs, 1024)
        self.offered = 0
        self.accepted = 0
        self.deduped = 0
        self.dropped = 0
        self.graphs = 0  # persisted
        self.packs = 0
        self._next_rank = 0  # reserved under the lock: concurrent
        # flushes must never write the same shard.<rank>.gpk

    def offer(self, graph, uncertainty=None, drifted: bool = False) -> bool:
        """Consider one served graph; returns True when it was buffered
        for labeling. Never raises into the request path."""
        try:
            return self._offer(graph, uncertainty, drifted)
        except Exception:
            return False

    def _offer(self, graph, uncertainty, drifted) -> bool:
        admit = bool(drifted)
        if not admit and uncertainty is not None:
            finite = [
                float(v) for v in uncertainty
                if v is not None and math.isfinite(float(v))
            ]
            admit = bool(finite) and max(finite) >= self.min_unc
        with self._lock:
            self.offered += 1
            if not admit:
                return False
            from hydragnn_tpu.serve.cache import canonical_graph_key

            key = canonical_graph_key(graph)
            if key in self._seen:
                self._seen.pop(key)
                self._seen[key] = True  # refresh LRU position
                self.deduped += 1
                return False
            if self.packs >= self.max_packs:
                self.dropped += 1
                return False
            self._seen[key] = True
            while len(self._seen) > self._seen_cap:
                self._seen.pop(next(iter(self._seen)))
            self._buf.append(graph.clone())
            self.accepted += 1
            flush = len(self._buf) >= self.max_graphs
        if flush:
            self.flush()
        return True

    def flush(self):
        """Persist the buffered graphs as one pack (tmp + rename via
        ShardWriter, so a reader never sees a torn pack)."""
        with self._lock:
            if not self._buf or self._next_rank >= self.max_packs:
                return
            buf, self._buf = self._buf, []
            rank = self._next_rank
            self._next_rank += 1
        from hydragnn_tpu.data.shard_store import ShardWriter

        writer = ShardWriter(self.queue_dir, rank=rank)
        writer.add(buf)
        writer.save()
        with self._lock:
            self.packs += 1
            self.graphs += len(buf)
        if self.emit is not None:
            self.emit("feedback_sink", **self.stats())

    def close(self):
        self.flush()

    def stats(self) -> Dict:
        with self._lock:
            return {
                "offered": self.offered,
                "accepted": self.accepted,
                "deduped": self.deduped,
                "dropped": self.dropped,
                "graphs": self.graphs,
                "packs": self.packs,
                "buffered": len(self._buf),
            }

    @classmethod
    def from_env(cls, emit=None) -> Optional["FeedbackSink"]:
        """``HYDRAGNN_FEEDBACK_DIR`` (unset = sink off) + bounded-size
        knobs, all via ``utils/envparse``."""
        import os

        from hydragnn_tpu.utils.envparse import env_float, env_int

        queue_dir = os.getenv("HYDRAGNN_FEEDBACK_DIR")
        if not queue_dir:
            return None
        return cls(
            queue_dir,
            max_graphs=env_int(
                "HYDRAGNN_FEEDBACK_MAX_GRAPHS", 256, minimum=1
            ),
            max_packs=env_int(
                "HYDRAGNN_FEEDBACK_MAX_PACKS", 16, minimum=1
            ),
            min_unc=env_float("HYDRAGNN_FEEDBACK_MIN_UNC", 0.0),
            emit=emit,
        )
