"""Fleet front-end: deadline-aware routing, budgeted retry, priority lanes.

One :class:`FleetRouter` per client process turns "a pool of replica
processes behind a coordination directory" into the single-endpoint
surface callers already know from
:class:`~hydragnn_tpu.serve.server.InferenceServer`: ``route()`` a graph,
get per-head outputs back, or one of the SAME exceptions the in-process
server raises (``ServerOverloaded`` with a retry-after hint,
``GraphTooLarge``, ``DeadlineExceeded``) — the degradation contract is
spelled identically whether the shed happened at a replica's queue or at
the router's admission gate.

Routing rules, in the order they bite:

- **Discovery is the lease scan**: live replicas are the ones holding a
  fresh ``replicas/replica-<k>.json`` lease in ``serving`` state (the
  same files the fleet supervisor heals from — the router needs no
  channel to the supervisor). Scans are cached for one heartbeat
  interval; the supervisor's ``fleet.json`` supplies the target count
  for the degradation check.
- **Admission control with priority lanes**: every request names a lane
  (default ``"default"``); each lane has a priority (0 = most
  important). While the fleet is degraded (live < target), lanes with
  priority >= ``shed_priority_when_degraded`` are rejected IMMEDIATELY
  with ``ServerOverloaded`` + retry-after — load-shedding the
  background traffic is what keeps the interactive lane's latency
  bounded while the supervisor heals. With ZERO live replicas
  everything sheds (never an unbounded client-side queue).
- **Deadline-aware retry with jittered backoff, budgeted**: a replica
  attempt that fails for a RETRYABLE reason (connection refused/reset —
  the replica died; 503 — it shed or is draining) is retried against
  the next replica after the shared ``utils/retry.py`` backoff curve,
  as long as (a) the request's deadline has room for another attempt
  and (b) the fleet-wide :class:`RetryBudget` grants a token. The
  budget earns a fraction of a token per SUCCESS (default 0.1) up to a
  small reserve: under total outage retries self-extinguish at ~10% of
  the success rate instead of amplifying the overload into a retry
  storm. Non-retryable failures (400/413/500 — the request itself is
  bad or genuinely failed) propagate immediately.
- **SLO accounting**: the router owns a
  :class:`~hydragnn_tpu.serve.metrics.ServeMetrics` — every
  deadline-carrying request lands in the PR 11 deadline series
  (``deadline_met/missed``, ``slo_miss_ratio``) measured END TO END
  (queueing + retries + transport), plus the ``hydragnn_fleet_*``
  per-lane shed/retry gauges from :class:`~hydragnn_tpu.serve.fleet.
  FleetMetrics`.
"""

import glob
import http.client
import json
import os
import re
import threading
import time
import urllib.error
import urllib.request
from typing import Dict, List, Optional, Tuple

import numpy as np

from hydragnn_tpu import coord
from hydragnn_tpu.serve.fleet import (
    DEFAULT_HEARTBEAT_S,
    DEFAULT_LEASE_S,
    REPLICA,
    FleetMetrics,
    encode_graph,
    lease_serving,
)
from hydragnn_tpu.obs.trace import TRACE_HEADER, new_id as _new_span_id
from hydragnn_tpu.serve.metrics import ServeMetrics
from hydragnn_tpu.serve.server import DeadlineExceeded, ServerOverloaded
from hydragnn_tpu.utils.retry import backoff_delay


def _span(tr, name: str, since_mono: float, span_id=None, **attrs):
    """Record one router-side span ending NOW (no-op with tracing off —
    the disabled path pays one ``is None`` check)."""
    if tr is None:
        return
    dur = time.monotonic() - since_mono
    tr.record(name, time.time() - dur, dur, span_id=span_id, **attrs)


class NoLiveReplica(ConnectionError):
    """Every routed attempt failed and no retry was possible."""


class RetryBudget:
    """Token bucket that caps fleet-wide retries to a fraction of the
    success rate (the classic retry-budget rule: a retry storm must not
    amplify an outage). Starts with ``reserve`` tokens so the FIRST
    failures of a healthy fleet can retry immediately; each success
    earns ``ratio`` tokens back, capped at the reserve."""

    def __init__(self, ratio: float = 0.1, reserve: float = 10.0):
        if ratio < 0 or reserve <= 0:
            raise ValueError("ratio must be >= 0 and reserve > 0")
        self.ratio = float(ratio)
        self.reserve = float(reserve)
        self._lock = threading.Lock()
        self._tokens = float(reserve)

    def on_success(self):
        with self._lock:
            self._tokens = min(self._tokens + self.ratio, self.reserve)

    def try_acquire(self) -> bool:
        """Take one retry token; False = budget exhausted, fail the
        request rather than add load."""
        with self._lock:
            if self._tokens >= 1.0:
                self._tokens -= 1.0
                return True
            return False

    @property
    def tokens(self) -> float:
        with self._lock:
            return self._tokens


class FleetRouter:
    """Route requests to the live replicas of one coordination dir."""

    def __init__(
        self,
        coord_dir: str,
        target_replicas: Optional[int] = None,
        lanes: Optional[Dict[str, int]] = None,
        shed_priority_when_degraded: int = 1,
        lease_s: float = DEFAULT_LEASE_S,
        scan_interval_s: float = DEFAULT_HEARTBEAT_S,
        retry_budget: Optional[RetryBudget] = None,
        retry_base_delay_s: float = 0.02,
        max_attempts: int = 4,
        default_deadline_s: Optional[float] = None,
        connect_timeout_s: float = 5.0,
        cache=None,
        tracer=None,
    ):
        self.coord_dir = coord_dir
        self._target = target_replicas
        self.lanes = dict(lanes or {"default": 0, "batch": 1})
        self.shed_priority_when_degraded = int(shed_priority_when_degraded)
        self.lease_s = float(lease_s)
        self.scan_interval_s = float(scan_interval_s)
        self.budget = retry_budget or RetryBudget()
        self.retry_base_delay_s = float(retry_base_delay_s)
        self.max_attempts = max(int(max_attempts), 1)
        self.default_deadline_s = default_deadline_s
        self.connect_timeout_s = float(connect_timeout_s)
        self.metrics = ServeMetrics()  # the PR 11 deadline/SLO series
        self.fleet_metrics = FleetMetrics()
        self._lock = threading.Lock()  # guards the scan cache + cursor
        self._scan_ts = 0.0
        self._cached: List[Tuple[int, int]] = []  # [(replica, port)]
        self._target_ts = float("-inf")  # fleet.json cache, same TTL
        self._target_cached: Optional[int] = None
        self._rr = 0  # round-robin cursor
        # shadow tap (serve/canary.py): called with every successful
        # response so a canary controller can mirror live traffic —
        # MUST be non-blocking and may never raise into the live path
        self._shadow = None
        # router-side response cache (serve/cache.py, optional): keyed
        # on the fleet's CONSENSUS active version per model name (read
        # off the same lease scan discovery uses) — mid-swap, when live
        # replicas disagree, lookups and fills are skipped entirely so a
        # cached answer is always the version the whole fleet serves
        self.cache = cache
        if cache is not None and cache.metrics is None:
            cache.metrics = self.metrics
        # request tracing (obs/trace.py): when armed, every route()
        # generates a trace id, propagates it to the replica attempts
        # as an X-Hydragnn-Trace header, buffers the spans per request
        # and tail-flushes at the terminal outcome. None = off (the
        # default): the hot path pays one None check
        self.tracer = tracer
        self._consensus: Dict[str, Optional[int]] = {}
        # tenant -> model name, learned from response bodies: lets a
        # tenant-routed request build its cache key without the router
        # holding a copy of the fleet's tenant spec
        self._tenant_models: Dict[str, str] = {}
        # tenant -> monotonic time until which that tenant is shed
        # locally (a replica answered its quota-503): the offender backs
        # off at the router while every other tenant routes normally
        self._tenant_backoff: Dict[str, float] = {}

    # ---- shadow routing ------------------------------------------------
    def set_shadow(self, tap) -> None:
        """Install ``tap(graph, body, latency_s)`` on the success path.
        The tap sees the routed graph and the full response body of
        every 200 AFTER the client's answer is already decided — a
        shadow comparison can never change, delay (the tap's contract is
        to enqueue-or-drop, never block) or fail a live response. Pass
        ``None`` to detach."""
        self._shadow = tap

    # ---- discovery -----------------------------------------------------
    def _scan(self, now: Optional[float] = None):
        """Fresh (replica, port) list from the lease files, plus the
        fleet's per-model consensus active version (None for any name
        the live replicas DISAGREE on — a hot-swap in flight)."""
        now = time.time() if now is None else now
        live = []
        versions: Dict[str, set] = {}
        pattern = os.path.join(
            self.coord_dir, f"{REPLICA}s", f"{REPLICA}-*.json"
        )
        for path in sorted(glob.glob(pattern)):
            m = re.search(rf"{REPLICA}-(\d+)\.json$", path)
            if not m:
                continue
            lease = coord.read_json(path)
            if not lease_serving(lease, self.lease_s, now):
                continue
            if not lease.get("port"):
                continue
            live.append((int(m.group(1)), int(lease["port"])))
            actives = lease.get("actives")
            if not actives:
                legacy = lease.get("active") or {}
                if legacy.get("name") is not None:
                    actives = {legacy["name"]: legacy.get("version")}
            for name, version in (actives or {}).items():
                versions.setdefault(name, set()).add(version)
        consensus = {
            name: (vs.pop() if len(vs) == 1 else None)
            for name, vs in versions.items()
        }
        return live, consensus

    def live_replicas(self) -> List[Tuple[int, int]]:
        """Live (replica, port) pairs, cached for one scan interval."""
        now = time.time()
        with self._lock:
            if now - self._scan_ts <= self.scan_interval_s:
                return list(self._cached)
        live, consensus = self._scan(now)
        with self._lock:
            self._cached = live
            self._consensus = consensus
            self._scan_ts = now
            return list(self._cached)

    def consensus_version(self, model: str) -> Optional[int]:
        """The version EVERY live replica reports active for ``model``
        (from the cached lease scan) — None while replicas disagree."""
        self.live_replicas()  # refresh the scan cache if stale
        with self._lock:
            return self._consensus.get(model)

    def _invalidate(self, replica: int):
        """Drop a replica we just watched fail from the cache — the next
        pick must not hand the same dead port out for a whole interval."""
        with self._lock:
            self._cached = [
                (rid, port) for rid, port in self._cached if rid != replica
            ]

    def target_replicas(self) -> Optional[int]:
        if self._target is not None:
            return self._target
        # cached like the lease scan: admission runs on every request
        # and must not pay a fleet.json read (a network round trip on a
        # shared coordination dir) per routed graph
        now = time.time()
        with self._lock:
            if now - self._target_ts <= self.scan_interval_s:
                return self._target_cached
        status = coord.read_json(
            os.path.join(self.coord_dir, "fleet.json")
        )
        target = None if status is None else int(status.get("target", 0))
        with self._lock:
            self._target_cached, self._target_ts = target, now
        return target

    def degraded(self) -> bool:
        target = self.target_replicas()
        if not target:
            return False
        return len(self.live_replicas()) < target

    # ---- admission -----------------------------------------------------
    def _admit(self, lane: str, tenant: Optional[str] = None):
        if lane not in self.lanes:
            raise ValueError(
                f"unknown lane {lane!r}; configured: {sorted(self.lanes)}"
            )
        if tenant is not None:
            # tenant-scoped backoff: a replica answered this tenant's
            # quota-503 recently, so ITS traffic sheds locally until the
            # window expires — other tenants in the SAME lane route
            # normally (the regression the lane-global retry-after had)
            now = time.monotonic()
            with self._lock:
                until = self._tenant_backoff.get(tenant, 0.0)
                if until <= now:
                    self._tenant_backoff.pop(tenant, None)
                    until = 0.0
            if until > now:
                self.metrics.on_shed()
                self.fleet_metrics.on_tenant_shed(tenant)
                raise ServerOverloaded(
                    retry_after_s=max(until - now, 0.001)
                )
        live = self.live_replicas()
        if not live:
            # nothing to route to: shed EVERYTHING with a hint scaled to
            # the heal cadence (supervisor respawn ~ boots + warms)
            self.metrics.on_shed()
            self.fleet_metrics.on_lane_shed(lane)
            raise ServerOverloaded(retry_after_s=max(self.lease_s, 0.1))
        if (
            self.degraded()
            and self.lanes[lane] >= self.shed_priority_when_degraded
        ):
            self.metrics.on_shed()
            self.fleet_metrics.on_lane_shed(lane)
            raise ServerOverloaded(
                retry_after_s=max(self.scan_interval_s * 4, 0.1)
            )
        return live

    def _pick(self, live: List[Tuple[int, int]],
              exclude: set) -> Optional[Tuple[int, int]]:
        candidates = [r for r in live if r[0] not in exclude] or live
        if not candidates:
            return None
        with self._lock:
            self._rr += 1
            return candidates[self._rr % len(candidates)]

    # ---- routing -------------------------------------------------------
    def route(
        self,
        graph,
        model: Optional[str] = None,
        lane: str = "default",
        deadline_s: Optional[float] = None,
        raw: bool = False,
        tenant: Optional[str] = None,
    ):
        """Route one graph; returns the per-head numpy outputs (or the
        full response dict with ``raw=True`` — version/batch_seq/replica
        included, the hot-swap tests' view). Raises
        :class:`ServerOverloaded` (shed — admission gate, tenant
        backoff, zero live replicas, or every live replica shedding),
        :class:`DeadlineExceeded`, or :class:`NoLiveReplica` (attempts
        exhausted on non-shed failures)."""
        if deadline_s is None:
            deadline_s = self.default_deadline_s
        tracer = self.tracer
        tr = (
            tracer.start(lane=lane, tenant=tenant, model=model)
            if tracer is not None
            else None
        )
        if tr is None:
            return self._route(
                graph, model, lane, deadline_s, raw, tenant, None
            )
        t0 = time.monotonic()
        try:
            out = self._route(
                graph, model, lane, deadline_s, raw, tenant, tr
            )
        except DeadlineExceeded:
            # a deadline-carrying request's expiry IS an SLO miss: the
            # tail rules keep 100% of these traces at any non-zero rate
            tr.finish("deadline_exceeded",
                      slo_missed=deadline_s is not None, error=True)
            raise
        except ServerOverloaded as e:
            tr.finish("shed", error=True,
                      retry_after_s=round(e.retry_after_s, 6))
            raise
        except BaseException as e:
            tr.finish("error", error=True, error_type=type(e).__name__)
            raise
        elapsed = time.monotonic() - t0
        slo_missed = deadline_s is not None and elapsed > deadline_s
        tr.finish("ok", slo_missed=slo_missed)
        return out

    def _route(self, graph, model, lane, deadline_s, raw, tenant, tr):
        t0 = time.monotonic()
        deadline = None if deadline_s is None else t0 + deadline_s
        t_admit = time.monotonic()
        try:
            live = self._admit(lane, tenant)  # ServerOverloaded raises
        except ServerOverloaded as e:
            _span(tr, "admit", t_admit, lane=lane, shed="admission",
                  retry_after_s=round(e.retry_after_s, 6))
            raise
        _span(tr, "admit", t_admit, lane=lane)
        self.metrics.on_submit()
        self.fleet_metrics.registry.inc("requests_routed_total")
        cache_name = cache_key = None
        if self.cache is not None:
            t_cache = time.monotonic()
            from hydragnn_tpu.serve.cache import (
                ResponseCache,
                canonical_graph_key,
            )

            # the cache key needs a model NAME: the explicit one, or the
            # tenant's (learned from this tenant's first response body)
            cache_name = model or (
                tenant and self._tenant_models.get(tenant)
            )
            version = (
                self.consensus_version(cache_name) if cache_name else None
            )
            if cache_name and version is not None:
                cache_key = ResponseCache.key(
                    canonical_graph_key(graph), cache_name, version,
                    tenant,
                )
                cached = self.cache.get(cache_key)
                if cached is not None:
                    _span(tr, "cache_lookup", t_cache, hit=True)
                    if tr is not None:
                        tr.attrs["cached"] = True
                    now = time.monotonic()
                    self.metrics.on_response()
                    self.metrics.on_response_latency(now - t0)
                    if deadline is not None:
                        self.metrics.on_deadline(now <= deadline)
                    if raw:
                        return {
                            "heads": [np.asarray(h).tolist()
                                      for h in cached],
                            "version": version,
                            "model": cache_name,
                            "tenant": tenant,
                            "cached": True,
                        }
                    return cached
            # miss (or skipped: no consensus/model name yet) — fall
            # through to dispatch with the lookup time on record
            _span(tr, "cache_lookup", t_cache, hit=False,
                  skipped=cache_key is None)
        tried: set = set()
        shed_hint: Optional[float] = None
        last_error: Optional[BaseException] = None
        for attempt in range(self.max_attempts):
            if attempt > 0:
                # retry gate: the deadline must have room for backoff +
                # an attempt BEFORE a budget token is taken — a request
                # that cannot retry anyway must not drain the budget
                # other requests need; then the budget (a storm must
                # die here)
                delay = backoff_delay(attempt - 1, self.retry_base_delay_s)
                if deadline is not None and (
                    time.monotonic() + delay >= deadline
                ):
                    break
                if not self.budget.try_acquire():
                    break
                t_back = time.monotonic()
                time.sleep(delay)
                _span(tr, "backoff", t_back, ordinal=attempt)
                self.metrics_on_retry(lane, tenant)
                live = self.live_replicas()
                if not live:
                    last_error = NoLiveReplica("no live replica to retry")
                    break
            pick = self._pick(live, tried)
            if pick is None:
                last_error = NoLiveReplica("no live replica")
                break
            rid, port = pick
            tried.add(rid)
            remaining = (
                None if deadline is None
                else max(deadline - time.monotonic(), 0.0)
            )
            if remaining is not None and remaining <= 0.0:
                self.metrics.on_timeout()
                raise DeadlineExceeded(
                    f"deadline expired after {time.monotonic() - t0:.3f}s "
                    f"({attempt} attempt(s))"
                )
            attempt_span = None if tr is None else _new_span_id()
            t_att = time.monotonic()
            try:
                status, body = self._post(
                    rid, port, graph, model, remaining, tenant,
                    trace_header=(
                        None if tr is None else tr.header(attempt_span)
                    ),
                )
            except (urllib.error.URLError, http.client.HTTPException,
                    ConnectionError, OSError, TimeoutError) as e:
                # transport failure: the replica just died or is being
                # respawned — retryable (HTTPException covers a kill
                # landing mid-response: IncompleteRead/BadStatusLine)
                _span(tr, "attempt", t_att, span_id=attempt_span,
                      replica=rid, ordinal=attempt,
                      error=type(e).__name__)
                self._invalidate(rid)
                self.fleet_metrics.registry.inc("replica_errors_total")
                last_error = e
                continue
            if tr is not None:
                # the replica's spans (queue_wait/batch_form/dispatch/
                # readback) ride every response body once the header
                # armed them — success AND failure bodies; retried
                # attempts join the SAME trace under their attempt span
                tr.merge(body.get("spans"))
                tr.attrs["attempts"] = attempt + 1
                _span(tr, "attempt", t_att, span_id=attempt_span,
                      replica=rid, ordinal=attempt, status=status)
            if status == 200:
                now = time.monotonic()
                self.budget.on_success()
                self.metrics.on_response()
                self.metrics.on_response_latency(now - t0)
                if deadline is not None:
                    self.metrics.on_deadline(now <= deadline)
                if tenant is not None and body.get("model"):
                    with self._lock:
                        self._tenant_models[tenant] = body["model"]
                if self.cache is not None and body.get("model"):
                    # fill ONLY when the answering version IS the fleet
                    # consensus: mid-swap answers (consensus None, or a
                    # straggler replica) are never cached
                    consensus = self.consensus_version(body["model"])
                    if (
                        consensus is not None
                        and body.get("version") == consensus
                    ):
                        from hydragnn_tpu.serve.cache import (
                            ResponseCache,
                            canonical_graph_key,
                        )

                        # store exactly what the uncached path returns
                        # (the JSON-decoded arrays): a hit is bitwise-
                        # equal to a fresh route of the same graph
                        self.cache.put(
                            ResponseCache.key(
                                canonical_graph_key(graph),
                                body["model"], consensus, tenant,
                            ),
                            [np.asarray(h) for h in body["heads"]],
                        )
                shadow = self._shadow
                if shadow is not None:
                    try:
                        shadow(graph, body, now - t0)
                    except Exception:
                        # the shadow path can NEVER fail a live
                        # response — a broken tap is the canary's
                        # problem, not the client's
                        pass
                if raw:
                    return body
                return [np.asarray(h) for h in body["heads"]]
            if status == 503:
                # the replica shed (queue full / draining): retryable,
                # and its hint rides along if we end up giving up
                shed_hint = float(body.get("retry_after_s", 0.05))
                shed_tenant = body.get("tenant")
                if shed_tenant is not None and shed_tenant == tenant:
                    # the 503 was a TENANT quota shed, not replica
                    # pressure: back off THIS tenant locally (admission
                    # sheds it until the window passes) and stop
                    # retrying — another replica enforces the same
                    # quota, so a retry only doubles the offender's load
                    with self._lock:
                        self._tenant_backoff[tenant] = max(
                            self._tenant_backoff.get(tenant, 0.0),
                            time.monotonic() + max(shed_hint, 0.001),
                        )
                    self.fleet_metrics.on_tenant_shed(tenant)
                    self.metrics.on_error()
                    if tr is not None:
                        tr.attrs["shed"] = "tenant_quota"
                    raise ServerOverloaded(retry_after_s=shed_hint)
                self.fleet_metrics.registry.inc("replica_errors_total")
                last_error = ServerOverloaded(retry_after_s=shed_hint)
                continue
            if status == 504:
                if deadline is not None:
                    self.metrics.on_timeout()
                else:
                    # the replica's own wait cap expired on a request
                    # that carried no deadline: a serving failure, not
                    # an SLO outcome (the deadline series must only see
                    # deadline-carrying requests)
                    self.metrics.on_error()
                raise DeadlineExceeded(
                    body.get("error", "replica-side deadline expiry")
                )
            # 400/413/500: the request is bad or genuinely failed —
            # retrying cannot help, propagate as a loud failure
            self.metrics.on_error()
            raise RuntimeError(
                f"replica {rid} answered {status}: "
                f"{body.get('error', 'unknown error')}"
            )
        if deadline is not None and time.monotonic() >= deadline:
            self.metrics.on_timeout()
            raise DeadlineExceeded(
                f"deadline expired after {time.monotonic() - t0:.3f}s "
                f"({len(tried)} replica(s) tried)"
            )
        if shed_hint is not None:
            # every reachable replica shed: the caller sees retry-after
            # exactly like the in-process queue-full path. This request
            # was already counted in requests_total at admission, so its
            # terminal outcome lands in errors_total — ServeMetrics'
            # shed_total is reserved for never-accepted rejections (the
            # admission gate above); the per-lane fleet gauge still
            # classifies it as a shed
            self.metrics.on_error()
            self.fleet_metrics.on_lane_shed(lane)
            if tr is not None:
                tr.attrs["shed"] = "all_replicas_shed"
            raise ServerOverloaded(retry_after_s=shed_hint)
        self.metrics.on_error()
        raise NoLiveReplica(
            f"all {len(tried)} attempted replica(s) failed"
            + (f": {last_error}" if last_error else "")
        )

    def metrics_on_retry(self, lane: str, tenant: Optional[str] = None):
        self.fleet_metrics.registry.inc("retries_total")
        self.fleet_metrics.on_lane_retry(lane)
        if tenant is not None:
            self.fleet_metrics.on_tenant_retry(tenant)

    def autoscale_signals(self) -> Dict:
        """Counter snapshot for :class:`FleetAutoscaler`: ``ServeMetrics``
        plus per-tenant quota sheds folded into ``shed_total``. A
        replica's quota-503 lands in ``errors_total`` by the admission
        accounting convention (the request was accepted and routed), but
        for capacity decisions a quota shed IS shed pressure — more
        replicas means more aggregate quota. Locally backed-off tenants
        appear in both series; the autoscaler only thresholds
        ``shed > 0``, so the overlap is harmless."""
        snap = dict(self.metrics.snapshot())
        labeled = self.fleet_metrics.snapshot().get("tenant_shed_total")
        if labeled:
            snap["shed_total"] = (
                snap.get("shed_total", 0) + sum(labeled.values())
            )
        return snap

    def _post(self, rid: int, port: int, graph, model: Optional[str],
              deadline_s: Optional[float],
              tenant: Optional[str] = None,
              trace_header: Optional[str] = None) -> Tuple[int, Dict]:
        payload = {"graph": encode_graph(graph)}
        if model is not None:
            payload["model"] = model
        if deadline_s is not None:
            payload["deadline_s"] = deadline_s
        if tenant is not None:
            payload["tenant"] = tenant
        data = json.dumps(payload).encode()
        headers = {"Content-Type": "application/json"}
        if trace_header is not None:
            headers[TRACE_HEADER] = trace_header
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/predict",
            data=data,
            headers=headers,
            method="POST",
        )
        # urllib's timeout bounds the WHOLE request, not just the
        # connect: a deadline-less request must be allowed a slow
        # predict (the replica's own wait cap answers 504 within 60s),
        # not be misread as replica death at connect_timeout_s
        timeout = (
            max(self.connect_timeout_s, 120.0)
            if deadline_s is None
            else max(min(deadline_s + 1.0, 120.0), 0.05)
        )
        try:
            with urllib.request.urlopen(req, timeout=timeout) as resp:
                return resp.status, json.loads(resp.read() or b"{}")
        except urllib.error.HTTPError as e:
            try:
                body = json.loads(e.read() or b"{}")
            except ValueError:
                body = {}
            return e.code, body
