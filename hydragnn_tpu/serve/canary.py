"""SLO-gated canary promotion: shadow routing + statistical quality gates.

PR 15's hot-swap promotes any candidate that CRC-loads and warms — a
purely mechanical gate. This module closes the train->serve flywheel
with a QUALITY gate: training publishes candidate snapshots into a
:class:`~hydragnn_tpu.serve.registry.CandidateChannel` (rank 0,
end-of-epoch, ordered behind the async checkpoint writer), and a
:class:`CanaryController` proves each candidate against live traffic
before the all-acked hot-swap may fire::

    publish            shadow                 gates            promote
    -------            ------                 -----            -------
    cand-<seq>.json -> canary replica boots   per-head MAE     all pass ->
    (training side)    the snapshot; the      per-bucket         fleet.promote
                       router's shadow tap    latency delta      (PR 15 swap)
                       mirrors a fraction     NaN/error VETO   any fail ->
                       of live 200s to it     min-sample floor   canary_rejected

Safety invariants (locked by ``tests/test_canary.py``):

- **The canary never serves a live request.** It leases under
  ``<dir>/canarys/`` — a namespace the router's discovery scan
  (``replicas/replica-*.json``) cannot even see — so exclusion from
  routing AND from the degradation ladder's capacity math is by
  construction, not by filtering.
- **Shadow work sheds first.** The tap drops (and counts) mirrored
  requests whenever the fleet is degraded or the bounded shadow queue
  is full; it never blocks, and a raising tap is swallowed by the
  router's success path. Live SLOs cannot pay for the canary.
- **A bad candidate can never reach active.** NaN answers and replica
  errors are hard vetoes; a crash-looping candidate exhausts its
  respawn budget into ``crash_loop``; a latency-regressing or diverged
  one fails its gate; and a candidate that cannot accumulate the
  min-sample floor in time is rejected as unproven — promotion only
  ever happens on an explicit all-gates-pass decision, and the reject
  path is loud (``canary_rejected`` with the reason attached).
"""

import dataclasses
import json
import os
import queue
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request
from typing import Callable, Dict, List, Optional

import numpy as np

from hydragnn_tpu import coord
from hydragnn_tpu.obs.metrics import MetricsRegistry
from hydragnn_tpu.utils import envparse
from hydragnn_tpu.serve.fleet import (
    CANARY,
    DEFAULT_HEARTBEAT_S,
    DEFAULT_LEASE_S,
    encode_graph,
    lease_serving,
)
from hydragnn_tpu.serve.registry import CandidateChannel


def _env_float(name: str, default: float) -> float:
    return float(os.getenv(name, str(default)))


def _env_int(name: str, default: int) -> int:
    return int(os.getenv(name, str(default)))


@dataclasses.dataclass(frozen=True)
class CanaryGates:
    """The statistical promotion gates, all knobs env-overridable
    (``HYDRAGNN_CANARY_*`` — the table lives in docs/serving.md).

    A candidate is promoted only when, over at least ``min_samples``
    shadow comparisons: every head's MAE vs the active version is
    within ``max(head_mae_tol, head_mae_rel_tol * mean|live|)``; every
    bucket with ``min_bucket_samples`` comparisons keeps its mean
    canary latency within ``live * latency_ratio_tol + latency_slack_s``
    (the additive slack keeps microsecond-scale buckets from failing on
    noise); and the hard vetoes never fired — more than
    ``max_shadow_errors`` canary-side errors, ANY non-finite canary
    answer, or more than ``max_crashes`` canary process deaths each
    reject immediately. A candidate that cannot reach the sample floor
    within ``decide_timeout_s`` is rejected as unproven: promotion
    requires positive evidence, never its absence."""

    min_samples: int = 24
    min_bucket_samples: int = 4
    head_mae_tol: float = 5e-3
    head_mae_rel_tol: float = 0.05
    latency_ratio_tol: float = 2.5
    latency_slack_s: float = 0.05
    max_shadow_errors: int = 0
    max_crashes: int = 1
    decide_timeout_s: float = 120.0
    # uncertainty veto (None = gate off): reject when the candidate's
    # mean predictive uncertainty exceeds live's by more than this
    # ratio — only meaningful when the serving path runs an
    # UncertaintyScorer, inert otherwise (no uncertainty samples ever
    # accumulate, and the gate skips on an empty record)
    max_unc_ratio: Optional[float] = None

    @classmethod
    def from_env(cls, **overrides) -> "CanaryGates":
        base = cls(**overrides)
        return cls(
            min_samples=_env_int(
                "HYDRAGNN_CANARY_MIN_SAMPLES", base.min_samples),
            min_bucket_samples=_env_int(
                "HYDRAGNN_CANARY_MIN_BUCKET_SAMPLES",
                base.min_bucket_samples),
            head_mae_tol=_env_float(
                "HYDRAGNN_CANARY_HEAD_MAE_TOL", base.head_mae_tol),
            head_mae_rel_tol=_env_float(
                "HYDRAGNN_CANARY_HEAD_MAE_REL_TOL", base.head_mae_rel_tol),
            latency_ratio_tol=_env_float(
                "HYDRAGNN_CANARY_LATENCY_RATIO_TOL", base.latency_ratio_tol),
            latency_slack_s=_env_float(
                "HYDRAGNN_CANARY_LATENCY_SLACK_S", base.latency_slack_s),
            max_shadow_errors=_env_int(
                "HYDRAGNN_CANARY_MAX_SHADOW_ERRORS", base.max_shadow_errors),
            max_crashes=_env_int(
                "HYDRAGNN_CANARY_MAX_CRASHES", base.max_crashes),
            decide_timeout_s=_env_float(
                "HYDRAGNN_CANARY_DECIDE_TIMEOUT_S", base.decide_timeout_s),
            max_unc_ratio=(
                envparse.env_float(
                    "HYDRAGNN_CANARY_MAX_UNC_RATIO", 0.0, minimum=1e-9
                )
                if os.getenv("HYDRAGNN_CANARY_MAX_UNC_RATIO")
                else base.max_unc_ratio
            ),
        )


class CanaryMetrics:
    """The ``hydragnn_canary_*`` series (one per controller)."""

    def __init__(self):
        r = MetricsRegistry("hydragnn_canary")
        r.gauge("evaluating", "1 while a candidate is under shadow eval")
        r.gauge("candidate_seq", "Channel seq of the candidate under eval")
        r.gauge("shadow_queue_depth", "Mirrored requests awaiting replay")
        r.counter("shadow_samples_total",
                  "Shadow comparisons accumulated into the gates")
        r.counter("shadow_shed_total",
                  "Mirrored requests dropped (degraded fleet / queue full)")
        r.counter("shadow_errors_total",
                  "Canary-side error answers (non-200) — the error veto")
        r.counter("nan_vetoes_total",
                  "Candidates rejected on a non-finite canary answer")
        r.counter("crashes_total", "Canary process deaths detected")
        r.counter("promotes_total", "Candidates promoted to active")
        r.counter("rejects_total", "Candidates rejected (any reason)")
        r.labeled_gauge("head_mae",
                        "Shadow MAE vs active, per output head")
        r.labeled_gauge("latency_ratio",
                        "Mean canary/live latency ratio, per bucket")
        self.registry = r

    def snapshot(self) -> Dict:
        return self.registry.snapshot()

    def render_prometheus(self) -> str:
        return self.registry.render_prometheus()


class _CandidateStats:
    """Thread-safe accumulator for one candidate's shadow evidence."""

    def __init__(self):
        self._lock = threading.Lock()
        self.samples = 0
        self.errors = 0
        self.nans = 0
        # first few NaN-veto origin payloads (analysis/guards.nan_origin
        # over the head outputs) — the rejection names WHICH head went
        # non-finite, not just that one did
        self.nan_origins: List[Dict] = []
        # per head: sum |canary - live|, sum |live|, element count
        self.head_abs_err: Dict[int, float] = {}
        self.head_abs_live: Dict[int, float] = {}
        self.head_elems: Dict[int, int] = {}
        # per bucket: latency sums + count (live and canary, same graphs)
        self.bucket_live_s: Dict[int, float] = {}
        self.bucket_canary_s: Dict[int, float] = {}
        self.bucket_n: Dict[int, int] = {}
        # mean predictive uncertainty sums (present only when the
        # serving path runs an UncertaintyScorer; the uncertainty veto
        # skips when either side never reported)
        self.unc_live_sum = 0.0
        self.unc_live_n = 0
        self.unc_canary_sum = 0.0
        self.unc_canary_n = 0

    def add_sample(self, live_heads: List[np.ndarray],
                   canary_heads: List[np.ndarray], bucket: int,
                   live_latency_s: float, canary_latency_s: float,
                   live_unc=None, canary_unc=None) -> bool:
        """Fold one compared pair in; returns False (and records a NaN
        veto instead of a sample) when the canary answer is non-finite."""
        finite = all(
            bool(np.all(np.isfinite(h))) for h in canary_heads
        )
        origin = None
        if not finite:
            from hydragnn_tpu.analysis.guards import nan_origin

            origin = nan_origin(
                {f"head_{i}": h for i, h in enumerate(canary_heads)},
                scope="canary",
            )
        with self._lock:
            if not finite:
                self.nans += 1
                if origin is not None and len(self.nan_origins) < 8:
                    self.nan_origins.append(origin)
                return False
            for i, (live, cand) in enumerate(zip(live_heads, canary_heads)):
                live = np.asarray(live, np.float64)
                cand = np.asarray(cand, np.float64)
                self.head_abs_err[i] = (
                    self.head_abs_err.get(i, 0.0)
                    + float(np.sum(np.abs(cand - live)))
                )
                self.head_abs_live[i] = (
                    self.head_abs_live.get(i, 0.0)
                    + float(np.sum(np.abs(live)))
                )
                self.head_elems[i] = self.head_elems.get(i, 0) + live.size
            b = int(bucket)
            self.bucket_live_s[b] = (
                self.bucket_live_s.get(b, 0.0) + float(live_latency_s)
            )
            self.bucket_canary_s[b] = (
                self.bucket_canary_s.get(b, 0.0) + float(canary_latency_s)
            )
            self.bucket_n[b] = self.bucket_n.get(b, 0) + 1
            for vals, which in ((live_unc, "live"), (canary_unc, "canary")):
                if not vals:
                    continue
                finite_u = [
                    float(v) for v in vals
                    if v is not None and np.isfinite(float(v))
                ]
                if not finite_u:
                    continue
                mean_u = sum(finite_u) / len(finite_u)
                if which == "live":
                    self.unc_live_sum += mean_u
                    self.unc_live_n += 1
                else:
                    self.unc_canary_sum += mean_u
                    self.unc_canary_n += 1
            self.samples += 1
        return True

    def add_error(self):
        with self._lock:
            self.errors += 1

    def snapshot(self) -> Dict:
        with self._lock:
            head_mae = {
                i: self.head_abs_err[i] / max(self.head_elems[i], 1)
                for i in self.head_abs_err
            }
            head_live_mag = {
                i: self.head_abs_live[i] / max(self.head_elems[i], 1)
                for i in self.head_abs_live
            }
            buckets = {
                b: {
                    "n": self.bucket_n[b],
                    "live_mean_s": self.bucket_live_s[b] / self.bucket_n[b],
                    "canary_mean_s":
                        self.bucket_canary_s[b] / self.bucket_n[b],
                }
                for b in self.bucket_n
            }
            return {
                "samples": self.samples,
                "errors": self.errors,
                "nans": self.nans,
                "nan_origins": [dict(o) for o in self.nan_origins],
                "head_mae": head_mae,
                "head_live_mag": head_live_mag,
                "buckets": buckets,
                "uncertainty": {
                    "live_n": self.unc_live_n,
                    "live_mean": (
                        self.unc_live_sum / self.unc_live_n
                        if self.unc_live_n else None
                    ),
                    "canary_n": self.unc_canary_n,
                    "canary_mean": (
                        self.unc_canary_sum / self.unc_canary_n
                        if self.unc_canary_n else None
                    ),
                },
            }


def evaluate_gates(stats: Dict, gates: CanaryGates) -> Dict:
    """Pure decision logic over a :meth:`_CandidateStats.snapshot`.

    Returns ``{"verdict": "promote"|"reject"|"wait", "reason": ...,
    "failures": [...]}`` — vetoes first, then the sample floor, then
    the per-head and per-bucket gates. Separated from the controller so
    the decision table is unit-testable without any serving stack."""
    if stats["nans"] > 0:
        origins = stats.get("nan_origins") or []
        where = (
            f" (first origin: `{origins[0]['subtree']}` at "
            f"{origins[0]['origin']})"
            if origins
            else ""
        )
        return {
            "verdict": "reject",
            "reason": (
                f"nan_outputs: {stats['nans']} non-finite canary "
                f"answer(s) — hard veto{where}"
            ),
        }
    if stats["errors"] > gates.max_shadow_errors:
        return {
            "verdict": "reject",
            "reason": (
                f"shadow_errors: {stats['errors']} canary error "
                f"answer(s) (max {gates.max_shadow_errors})"
            ),
        }
    if stats["samples"] < gates.min_samples:
        return {"verdict": "wait", "reason": "below min-sample floor"}
    failures = []
    for head, mae in sorted(stats["head_mae"].items()):
        tol = max(
            gates.head_mae_tol,
            gates.head_mae_rel_tol * stats["head_live_mag"].get(head, 0.0),
        )
        if mae > tol:
            failures.append(
                f"head_mae: head {head} MAE {mae:.3e} > tol {tol:.3e}"
            )
    for bucket, rec in sorted(stats["buckets"].items()):
        if rec["n"] < gates.min_bucket_samples:
            continue
        limit = (
            rec["live_mean_s"] * gates.latency_ratio_tol
            + gates.latency_slack_s
        )
        if rec["canary_mean_s"] > limit:
            failures.append(
                f"latency: bucket {bucket} canary mean "
                f"{rec['canary_mean_s'] * 1e3:.1f}ms > limit "
                f"{limit * 1e3:.1f}ms (live "
                f"{rec['live_mean_s'] * 1e3:.1f}ms over {rec['n']})"
            )
    unc = stats.get("uncertainty") or {}
    if (
        gates.max_unc_ratio is not None
        and unc.get("live_mean") is not None
        and unc.get("canary_mean") is not None
        and unc.get("live_n", 0) >= gates.min_bucket_samples
        and unc.get("canary_n", 0) >= gates.min_bucket_samples
    ):
        # the 1e-12 floor keeps a zero-variance live baseline (models
        # without dropout) from turning ANY canary noise into a reject
        limit = max(unc["live_mean"], 1e-12) * gates.max_unc_ratio
        if unc["canary_mean"] > limit:
            failures.append(
                f"uncertainty: canary mean {unc['canary_mean']:.3e} > "
                f"limit {limit:.3e} (live {unc['live_mean']:.3e}, "
                f"ratio tol {gates.max_unc_ratio})"
            )
    if failures:
        return {
            "verdict": "reject",
            "reason": "; ".join(failures),
            "failures": failures,
        }
    return {"verdict": "promote", "reason": "all gates passed"}


class _SubprocessCanary:
    """Default canary replica: the fleet CLI re-entered with
    ``HYDRAGNN_FLEET_CANARY=1`` against a candidate-specific spec."""

    def __init__(self, spec_path: str, coord_dir: str, canary_id: int,
                 incarnation: int, heartbeat_s: float):
        env = dict(os.environ)
        env.update(
            HYDRAGNN_FLEET_DIR=coord_dir,
            HYDRAGNN_FLEET_REPLICA=str(canary_id),
            HYDRAGNN_FLEET_GEN=str(incarnation),
            HYDRAGNN_FLEET_HEARTBEAT_S=str(heartbeat_s),
            HYDRAGNN_FLEET_CANARY="1",
        )
        self.proc = subprocess.Popen(
            [sys.executable, "-m", "hydragnn_tpu.serve.fleet",
             "--spec", spec_path],
            env=env,
        )

    def alive(self) -> bool:
        return self.proc.poll() is None

    def stop(self):
        if self.proc.poll() is None:
            self.proc.terminate()
            try:
                self.proc.wait(timeout=15)
            except subprocess.TimeoutExpired:
                self.proc.kill()
                self.proc.wait(timeout=10)


class CanaryController:
    """Consume published candidates, shadow-evaluate each on a dedicated
    canary replica, and promote (the PR 15 all-acked hot-swap) or
    reject loudly.

    ``fleet`` needs the supervisor surface only (duck-typed so tests
    can stub the swap): ``coord_dir``, ``lease_s``, ``emit(event,
    **fields)`` and ``promote(checkpoint, path, arch_config=, name=,
    timeout=)``. ``channel`` is a :class:`CandidateChannel` or its root
    path. ``spec_path`` (default ``fleet.spec_path``) supplies the
    arch/plan/samples the canary replica boots with and the bucket plan
    the latency gate classifies by.

    ``replica_factory(spec_path, canary_id, incarnation)`` overrides
    the subprocess default with anything exposing ``alive()``/``stop()``
    — the controller discovers serving state and port uniformly from
    the canary's OWN lease file, so in-process test replicas need no
    extra plumbing.
    """

    def __init__(
        self,
        fleet,
        channel,
        spec_path: Optional[str] = None,
        *,
        fraction: float = 0.25,
        gates: Optional[CanaryGates] = None,
        queue_capacity: int = 64,
        poll_s: float = 0.1,
        boot_timeout_s: float = 180.0,
        promote_timeout_s: float = 120.0,
        shadow_deadline_s: float = 30.0,
        keep_last: Optional[int] = None,
        heartbeat_s: float = DEFAULT_HEARTBEAT_S,
        replica_factory: Optional[Callable] = None,
    ):
        self.fleet = fleet
        self.coord_dir = fleet.coord_dir
        self.lease_s = float(getattr(fleet, "lease_s", DEFAULT_LEASE_S))
        self.channel = (
            channel if isinstance(channel, CandidateChannel)
            else CandidateChannel(channel)
        )
        self.spec_path = spec_path or getattr(fleet, "spec_path", None)
        if self.spec_path is None:
            raise ValueError("need spec_path (or a fleet that carries one)")
        with open(self.spec_path) as f:
            self._spec = json.load(f)
        fraction = float(
            os.getenv("HYDRAGNN_CANARY_FRACTION", str(fraction))
        )
        if not 0.0 < fraction <= 1.0:
            raise ValueError(f"fraction must be in (0, 1], got {fraction}")
        self.fraction = fraction
        self._stride = max(1, int(round(1.0 / fraction)))
        self.gates = gates or CanaryGates.from_env()
        self.poll_s = float(poll_s)
        self.boot_timeout_s = float(boot_timeout_s)
        self.promote_timeout_s = float(promote_timeout_s)
        self.shadow_deadline_s = float(shadow_deadline_s)
        self.keep_last = (
            keep_last if keep_last is not None
            else int(os.getenv("HYDRAGNN_CANARY_KEEP_LAST", "0")) or None
        )
        self.heartbeat_s = float(heartbeat_s)
        self._factory = replica_factory or self._spawn_subprocess
        self.metrics = CanaryMetrics()
        self.decisions: List[Dict] = []  # terminal verdicts, oldest first
        self._plan = None  # lazy: the latency gate's bucket classifier
        self._q: "queue.Queue" = queue.Queue(maxsize=int(queue_capacity))
        self._stop = threading.Event()
        self._armed = threading.Event()  # tap mirrors only while set
        self._tap_lock = threading.Lock()
        self._tap_n = 0
        self._deg_lock = threading.Lock()
        self._deg_cached = False
        self._deg_ts = float("-inf")
        self._lock = threading.Lock()  # guards candidate state below
        self._last_seq = max(self.channel.pinned(), default=0)
        self._cand: Optional[Dict] = None  # manifest under evaluation
        self._handle = None
        self._canary_id = 0
        self._incarnation = 0
        self._crashes = 0
        self._port: Optional[int] = None
        self._armed_ts = 0.0
        self._published_ts = 0.0
        self._boot_ts = 0.0
        self._stats = _CandidateStats()
        self._spec_cand_path = self.spec_path
        self._threads: List[threading.Thread] = []

    # -- lifecycle -----------------------------------------------------------
    def start(self) -> "CanaryController":
        self._stop.clear()
        loop = threading.Thread(
            target=self._loop, name="hydragnn-canary-loop", daemon=True
        )
        worker = threading.Thread(
            target=self._shadow_worker, name="hydragnn-canary-shadow",
            daemon=True,
        )
        self._threads = [loop, worker]
        loop.start()
        worker.start()
        return self

    def stop(self):
        self._armed.clear()
        self._stop.set()
        for t in self._threads:
            if t.is_alive():
                t.join(timeout=max(self.poll_s * 20, 10.0))
        self._threads = []
        self._teardown()

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()

    def attach(self, router) -> None:
        """Install the shadow tap on a :class:`FleetRouter`."""
        router.set_shadow(self.shadow_tap)

    def status(self) -> Dict:
        with self._lock:
            cand = self._cand
            return {
                "evaluating": cand is not None,
                "seq": None if cand is None else cand["seq"],
                "crashes": self._crashes,
                "last_seq": self._last_seq,
                "samples":
                    0 if cand is None else self._stats.snapshot()["samples"],
            }

    def wait_decision(self, seq: int, timeout: float = 300.0) -> Dict:
        """Block until the candidate at ``seq`` reached a terminal
        verdict; returns its decision record (test/bench helper)."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            with self._lock:
                for d in self.decisions:
                    if d["seq"] == seq:
                        return d
            time.sleep(self.poll_s)
        raise TimeoutError(f"no canary decision for seq {seq} in {timeout}s")

    # -- shadow tap (router threads) -----------------------------------------
    def shadow_tap(self, graph, body: Dict, latency_s: float) -> None:
        """The router's success-path hook: enqueue-or-drop, never block.
        Sheds (counted) whenever the fleet is degraded — shadow work is
        the FIRST load shed, before any priority lane — or the bounded
        queue is full; samples 1/stride of eligible responses."""
        if not self._armed.is_set():
            return
        with self._tap_lock:
            n = self._tap_n
            self._tap_n += 1
        if n % self._stride:
            return
        if self._degraded_now():
            self.metrics.registry.inc("shadow_shed_total")
            return
        try:
            self._q.put_nowait((
                graph, body.get("heads"), float(latency_s),
                body.get("uncertainty"),
            ))
        except queue.Full:
            self.metrics.registry.inc("shadow_shed_total")
            return
        self.metrics.registry.set(
            "shadow_queue_depth", float(self._q.qsize())
        )

    def _degraded_now(self) -> bool:
        now = time.time()
        with self._deg_lock:
            if now - self._deg_ts <= self.heartbeat_s:
                return self._deg_cached
        status = coord.read_json(
            os.path.join(self.coord_dir, "fleet.json")
        )
        degraded = bool(status and status.get("degraded"))
        with self._deg_lock:
            self._deg_cached, self._deg_ts = degraded, now
        return degraded

    # -- canary replica management -------------------------------------------
    def _spawn_subprocess(self, spec_path: str, canary_id: int,
                          incarnation: int):
        return _SubprocessCanary(
            spec_path, self.coord_dir, canary_id, incarnation,
            self.heartbeat_s,
        )

    def _lease(self) -> Optional[Dict]:
        lease = coord.read_json(
            coord.hb_path(
                self.coord_dir, CANARY, self._canary_id, prefix=CANARY
            )
        )
        if lease is None:
            return None
        if int(lease.get("gen", -1)) != self._incarnation:
            return None  # a previous incarnation's (or candidate's) lease
        return lease

    def _candidate_spec(self, manifest: Dict) -> str:
        """The fleet spec with the checkpoint swapped for the candidate
        snapshot — what the canary replica boots (and warms) from."""
        spec = dict(self._spec)
        spec["checkpoint"] = {
            "name": manifest["checkpoint"],
            "path": manifest["path"],
        }
        path = os.path.join(
            self.coord_dir, "canary", f"spec-{int(manifest['seq']):06d}.json"
        )
        coord.write_json(path, spec)
        return path

    def _begin(self, manifest: Dict):
        seq = int(manifest["seq"])
        spec_path = self._candidate_spec(manifest)
        with self._lock:
            self._cand = manifest
            # unique member id per candidate: lease files never collide
            # across evaluations, and a stale previous canary's lease can
            # never read as this one's
            self._canary_id = seq
            self._incarnation = 0
            self._crashes = 0
            self._port = None
            self._stats = _CandidateStats()
            self._spec_cand_path = spec_path
            self._published_ts = float(manifest.get("ts", time.time()))
            self._boot_ts = time.monotonic()
        self.metrics.registry.set("evaluating", 1.0)
        self.metrics.registry.set("candidate_seq", float(seq))
        self.fleet.emit(
            "canary_started", candidate=seq,
            checkpoint=manifest["checkpoint"], fraction=self.fraction,
        )
        self._handle = self._factory(spec_path, seq, 0)

    def _respawn(self):
        with self._lock:
            self._incarnation += 1
            inc = self._incarnation
            self._port = None
            # a fresh incarnation gets fresh evidence: samples compared
            # against a torn predecessor must not leak into its gates
            self._stats = _CandidateStats()
            self._boot_ts = time.monotonic()
            spec_path = self._spec_cand_path
            seq = self._canary_id
        self._armed.clear()
        self._handle = self._factory(spec_path, seq, inc)

    def _teardown(self):
        self._armed.clear()
        handle, self._handle = self._handle, None
        if handle is not None:
            try:
                handle.stop()
            except Exception:
                pass
        with self._lock:
            self._cand = None
            self._port = None
        # drain mirrored-but-unreplayed requests: they belong to the
        # torn-down candidate
        try:
            while True:
                self._q.get_nowait()
        except queue.Empty:
            pass
        self.metrics.registry.set("evaluating", 0.0)
        self.metrics.registry.set("shadow_queue_depth", 0.0)

    # -- supervision + decision loop -----------------------------------------
    def _loop(self):
        while not self._stop.wait(self.poll_s):
            try:
                self._tick()
            except Exception:
                pass  # supervision must outlive any single bad read

    def _tick(self):
        with self._lock:
            cand = self._cand
        if cand is None:
            pending = self.channel.pending(self._last_seq)
            if not pending:
                return
            # newest pending wins: older unevaluated candidates are
            # already stale training states — reject them loudly rather
            # than spend shadow budget proving yesterday's checkpoint
            for stale in pending[:-1]:
                self._record(
                    stale, "rejected",
                    f"superseded by seq {pending[-1]['seq']}",
                    samples=0,
                )
            self._begin(pending[-1])
            return
        handle = self._handle
        alive = handle is not None and handle.alive()
        lease = self._lease()
        serving = lease_serving(lease, self.lease_s) and lease.get("port")
        if serving and not self._armed.is_set():
            with self._lock:
                self._port = int(lease["port"])
                self._armed_ts = time.monotonic()
            self._armed.set()
        if not alive or (
            self._armed.is_set() and not serving
        ):
            # dead process, or a wedged one whose lease went stale
            self._armed.clear()
            if handle is not None:
                try:
                    handle.stop()
                except Exception:
                    pass
            self.metrics.registry.inc("crashes_total")
            with self._lock:
                self._crashes += 1
                crashes = self._crashes
            if crashes > self.gates.max_crashes:
                self._reject(
                    cand,
                    f"crash_loop: candidate died {crashes} time(s) "
                    f"(respawn budget {self.gates.max_crashes})",
                )
            else:
                self._respawn()
            return
        if not self._armed.is_set():
            if time.monotonic() - self._boot_ts > self.boot_timeout_s:
                self._reject(
                    cand,
                    f"crash_loop: candidate never reached serving within "
                    f"{self.boot_timeout_s:.0f}s",
                )
            return
        stats = self._stats.snapshot()
        self._export_gauges(stats)
        decision = evaluate_gates(stats, self.gates)
        if decision["verdict"] == "promote":
            self._promote(cand, stats)
        elif decision["verdict"] == "reject":
            self._reject(cand, decision["reason"],
                         samples=stats["samples"],
                         nan_origins=stats.get("nan_origins") or [])
        elif (
            time.monotonic() - self._armed_ts > self.gates.decide_timeout_s
        ):
            self._reject(
                cand,
                f"insufficient_samples: {stats['samples']}/"
                f"{self.gates.min_samples} within "
                f"{self.gates.decide_timeout_s:.0f}s — unproven candidates "
                "are never promoted",
                samples=stats["samples"],
            )

    def _export_gauges(self, stats: Dict):
        for head, mae in stats["head_mae"].items():
            self.metrics.registry.set_labeled(
                "head_mae", round(mae, 9), head=str(head)
            )
        for bucket, rec in stats["buckets"].items():
            ratio = rec["canary_mean_s"] / max(rec["live_mean_s"], 1e-9)
            self.metrics.registry.set_labeled(
                "latency_ratio", round(ratio, 4), bucket=str(bucket)
            )

    def _record(self, manifest: Dict, verdict: str, reason: Optional[str],
                samples: int, **extra) -> Dict:
        seq = int(manifest["seq"])
        decision = {
            "seq": seq,
            "checkpoint": manifest["checkpoint"],
            "verdict": verdict,
            "reason": reason,
            "samples": samples,
            "gate_latency_s": round(
                max(time.time() - float(manifest.get("ts", time.time())),
                    0.0), 3,
            ),
        }
        decision.update(extra)
        with self._lock:
            self.decisions.append(decision)
            self._last_seq = max(self._last_seq, seq)
        if verdict == "rejected":
            self.metrics.registry.inc("rejects_total")
            if reason and reason.startswith("nan_outputs"):
                self.metrics.registry.inc("nan_vetoes_total")
                # every NaN veto carries its origin into the event
                # stream: WHICH head went non-finite, not just a count
                for origin in decision.get("nan_origins") or []:
                    self.fleet.emit(
                        "nan_origin",
                        **{**origin, "scope": f"canary:{seq}"},
                    )
            self.fleet.emit(
                "canary_rejected", candidate=seq,
                checkpoint=manifest["checkpoint"], reason=reason,
                samples=samples,
            )
        else:
            self.metrics.registry.inc("promotes_total")
            self.fleet.emit(
                "canary_promoted", candidate=seq,
                checkpoint=manifest["checkpoint"], samples=samples,
                **{k: v for k, v in extra.items() if k == "version"},
            )
        return decision

    def _reject(self, manifest: Dict, reason: str, samples: int = 0,
                **extra):
        self._record(manifest, "rejected", reason, samples, **extra)
        self._teardown()

    def _promote(self, manifest: Dict, stats: Dict):
        # disarm BEFORE the swap: mirrored traffic compared across the
        # version flip would read as disagreement
        self._armed.clear()
        res = self.fleet.promote(
            manifest["checkpoint"],
            path=manifest["path"],
            arch_config=self._spec.get("arch"),
            # a multi-tenant channel can target any packed model: the
            # candidate manifest's model_name wins over the spec default
            name=manifest.get("model_name") or self._spec.get("model_name"),
            timeout=self.promote_timeout_s,
        )
        if res.get("status") == "promoted":
            versions = res.get("versions") or {}
            self._record(
                manifest, "promoted", None, samples=stats["samples"],
                version=max(versions.values()) if versions else None,
            )
            self.channel.record_promotion(manifest["seq"])
            if self.keep_last:
                self.channel.gc(self.keep_last)
        else:
            # the mechanical gate failed AFTER the quality gates passed
            # (a replica's strict load refused the snapshot, ack
            # timeout...): the fleet already rolled back loudly; the
            # canary verdict is still a rejection with the cause chained
            self._record(
                manifest, "rejected",
                f"hot_swap_rolled_back: {res.get('reason', 'unknown')}",
                samples=stats["samples"],
            )
        self._teardown()

    # -- shadow worker -------------------------------------------------------
    def _shadow_worker(self):
        while not self._stop.is_set():
            try:
                graph, live_heads, live_latency, live_unc = self._q.get(
                    timeout=0.1
                )
            except queue.Empty:
                continue
            self.metrics.registry.set(
                "shadow_queue_depth", float(self._q.qsize())
            )
            if not self._armed.is_set() or live_heads is None:
                continue  # torn-down mid-flight, or a raw-less response
            with self._lock:
                port = self._port
            if port is None:
                continue
            t0 = time.monotonic()
            try:
                status, body = self._post(port, graph)
            except (urllib.error.URLError, ConnectionError, OSError,
                    TimeoutError):
                # transport-level failure: the canary just died (or is
                # being respawned) — the supervision tick owns process
                # death, so this is a dropped sample, not an error veto
                continue
            canary_latency = time.monotonic() - t0
            if status != 200:
                self._stats.add_error()
                self.metrics.registry.inc("shadow_errors_total")
                continue
            try:
                canary_heads = [
                    np.asarray(h, np.float64) for h in body["heads"]
                ]
                live_arrs = [
                    np.asarray(h, np.float64) for h in live_heads
                ]
                bucket = self._bucket_of(graph)
            except Exception:
                self._stats.add_error()
                self.metrics.registry.inc("shadow_errors_total")
                continue
            ok = self._stats.add_sample(
                live_arrs, canary_heads, bucket, live_latency,
                canary_latency, live_unc=live_unc,
                canary_unc=body.get("uncertainty"),
            )
            if ok:
                self.metrics.registry.inc("shadow_samples_total")

    def _post(self, port: int, graph):
        data = json.dumps(
            {"graph": encode_graph(graph),
             "deadline_s": self.shadow_deadline_s}
        ).encode()
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/predict",
            data=data,
            headers={"Content-Type": "application/json"},
            method="POST",
        )
        with urllib.request.urlopen(
            req, timeout=self.shadow_deadline_s + 5.0
        ) as resp:
            return resp.status, json.loads(resp.read() or b"{}")

    def _bucket_of(self, graph) -> int:
        if self._plan is None:
            import pickle

            from hydragnn_tpu.serve.buckets import plan_from_samples

            with open(self._spec["samples"], "rb") as f:
                samples = pickle.load(f)
            self._plan = plan_from_samples(
                samples, **dict(self._spec.get("plan", {}))
            )
        return int(self._plan.select(graph))
