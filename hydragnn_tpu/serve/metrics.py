"""Serving metrics — re-export of the shared observability core.

The ``MetricsRegistry``/``LatencyHistogram``/Prometheus-text machinery
that started here (PR 2) was promoted to :mod:`hydragnn_tpu.obs.metrics`
so training and serving report through ONE implementation; this module
keeps the historical import path alive with an unchanged public API.
``/metrics`` output is byte-identical to the pre-refactor module (locked
by ``tests/test_observability.py``). The serving metrics contract itself
is documented in docs/serving.md ("Metrics schema").
"""

from hydragnn_tpu.obs.metrics import (  # noqa: F401  (re-exported API)
    DEFAULT_LATENCY_BOUNDS,
    LatencyHistogram,
    MetricsRegistry,
    ServeMetrics,
)

__all__ = [
    "DEFAULT_LATENCY_BOUNDS",
    "LatencyHistogram",
    "MetricsRegistry",
    "ServeMetrics",
]
