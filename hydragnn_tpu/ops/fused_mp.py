"""Fused Pallas message-passing kernels: gather -> edge op -> segment reduce.

``ops/pallas_segment.py`` established why a standalone Pallas segment-sum is
a dead heat with XLA scatter: the opaque ``pallas_call`` boundary forfeits
the gather -> edge-MLP -> reduce fusion XLA performs around its own scatter.
This module moves the WHOLE message-passing step inside one kernel, so
nothing is left outside to fuse with:

- **gather**: the node table lives in VMEM for the whole grid; each edge
  block gathers sender (and optionally receiver) rows as
  ``onehot(ids) @ table`` — a dense matmul the MXU eats, and the table is
  read from HBM exactly once;
- **edge op**: the per-edge computation (masking, filter weighting, PNA
  moments packing, EGNN's two-layer edge MLP + coordinate update) runs on
  the block while it is VMEM-resident — the ``[E, *]`` message intermediate
  never exists in HBM;
- **reduce**: ``onehot(reduce_ids)^T @ messages`` accumulates into a VMEM
  accumulator, replacing the serializing scatter.

Edge ops are *pure functions* over ``(xs, xr, ef, params)`` registered in
:data:`EDGE_OPS`; the SAME function body runs inside the kernel (per block)
and in the custom VJP (full edge axis, via ``jax.vjp`` on XLA) — backward
parity with the reference segment path is by construction, and the backward
stays scatter-light: per-edge cotangents are gathered, only the final
node-table fold is a segment-sum.

Enablement is decided per bucket by ``ops/autotune.py`` (measured, cached)
or forced via ``HYDRAGNN_FUSED_MP=0/1``; :func:`fused_mp_enabled` guards the
VMEM footprint (node tables + one-hot indicators + accumulator must fit the
~16 MB scoped limit). Non-TPU backends run the Pallas interpreter so tier-1
CPU tests exercise full numeric parity including gradients.
"""

import functools
from typing import Callable, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp

from hydragnn_tpu.ops.pallas_segment import _interpret, _onehot

_EDGE_BLOCK = 256
# everything the kernel keeps VMEM-resident across the grid (node tables,
# accumulator) plus the per-block indicators; headroom below the 16 MB
# scoped limit for the block operands and Mosaic's own temporaries
_VMEM_BUDGET = 10 * 1024 * 1024


class EdgeOp(NamedTuple):
    """One registered edge computation.

    ``fn(xs, xr, ef, params) -> (msg, edge_out)``: ``xs``/``xr`` are the
    node-table rows gathered at ``gather_ids``/``gather_ids_b``, ``ef`` the
    per-edge features, ``params`` the op's parameter tuple (reshaped back to
    their original shapes before the call). ``msg`` is segment-reduced at
    ``reduce_ids``; ``edge_out`` (or None) is written back per edge for
    callers that also need the un-reduced messages (PNA's min/max pass).
    MUST be pure jnp/VPU/MXU code: the same body is traced inside the
    Pallas kernel and differentiated with ``jax.vjp`` in the backward.
    """

    fn: Callable
    uses_recv: bool
    has_edge_out: bool


def _op_copy(xs, xr, ef, params):
    # ef = [E, 1] edge mask; msg = masked sender rows
    return xs * ef, None


def _op_copy_count(xs, xr, ef, params):
    # packed [msg, mask]: sum AND real in-degree from one reduction
    return jnp.concatenate([xs * ef, ef], axis=-1), None


def _op_mul(xs, xr, ef, params):
    # SchNet CFConv: msg = h[sender] * w  (w pre-masked, [E, F])
    return xs * ef, None


def _op_moments(xs, xr, ef, params):
    # PNA: z = yj[sender] (+ encoded edge), masked; packed [z, z^2, mask]
    # so one reduction yields sum / sum-of-squares / count. z is also
    # written back per edge — the min/max pass consumes it without a
    # second gather.
    d = xs.shape[-1]
    if ef.shape[-1] == d + 1:  # edge-encoder contribution rides along
        z = (xs + ef[..., :d]) * ef[..., d:]
        mask = ef[..., d:]
    else:
        z = xs * ef
        mask = ef
    return jnp.concatenate([z, z * z, mask], axis=-1), z


def _op_egnn(xs, xr, ef, params):
    # EGNN E_GCL: xs = [y_snd, pos] @ senders, xr = [y_rcv, pos] @ receivers,
    # ef = [ze(H or 0), mask]; params = (w_rad, W2, b2[, Wc0, bc0, Wc1]).
    # Computes the full two-layer edge MLP (and, with the coord params
    # present, the tanh-bounded equivariant update) and reduces the packed
    # [e(, trans), mask] at the SENDER index — the whole E_GCL edge phase
    # in one kernel.
    w_rad = params[0]
    h = w_rad.shape[-1]
    y_s, pos_s = xs[..., :h], xs[..., h:]
    y_r, pos_r = xr[..., :h], xr[..., h:]
    mask = ef[..., -1:]
    coord_diff = pos_s - pos_r
    radial = jnp.sum(coord_diff * coord_diff, axis=-1, keepdims=True)
    # norm_diff=True with the safe-sqrt contract of egnn._safe_sqrt:
    # zero-distance pairs are masked rows, whose gradients are killed by
    # the mask multiply below — the double-where is still used so the
    # forward value (and any unmasked degenerate pair) stays finite
    nonzero = radial > 0
    norm = jnp.where(nonzero, jnp.sqrt(jnp.where(nonzero, radial, 1.0)), 0.0)
    coord_diff = coord_diff / (norm + 1.0)
    pre = y_s + y_r + radial * w_rad
    if ef.shape[-1] > 1:  # encoded edge_attr contribution
        pre = pre + ef[..., :h]
    e = jax.nn.relu(pre)
    e = jax.nn.relu(
        jax.lax.dot_general(
            e, params[1],
            dimension_numbers=(((e.ndim - 1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        + params[2]
    )
    e = e * mask
    if len(params) > 3:  # equivariant: coord MLP + bounded update
        cw = jax.nn.relu(
            jax.lax.dot_general(
                e, params[3],
                dimension_numbers=(((e.ndim - 1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            + params[4]
        )
        cw = jnp.tanh(
            jax.lax.dot_general(
                cw, params[5],
                dimension_numbers=(((cw.ndim - 1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
        )
        trans = jnp.clip(coord_diff * cw, -100.0, 100.0) * mask
        return jnp.concatenate([e, trans, mask], axis=-1), None
    return jnp.concatenate([e, mask], axis=-1), None


EDGE_OPS = {
    "copy": EdgeOp(_op_copy, uses_recv=False, has_edge_out=False),
    "copy_count": EdgeOp(_op_copy_count, uses_recv=False, has_edge_out=False),
    "mul": EdgeOp(_op_mul, uses_recv=False, has_edge_out=False),
    "moments": EdgeOp(_op_moments, uses_recv=False, has_edge_out=True),
    "egnn": EdgeOp(_op_egnn, uses_recv=True, has_edge_out=False),
}


def fused_mp_enabled(
    num_nodes: int,
    num_segments: int,
    table_dim: int,
    out_dim: int,
    table_dim_b: int = 0,
) -> bool:
    """VMEM-footprint guard for one fused call: node table(s) + accumulator
    + the two per-block one-hot indicators must fit the budget. Callers
    (``ops/autotune.py`` and the env force) AND the parity tests route
    eligibility through here so a config that would VMEM-OOM at compile
    time is never selected."""
    table_bytes = num_nodes * (table_dim + table_dim_b) * 4
    acc_bytes = num_segments * out_dim * 4
    onehot_bytes = _EDGE_BLOCK * (num_nodes * (2 if table_dim_b else 1)
                                  + num_segments) * 4
    return table_bytes + acc_bytes + onehot_bytes <= _VMEM_BUDGET


def _pad_ids(ids, e_pad):
    pad = e_pad - ids.shape[0]
    ids = ids.astype(jnp.int32)
    if pad:
        ids = jnp.pad(ids, (0, pad), constant_values=jnp.iinfo(jnp.int32).max)
    return ids.reshape(-1, 1)  # 2-D: Mosaic tiles it conventionally


def _shape_params(params):
    """Transport shapes for the kernel: every param >= 2-D (0/1-D operands
    get XLA's T(1024) layout, which Mosaic cannot block). Edge fns see the
    SAME >=2-D shapes in the kernel and in the backward recompute — 1-D
    params broadcast identically as ``[1, K]``."""
    leaves = [jnp.asarray(p, jnp.float32) for p in params]
    return [p.reshape(1, -1) if p.ndim < 2 else p for p in leaves]


def _edge_fn_result_dim(op_name, table_dim, table_dim_b, ef_dim, params):
    """Static (out_dim, edge_out_dim) probe via eval_shape — the kernel and
    pallas_call out_shape need them before tracing."""
    op = EDGE_OPS[op_name]
    xs = jax.ShapeDtypeStruct((_EDGE_BLOCK, table_dim), jnp.float32)
    xr = jax.ShapeDtypeStruct((_EDGE_BLOCK, table_dim_b or table_dim),
                              jnp.float32)
    ef = jax.ShapeDtypeStruct((_EDGE_BLOCK, ef_dim), jnp.float32)
    p_shapes = [jax.ShapeDtypeStruct(p.shape, jnp.float32) for p in params]
    msg, edge_out = jax.eval_shape(
        lambda a, b, c, p: op.fn(a, b, c, p), xs, xr, ef, p_shapes
    )
    return msg.shape[-1], None if edge_out is None else edge_out.shape[-1]


def _fused_impl(
    op_name,
    num_segments,
    interpret,
    node_a,
    node_b,
    edge_feat,
    params,
    gather_ids,
    gather_ids_b,
    reduce_ids,
):
    from jax.experimental import pallas as pl

    op = EDGE_OPS[op_name]
    interpret = _interpret(interpret)
    node_a = node_a.astype(jnp.float32)
    n_a, d_a = node_a.shape
    if op.uses_recv:
        node_b = node_b.astype(jnp.float32)
        n_b, d_b = node_b.shape
    else:
        node_b, n_b, d_b = None, 0, 0

    e = gather_ids.shape[0]
    e_pad = e + ((-e) % _EDGE_BLOCK)
    grid = e_pad // _EDGE_BLOCK
    edge_feat = edge_feat.astype(jnp.float32)
    if e_pad != e:
        edge_feat = jnp.pad(edge_feat, ((0, e_pad - e), (0, 0)))
    ef_dim = edge_feat.shape[1]
    gid_a = _pad_ids(gather_ids, e_pad)
    rid = _pad_ids(reduce_ids, e_pad)
    gid_b = _pad_ids(gather_ids_b, e_pad) if op.uses_recv else None

    param_shaped = _shape_params(params)
    out_dim, edge_out_dim = _edge_fn_result_dim(
        op_name, d_a, d_b, ef_dim, param_shaped
    )

    n_params = len(param_shaped)

    def kernel(*refs):
        i = 0
        gid_a_ref = refs[i]; i += 1
        if op.uses_recv:
            gid_b_ref = refs[i]; i += 1
        rid_ref = refs[i]; i += 1
        ef_ref = refs[i]; i += 1
        na_ref = refs[i]; i += 1
        if op.uses_recv:
            nb_ref = refs[i]; i += 1
        p_refs = refs[i : i + n_params]; i += n_params
        out_ref = refs[i]; i += 1
        edge_out_ref = refs[i] if op.has_edge_out else None

        @pl.when(pl.program_id(0) == 0)
        def _():
            out_ref[:] = jnp.zeros_like(out_ref)

        tdot = functools.partial(
            jax.lax.dot_general, preferred_element_type=jnp.float32
        )
        # gather: onehot(ids) @ table — out-of-range (padded) ids give a
        # zero row, so padded edges gather zeros
        xs = tdot(
            _onehot(gid_a_ref[:], n_a), na_ref[:],
            dimension_numbers=(((1,), (0,)), ((), ())),
        )
        xr = (
            tdot(
                _onehot(gid_b_ref[:], n_b), nb_ref[:],
                dimension_numbers=(((1,), (0,)), ((), ())),
            )
            if op.uses_recv
            else xs
        )
        kernel_params = [r[:] for r in p_refs]
        msg, edge_out = op.fn(xs, xr, ef_ref[:], kernel_params)
        # reduce: onehot(reduce_ids)^T @ msg — padded edges' reduce rows
        # are all-zero, so whatever the edge op produced on them (bias
        # terms survive a zero input) contributes nothing
        out_ref[:] += tdot(
            _onehot(rid_ref[:], num_segments), msg,
            dimension_numbers=(((0,), (0,)), ((), ())),
        )
        if edge_out_ref is not None:
            edge_out_ref[:] = edge_out

    blk = lambda w: pl.BlockSpec((_EDGE_BLOCK, w), lambda i: (i, 0))
    full = lambda s: pl.BlockSpec(s, lambda i: tuple(0 for _ in s))
    in_specs = [blk(1)]
    operands = [gid_a]
    if op.uses_recv:
        in_specs.append(blk(1)); operands.append(gid_b)
    in_specs += [blk(1), blk(ef_dim), full((n_a, d_a))]
    operands += [rid, edge_feat, node_a]
    if op.uses_recv:
        in_specs.append(full((n_b, d_b))); operands.append(node_b)
    for p in param_shaped:
        in_specs.append(full(p.shape)); operands.append(p)

    out_shape = [jax.ShapeDtypeStruct((num_segments, out_dim), jnp.float32)]
    out_specs = [full((num_segments, out_dim))]
    if op.has_edge_out:
        out_shape.append(
            jax.ShapeDtypeStruct((e_pad, edge_out_dim), jnp.float32)
        )
        out_specs.append(blk(edge_out_dim))

    outs = pl.pallas_call(
        kernel,
        out_shape=tuple(out_shape),
        grid=(grid,),
        in_specs=in_specs,
        out_specs=tuple(out_specs),
        interpret=interpret,
    )(*operands)
    if not isinstance(outs, (tuple, list)):
        outs = (outs,)
    if op.has_edge_out:
        return outs[0], outs[1][:e]
    return outs[0], None


@functools.partial(jax.custom_vjp, nondiff_argnums=(0, 1, 2))
def fused_message_reduce(
    op_name: str,
    num_segments: int,
    interpret: bool,
    node_a,
    node_b,
    edge_feat,
    params: Tuple,
    gather_ids,
    gather_ids_b,
    reduce_ids,
):
    """One fused message-passing step.

    ``out[s] = sum_{e: reduce_ids[e]==s} fn(node_a[gather_ids[e]],
    node_b[gather_ids_b[e]], edge_feat[e], params)`` with ``fn`` =
    ``EDGE_OPS[op_name]``; ops with ``has_edge_out`` also return the
    per-edge messages (else None). All floating inputs are differentiable;
    id arrays are not. Numerics: f32 accumulation regardless of input
    dtype (callers cast the result back if they need to)."""
    out, edge_out = _fused_impl(
        op_name, num_segments, interpret,
        node_a, node_b, edge_feat, params,
        gather_ids, gather_ids_b, reduce_ids,
    )
    return out, edge_out


def _fused_fwd(op_name, num_segments, interpret, node_a, node_b, edge_feat,
               params, gather_ids, gather_ids_b, reduce_ids):
    out = fused_message_reduce(
        op_name, num_segments, interpret, node_a, node_b, edge_feat, params,
        gather_ids, gather_ids_b, reduce_ids,
    )
    return out, (node_a, node_b, edge_feat, params, gather_ids,
                 gather_ids_b, reduce_ids)


def _fused_bwd(op_name, num_segments, interpret, res, g):
    """Gather-based backward on XLA: recompute the edge op per edge from
    the residual inputs and pull cotangents through ``jax.vjp`` of the
    SAME edge function — gradient parity with the unfused path by
    construction. The only scatters are the final node-table folds
    (f32 segment-sums XLA fuses with the surrounding gathers)."""
    node_a, node_b, edge_feat, params, gid_a, gid_b, rid = res
    op = EDGE_OPS[op_name]
    g_red, g_edge = g

    def _safe_gather(table, ids):
        """Same padding contract as the forward one-hot gather: rows with
        out-of-range ids read ZERO (a bare table[ids] would clamp-gather
        the last row and linearize the edge op around the wrong point —
        the padded-edge bug class fixed in pallas_segment's VJPs too)."""
        valid = (ids >= 0) & (ids < table.shape[0])
        safe = jnp.clip(ids, 0, table.shape[0] - 1)
        return jnp.where(valid[:, None], table[safe], 0.0)

    node_a32 = node_a.astype(jnp.float32)
    xs = _safe_gather(node_a32, gid_a)
    if op.uses_recv:
        node_b32 = node_b.astype(jnp.float32)
        xr = _safe_gather(node_b32, gid_b)
    else:
        xr = xs
    ef = edge_feat.astype(jnp.float32)
    p32 = _shape_params(params)

    def f(xs_, xr_, ef_, p_):
        msg, edge_out = op.fn(xs_, xr_, ef_, p_)
        return (msg, edge_out) if op.has_edge_out else msg

    _, vjp_fn = jax.vjp(f, xs, xr, ef, p32)
    # out-of-range reduce ids contributed nothing forward -> zero cotangent
    ge = _safe_gather(g_red.astype(jnp.float32), rid)
    if op.has_edge_out:
        if g_edge is None:
            # custom_vjp instantiates zero cotangents today; this guards a
            # future symbolic-zeros change — shape comes from the op probe
            _, ed = _edge_fn_result_dim(
                op_name, xs.shape[-1], xr.shape[-1], ef.shape[-1], p32
            )
            gz = jnp.zeros((xs.shape[0], ed), jnp.float32)
        else:
            gz = g_edge.astype(jnp.float32)
        d_xs, d_xr, d_ef, d_params = vjp_fn((ge, gz))
    else:
        d_xs, d_xr, d_ef, d_params = vjp_fn(ge)
    # the cotangents are f32 by construction (vjp of an f32 edge fn);
    # the explicit upcast makes the scatter-add's f32 accumulation a
    # static contract rather than an artifact of the current edge fn
    d_node_a = jax.ops.segment_sum(
        d_xs.astype(jnp.float32), gid_a, num_segments=node_a.shape[0]
    )
    if op.uses_recv:
        d_node_a_b = jax.ops.segment_sum(
            d_xr.astype(jnp.float32), gid_b, num_segments=node_b.shape[0]
        )
        d_node_b = d_node_a_b.astype(node_b.dtype)
    else:
        # xr aliased xs: its cotangent already flowed through d_xs's vjp
        # output only when the op read it — copy-family ops ignore xr
        d_node_b = None
    d_params = tuple(
        dp.reshape(jnp.shape(p)).astype(jnp.asarray(p).dtype)
        for dp, p in zip(d_params, params)
    )
    return (
        d_node_a.astype(node_a.dtype),
        d_node_b,
        d_ef.astype(edge_feat.dtype),
        d_params,
        None,
        None,
        None,
    )


fused_message_reduce.defvjp(_fused_fwd, _fused_bwd)


# ---------------------------------------------------------------------------
# model-facing wrappers (thin shape/packing adapters over the one kernel)
# ---------------------------------------------------------------------------


def fused_gather_sum(x, senders, receivers, num_segments, edge_mask,
                     interpret: bool = False):
    """``segment_sum(where(mask, x[senders], 0), receivers)`` in one fused
    kernel (GIN's aggregation). Returns ``[num_segments, D]`` f32."""
    out, _ = fused_message_reduce(
        "copy", num_segments, interpret,
        x, None, edge_mask.astype(jnp.float32)[:, None], (),
        senders, None, receivers,
    )
    return out


def fused_gather_mean(x, senders, receivers, num_segments, edge_mask,
                      interpret: bool = False):
    """Masked mean over real incoming edges (SAGE): sum and real in-degree
    from ONE fused reduction. Returns ``([S, D] mean, [S, 1] degree)``."""
    out, _ = fused_message_reduce(
        "copy_count", num_segments, interpret,
        x, None, edge_mask.astype(jnp.float32)[:, None], (),
        senders, None, receivers,
    )
    d = x.shape[-1]
    deg = out[:, d:]
    return out[:, :d] / jnp.maximum(deg, 1.0), deg


def fused_gather_weighted_sum(h, w, senders, receivers, num_segments,
                              interpret: bool = False):
    """``segment_sum(h[senders] * w, receivers)`` in one fused kernel
    (SchNet's CFConv aggregation; ``w`` pre-masked ``[E, F]``)."""
    out, _ = fused_message_reduce(
        "mul", num_segments, interpret,
        h, None, w, (),
        senders, None, receivers,
    )
    return out


def fused_gather_moments(yj, senders, receivers, num_segments, edge_mask,
                         ze=None, interpret: bool = False):
    """PNA's statistics pass: ``z = (yj[senders] (+ ze)) * mask`` with
    (sum, count, sum-of-squares) reduced at receivers AND ``z`` returned
    per edge for the min/max pass — one gather, one reduction.
    Returns ``(s [S, D], cnt [S, 1], sq [S, D], z [E, D])``."""
    mask = edge_mask.astype(jnp.float32)[:, None]
    ef = mask if ze is None else jnp.concatenate(
        [ze.astype(jnp.float32), mask], axis=-1
    )
    out, z = fused_message_reduce(
        "moments", num_segments, interpret,
        yj, None, ef, (),
        senders, None, receivers,
    )
    d = yj.shape[-1]
    return out[:, :d], out[:, 2 * d :], out[:, d : 2 * d], z


def fused_egnn_edge_phase(
    y_snd, y_rcv, pos, edge_params, senders, receivers, num_segments,
    edge_mask, ze=None, interpret: bool = False,
):
    """EGNN's whole edge phase — radial, two-layer edge MLP, optional
    equivariant coordinate weighting — fused with the sender-side
    aggregation. ``edge_params`` = (w_rad [1, H], W2, b2[, Wc0, bc0, Wc1]);
    with the coord params present the packed result carries the coordinate
    update. Returns ``[S, H + (3) + 1]`` packed (agg, (coord_agg), count)."""
    node_a = jnp.concatenate(
        [y_snd.astype(jnp.float32), pos.astype(jnp.float32)], axis=-1
    )
    node_b = jnp.concatenate(
        [y_rcv.astype(jnp.float32), pos.astype(jnp.float32)], axis=-1
    )
    mask = edge_mask.astype(jnp.float32)[:, None]
    ef = mask if ze is None else jnp.concatenate(
        [ze.astype(jnp.float32), mask], axis=-1
    )
    out, _ = fused_message_reduce(
        "egnn", num_segments, interpret,
        node_a, node_b, ef, tuple(edge_params),
        senders, receivers, senders,
    )
    return out
