"""Banded gather/scatter Pallas kernels for dense neighbor aggregation.

The dense path's cost is not FLOPs but the row gather ``table[idx]``
(``[N, K]`` indices into ``[N, D]``): XLA's TPU gather walks rows at
~12 GB/s effective (measured, ``benchmarks/agg_profile.py``), and its
backward is a scatter-add. But packed batches give the indices *banded*
structure for free: ``collate_graphs`` lays each graph's nodes out
contiguously and neighbors never leave their graph, so
``|idx[n, k] - n| < max_graph_nodes``. These kernels exploit that: the
gather becomes, per 128-row block, a short loop over the ±halo
neighboring table blocks accumulating ``onehot(local_idx) @ table_block``
— pure MXU work on VMEM-resident tiles, no random access, messages read
from HBM exactly once.

``window_gather`` and ``window_scatter_add`` are mutual duals; each is
the other's VJP, so the backward pass needs no reverse neighbor lists.

Band contract: every valid row index must satisfy
``|idx[r] - anchor(r)| <= halo_blocks * 128`` where ``anchor(r)`` is the
first table row of r's block (anchor ratio maps index-blocks to table
blocks for tables with a different row density, e.g. edge tables).
Out-of-band indices are silently dropped (forward contributes zero,
backward drops the gradient) — callers must derive ``halo_blocks`` from
a static bound (max graph size) that makes violations impossible.

Reference analog: the torch_scatter gather/scatter pair underneath PyG
message passing (SURVEY.md §2.4); there is no banded trick there because
CUDA's native gathers are fast — this is TPU-first design, not a port.
"""

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

_BLOCK = 128  # table-row block (MXU-native tile edge)
_MAX_VMEM_TILE = 8 * 1024 * 1024  # value-tile budget (bytes, f32)


def window_enabled(
    halo_blocks: Optional[int],
    rows_per_anchor: int,
    dim: int,
    env_default: str = "0",
) -> bool:
    """Static enablement: ``HYDRAGNN_WINDOW=1`` opts in where legal (halo
    known, >=64 features, VMEM budget); default OFF.

    TRACE-TIME CAPTURE: the env var is read when the surrounding conv is
    traced, and the chosen path is baked into the compiled program —
    toggling ``HYDRAGNN_WINDOW`` mid-process keeps serving the previously
    compiled path until ``jax.clear_caches()`` is called. Set it before
    the first forward (tests that toggle it clear caches explicitly).

    Measured 2026-07-31 (v5e, OC20-scale PNA dense bf16): the standalone
    banded gather is ~1.1-1.3x XLA's in isolation but NEUTRAL end-to-end
    (XLA fuses its gather with the surrounding mask/stats work — the same
    fusion-forfeit economics as ops/pallas_segment.py). Kept opt-in:
    parity-proven machinery (the interpreter runs it on CPU), and the
    banded-scatter VJP needs no reverse lists."""
    import os

    flag = os.getenv("HYDRAGNN_WINDOW", env_default)
    if flag != "1" or halo_blocks is None or dim < 64:
        # below ~64 features the onehot matmuls are degenerate and the
        # [BR, 1] index/mask operands lane-pad 128x in VMEM — XLA wins
        return False
    br = _BLOCK * rows_per_anchor
    span = 2 * halo_blocks + 1
    budget = (
        br * dim * 4  # gathered accumulator
        + 2 * br * 128 * 4  # idx+mask blocks ([BR, 1] lane-pads to 128)
        + br * _BLOCK * 4  # onehot tile
        + span * _BLOCK * dim * 4 * 2  # double-buffered table tiles
    )
    return budget <= _MAX_VMEM_TILE


def _interpret() -> bool:
    try:
        return jax.default_backend() != "tpu"
    except Exception:
        return True


def _pad_rows(a, mult, fill=0):
    pad = (-a.shape[0]) % mult
    if pad:
        widths = ((0, pad),) + ((0, 0),) * (a.ndim - 1)
        a = jnp.pad(a, widths, constant_values=fill)
    return a, pad


def _spans(halo, ratio):
    """Window geometry. Gather: idx block i reads table blocks
    ``(i*num)//den + j - halo`` for j in [0, 2*halo + ceil(num/den));
    the ceil term covers the blocks an anchor block's scaled image spans.
    Scatter (the dual): out block i reads value blocks
    ``(i*den)//num + j - off`` with loose-but-sound bounds (extra visits
    only cost compute; matching is exact)."""
    num, den = ratio
    cg = -(-num // den)
    g_span = 2 * halo + cg
    s_off = ((halo + cg - 1) * den + num - 1) // num
    s_span = ((2 * halo + cg - 1) * den + num - 1) // num + 1
    return g_span, s_off, s_span



def _table_map(j, halo, tblocks, ratio):
    """Index map for the j-th window table input: idx block i reads table
    block ``clip((i*num)//den + j - halo)``; the kernel masks the clipped
    (out-of-range) visits."""

    def f(i, *, _j=j, _h=halo, _t=tblocks, _r=ratio):
        return (jnp.clip((i * _r[0]) // _r[1] + _j - _h, 0, _t - 1), 0)

    return f


def _accumulate_gather(idx_col, tables, i, halo, tblocks, ratio):
    """Shared banded-gather body: f32 [BR, D] accumulation of
    ``onehot(local idx) @ table_block`` over the unrolled ±halo window.
    The validity test (band bounds + local-index equality) lives ONLY
    here so forward and backward kernels cannot diverge."""
    cols = jax.lax.broadcasted_iota(jnp.int32, (idx_col.shape[0], _BLOCK), 1)
    acc = jnp.zeros((idx_col.shape[0], tables[0].shape[1]), jnp.float32)
    for j, tref in enumerate(tables):
        tb = (i * ratio[0]) // ratio[1] + j - halo
        valid = jnp.logical_and(tb >= 0, tb < tblocks)
        onehot = jnp.where(
            jnp.logical_and(idx_col - tb * _BLOCK == cols, valid), 1.0, 0.0
        ).astype(tref.dtype)
        acc += jax.lax.dot_general(
            onehot,
            tref[:],
            dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
    return acc


def _gather_kernel(*refs, halo, tblocks, ratio, span):
    from jax.experimental import pallas as pl

    idx_ref = refs[0]
    tables = refs[1 : 1 + span]
    out_ref = refs[1 + span]
    out_ref[:] = _accumulate_gather(
        idx_ref[:], tables, pl.program_id(0), halo, tblocks, ratio
    )


def _scatter_kernel(idx_ref, values_ref, out_ref, *, off, vblocks, ratio):
    from jax.experimental import pallas as pl

    i = pl.program_id(0)  # output table block
    j = pl.program_id(1)
    vb = (i * ratio[1]) // ratio[0] + j - off  # contributing value block

    @pl.when(j == 0)
    def _():
        out_ref[:] = jnp.zeros_like(out_ref)

    @pl.when(jnp.logical_and(vb >= 0, vb < vblocks))
    def _():
        local = idx_ref[:] - i * _BLOCK  # [BR, 1] targets within this block
        rows = jax.lax.broadcasted_iota(
            jnp.int32, (_BLOCK, local.shape[0]), 0
        )
        onehot_t = (rows == local.reshape(1, -1)).astype(values_ref.dtype)
        out_ref[:] += jax.lax.dot_general(
            onehot_t,
            values_ref[:],
            dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )


def _gather_impl(table, idx, halo_blocks, rows_per_anchor, ratio):
    from jax.experimental import pallas as pl

    r = idx.shape[0]
    br = _BLOCK * rows_per_anchor
    table, _ = _pad_rows(table, _BLOCK)
    idx, _ = _pad_rows(idx.astype(jnp.int32), br, fill=-1)
    tblocks = table.shape[0] // _BLOCK
    iblocks = idx.shape[0] // br
    dim = table.shape[1]
    g_span, _, _ = _spans(halo_blocks, ratio)

    out = pl.pallas_call(
        functools.partial(
            _gather_kernel,
            halo=halo_blocks,
            tblocks=tblocks,
            ratio=ratio,
            span=g_span,
        ),
        out_shape=jax.ShapeDtypeStruct((idx.shape[0], dim), jnp.float32),
        grid=(iblocks,),
        in_specs=[pl.BlockSpec((br, 1), lambda i: (i, 0))]
        + [
            pl.BlockSpec((_BLOCK, dim), _table_map(j, halo_blocks, tblocks, ratio)) for j in range(g_span)
        ],
        out_specs=pl.BlockSpec((br, dim), lambda i: (i, 0)),
        interpret=_interpret(),
    )(idx.reshape(-1, 1), *([table] * g_span))
    return out[:r]


def _scatter_impl(values, idx, num_rows, halo_blocks, rows_per_anchor, ratio):
    from jax.experimental import pallas as pl

    br = _BLOCK * rows_per_anchor
    values, _ = _pad_rows(values, br)
    idx, _ = _pad_rows(idx.astype(jnp.int32), br, fill=-1)
    out_rows = num_rows + ((-num_rows) % _BLOCK)
    vblocks = values.shape[0] // br
    oblocks = out_rows // _BLOCK
    dim = values.shape[1]
    _, s_off, s_span = _spans(halo_blocks, ratio)

    def _vmap(i, j, *, _o=s_off, _v=vblocks, _r=ratio):
        return (jnp.clip((i * _r[1]) // _r[0] + j - _o, 0, _v - 1), 0)

    out = pl.pallas_call(
        functools.partial(
            _scatter_kernel, off=s_off, vblocks=vblocks, ratio=ratio
        ),
        out_shape=jax.ShapeDtypeStruct((out_rows, dim), jnp.float32),
        grid=(oblocks, s_span),
        in_specs=[
            pl.BlockSpec((br, 1), _vmap),
            pl.BlockSpec((br, dim), _vmap),
        ],
        out_specs=pl.BlockSpec((_BLOCK, dim), lambda i, j: (i, 0)),
        interpret=_interpret(),
    )(idx.reshape(-1, 1), values)
    return out[:num_rows]


def window_gather(
    table,
    idx,
    halo_blocks: int,
    rows_per_anchor: int = 1,
    ratio: Tuple[int, int] = (1, 1),
):
    """``table[idx]`` for banded ``idx`` — [R] flat indices into [N, D].

    ``rows_per_anchor``: idx rows per table-anchor row (K for flattened
    [N, K] neighbor lists). ``ratio=(num, den)``: anchor mapping for
    tables with different row density (idx block i targets table block
    ``(i*num)//den``); (1, 1) for node-table gathers. Out-of-band or
    negative indices yield zero rows. Returns f32 [R, D]."""
    # table.shape[0] rides as a static nondiff argument (the file's
    # pattern for shape state) rather than a residual — residuals hold
    # arrays only
    return _window_gather_n(
        table, idx, table.shape[0], halo_blocks, rows_per_anchor, ratio
    )


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3, 4, 5))
def _window_gather_n(
    table, idx, num_rows, halo_blocks, rows_per_anchor, ratio
):
    return _gather_impl(table, idx, halo_blocks, rows_per_anchor, ratio)


def _wg_fwd(table, idx, num_rows, halo_blocks, rows_per_anchor, ratio):
    out = _gather_impl(table, idx, halo_blocks, rows_per_anchor, ratio)
    return out, (idx, jnp.zeros((), table.dtype))


def _wg_bwd(num_rows, halo_blocks, rows_per_anchor, ratio, res, g):
    idx, proto = res
    gt = _scatter_impl(g, idx, num_rows, halo_blocks, rows_per_anchor, ratio)
    return gt.astype(proto.dtype), None


_window_gather_n.defvjp(_wg_fwd, _wg_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3, 4, 5))
def window_scatter_add(
    values,
    idx,
    num_rows: int,
    halo_blocks: int,
    rows_per_anchor: int = 1,
    ratio: Tuple[int, int] = (1, 1),
):
    """Scatter-add banded rows: ``out[idx[r]] += values[r]`` -> [num_rows, D].

    Dual of :func:`window_gather` (same band contract); negative indices
    are dropped. Returns f32."""
    return _scatter_impl(
        values, idx, num_rows, halo_blocks, rows_per_anchor, ratio
    )


def _ws_fwd(values, idx, num_rows, halo_blocks, rows_per_anchor, ratio):
    out = _scatter_impl(
        values, idx, num_rows, halo_blocks, rows_per_anchor, ratio
    )
    return out, (idx, jnp.zeros((), values.dtype))


def _ws_bwd(num_rows, halo_blocks, rows_per_anchor, ratio, res, g):
    idx, proto = res
    gv = _gather_impl(g, idx, halo_blocks, rows_per_anchor, ratio)
    return gv.astype(proto.dtype), None


window_scatter_add.defvjp(_ws_fwd, _ws_bwd)


# ---------------------------------------------------------------------------
# Fused banded gather + PNA statistics: the [N, K, D] gathered tensor never
# exists in HBM. Forward gathers each node block's neighbor messages into
# VMEM (onehot @ table-block dots) and reduces mean/std/min/max/count over
# K in-register; backward RECOMPUTES the gathered tile (cheaper than saving
# 2*K*D floats per node) to form the per-slot gradient, which the dual
# banded scatter routes back to the message table. Semantics exactly match
# dense_moments + dense_minmax (incl. the equal-split min/max tie gradient
# and the relu'd variance clamp).
# ---------------------------------------------------------------------------

_STD_EPS = 1e-5
_BIG = 1e30


def _slot_stats(a3, m2, k):
    """Slot-wise masked statistics over the K axis of ``a3 [b, k, d]`` with
    mask ``m2 [b, k]``: (sum, sum-of-squares, min, max, count), only
    [b, d]-sized temporaries live. ONE implementation shared by the fused
    forward and backward kernels so their recomputed statistics cannot
    diverge (the gradient-vs-function mismatch class)."""
    b, _, d = a3.shape
    s = jnp.zeros((b, d), jnp.float32)
    sq = jnp.zeros((b, d), jnp.float32)
    mn = jnp.full((b, d), _BIG, jnp.float32)
    mx = jnp.full((b, d), -_BIG, jnp.float32)
    cnt = jnp.zeros((b, 1), jnp.float32)
    for kk in range(k):
        hk = a3[:, kk, :]
        mk = m2[:, kk][:, None]
        hm = hk * mk
        s += hm
        sq += hm * hk
        mn = jnp.minimum(mn, jnp.where(mk > 0, hk, _BIG))
        mx = jnp.maximum(mx, jnp.where(mk > 0, hk, -_BIG))
        cnt += mk
    return s, sq, mn, mx, cnt


def _gstats_fwd_kernel(*refs, halo, tblocks, ratio, span, k):
    from jax.experimental import pallas as pl

    idx_ref, mask_ref = refs[0], refs[1]
    tables = refs[2 : 2 + span]
    mean_ref, std_ref, mn_ref, mx_ref, cnt_ref = refs[2 + span :]
    i = pl.program_id(0)
    acc = _accumulate_gather(idx_ref[:], tables, i, halo, tblocks, ratio)
    b = acc.shape[0] // k
    d = acc.shape[1]
    a3 = acc.reshape(b, k, d)
    m2 = mask_ref[:].reshape(b, k).astype(jnp.float32)
    # slot-wise accumulation: a vectorized K-axis reduce would hold ~6
    # [BR, D] temporaries and blow the 16MB VMEM scope at k*dim >= ~4k
    s, sq, mn, mx, cnt = _slot_stats(a3, m2, k)
    deg = jnp.maximum(cnt, 1.0)
    mean = s / deg
    std = jnp.sqrt(jnp.maximum(sq / deg - mean * mean, 0.0) + _STD_EPS)
    has = cnt > 0
    mean_ref[:] = mean
    std_ref[:] = std
    mn_ref[:] = jnp.where(has, mn, 0.0)
    mx_ref[:] = jnp.where(has, mx, 0.0)
    cnt_ref[:] = cnt


def _gstats_bwd_kernel(*refs, halo, tblocks, ratio, span, k):
    from jax.experimental import pallas as pl

    idx_ref, mask_ref, gmean_ref, gstd_ref, gmn_ref, gmx_ref = refs[:6]
    tables = refs[6 : 6 + span]
    gslot_ref = refs[6 + span]
    i = pl.program_id(0)
    acc = _accumulate_gather(idx_ref[:], tables, i, halo, tblocks, ratio)
    b = acc.shape[0] // k
    d = acc.shape[1]
    a3 = acc.reshape(b, k, d)
    m2 = mask_ref[:].reshape(b, k).astype(jnp.float32)
    # pass 1: recompute the statistics (shared body = same arithmetic)
    s, sq, mn, mx, cnt = _slot_stats(a3, m2, k)
    deg = jnp.maximum(cnt, 1.0)
    mean = s / deg
    var_pre = sq / deg - mean * mean
    std = jnp.sqrt(jnp.maximum(var_pre, 0.0) + _STD_EPS)
    n_mn = jnp.zeros((b, d), jnp.float32)
    n_mx = jnp.zeros((b, d), jnp.float32)
    for kk in range(k):
        hk = a3[:, kk, :]
        mk = m2[:, kk][:, None]
        n_mn += jnp.where((hk == mn) & (mk > 0), 1.0, 0.0)
        n_mx += jnp.where((hk == mx) & (mk > 0), 1.0, 0.0)
    n_mn = jnp.maximum(n_mn, 1.0)
    n_mx = jnp.maximum(n_mx, 1.0)
    clamp = (var_pre > 0.0).astype(jnp.float32)  # relu'd variance gate
    dstd = gstd_ref[:] * clamp / (deg * std)
    gmean_t = gmean_ref[:] / deg
    gmn_t = gmn_ref[:] / n_mn
    gmx_t = gmx_ref[:] / n_mx
    # pass 2: per-slot gradient, written slot-wise (equal tie split,
    # matching lax reduce min/max VJP)
    for kk in range(k):
        hk = a3[:, kk, :]
        mk = m2[:, kk][:, None]
        gs = (
            gmean_t
            + dstd * (hk - mean)
            + gmn_t * jnp.where((hk == mn) & (mk > 0), 1.0, 0.0)
            + gmx_t * jnp.where((hk == mx) & (mk > 0), 1.0, 0.0)
        )
        gslot_ref[kk::k, :] = gs * mk  # slot-strided rows of [b*k, d]


def _gstats_impl(table, idx, mask, halo_blocks, k, ratio):
    from jax.experimental import pallas as pl

    r = idx.shape[0]
    br = _BLOCK * k
    table, _ = _pad_rows(table, _BLOCK)
    idx, _ = _pad_rows(idx.astype(jnp.int32), br, fill=-1)
    mask, _ = _pad_rows(mask.astype(jnp.int32), br, fill=0)
    tblocks = table.shape[0] // _BLOCK
    iblocks = idx.shape[0] // br
    dim = table.shape[1]
    n_anchor = idx.shape[0] // k
    g_span, _, _ = _spans(halo_blocks, ratio)

    outs = pl.pallas_call(
        functools.partial(
            _gstats_fwd_kernel,
            halo=halo_blocks,
            tblocks=tblocks,
            ratio=ratio,
            span=g_span,
            k=k,
        ),
        out_shape=(
            jax.ShapeDtypeStruct((n_anchor, dim), jnp.float32),
            jax.ShapeDtypeStruct((n_anchor, dim), jnp.float32),
            jax.ShapeDtypeStruct((n_anchor, dim), jnp.float32),
            jax.ShapeDtypeStruct((n_anchor, dim), jnp.float32),
            jax.ShapeDtypeStruct((n_anchor, 1), jnp.float32),
        ),
        grid=(iblocks,),
        in_specs=[
            pl.BlockSpec((br, 1), lambda i: (i, 0)),
            pl.BlockSpec((br, 1), lambda i: (i, 0)),
        ]
        + [pl.BlockSpec((_BLOCK, dim), _table_map(j, halo_blocks, tblocks, ratio)) for j in range(g_span)],
        out_specs=(
            pl.BlockSpec((_BLOCK, dim), lambda i: (i, 0)),
            pl.BlockSpec((_BLOCK, dim), lambda i: (i, 0)),
            pl.BlockSpec((_BLOCK, dim), lambda i: (i, 0)),
            pl.BlockSpec((_BLOCK, dim), lambda i: (i, 0)),
            pl.BlockSpec((_BLOCK, 1), lambda i: (i, 0)),
        ),
        interpret=_interpret(),
    )(idx.reshape(-1, 1), mask.reshape(-1, 1), *([table] * g_span))
    n_real = r // k
    return tuple(o[:n_real] for o in outs)


def _gstats_bwd_impl(table, idx, mask, gmean, gstd, gmn, gmx, halo_blocks,
                     k, ratio):
    from jax.experimental import pallas as pl

    r = idx.shape[0]
    br = _BLOCK * k
    table_p, _ = _pad_rows(table, _BLOCK)
    idx_p, _ = _pad_rows(idx.astype(jnp.int32), br, fill=-1)
    mask_p, _ = _pad_rows(mask.astype(jnp.int32), br, fill=0)
    grads = [
        _pad_rows(g.astype(jnp.float32), _BLOCK)[0]
        for g in (gmean, gstd, gmn, gmx)
    ]
    tblocks = table_p.shape[0] // _BLOCK
    iblocks = idx_p.shape[0] // br
    dim = table_p.shape[1]
    g_span, _, _ = _spans(halo_blocks, ratio)

    gslot = pl.pallas_call(
        functools.partial(
            _gstats_bwd_kernel,
            halo=halo_blocks,
            tblocks=tblocks,
            ratio=ratio,
            span=g_span,
            k=k,
        ),
        out_shape=jax.ShapeDtypeStruct((idx_p.shape[0], dim), jnp.float32),
        grid=(iblocks,),
        in_specs=[
            pl.BlockSpec((br, 1), lambda i: (i, 0)),
            pl.BlockSpec((br, 1), lambda i: (i, 0)),
            pl.BlockSpec((_BLOCK, dim), lambda i: (i, 0)),
            pl.BlockSpec((_BLOCK, dim), lambda i: (i, 0)),
            pl.BlockSpec((_BLOCK, dim), lambda i: (i, 0)),
            pl.BlockSpec((_BLOCK, dim), lambda i: (i, 0)),
        ]
        + [pl.BlockSpec((_BLOCK, dim), _table_map(j, halo_blocks, tblocks, ratio)) for j in range(g_span)],
        out_specs=pl.BlockSpec((br, dim), lambda i: (i, 0)),
        interpret=_interpret(),
    )(
        idx_p.reshape(-1, 1),
        mask_p.reshape(-1, 1),
        *grads,
        *([table_p] * g_span),
    )
    return _scatter_impl(
        gslot[:r], idx[:r], table.shape[0], halo_blocks, k, ratio
    ).astype(table.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def window_gather_stats(
    table,
    idx,
    mask,
    halo_blocks: int,
    k: int,
    ratio: Tuple[int, int] = (1, 1),
):
    """(mean, std, mn, mx, cnt) over each anchor's K banded-gathered rows.

    ``table [N, D]``, ``idx/mask [A*K]`` flat. One fused kernel: the
    [A, K, D] gathered tensor lives only in VMEM; outputs are the PNA
    aggregation statistics with dense_moments/dense_minmax semantics
    (empty anchors -> mean/std of masked-zero rows, min/max fill 0).
    Backward recomputes the tile and scatters the per-slot gradient with
    the dual banded scatter -- no reverse lists, nothing saved but idx
    and mask."""
    return _gstats_impl(table, idx, mask, halo_blocks, k, ratio)


def _wgs_fwd(table, idx, mask, halo_blocks, k, ratio):
    outs = _gstats_impl(table, idx, mask, halo_blocks, k, ratio)
    return outs, (table, idx, mask)


def _wgs_bwd(halo_blocks, k, ratio, res, gs):
    table, idx, mask = res
    gmean, gstd, gmn, gmx, _gcnt = gs  # cnt is piecewise constant
    gt = _gstats_bwd_impl(
        table, idx, mask, gmean, gstd, gmn, gmx, halo_blocks, k, ratio
    )
    return gt, None, None


window_gather_stats.defvjp(_wgs_fwd, _wgs_bwd)
