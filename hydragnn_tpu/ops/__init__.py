from hydragnn_tpu.ops.pallas_segment import (
    pallas_segments_enabled,
    segment_moments,
    segment_sum_onehot,
)
