from hydragnn_tpu.ops.pallas_segment import (
    pallas_segments_enabled,
    segment_moments,
    segment_sum_onehot,
)
from hydragnn_tpu.ops.fused_mp import (
    fused_egnn_edge_phase,
    fused_gather_mean,
    fused_gather_moments,
    fused_gather_sum,
    fused_gather_weighted_sum,
    fused_message_reduce,
    fused_mp_enabled,
)
