"""Pallas TPU kernels for the message-passing aggregation hot path.

The reference's aggregation runs on torch_scatter CUDA kernels
(SURVEY.md §2.4). On TPU, XLA lowers ``jax.ops.segment_*`` to scatter-adds,
which serialize on duplicate indices and re-read the ``[E, D]`` message
array once per requested statistic — PNA wants mean, std AND the degree
count, i.e. three passes over HBM.

These kernels make aggregation MXU work instead of scatter work: the output
``[N, D]`` accumulator lives in VMEM across the whole grid; each step loads
one block of edges and accumulates ``onehot(receivers)^T @ messages`` — a
dense matmul the systolic array eats — so the messages are read from HBM
exactly ONCE. ``segment_moments`` produces sum, count and sum-of-squares in
that single pass (mean/std/degree all derive from it).

Enablement: ``HYDRAGNN_PALLAS=1`` opts in (with the VMEM-budget guard
below), ``0``/unset keeps the XLA path. Fence-true measurement on the
tunneled v5e (bench.py fit_staged, PNA multihead, ~4.6k nodes / ~18k edges
/ dim 64, 2026-07-30): pallas 4.44 ms/step vs XLA scatter 4.45 — a dead
heat end-to-end, because the moments kernel replaces only one of the
remaining scatter passes and the step is op-latency-bound on this backend.
XLA additionally fuses its scatter with the surrounding elementwise work —
a fusion the opaque pallas_call boundary forfeits — so the default stays
OFF. Revisit with a kernel that fuses the message MLP + aggregation on
hardware where scatters dominate. Gradients are provided via custom VJPs
(gather-based, XLA-fused).
"""

import functools
import os

import jax
import jax.numpy as jnp

_EDGE_BLOCK = 256
_VMEM_ACC_BUDGET = 6 * 1024 * 1024  # bytes of VMEM we allow the accumulators


def pallas_segments_enabled(num_segments: int, dim: int, n_outputs: int = 1):
    """Decide kernel vs XLA fallback for a [num_segments, dim] accumulation.

    On via ``HYDRAGNN_PALLAS=1`` or the autotuner's family force
    ``HYDRAGNN_AGG=fused`` (``ops/autotune.py``): forcing the fused
    message-passing family also turns on the one-hot segment kernels at
    the sites the fused ops don't cover, so an A/B flips the whole tree.

    Budget covers everything the kernel keeps resident in VMEM: the
    accumulators AND the per-block ``[_EDGE_BLOCK, num_segments]`` one-hot
    indicator (at 16k+ segments the indicator alone exceeds the 16 MB VMEM
    scoped limit — observed as a compile-time VMEM OOM on the giant-graph
    partition config before this guard included it)."""
    if os.getenv("HYDRAGNN_PALLAS", "0") != "1":
        from hydragnn_tpu.ops.autotune import env_force

        if env_force() != "fused":
            return False
    acc_bytes = n_outputs * num_segments * max(dim, 1) * 4
    onehot_bytes = _EDGE_BLOCK * num_segments * 4
    return acc_bytes + onehot_bytes <= _VMEM_ACC_BUDGET


def _interpret(requested: bool) -> bool:
    """Compiled pallas is TPU-only; other backends run the interpreter (so
    HYDRAGNN_PALLAS=1 is testable on CPU)."""
    if requested:
        return True
    try:
        return jax.default_backend() != "tpu"
    except Exception:
        return True


def _pad_edges(data, segment_ids, block):
    """Pad the edge axis to a block multiple; padded ids point past the last
    segment so their one-hot row is all zeros (no contribution).

    ids are returned as ``[E, 1]`` — 1-D i32 operands get XLA's T(1024)
    layout, which Mosaic cannot block at the edge-block size; the 2-D shape
    tiles conventionally (verified on v5e)."""
    e = data.shape[0]
    pad = (-e) % block
    if pad:
        data = jnp.pad(data, ((0, pad), (0, 0)))
        segment_ids = jnp.pad(
            segment_ids, (0, pad), constant_values=jnp.iinfo(jnp.int32).max
        )
    return data, segment_ids.reshape(-1, 1)


def _onehot(ids_block, num_segments):
    """[E_blk, N] float32 indicator from [E_blk, 1] ids; out-of-range ids
    give a zero row."""
    cols = jax.lax.broadcasted_iota(jnp.int32, (ids_block.shape[0], num_segments), 1)
    return (ids_block == cols).astype(jnp.float32)


# ---------------------------------------------------------------------------
# segment_sum
# ---------------------------------------------------------------------------

def _sum_kernel(ids_ref, data_ref, out_ref):
    from jax.experimental import pallas as pl

    @pl.when(pl.program_id(0) == 0)
    def _():
        out_ref[:] = jnp.zeros_like(out_ref)

    onehot = _onehot(ids_ref[:], out_ref.shape[0])
    out_ref[:] += jax.lax.dot_general(
        onehot, data_ref[:],
        dimension_numbers=(((0,), (0,)), ((), ())),  # onehot^T @ data
        preferred_element_type=jnp.float32,
    )


def _segment_sum_fwd_impl(data, segment_ids, num_segments, interpret=False):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    interpret = _interpret(interpret)
    data = data.astype(jnp.float32)
    data, ids = _pad_edges(data, segment_ids.astype(jnp.int32), _EDGE_BLOCK)
    e_pad, dim = data.shape
    grid = e_pad // _EDGE_BLOCK
    return pl.pallas_call(
        _sum_kernel,
        out_shape=jax.ShapeDtypeStruct((num_segments, dim), jnp.float32),
        grid=(grid,),
        in_specs=[
            pl.BlockSpec((_EDGE_BLOCK, 1), lambda i: (i, 0)),
            pl.BlockSpec((_EDGE_BLOCK, dim), lambda i: (i, 0)),
        ],
        out_specs=pl.BlockSpec((num_segments, dim), lambda i: (0, 0)),
        interpret=interpret,
    )(ids, data)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3))
def segment_sum_onehot(data, segment_ids, num_segments, interpret=False):
    """Pallas segment-sum: ``out[n] = sum_{e: ids[e]==n} data[e]``.

    ``data`` must be 2-D ``[E, D]``. Same contract as
    ``jax.ops.segment_sum`` with static ``num_segments``.
    """
    return _segment_sum_fwd_impl(data, segment_ids, num_segments, interpret)


def _segment_sum_fwd(data, segment_ids, num_segments, interpret):
    out = _segment_sum_fwd_impl(data, segment_ids, num_segments, interpret)
    return out, (segment_ids, data.shape[0])


def _segment_sum_bwd(num_segments, interpret, res, g):
    segment_ids, _ = res
    # d/d_data = g gathered at each edge's segment. Out-of-range ids (the
    # kernels' padded-edge contract: they contribute nothing forward) must
    # get exactly ZERO gradient — a bare g[ids] would clamp-gather the
    # last segment's cotangent onto them.
    valid = (segment_ids >= 0) & (segment_ids < num_segments)
    safe = jnp.clip(segment_ids, 0, num_segments - 1)
    return jnp.where(valid[:, None], g[safe], 0.0), None


segment_sum_onehot.defvjp(_segment_sum_fwd, _segment_sum_bwd)


# ---------------------------------------------------------------------------
# segment_moments: sum / count / sum-of-squares in ONE pass
# ---------------------------------------------------------------------------

def _moments_kernel(ids_ref, data_ref, sum_ref, cnt_ref, sq_ref):
    from jax.experimental import pallas as pl

    @pl.when(pl.program_id(0) == 0)
    def _():
        sum_ref[:] = jnp.zeros_like(sum_ref)
        cnt_ref[:] = jnp.zeros_like(cnt_ref)
        sq_ref[:] = jnp.zeros_like(sq_ref)

    data = data_ref[:]
    onehot = _onehot(ids_ref[:], sum_ref.shape[0])
    tdot = functools.partial(
        jax.lax.dot_general,
        dimension_numbers=(((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    sum_ref[:] += tdot(onehot, data)
    sq_ref[:] += tdot(onehot, data * data)
    cnt_ref[:] += jnp.sum(onehot, axis=0, keepdims=True).T


def _moments_impl(data, segment_ids, num_segments, interpret=False):
    from jax.experimental import pallas as pl

    interpret = _interpret(interpret)
    data = data.astype(jnp.float32)
    data, ids = _pad_edges(data, segment_ids.astype(jnp.int32), _EDGE_BLOCK)
    e_pad, dim = data.shape
    grid = e_pad // _EDGE_BLOCK
    return pl.pallas_call(
        _moments_kernel,
        out_shape=(
            jax.ShapeDtypeStruct((num_segments, dim), jnp.float32),
            jax.ShapeDtypeStruct((num_segments, 1), jnp.float32),
            jax.ShapeDtypeStruct((num_segments, dim), jnp.float32),
        ),
        grid=(grid,),
        in_specs=[
            pl.BlockSpec((_EDGE_BLOCK, 1), lambda i: (i, 0)),
            pl.BlockSpec((_EDGE_BLOCK, dim), lambda i: (i, 0)),
        ],
        out_specs=(
            pl.BlockSpec((num_segments, dim), lambda i: (0, 0)),
            pl.BlockSpec((num_segments, 1), lambda i: (0, 0)),
            pl.BlockSpec((num_segments, dim), lambda i: (0, 0)),
        ),
        interpret=interpret,
    )(ids, data)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3))
def segment_moments(data, segment_ids, num_segments, interpret=False):
    """(sum, count, sum_of_squares) per segment in one pass over the edges.

    mean = sum / max(count, 1); var = sq/count - mean^2 — the PNA aggregator
    statistics (``models/PNAStack.py:28-34`` in the reference) from a single
    HBM read of the messages.
    """
    return _moments_impl(data, segment_ids, num_segments, interpret)


def _moments_fwd(data, segment_ids, num_segments, interpret):
    out = _moments_impl(data, segment_ids, num_segments, interpret)
    return out, (data, segment_ids)


def _moments_bwd(num_segments, interpret, res, grads):
    data, segment_ids = res
    g_sum, _g_cnt, g_sq = grads  # count is piecewise constant: no gradient
    # same padded-edge contract as _segment_sum_bwd: out-of-range ids
    # contributed nothing forward, so they get zero gradient back
    valid = (segment_ids >= 0) & (segment_ids < num_segments)
    safe = jnp.clip(segment_ids, 0, num_segments - 1)
    d_data = g_sum[safe] + 2.0 * data * g_sq[safe]
    return jnp.where(valid[:, None], d_data, 0.0), None


segment_moments.defvjp(_moments_fwd, _moments_bwd)
