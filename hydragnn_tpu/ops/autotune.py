"""Per-bucket aggregation autotuner: measure {segment, dense, fused}, cache
the winner, make every decision observable.

Three aggregation strategies coexist for the message-passing hot path:

- **segment**: ``jax.ops.segment_*`` scatters (XLA fuses them with the
  surrounding elementwise work) — the safe default;
- **dense**: host-built fixed-width neighbor lists, scatter-free masked
  K-axis reductions (``ops/dense_agg.py``) — wins at MXU widths for
  scatter-heavy stacks (measured crossovers below);
- **fused**: single-kernel Pallas gather -> edge-op -> reduce
  (``ops/fused_mp.py``) — wins where the scatter AND the ``[E, D]``
  message materialization dominate and the node table fits VMEM.

Decision order (first match wins), evaluated per bucket layout:

1. ``HYDRAGNN_AGG=segment|dense|fused`` — operator force, everywhere.
2. ``HYDRAGNN_FUSED_MP=1`` — force the fused kernels wherever the VMEM
   guard admits them (``0`` forbids them everywhere, beating the cache).
3. The on-disk cache — one measured choice per (device kind, bucket
   signature), written by :func:`autotune_bucket` at warmup. Cached
   decisions are DETERMINISTIC: no re-timing, same file -> same choices.
4. The measured-crossover static policy (tables promoted here from
   ``data/loaders.py``; bench.py's ``auto_choice`` reports this tier).

Every decision is emitted as an ``agg_choice`` obs event (schema in
``obs/events.py``) and an ``aggregation_kernel`` labeled gauge, so run
reports show which kernel each bucket actually used.
"""

import json
import os
import threading
import time
from typing import Dict, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp

CHOICES = ("segment", "dense", "fused")

# ---------------------------------------------------------------------------
# static policy (promoted from data/loaders.py — the tier bench.py's
# auto_choice column has always reported)
# ---------------------------------------------------------------------------

# Measured dense/segment crossovers (BASELINE.md rounds 2-4, v5e, same-
# session A/Bs at deg ~12): minimum hidden_dim at which the dense
# scatter-free path beats segment reductions for each model. Scatter-heavy
# models (PNA's 4 aggregators, GAT's edge softmax, MFC's degree banks,
# DimeNet's triplet axis) cross early; GIN/SAGE only win mildly at MXU
# widths; SchNet and EGNN never do (one already-fused scatter per layer).
DENSE_AUTO_MIN_HIDDEN = {
    "PNA": 96,
    "GAT": 96,
    "MFC": 96,
    "DimeNet": 96,
    "GIN": 192,
    "SAGE": 192,
    # CGCNN absent from THIS table: its convs run at input_dim width
    # (constant-width CGConv), so hidden_dim says nothing about where it
    # sits relative to the crossover — it gets its own rule below.
}

# CGCNN's crossover keyed on its TRUE conv width (round-4 verdict item 8,
# measured round 5 at OC20 shape): INVERSE to the hidden-width table —
# dense gathers [N, K, input_dim] blocks, so gather traffic grows with
# input width while the segment scatter cost stays flat. Maximum input_dim
# at which the dense path is picked automatically.
DENSE_AUTO_MAX_INPUT_DIM = {
    "CGCNN": 64,
}


def auto_dense_aggregation(arch_config: dict) -> bool:
    """The measured-crossover policy: dense iff the (model type, width)
    point sits on the dense-winning side of the tables above. Width is
    hidden_dim for most stacks; CGCNN's constant-width convs key on
    input_dim instead — and inversely. Absent/0 input_dim stays
    conservative: segment."""
    mt = arch_config.get("model_type")
    th_in = DENSE_AUTO_MAX_INPUT_DIM.get(mt)
    if th_in is not None:
        dim = int(arch_config.get("input_dim") or 0)
        return 1 <= dim <= th_in
    th = DENSE_AUTO_MIN_HIDDEN.get(mt)
    return th is not None and int(arch_config.get("hidden_dim") or 0) >= th


def static_aggregation_choice(arch_config: dict) -> str:
    """Policy-tier choice for a model config (no cache, no env): what
    bench.py records as ``auto_choice`` when nothing measured overrides."""
    return "dense" if auto_dense_aggregation(arch_config) else "segment"


# ---------------------------------------------------------------------------
# env overrides
# ---------------------------------------------------------------------------


def env_force() -> Optional[str]:
    """``HYDRAGNN_AGG`` when it names a valid choice, else None."""
    v = (os.getenv("HYDRAGNN_AGG") or "").strip().lower()
    return v if v in CHOICES else None


def fused_forbidden() -> bool:
    """``HYDRAGNN_FUSED_MP=0`` is the fused kill switch — it beats the
    cache AND ``HYDRAGNN_AGG=fused`` (the operator's last word when a
    cached decision misbehaves on a new jax/backend)."""
    return (os.getenv("HYDRAGNN_FUSED_MP") or "").strip() == "0"


def fused_forced() -> bool:
    return (os.getenv("HYDRAGNN_FUSED_MP") or "").strip() == "1"


# ---------------------------------------------------------------------------
# bucket signatures + on-disk cache
# ---------------------------------------------------------------------------

_STACK_KEYS = {
    "PNAStack": "PNA",
    "GINStack": "GIN",
    "GATStack": "GAT",
    "MFCStack": "MFC",
    "SAGEStack": "SAGE",
    "CGCNNStack": "CGCNN",
    "SCFStack": "SchNet",
    "EGCLStack": "EGNN",
    "DIMEStack": "DimeNet",
}


def model_key_for(model) -> str:
    """Short model key ("PNA", "SchNet", ...) from a stack instance."""
    name = type(model).__name__
    return _STACK_KEYS.get(name, name.replace("Stack", ""))


def bucket_signature(model_key: str, num_nodes: int, num_edges: int,
                     dim: int) -> str:
    """One bucket layout's identity: padded node/edge counts + feature
    width + model. These are exactly the statics a compiled program is
    specialized on, so one cached choice maps to one XLA program."""
    return f"{model_key}/n{int(num_nodes)}/e{int(num_edges)}/d{int(dim)}"


def device_kind() -> str:
    try:
        import jax

        d = jax.devices()[0]
        return getattr(d, "device_kind", None) or d.platform
    except Exception:
        return "unknown"


def cache_path() -> str:
    p = os.getenv("HYDRAGNN_AUTOTUNE_CACHE")
    if p:
        return p
    return os.path.join(
        os.path.expanduser("~"), ".cache", "hydragnn_tpu", "autotune.json"
    )


_lock = threading.Lock()
_cache: Optional[Dict] = None
_cache_from: Optional[str] = None


def _load_cache() -> Dict:
    """Lazy singleton keyed on the active cache path (tests repoint it via
    the env var). File I/O happens OUTSIDE the lock; the lock only guards
    the singleton swap (a racing double-read is harmless — last one
    wins with identical content)."""
    global _cache, _cache_from
    path = cache_path()
    with _lock:
        if _cache is not None and _cache_from == path:
            return _cache
    try:
        with open(path) as f:
            data = json.load(f)
        if not isinstance(data.get("devices"), dict):
            raise ValueError("malformed cache")
    except (OSError, ValueError):
        data = {"version": 1, "devices": {}}
    with _lock:
        if _cache is None or _cache_from != path:
            _cache, _cache_from = data, path
        return _cache


def reset_cache_state():
    """Drop the in-process cache singleton (tests; also lets a long-lived
    process pick up an externally rewritten file)."""
    global _cache, _cache_from
    with _lock:
        _cache = None
        _cache_from = None


def cached_choice(signature: str) -> Optional[Dict]:
    return _load_cache()["devices"].get(device_kind(), {}).get(signature)


def cached_model_choice(model_key: str, width: int) -> Optional[str]:
    """Most-recent cached decision for this model AT THIS FEATURE WIDTH
    that ACTUALLY TIMED THE DENSE CANDIDATE — the loader's lookup: the
    dense-vs-segment choice is enacted at LAYOUT time (host-built
    neighbor lists), before bucket shapes exist, so a measured ``dense``
    win is applied on the next layout build. Two qualifiers keep the
    cache honest: records whose measurement never included dense (a
    segment-vs-fused-only probe) say NOTHING about dense-vs-segment, and
    the dense/segment crossover is WIDTH-dependent (CGCNN's is even
    inverse in input width), so only records measured at the config's
    own width apply. Returns None with no qualifying entry."""
    prefix = f"{model_key}/"
    suffix = f"/d{int(width)}"
    dev = _load_cache()["devices"].get(device_kind(), {})
    best = None
    for sig, rec in dev.items():
        if (
            sig.startswith(prefix)
            and sig.endswith(suffix)
            and "dense" in (rec.get("timings_ms") or {})
        ):
            if best is None or rec.get("ts", 0) > best.get("ts", 0):
                best = rec
    return None if best is None else best["choice"]


def cached_choice_same_bucket(model_key: str, num_nodes: int,
                              num_edges: int) -> Optional[Dict]:
    """Width-agnostic fallback lookup: the warmup autotune measures one
    representative width (the model's hidden_dim), while aggregation
    sites see their own table widths (layer-0 input width, EGNN's
    ``hidden+3`` pos-extended table). A decision transfers across widths
    within the same (model, padded-nodes, padded-edges) bucket — the
    scatter-vs-gather economics it measured are set by N/E, not by a few
    columns."""
    prefix = f"{model_key}/n{int(num_nodes)}/e{int(num_edges)}/"
    dev = _load_cache()["devices"].get(device_kind(), {})
    for sig, rec in dev.items():
        if sig.startswith(prefix):
            return rec
    return None


def record_choice(signature: str, choice: str, timings_ms: Optional[Dict],
                  persist: bool = True):
    data = _load_cache()
    with _lock:
        dev = data["devices"].setdefault(device_kind(), {})
        dev[signature] = {
            "choice": choice,
            "timings_ms": timings_ms or {},
            "ts": round(time.time(), 3),
        }
    if persist:
        # serialize UNDER the lock (pure CPU — a concurrent recorder
        # mutating the dict mid-dump would raise RuntimeError, which the
        # OSError guard below would not catch); write the blob outside
        with _lock:
            blob = json.dumps(data, indent=1, sort_keys=True)
        path = cache_path()
        try:
            os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
            tmp = path + ".tmp"
            with open(tmp, "w") as f:
                f.write(blob)
            os.replace(tmp, path)
        except OSError:
            pass  # an unwritable cache dir must not kill training


# ---------------------------------------------------------------------------
# observability
# ---------------------------------------------------------------------------

def emit_choice(signature: str, choice: str, source: str,
                timings_ms: Optional[Dict] = None):
    """One ``agg_choice`` event + ``aggregation_kernel`` gauge per novel
    (signature, choice, source) PER TELEMETRY RUN — deduplicated so
    per-trace re-decisions don't spam the stream. The dedup set lives ON
    the active RunTelemetry (not process-global, and not keyed by id() —
    a GC'd run's address gets reused), so every run's events.jsonl
    stands alone; with no run active there is nothing to emit."""
    try:
        from hydragnn_tpu.obs import runtime as obs_rt
    except Exception:
        return
    run = obs_rt.active()
    if run is None:
        return
    emitted = getattr(run, "_agg_choice_emitted", None)
    if emitted is None:
        emitted = set()
        run._agg_choice_emitted = emitted
    key = (signature, choice, source)
    if key in emitted:
        return
    emitted.add(key)
    try:
        fields = {"bucket": signature, "choice": choice, "source": source}
        if timings_ms:
            fields["timings_ms"] = {
                k: round(float(v), 4) for k, v in timings_ms.items()
            }
        obs_rt.emit("agg_choice", **fields)
        # exactly ONE choice label reads 1 per bucket: a re-decision
        # (env override after a measured pass, fused->segment VMEM
        # fallback) must zero the previously-active label or dashboards
        # show two kernels live on one bucket
        for c in CHOICES:
            run.metrics.registry.set_labeled(
                "aggregation_kernel",
                1.0 if c == choice else 0.0,
                bucket=signature,
                choice=c,
            )
    except Exception:
        pass


# ---------------------------------------------------------------------------
# trace-time decision (the models' entry point)
# ---------------------------------------------------------------------------


def use_fused(model_key: str, num_nodes: int, num_edges: int,
              table_dim: int, out_dim: int,
              num_segments: Optional[int] = None,
              table_dim_b: int = 0) -> bool:
    """Should THIS aggregation site use the fused Pallas kernel?

    Called at trace time from the models' segment branches (shapes are
    static under jit). Applies the decision order from the module
    docstring; "fused" additionally requires the VMEM guard
    (``fused_mp.fused_mp_enabled``) to pass — an env/cache override can
    never select a config that would VMEM-OOM at compile time."""
    from hydragnn_tpu.ops.fused_mp import fused_mp_enabled

    if fused_forbidden():
        return False
    num_segments = num_nodes if num_segments is None else num_segments
    fits = fused_mp_enabled(
        num_nodes, num_segments, table_dim, out_dim, table_dim_b
    )
    sig = bucket_signature(model_key, num_nodes, num_edges, table_dim)
    forced = env_force()
    if forced is not None:
        choice = forced if (forced != "fused" or fits) else "segment"
        if choice == "dense":
            # dense is a LAYOUT-time decision; a segment-laid-out batch
            # reaching this trace-time site runs the segment path
            # whatever the force says — report what actually runs
            choice = "segment"
        emit_choice(sig, choice, "env")
        return choice == "fused"
    if fused_forced():
        choice = "fused" if fits else "segment"
        emit_choice(sig, choice, "env")
        return choice == "fused"
    rec = cached_choice(sig) or cached_choice_same_bucket(
        model_key, num_nodes, num_edges
    )
    if rec is not None:
        choice = rec["choice"]
        if choice == "fused" and not fits:
            choice = "segment"
        if choice == "dense":
            # dense is enacted by the LOADER (host-built lists, via
            # cached_model_choice); reaching this site means the batch
            # is segment-laid-out, so report what actually runs here
            choice = "segment"
        emit_choice(sig, choice, "cache")
        return choice == "fused"
    return False  # policy tier: fused is opt-in by measurement only


# ---------------------------------------------------------------------------
# measurement
# ---------------------------------------------------------------------------


def _fence(x):
    # true-completion fence: materialize a host byte (block_until_ready
    # does not block on the tunneled axon backend — model_bench.py)
    np.asarray(jax.tree_util.tree_leaves(x)[0]).ravel()[:1]


def measure_candidates(
    num_nodes: int,
    num_edges: int,
    dim: int,
    candidates: Tuple[str, ...] = ("segment", "fused"),
    iters: int = 10,
    seed: int = 0,
    interpret: Optional[bool] = None,
) -> Dict[str, float]:
    """Time each candidate's representative aggregation microbench at one
    bucket shape (ms per call). The probe is the common denominator of the
    model hot paths: gather sender rows, mask, reduce at receivers.
    Candidates that fail to compile/run are disqualified (absent from the
    result) rather than propagating — a broken kernel must lose the
    autotune, not kill the run."""
    rng = np.random.default_rng(seed)
    x = jnp.asarray(rng.standard_normal((num_nodes, dim)), jnp.float32)
    snd = jnp.asarray(rng.integers(0, num_nodes, num_edges), jnp.int32)
    rcv = jnp.asarray(rng.integers(0, num_nodes, num_edges), jnp.int32)
    mask = jnp.asarray(rng.random(num_edges) > 0.1)

    probes = {}
    if "segment" in candidates:
        probes["segment"] = jax.jit(
            lambda x: jax.ops.segment_sum(
                jnp.where(mask[:, None], x[snd], 0.0),
                rcv,
                num_segments=num_nodes,
            )
        )
    if "fused" in candidates:
        from hydragnn_tpu.ops.fused_mp import fused_gather_sum

        kw = {} if interpret is None else {"interpret": interpret}
        probes["fused"] = jax.jit(
            lambda x: fused_gather_sum(x, snd, rcv, num_nodes, mask, **kw)
        )
    if "dense" in candidates:
        from hydragnn_tpu.ops.dense_agg import (
            build_neighbor_lists,
            dense_sum,
            max_degree,
        )

        k_in, k_out = max_degree(snd, rcv, mask)
        lists = build_neighbor_lists(
            np.asarray(snd), np.asarray(rcv), np.asarray(mask),
            num_nodes, k_in, k_out,
        )
        nbr = jnp.asarray(lists["nbr_idx"])
        nmask = jnp.asarray(lists["nbr_mask"])
        probes["dense"] = jax.jit(lambda x: dense_sum(x[nbr], nmask))

    timings = {}
    for name, fn in probes.items():
        try:
            _fence(fn(x))  # compile + warm
            t0 = time.perf_counter()
            out = None
            for _ in range(iters):
                out = fn(x)
            _fence(out)
            timings[name] = (time.perf_counter() - t0) / iters * 1e3
        except Exception:
            continue  # disqualified
    return timings


def autotune_bucket(
    model_key: str,
    num_nodes: int,
    num_edges: int,
    dim: int,
    candidates: Tuple[str, ...] = ("segment", "fused"),
    iters: int = 10,
    persist: bool = True,
    interpret: Optional[bool] = None,
) -> str:
    """Decide one bucket: cached decision if present (deterministic, no
    timing), else measure the candidates, cache and persist the winner.
    Emits the decision as an ``agg_choice`` event either way."""
    sig = bucket_signature(model_key, num_nodes, num_edges, dim)
    forced = env_force()
    if forced is not None:
        emit_choice(sig, forced, "env")
        return forced
    rec = cached_choice(sig)
    if rec is not None:
        emit_choice(sig, rec["choice"], "cache", rec.get("timings_ms"))
        return rec["choice"]
    if interpret is None:
        try:
            on_tpu = jax.default_backend() == "tpu"
        except Exception:
            on_tpu = False
        if not on_tpu:
            # off-TPU the fused probe runs the Pallas INTERPRETER — its
            # timing says nothing about the compiled kernel, and letting
            # emulation win a noisy microbench would flip real runs onto
            # it. Time it only where it compiles natively (or when the
            # caller explicitly asks for interpreter mode, as the CI
            # smoke does to exercise the machinery).
            candidates = tuple(c for c in candidates if c != "fused")
            if not candidates:
                candidates = ("segment",)
    timings = measure_candidates(
        num_nodes, num_edges, dim, candidates, iters=iters,
        interpret=interpret,
    )
    if not timings:
        choice = "segment"  # every probe failed: safest fallback
    else:
        choice = min(timings, key=timings.get)
    record_choice(sig, choice, timings, persist=persist)
    emit_choice(sig, choice, "measured", timings)
    return choice


def maybe_autotune(model, example_batch, training_config: dict) -> Optional[str]:
    """Trainer warmup hook: autotune the example batch's bucket when
    enabled (``HYDRAGNN_AUTOTUNE=1`` or ``Training.autotune_aggregation``)
    — BEFORE the step programs trace, so the models' trace-time
    :func:`use_fused` reads a warm cache. No-op for dense-layout batches
    (the loader already committed to neighbor lists) and partitioned runs
    (per-shard lists are the partitioner's business)."""
    env = os.getenv("HYDRAGNN_AUTOTUNE")
    enabled = (
        env.strip().lower() not in ("", "0", "false", "no", "off")
        if env is not None
        else bool(training_config.get("autotune_aggregation", False))
    )
    if not enabled:
        return None
    extras = getattr(example_batch, "extras", None) or {}
    if "nbr_idx" in extras or getattr(model, "partition_axis", None):
        return None
    try:
        num_nodes = int(example_batch.x.shape[-2])
        num_edges = int(example_batch.senders.shape[-1])
    except Exception:
        return None
    dim = int(getattr(model, "hidden_dim", 0) or example_batch.x.shape[-1])
    # all three candidates: a record that never timed dense says nothing
    # about the layout decision (cached_model_choice skips it), so the
    # warmup measures the complete family — this is the one place a
    # measured "dense" win can enter the cache and steer the next
    # layout build
    return autotune_bucket(
        model_key_for(model), num_nodes, num_edges, dim,
        candidates=("segment", "dense", "fused"),
    )
