"""Dense neighbor-list aggregation — scatter-free message passing.

XLA's scatter on TPU is the hot cost of segment-reduction message passing
at MXU-scale widths (measured on v5e: a single packed segment scatter at
E=70k, D=513 costs ~3-6 ms while the step's matmuls cost ~1 ms — the
whole PNA train step is scatter-bound). This module removes scatters from
BOTH directions of the conv:

- forward: neighbors are materialized host-side as fixed-width per-receiver
  lists (``nbr_idx [N, K]`` + mask), so every aggregation (sum/mean/min/
  max/std) is a masked reduction over the K axis — pure vectorized VPU
  work, no scatter;
- backward: the VJP of the neighbor gather is normally a scatter-add; we
  give it a custom VJP that reads the cotangent through the REVERSE
  neighbor list (sender-side slots, also precomputed host-side), so the
  backward pass is a gather + masked reduction too.

Numerics are identical to the segment path (same masking, same empty-
segment fill); see ``tests/test_dense_agg.py`` for the parity proof.
The lists live in ``batch.extras`` and are built by the loader when the
architecture opts in (``dense_aggregation: true``).
"""

from typing import Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp

_BIG = 1e9


def max_degree(senders, receivers, edge_mask=None) -> Tuple[int, int]:
    """(max in-degree, max out-degree) over REAL edges — the K widths a
    layout needs for dense lists."""
    senders = np.asarray(senders)
    receivers = np.asarray(receivers)
    if edge_mask is not None:
        senders = senders[np.asarray(edge_mask)]
        receivers = receivers[np.asarray(edge_mask)]
    if senders.size == 0:
        return 1, 1
    k_in = int(np.bincount(receivers).max())
    k_out = int(np.bincount(senders).max())
    return max(k_in, 1), max(k_out, 1)


def build_neighbor_lists(
    senders: np.ndarray,
    receivers: np.ndarray,
    edge_mask: Optional[np.ndarray],
    num_nodes: int,
    k_in: int,
    k_out: int,
):
    """Host-side (numpy) conversion of an edge list into dense lists.

    Returns extras dict:
      ``nbr_idx   [N, K_in]``  sender node of each incoming-edge slot
      ``nbr_edge  [N, K_in]``  edge-list row of that slot (for edge_attr)
      ``nbr_mask  [N, K_in]``  slot validity
      ``rev_idx   [N, K_out]`` flat (receiver*K_in + slot) position of each
                               outgoing edge — the backward-gather index
      ``rev_mask  [N, K_out]``
    Real edges only (``edge_mask`` False rows are padding and excluded).
    Built on :func:`build_group_lists` (one slot-assignment implementation
    for every single-owner grouping).
    """
    senders = np.asarray(senders, np.int64)
    # incoming lists: edges grouped by receiver; sender per slot
    nbr_edge, nbr_mask = build_group_lists(
        receivers, edge_mask, num_nodes, k_in, label="k_in"
    )
    nbr_idx = np.where(nbr_mask, senders[nbr_edge], 0).astype(np.int32)
    # flat [N*K_in] dense slot of every edge row
    flat_of_edge = np.zeros(senders.shape[0], np.int64)
    rr, ss = np.nonzero(nbr_mask)
    flat_of_edge[nbr_edge[rr, ss]] = rr * k_in + ss
    # reverse lists: edges grouped by sender; flat slot per entry
    out_edge, rev_mask = build_group_lists(
        senders, edge_mask, num_nodes, k_out, label="k_out"
    )
    rev_idx = np.where(rev_mask, flat_of_edge[out_edge], 0).astype(np.int32)
    return {
        "nbr_idx": nbr_idx,
        "nbr_edge": nbr_edge,
        "nbr_mask": nbr_mask,
        "rev_idx": rev_idx,
        "rev_mask": rev_mask,
    }


@jax.custom_vjp
def gather_neighbors(x, nbr_idx, rev_idx, rev_mask):
    """``x[nbr_idx]`` ([N, D] -> [N, K, D]) whose backward pass is a
    gather through the reverse list instead of a scatter-add."""
    return x[nbr_idx]


def _gather_fwd(x, nbr_idx, rev_idx, rev_mask):
    return x[nbr_idx], (x.shape, nbr_idx.shape, rev_idx, rev_mask)


def _gather_bwd(res, g):
    (n, d), (_, k_in), rev_idx, rev_mask = res
    flat = g.reshape(n * k_in, d)
    contrib = flat[rev_idx]  # [N, K_out, D]
    gx = jnp.where(rev_mask[..., None], contrib, 0.0).sum(axis=1)
    return gx, None, None, None


gather_neighbors.defvjp(_gather_fwd, _gather_bwd)


@jax.custom_vjp
def group_sum(values, lists, lists_mask, owner_ids, valid):
    """Generic scatter-free segment sum for SINGLE-OWNER groupings.

    ``values [T, D]`` where every valid row belongs to exactly one group
    (``owner_ids [T]``, ``valid [T]`` row validity); ``lists [G, K]``
    enumerates each group's member rows with ``lists_mask`` validity.
    Forward is a gather + masked K-axis sum (= ``segment_sum(values,
    owner_ids, G)`` over valid rows, without the scatter); backward is the
    exact dual — a gather ``g[owner_ids]`` masked by ``valid`` (padded
    rows share owner slot 0, so an unmasked backward would corrupt real
    rows' gradients). Covers DimeNet's triplet->edge and edge->node
    aggregations (and any other one-owner grouping) with precomputed
    host-side lists.
    """
    member = values[lists]  # [G, K, D]
    return jnp.where(lists_mask[..., None], member, 0.0).sum(axis=1)


def _group_sum_fwd(values, lists, lists_mask, owner_ids, valid):
    return group_sum(values, lists, lists_mask, owner_ids, valid), (
        owner_ids,
        valid,
    )


def _group_sum_bwd(res, g):
    owner_ids, valid = res
    gv = jnp.where(valid[:, None], g[owner_ids], 0.0)
    return gv, None, None, None, None


group_sum.defvjp(_group_sum_fwd, _group_sum_bwd)


def build_group_lists(
    owner_ids, valid_mask, num_groups: int, k: int, label: str = "k"
):
    """Host-side (numpy): invert a single-owner mapping into fixed-width
    member lists. Returns (lists [G, k] int32, mask [G, k] bool).
    ``label`` names the budget in overflow errors (k_in/k_out/kt)."""
    owner_ids = np.asarray(owner_ids, np.int64)
    rows = np.arange(owner_ids.shape[0])
    if valid_mask is not None:
        keep = np.asarray(valid_mask, bool)
        owner_ids, rows = owner_ids[keep], rows[keep]
    lists = np.zeros((num_groups, k), np.int32)
    mask = np.zeros((num_groups, k), bool)
    order = np.argsort(owner_ids, kind="stable")
    o_sorted = owner_ids[order]
    slot = np.arange(o_sorted.shape[0]) - np.searchsorted(
        o_sorted, o_sorted, side="left"
    )
    if o_sorted.size and np.any(slot >= k):
        raise ValueError(
            f"group size exceeds layout {label}={k}; recompute the layout"
        )
    lists[o_sorted, slot] = rows[order]
    mask[o_sorted, slot] = True
    return lists, mask


@jax.custom_vjp
def aggregate_to_senders(h, nbr_idx, nbr_mask, rev_idx, rev_mask):
    """Sum dense per-edge values ``h [N, K_in, D]`` (keyed by receiver x
    slot) onto their SENDER nodes -> ``[N, D]``, scatter-free.

    Forward reads each sender's outgoing slots through the reverse list;
    backward is the exact dual — a gather through the forward list:
    ``grad_h[r, k] = g_out[nbr_idx[r, k]]`` — so EGNN/SchNet-style
    sender-side aggregations stay scatter-free in both directions too.
    """
    n, k_in, d = h.shape
    flat = h.reshape(n * k_in, d)
    contrib = flat[rev_idx]  # [N, K_out, D]
    return jnp.where(rev_mask[..., None], contrib, 0.0).sum(axis=1)


def _agg_send_fwd(h, nbr_idx, nbr_mask, rev_idx, rev_mask):
    return (
        aggregate_to_senders(h, nbr_idx, nbr_mask, rev_idx, rev_mask),
        (nbr_idx, nbr_mask),
    )


def _agg_send_bwd(res, g):
    nbr_idx, nbr_mask = res
    gh = g[nbr_idx]  # [N, K_in, D]
    gh = jnp.where(nbr_mask[..., None], gh, 0.0)
    return gh, None, None, None, None


aggregate_to_senders.defvjp(_agg_send_fwd, _agg_send_bwd)


def dense_moments(h, nbr_mask):
    """(mean, std, deg, has) over the K axis of masked messages
    ``h [N, K, D]`` — PNA's count/mean/std statistics without a scatter.
    Matches segment_moments semantics: empty receivers -> mean/std of 0."""
    m = nbr_mask[..., None]
    hm = jnp.where(m, h, 0.0)
    cnt = nbr_mask.sum(axis=1).astype(h.dtype)[:, None]
    has = cnt > 0
    deg = jnp.maximum(cnt, 1.0)
    mean = hm.sum(axis=1) / deg
    sq = (hm * hm).sum(axis=1) / deg
    std = jnp.sqrt(jnp.maximum(sq - mean * mean, 0.0) + 1e-5)
    return mean, std, deg, has


def dense_minmax(h, nbr_mask, has, fill=0.0):
    """(min, max) over the K axis; empty receivers -> ``fill`` (segment
    fill semantics so padded nodes stay finite)."""
    m = nbr_mask[..., None]
    mx = jnp.where(m, h, -_BIG).max(axis=1)
    mn = jnp.where(m, h, _BIG).min(axis=1)
    mx = jnp.where(has, mx, fill)
    mn = jnp.where(has, mn, fill)
    return mn, mx


def dense_sum(h, nbr_mask):
    return jnp.where(nbr_mask[..., None], h, 0.0).sum(axis=1)


def attach_neighbor_lists(batch):
    """Batch -> batch with dense-list extras attached (the one canonical
    attach operation; the loader, benches and tests all route through
    here). Host-side; keys match what the conv's dense path reads."""
    k_in, k_out = max_degree(batch.senders, batch.receivers, batch.edge_mask)
    extras = build_neighbor_lists(
        np.asarray(batch.senders),
        np.asarray(batch.receivers),
        np.asarray(batch.edge_mask),
        int(batch.x.shape[-2]),
        k_in,
        k_out,
    )
    merged = dict(batch.extras or {})
    merged.update({k: jnp.asarray(v) for k, v in extras.items()})
    if "trip_ji" in merged:
        # DimeNet batches: per-edge incoming-triplet member lists too
        tji = np.asarray(merged["trip_ji"])
        tmask = np.asarray(merged["trip_mask"])
        kt = (
            int(np.bincount(tji[tmask]).max()) if tmask.any() else 1
        )
        tl, tm = build_group_lists(
            tji, tmask, int(batch.senders.shape[-1]), kt, label="kt"
        )
        merged["tripnbr_idx"] = jnp.asarray(tl)
        merged["tripnbr_mask"] = jnp.asarray(tm)
    return batch.replace(extras=merged)
