"""Dense neighbor-list aggregation — scatter-free message passing.

XLA's scatter on TPU is the hot cost of segment-reduction message passing
at MXU-scale widths (measured on v5e: a single packed segment scatter at
E=70k, D=513 costs ~3-6 ms while the step's matmuls cost ~1 ms — the
whole PNA train step is scatter-bound). This module removes scatters from
BOTH directions of the conv:

- forward: neighbors are materialized host-side as fixed-width per-receiver
  lists (``nbr_idx [N, K]`` + mask), so every aggregation (sum/mean/min/
  max/std) is a masked reduction over the K axis — pure vectorized VPU
  work, no scatter;
- backward: the VJP of the neighbor gather is normally a scatter-add; we
  give it a custom VJP that reads the cotangent through the REVERSE
  neighbor list (sender-side slots, also precomputed host-side), so the
  backward pass is a gather + masked reduction too.

Numerics are identical to the segment path (same masking, same empty-
segment fill); see ``tests/test_dense_agg.py`` for the parity proof.
The lists live in ``batch.extras`` and are built by the loader when the
architecture opts in (``dense_aggregation: true``).
"""

from typing import Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp

_BIG = 1e9


def max_degree(senders, receivers, edge_mask=None) -> Tuple[int, int]:
    """(max in-degree, max out-degree) over REAL edges — the K widths a
    layout needs for dense lists."""
    senders = np.asarray(senders)
    receivers = np.asarray(receivers)
    if edge_mask is not None:
        senders = senders[np.asarray(edge_mask)]
        receivers = receivers[np.asarray(edge_mask)]
    if senders.size == 0:
        return 1, 1
    k_in = int(np.bincount(receivers).max())
    k_out = int(np.bincount(senders).max())
    return max(k_in, 1), max(k_out, 1)


def build_neighbor_lists(
    senders: np.ndarray,
    receivers: np.ndarray,
    edge_mask: Optional[np.ndarray],
    num_nodes: int,
    k_in: int,
    k_out: int,
    with_slot_tables: bool = False,
):
    """Host-side (numpy) conversion of an edge list into dense lists.

    Returns extras dict:
      ``nbr_idx   [N, K_in]``  sender node of each incoming-edge slot
      ``nbr_edge  [N, K_in]``  edge-list row of that slot (for edge_attr)
      ``nbr_mask  [N, K_in]``  slot validity
      ``rev_idx   [N, K_out]`` flat (receiver*K_in + slot) position of each
                               outgoing edge — the backward-gather index
      ``rev_mask  [N, K_out]``
    ``with_slot_tables`` (DimeNet's bmm-triplet path only — they are wire
    overhead for every other model) adds:
      ``out_edge  [N, K_out]`` edge-list row of each outgoing-edge slot
      ``edge_slot [E]``        flat (receiver*K_in + slot) of each edge
      ``out_slot  [E]``        flat (sender*K_out + slot) of each edge
    (the out-slot validity mask is ``rev_mask`` — same grouping).
    Real edges only (``edge_mask`` False rows are padding and excluded).
    Built on :func:`build_group_lists` (one slot-assignment implementation
    for every single-owner grouping).
    """
    senders = np.asarray(senders, np.int64)
    # incoming lists: edges grouped by receiver; sender per slot
    nbr_edge, nbr_mask = build_group_lists(
        receivers, edge_mask, num_nodes, k_in, label="k_in"
    )
    nbr_idx = np.where(nbr_mask, senders[nbr_edge], 0).astype(np.int32)
    # flat [N*K_in] dense slot of every edge row
    flat_of_edge = np.zeros(senders.shape[0], np.int64)
    rr, ss = np.nonzero(nbr_mask)
    flat_of_edge[nbr_edge[rr, ss]] = rr * k_in + ss
    # reverse lists: edges grouped by sender; flat slot per entry
    out_edge, rev_mask = build_group_lists(
        senders, edge_mask, num_nodes, k_out, label="k_out"
    )
    rev_idx = np.where(rev_mask, flat_of_edge[out_edge], 0).astype(np.int32)
    out = {
        "nbr_idx": nbr_idx,
        "nbr_edge": nbr_edge,
        "nbr_mask": nbr_mask,
        "rev_idx": rev_idx,
        "rev_mask": rev_mask,
    }
    if with_slot_tables:
        # inverse permutation of out_edge — the bmm-triplet path routes
        # per-(sender, out-slot) results back onto the edge table with it
        slot_out_of_edge = np.zeros(senders.shape[0], np.int64)
        rr, ss = np.nonzero(rev_mask)
        slot_out_of_edge[out_edge[rr, ss]] = rr * k_out + ss
        out.update(
            out_edge=out_edge,
            edge_slot=flat_of_edge.astype(np.int32),
            out_slot=slot_out_of_edge.astype(np.int32),
        )
    return out


@jax.custom_vjp
def gather_neighbors(x, nbr_idx, rev_idx, rev_mask):
    """``x[nbr_idx]`` ([N, D] -> [N, K, D]) whose backward pass is a
    gather through the reverse list instead of a scatter-add."""
    # host-built lists: padded slots hold index 0 (always in range);
    # every consumer masks the gathered rows with nbr_mask before
    # accumulating, so the raw gather is the masking contract's input
    # numlint: disable=unmasked-gather-id
    return x[nbr_idx]


def _gather_fwd(x, nbr_idx, rev_idx, rev_mask):
    # numlint: disable=unmasked-gather-id — mirrors the primal above
    return x[nbr_idx], (x.shape, nbr_idx.shape, rev_idx, rev_mask)


def _gather_bwd(res, g):
    (n, d), (_, k_in), rev_idx, rev_mask = res
    flat = g.reshape(n * k_in, d)
    contrib = flat[rev_idx]  # [N, K_out, D]
    # K_out-axis accumulation in f32 (a bf16 cotangent would otherwise
    # sum at bf16); the upcast is a no-op on the f32 path
    gm = jnp.where(rev_mask[..., None], contrib, 0.0).astype(jnp.float32)
    gx = gm.sum(axis=1).astype(g.dtype)
    return gx, None, None, None


gather_neighbors.defvjp(_gather_fwd, _gather_bwd)


@jax.custom_vjp
def group_sum(values, lists, lists_mask, owner_ids, valid):
    """Generic scatter-free segment sum for SINGLE-OWNER groupings.

    ``values [T, D]`` where every valid row belongs to exactly one group
    (``owner_ids [T]``, ``valid [T]`` row validity); ``lists [G, K]``
    enumerates each group's member rows with ``lists_mask`` validity.
    Forward is a gather + masked K-axis sum (= ``segment_sum(values,
    owner_ids, G)`` over valid rows, without the scatter); backward is the
    exact dual — a gather ``g[owner_ids]`` masked by ``valid`` (padded
    rows share owner slot 0, so an unmasked backward would corrupt real
    rows' gradients). Covers DimeNet's triplet->edge and edge->node
    aggregations (and any other one-owner grouping) with precomputed
    host-side lists.
    """
    member = values[lists]  # [G, K, D]
    # masked K-axis sum accumulates in f32, result back at the input
    # dtype (PNA fused-stats convention; no-op on the f32 path)
    hm = jnp.where(lists_mask[..., None], member, 0.0).astype(jnp.float32)
    return hm.sum(axis=1).astype(values.dtype)


def _group_sum_fwd(values, lists, lists_mask, owner_ids, valid):
    return group_sum(values, lists, lists_mask, owner_ids, valid), (
        owner_ids,
        valid,
    )


def _group_sum_bwd(res, g):
    owner_ids, valid = res
    gv = jnp.where(valid[:, None], g[owner_ids], 0.0)
    return gv, None, None, None, None


group_sum.defvjp(_group_sum_fwd, _group_sum_bwd)


@jax.custom_vjp
def gather_rows_to_slots(table, lists, lists_mask, slot_of_row, row_valid):
    """``table[lists]`` ([R, D] -> [G, K, D]) for a SINGLE-OWNER grouping
    (every valid table row appears in exactly one list slot). Backward is
    the inverse permutation ``g.reshape(G*K, D)[slot_of_row]`` — a pure
    gather, no scatter-add in either direction."""
    return jnp.where(lists_mask[..., None], table[lists], 0.0)


def _grs_fwd(table, lists, lists_mask, slot_of_row, row_valid):
    return (
        gather_rows_to_slots(table, lists, lists_mask, slot_of_row, row_valid),
        (table.shape, lists.shape, slot_of_row, row_valid),
    )


def _grs_bwd(res, g):
    (r, d), (grp, k), slot_of_row, row_valid = res
    gt = g.reshape(grp * k, d)[slot_of_row]
    return jnp.where(row_valid[:, None], gt, 0.0), None, None, None, None


gather_rows_to_slots.defvjp(_grs_fwd, _grs_bwd)


@jax.custom_vjp
def slots_to_rows(slots, slot_of_row, row_valid, lists, lists_mask):
    """Inverse of :func:`gather_rows_to_slots`: route per-slot values
    ``slots [G, K, D]`` back onto their owning rows -> ``[R, D]``.
    Backward gathers the row cotangent through ``lists`` — the exact dual,
    scatter-free both directions."""
    g, k, d = slots.shape
    out = slots.reshape(g * k, d)[slot_of_row]
    return jnp.where(row_valid[:, None], out, 0.0)


def _str_fwd(slots, slot_of_row, row_valid, lists, lists_mask):
    return (
        slots_to_rows(slots, slot_of_row, row_valid, lists, lists_mask),
        (lists, lists_mask),
    )


def _str_bwd(res, g):
    lists, lists_mask = res
    gs = jnp.where(lists_mask[..., None], g[lists], 0.0)
    return gs, None, None, None, None


slots_to_rows.defvjp(_str_fwd, _str_bwd)


def build_group_lists(
    owner_ids, valid_mask, num_groups: int, k: int, label: str = "k"
):
    """Host-side (numpy): invert a single-owner mapping into fixed-width
    member lists. Returns (lists [G, k] int32, mask [G, k] bool).
    ``label`` names the budget in overflow errors (k_in/k_out/kt)."""
    owner_ids = np.asarray(owner_ids, np.int64)
    rows = np.arange(owner_ids.shape[0])
    if valid_mask is not None:
        keep = np.asarray(valid_mask, bool)
        owner_ids, rows = owner_ids[keep], rows[keep]
    lists = np.zeros((num_groups, k), np.int32)
    mask = np.zeros((num_groups, k), bool)
    order = np.argsort(owner_ids, kind="stable")
    o_sorted = owner_ids[order]
    slot = np.arange(o_sorted.shape[0]) - np.searchsorted(
        o_sorted, o_sorted, side="left"
    )
    if o_sorted.size and np.any(slot >= k):
        raise ValueError(
            f"group size exceeds layout {label}={k}; recompute the layout"
        )
    lists[o_sorted, slot] = rows[order]
    mask[o_sorted, slot] = True
    return lists, mask


@jax.custom_vjp
def aggregate_to_senders(h, nbr_idx, nbr_mask, rev_idx, rev_mask):
    """Sum dense per-edge values ``h [N, K_in, D]`` (keyed by receiver x
    slot) onto their SENDER nodes -> ``[N, D]``, scatter-free.

    Forward reads each sender's outgoing slots through the reverse list;
    backward is the exact dual — a gather through the forward list:
    ``grad_h[r, k] = g_out[nbr_idx[r, k]]`` — so EGNN/SchNet-style
    sender-side aggregations stay scatter-free in both directions too.
    """
    n, k_in, d = h.shape
    flat = h.reshape(n * k_in, d)
    contrib = flat[rev_idx]  # [N, K_out, D]
    # masked K_out-axis sum accumulates in f32 (bf16 dense path), cast
    # back to the message dtype — no-op when h is already f32
    hm = jnp.where(rev_mask[..., None], contrib, 0.0).astype(jnp.float32)
    return hm.sum(axis=1).astype(h.dtype)


def _agg_send_fwd(h, nbr_idx, nbr_mask, rev_idx, rev_mask):
    return (
        aggregate_to_senders(h, nbr_idx, nbr_mask, rev_idx, rev_mask),
        (nbr_idx, nbr_mask),
    )


def _agg_send_bwd(res, g):
    nbr_idx, nbr_mask = res
    gh = g[nbr_idx]  # [N, K_in, D]
    gh = jnp.where(nbr_mask[..., None], gh, 0.0)
    return gh, None, None, None, None


aggregate_to_senders.defvjp(_agg_send_fwd, _agg_send_bwd)


def dense_moments(h, nbr_mask):
    """(mean, std, deg, has) over the K axis of masked messages
    ``h [N, K, D]`` — PNA's count/mean/std statistics without a scatter.
    Matches segment_moments semantics: empty receivers -> mean/std of 0."""
    m = nbr_mask[..., None]
    # statistics accumulate in f32 regardless of the message dtype and
    # come back at h.dtype — the dense twin of the fused-kernel f32
    # stats path (models/pna.py casts the same way)
    hm = jnp.where(m, h, 0.0).astype(jnp.float32)
    cnt = nbr_mask.sum(axis=1).astype(jnp.float32)[:, None]
    has = cnt > 0
    deg = jnp.maximum(cnt, 1.0)
    mean = hm.sum(axis=1) / deg
    sq = (hm * hm).sum(axis=1) / deg
    std = jnp.sqrt(jnp.maximum(sq - mean * mean, 0.0) + 1e-5)
    return (
        mean.astype(h.dtype), std.astype(h.dtype),
        deg.astype(h.dtype), has,
    )


def dense_minmax(h, nbr_mask, has, fill=0.0):
    """(min, max) over the K axis; empty receivers -> ``fill`` (segment
    fill semantics so padded nodes stay finite)."""
    m = nbr_mask[..., None]
    mx = jnp.where(m, h, -_BIG).max(axis=1)
    mn = jnp.where(m, h, _BIG).min(axis=1)
    mx = jnp.where(has, mx, fill)
    mn = jnp.where(has, mn, fill)
    return mn, mx


def dense_sum(h, nbr_mask):
    # masked K-axis sum in f32, result at the message dtype (no-op for
    # f32 inputs; the guard the bf16 dense path needs)
    hm = jnp.where(nbr_mask[..., None], h, 0.0).astype(jnp.float32)
    return hm.sum(axis=1).astype(h.dtype)


def attach_neighbor_lists(batch):
    """Batch -> batch with dense-list extras attached (the one canonical
    attach operation; the loader, benches and tests all route through
    here). Host-side; keys match what the conv's dense path reads."""
    k_in, k_out = max_degree(batch.senders, batch.receivers, batch.edge_mask)
    extras = build_neighbor_lists(
        np.asarray(batch.senders),
        np.asarray(batch.receivers),
        np.asarray(batch.edge_mask),
        int(batch.x.shape[-2]),
        k_in,
        k_out,
        # DimeNet batches (triplet extras present) get the bmm-path slot
        # tables; other models never read them
        with_slot_tables="trip_ji" in (batch.extras or {}),
    )
    merged = dict(batch.extras or {})
    merged.update({k: jnp.asarray(v) for k, v in extras.items()})
    return batch.replace(extras=merged)
