"""Dense neighbor-list aggregation — scatter-free message passing.

XLA's scatter on TPU is the hot cost of segment-reduction message passing
at MXU-scale widths (measured on v5e: a single packed segment scatter at
E=70k, D=513 costs ~3-6 ms while the step's matmuls cost ~1 ms — the
whole PNA train step is scatter-bound). This module removes scatters from
BOTH directions of the conv:

- forward: neighbors are materialized host-side as fixed-width per-receiver
  lists (``nbr_idx [N, K]`` + mask), so every aggregation (sum/mean/min/
  max/std) is a masked reduction over the K axis — pure vectorized VPU
  work, no scatter;
- backward: the VJP of the neighbor gather is normally a scatter-add; we
  give it a custom VJP that reads the cotangent through the REVERSE
  neighbor list (sender-side slots, also precomputed host-side), so the
  backward pass is a gather + masked reduction too.

Numerics are identical to the segment path (same masking, same empty-
segment fill); see ``tests/test_dense_agg.py`` for the parity proof.
The lists live in ``batch.extras`` and are built by the loader when the
architecture opts in (``dense_aggregation: true``).
"""

from typing import Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp

_BIG = 1e9


def max_degree(senders, receivers, edge_mask=None) -> Tuple[int, int]:
    """(max in-degree, max out-degree) over REAL edges — the K widths a
    layout needs for dense lists."""
    senders = np.asarray(senders)
    receivers = np.asarray(receivers)
    if edge_mask is not None:
        senders = senders[np.asarray(edge_mask)]
        receivers = receivers[np.asarray(edge_mask)]
    if senders.size == 0:
        return 1, 1
    k_in = int(np.bincount(receivers).max())
    k_out = int(np.bincount(senders).max())
    return max(k_in, 1), max(k_out, 1)


def build_neighbor_lists(
    senders: np.ndarray,
    receivers: np.ndarray,
    edge_mask: Optional[np.ndarray],
    num_nodes: int,
    k_in: int,
    k_out: int,
):
    """Host-side (numpy) conversion of an edge list into dense lists.

    Returns extras dict:
      ``nbr_idx   [N, K_in]``  sender node of each incoming-edge slot
      ``nbr_edge  [N, K_in]``  edge-list row of that slot (for edge_attr)
      ``nbr_mask  [N, K_in]``  slot validity
      ``rev_idx   [N, K_out]`` flat (receiver*K_in + slot) position of each
                               outgoing edge — the backward-gather index
      ``rev_mask  [N, K_out]``
    Real edges only (``edge_mask`` False rows are padding and excluded).
    """
    senders = np.asarray(senders, np.int64)
    receivers = np.asarray(receivers, np.int64)
    rows = np.arange(senders.shape[0])
    if edge_mask is not None:
        keep = np.asarray(edge_mask, bool)
        senders, receivers, rows = senders[keep], receivers[keep], rows[keep]

    nbr_idx = np.zeros((num_nodes, k_in), np.int32)
    nbr_edge = np.zeros((num_nodes, k_in), np.int32)
    nbr_mask = np.zeros((num_nodes, k_in), bool)
    rev_idx = np.zeros((num_nodes, k_out), np.int32)
    rev_mask = np.zeros((num_nodes, k_out), bool)

    # stable order by receiver: slot = running index within the receiver
    order = np.argsort(receivers, kind="stable")
    r_sorted = receivers[order]
    slot_in = np.arange(r_sorted.shape[0]) - np.searchsorted(
        r_sorted, r_sorted, side="left"
    )
    if np.any(slot_in >= k_in):
        raise ValueError(
            f"in-degree exceeds layout k_in={k_in}; recompute the layout"
        )
    nbr_idx[r_sorted, slot_in] = senders[order]
    nbr_edge[r_sorted, slot_in] = rows[order]
    nbr_mask[r_sorted, slot_in] = True

    # reverse: for each sender, the flat [N*K_in] slot its edge landed in
    flat = (r_sorted * k_in + slot_in).astype(np.int64)
    s_sorted_order = np.argsort(senders[order], kind="stable")
    s_sorted = senders[order][s_sorted_order]
    slot_out = np.arange(s_sorted.shape[0]) - np.searchsorted(
        s_sorted, s_sorted, side="left"
    )
    if np.any(slot_out >= k_out):
        raise ValueError(
            f"out-degree exceeds layout k_out={k_out}; recompute the layout"
        )
    rev_idx[s_sorted, slot_out] = flat[s_sorted_order].astype(np.int32)
    rev_mask[s_sorted, slot_out] = True

    return {
        "nbr_idx": nbr_idx,
        "nbr_edge": nbr_edge,
        "nbr_mask": nbr_mask,
        "rev_idx": rev_idx,
        "rev_mask": rev_mask,
    }


@jax.custom_vjp
def gather_neighbors(x, nbr_idx, rev_idx, rev_mask):
    """``x[nbr_idx]`` ([N, D] -> [N, K, D]) whose backward pass is a
    gather through the reverse list instead of a scatter-add."""
    return x[nbr_idx]


def _gather_fwd(x, nbr_idx, rev_idx, rev_mask):
    return x[nbr_idx], (x.shape, nbr_idx.shape, rev_idx, rev_mask)


def _gather_bwd(res, g):
    (n, d), (_, k_in), rev_idx, rev_mask = res
    flat = g.reshape(n * k_in, d)
    contrib = flat[rev_idx]  # [N, K_out, D]
    gx = jnp.where(rev_mask[..., None], contrib, 0.0).sum(axis=1)
    return gx, None, None, None


gather_neighbors.defvjp(_gather_fwd, _gather_bwd)


@jax.custom_vjp
def aggregate_to_senders(h, nbr_idx, nbr_mask, rev_idx, rev_mask):
    """Sum dense per-edge values ``h [N, K_in, D]`` (keyed by receiver x
    slot) onto their SENDER nodes -> ``[N, D]``, scatter-free.

    Forward reads each sender's outgoing slots through the reverse list;
    backward is the exact dual — a gather through the forward list:
    ``grad_h[r, k] = g_out[nbr_idx[r, k]]`` — so EGNN/SchNet-style
    sender-side aggregations stay scatter-free in both directions too.
    """
    n, k_in, d = h.shape
    flat = h.reshape(n * k_in, d)
    contrib = flat[rev_idx]  # [N, K_out, D]
    return jnp.where(rev_mask[..., None], contrib, 0.0).sum(axis=1)


def _agg_send_fwd(h, nbr_idx, nbr_mask, rev_idx, rev_mask):
    return (
        aggregate_to_senders(h, nbr_idx, nbr_mask, rev_idx, rev_mask),
        (nbr_idx, nbr_mask),
    )


def _agg_send_bwd(res, g):
    nbr_idx, nbr_mask = res
    gh = g[nbr_idx]  # [N, K_in, D]
    gh = jnp.where(nbr_mask[..., None], gh, 0.0)
    return gh, None, None, None, None


aggregate_to_senders.defvjp(_agg_send_fwd, _agg_send_bwd)


def dense_moments(h, nbr_mask):
    """(mean, std, deg, has) over the K axis of masked messages
    ``h [N, K, D]`` — PNA's count/mean/std statistics without a scatter.
    Matches segment_moments semantics: empty receivers -> mean/std of 0."""
    m = nbr_mask[..., None]
    hm = jnp.where(m, h, 0.0)
    cnt = nbr_mask.sum(axis=1).astype(h.dtype)[:, None]
    has = cnt > 0
    deg = jnp.maximum(cnt, 1.0)
    mean = hm.sum(axis=1) / deg
    sq = (hm * hm).sum(axis=1) / deg
    std = jnp.sqrt(jnp.maximum(sq - mean * mean, 0.0) + 1e-5)
    return mean, std, deg, has


def dense_minmax(h, nbr_mask, has, fill=0.0):
    """(min, max) over the K axis; empty receivers -> ``fill`` (segment
    fill semantics so padded nodes stay finite)."""
    m = nbr_mask[..., None]
    mx = jnp.where(m, h, -_BIG).max(axis=1)
    mn = jnp.where(m, h, _BIG).min(axis=1)
    mx = jnp.where(has, mx, fill)
    mn = jnp.where(has, mn, fill)
    return mn, mx


def dense_sum(h, nbr_mask):
    return jnp.where(nbr_mask[..., None], h, 0.0).sum(axis=1)


def attach_neighbor_lists(batch):
    """Batch -> batch with dense-list extras attached (the one canonical
    attach operation; the loader, benches and tests all route through
    here). Host-side; keys match what the conv's dense path reads."""
    k_in, k_out = max_degree(batch.senders, batch.receivers, batch.edge_mask)
    extras = build_neighbor_lists(
        np.asarray(batch.senders),
        np.asarray(batch.receivers),
        np.asarray(batch.edge_mask),
        int(batch.x.shape[-2]),
        k_in,
        k_out,
    )
    merged = dict(batch.extras or {})
    merged.update({k: jnp.asarray(v) for k, v in extras.items()})
    return batch.replace(extras=merged)
