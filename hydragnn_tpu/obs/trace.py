"""Distributed request tracing: one causal span tree per routed request.

The serving stack spans five cooperating components (FleetRouter ->
tenant admission -> response cache -> micro-batcher -> bucket dispatch),
two of them in OTHER processes (the replicas). Aggregate metrics say
*that* a tenant's p99 blew its SLO; this module says *where* the time
went: every routed request carries a ``trace_id`` generated at
``FleetRouter.route``, propagated to the replica as an
``X-Hydragnn-Trace`` header, so retries and failovers across replicas
land in ONE trace whose spans cover
``route/admit/cache_lookup/backoff/attempt`` (router side) and
``queue_wait/batch_form/dispatch/readback`` (replica side).

Design rules, in the order they bite:

- **Stdlib only, events.jsonl native**: spans are schema-gated ``span``
  events appended to the SAME ``RunEventLog`` streams everything else
  uses — no new storage, no new daemon; ``python -m hydragnn_tpu.obs
  trace <run>`` reconstructs the trees from the merged streams.
- **Tail-based sampling**: ``HYDRAGNN_TRACE_SAMPLE`` (default 0 = off)
  arms per-request BUFFERING; the flush decision happens at the
  request's terminal outcome. Head-sampled traces (a deterministic hash
  of the trace id under the rate) always flush; SLO-missed and errored
  requests flush at ANY non-zero rate — the traces worth keeping are
  exactly the ones a head-only sampler throws away.
- **Replica spans ride the response body**: a replica process cannot
  append to the router's stream (per-file seq is single-writer), and
  tail-flushing needs every span of a request in ONE place at outcome
  time. When the header arms a request, the replica collects its spans
  in memory and returns them in the response body (success AND error
  bodies); the router merges them into the request's buffer and owns
  the flush. One trace, complete tree, any outcome.
- **Zero cost when off**: with ``HYDRAGNN_TRACE_SAMPLE=0`` (or no emit
  sink) ``Tracer.start`` returns ``None``, no header is sent, replicas
  record nothing — the hot path pays one ``is None`` check.
"""

import json
import os
import threading
import time
from typing import Callable, Dict, List, Optional

from hydragnn_tpu.obs.metrics import MetricsRegistry
from hydragnn_tpu.utils.envparse import env_float

TRACE_HEADER = "X-Hydragnn-Trace"

# span names recorded by each side — the CLI's anatomy table and the
# docs catalog mirror this split
ROUTER_SPANS = ("route", "admit", "cache_lookup", "backoff", "attempt")
REPLICA_SPANS = ("queue_wait", "batch_form", "dispatch", "readback")
# container spans hold other spans; segment accounting uses their
# EXCLUSIVE time (container minus children) so segments sum to the root
CONTAINER_SPANS = ("route", "attempt")


def new_id(nbytes: int = 8) -> str:
    """Random lowercase-hex id (16 chars for traces, 8 for spans)."""
    return os.urandom(nbytes).hex()


def head_sampled(trace_id: str, rate: float) -> bool:
    """Deterministic head-sampling decision from the trace id alone —
    every component that sees the id agrees without coordination."""
    if rate <= 0.0:
        return False
    if rate >= 1.0:
        return True
    try:
        return int(trace_id[:8], 16) / float(0xFFFFFFFF) < rate
    except ValueError:
        return False


def encode_header(trace_id: str, parent_span: str) -> str:
    """``X-Hydragnn-Trace`` value: ``<trace_id>-<parent_span>-01``
    (W3C-traceparent-shaped; the trailing flags byte says "armed")."""
    return f"{trace_id}-{parent_span}-01"


def decode_header(value: Optional[str]):
    """``(trace_id, parent_span)`` or None for absent/malformed values —
    a garbled header must disarm tracing, never fail the request."""
    if not value:
        return None
    parts = value.strip().split("-")
    if len(parts) < 2 or not parts[0] or not parts[1]:
        return None
    return parts[0], parts[1]


class TraceContext:
    """Replica-side span collector for ONE armed request.

    Created from the propagated header; ``export()`` returns the
    JSON-able spans the response body carries back to the router (the
    single writer of the trace's event stream). Thread-safe: the batcher
    thread records while the handler thread exports."""

    __slots__ = ("trace_id", "parent_id", "_lock", "_spans")

    def __init__(self, trace_id: str, parent_id: str):
        self.trace_id = trace_id
        self.parent_id = parent_id
        self._lock = threading.Lock()
        self._spans: List[Dict] = []

    @classmethod
    def from_header(cls, value: Optional[str]) -> Optional["TraceContext"]:
        decoded = decode_header(value)
        if decoded is None:
            return None
        return cls(*decoded)

    def record(self, name: str, start: float, dur_s: float,
               parent: Optional[str] = None, **attrs) -> str:
        span_id = new_id()
        span = {
            "trace": self.trace_id,
            "span": span_id,
            # None defaults to the propagated parent; "" is an explicit
            # root marker and must survive
            "parent": self.parent_id if parent is None else parent,
            "name": name,
            "start": round(float(start), 6),
            "dur_s": round(max(float(dur_s), 0.0), 9),
            "attrs": attrs,
        }
        with self._lock:
            self._spans.append(span)
        return span_id

    def export(self) -> List[Dict]:
        with self._lock:
            return list(self._spans)


class RequestTrace:
    """Router-side per-request span buffer (the tail-sampling unit).

    Spans accumulate here — recorded locally or merged from replica
    response bodies — until :meth:`finish` decides the flush: head
    sample says yes, OR the request missed its SLO, OR it errored."""

    def __init__(self, tracer: "Tracer", trace_id: str, sampled: bool,
                 **attrs):
        self.tracer = tracer
        self.trace_id = trace_id
        self.sampled = sampled
        self.root_id = new_id()
        self.attrs = dict(attrs)
        self._lock = threading.Lock()
        self._spans: List[Dict] = []
        self._start_wall = time.time()
        self._start_mono = time.monotonic()
        self._finished = False

    # ---- recording -----------------------------------------------------
    def record(self, name: str, start: float, dur_s: float,
               parent: Optional[str] = None,
               span_id: Optional[str] = None, **attrs) -> str:
        span_id = span_id or new_id()
        span = {
            "trace": self.trace_id,
            "span": span_id,
            "parent": self.root_id if parent is None else parent,
            "name": name,
            "start": round(float(start), 6),
            "dur_s": round(max(float(dur_s), 0.0), 9),
            "attrs": attrs,
        }
        with self._lock:
            self._spans.append(span)
        return span_id

    def merge(self, spans) -> None:
        """Fold a replica's exported spans (response-body ``spans``
        field) into this buffer. Tolerant of garbage — a malformed
        remote span drops, it never fails the live response."""
        if not spans:
            return
        keep = []
        for s in spans:
            if not isinstance(s, dict):
                continue
            if s.get("trace") != self.trace_id:
                continue
            if not s.get("span") or not s.get("name"):
                continue
            keep.append({
                "trace": self.trace_id,
                "span": str(s["span"]),
                "parent": s.get("parent") or self.root_id,
                "name": str(s["name"]),
                "start": float(s.get("start", 0.0)),
                "dur_s": float(s.get("dur_s", 0.0)),
                "attrs": dict(s.get("attrs") or {}),
            })
        if keep:
            with self._lock:
                self._spans.extend(keep)

    def header(self, parent_span: Optional[str] = None) -> str:
        """Propagation header for one replica attempt; ``parent_span``
        (usually the attempt span's pre-generated id) roots the
        replica's spans under that attempt."""
        return encode_header(self.trace_id, parent_span or self.root_id)

    # ---- outcome -------------------------------------------------------
    def finish(self, status: str, slo_missed: bool = False,
               error: bool = False, **attrs) -> bool:
        """Terminal outcome: record the root ``route`` span and flush
        the buffer when head-sampled or tail-selected (SLO miss /
        error). Returns whether the trace flushed. Idempotent — only
        the first call emits."""
        with self._lock:
            if self._finished:
                return False
            self._finished = True
        dur = time.monotonic() - self._start_mono
        root_attrs = dict(self.attrs)
        root_attrs.update(attrs)
        root_attrs["status"] = status
        root_attrs["slo_missed"] = bool(slo_missed)
        self.record(
            "route", self._start_wall, dur, parent="", span_id=self.root_id,
            **root_attrs,
        )
        flush = self.sampled or slo_missed or error
        self.tracer._on_finish(self, flush, slo_missed, error)
        return flush


class Tracer:
    """Process-wide tracing front door: sampling config + flush sink.

    ``emit(event_type, **fields)`` is any schema-gated event emitter —
    ``RunEventLog.emit`` or ``ServingFleet.emit``. With no sink or a
    zero rate, :meth:`start` returns ``None`` and tracing costs one
    ``is None`` check per request."""

    def __init__(self, sample: float = 0.0,
                 emit: Optional[Callable] = None):
        self.sample = max(float(sample), 0.0)
        self.emit = emit
        self.metrics = MetricsRegistry("hydragnn")
        self.metrics.counter(
            "trace_requests_total", "Requests armed for tracing"
        )
        self.metrics.counter(
            "trace_flushed_total", "Traces flushed to the event stream"
        )
        self.metrics.counter(
            "trace_sampled_total", "Traces flushed by the head sample"
        )
        self.metrics.counter(
            "trace_tail_total",
            "Traces flushed ONLY by the tail rules (SLO miss / error)",
        )
        self.metrics.counter(
            "trace_spans_total", "Spans written to the event stream"
        )

    @classmethod
    def from_env(cls, emit: Optional[Callable] = None) -> "Tracer":
        """Rate from ``HYDRAGNN_TRACE_SAMPLE`` (0 disables; fraction of
        traces head-sampled — SLO misses and errors always flush)."""
        return cls(
            sample=env_float("HYDRAGNN_TRACE_SAMPLE", 0.0, minimum=0.0),
            emit=emit,
        )

    @property
    def enabled(self) -> bool:
        return self.sample > 0.0 and self.emit is not None

    def start(self, **attrs) -> Optional[RequestTrace]:
        """Arm one request (or return None when tracing is off). EVERY
        armed request buffers — the tail rules need the spans of
        requests the head sample rejected."""
        if not self.enabled:
            return None
        trace_id = new_id(8)
        self.metrics.inc("trace_requests_total")
        return RequestTrace(
            self, trace_id, head_sampled(trace_id, self.sample), **attrs
        )

    def _on_finish(self, trace: RequestTrace, flush: bool,
                   slo_missed: bool, error: bool) -> None:
        if not flush:
            return
        self.metrics.inc("trace_flushed_total")
        if trace.sampled:
            self.metrics.inc("trace_sampled_total")
        elif slo_missed or error:
            self.metrics.inc("trace_tail_total")
        emit = self.emit
        if emit is None:
            return
        spans = sorted(trace._spans, key=lambda s: (s["start"], s["span"]))
        for span in spans:
            try:
                emit("span", **span)
            except Exception:
                return  # a full disk must not fail the request path
        self.metrics.inc("trace_spans_total", len(spans))

    def render_prometheus(self) -> str:
        return self.metrics.render_prometheus()


# ---- reconstruction (the ``obs trace`` CLI's engine) ----------------------


def load_span_events(root: str) -> List[Dict]:
    """Every ``span`` event under ``root`` (a directory searched
    recursively for ``events*.jsonl``, or one stream file). Tolerant:
    unparseable lines skip — a live fleet's streams are read mid-write."""
    paths: List[str] = []
    if os.path.isfile(root):
        paths = [root]
    else:
        for dirpath, _dirnames, filenames in os.walk(root):
            for fn in sorted(filenames):
                if fn.startswith("events") and fn.endswith(".jsonl"):
                    paths.append(os.path.join(dirpath, fn))
    spans: List[Dict] = []
    for path in sorted(paths):
        try:
            with open(path) as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        rec = json.loads(line)
                    except ValueError:
                        continue
                    if rec.get("event") == "span" and rec.get("trace"):
                        spans.append(rec)
        except OSError:
            continue
    return spans


def build_traces(spans: List[Dict]) -> Dict[str, Dict]:
    """Group spans into trace trees: ``{trace_id: {"root": span|None,
    "spans": [...], "children": {span_id: [child span, ...]}}}``."""
    traces: Dict[str, Dict] = {}
    for span in spans:
        t = traces.setdefault(
            span["trace"], {"root": None, "spans": [], "children": {}}
        )
        t["spans"].append(span)
        if span.get("name") == "route" or not span.get("parent"):
            t["root"] = span
        else:
            t["children"].setdefault(span["parent"], []).append(span)
    for t in traces.values():
        t["spans"].sort(key=lambda s: (s.get("start", 0.0), s["span"]))
        for kids in t["children"].values():
            kids.sort(key=lambda s: (s.get("start", 0.0), s["span"]))
    return traces


def segment_durations(trace: Dict) -> Dict[str, float]:
    """Per-segment seconds of one trace. Leaf spans contribute their
    duration under their name; container spans (``route``/``attempt``)
    contribute their EXCLUSIVE time — container minus direct children —
    as ``other`` (route) / ``transport`` (attempt: HTTP + replica
    handling outside the recorded server spans). Segments therefore sum
    to the root duration (when every component reported)."""
    children = trace["children"]
    segments: Dict[str, float] = {}

    def child_sum(span):
        return sum(
            c.get("dur_s", 0.0) for c in children.get(span["span"], ())
        )

    for span in trace["spans"]:
        name = span.get("name", "?")
        dur = float(span.get("dur_s", 0.0))
        if name in CONTAINER_SPANS:
            exclusive = max(dur - child_sum(span), 0.0)
            label = "transport" if name == "attempt" else "other"
            segments[label] = segments.get(label, 0.0) + exclusive
        else:
            segments[name] = segments.get(name, 0.0) + dur
    return segments


def dominant_segment(trace: Dict) -> Optional[str]:
    """The segment this trace spent the most time in (None when the
    trace recorded nothing but its root)."""
    segments = segment_durations(trace)
    segments.pop("other", None)
    if not segments:
        return None
    return max(sorted(segments), key=lambda k: segments[k])


def _percentile(values: List[float], q: float) -> float:
    if not values:
        return 0.0
    vs = sorted(values)
    idx = min(int(q * len(vs)), len(vs) - 1)
    return vs[idx]


def anatomy(traces: Dict[str, Dict]) -> Dict:
    """Cross-trace rollup: per-segment count/p50/p99/total seconds, the
    same per (tenant, lane), and the slowest traces with their dominant
    segment flagged — the "request latency anatomy" table."""
    per_segment: Dict[str, List[float]] = {}
    per_group: Dict[str, Dict[str, float]] = {}
    rows = []
    for trace_id, trace in traces.items():
        segments = segment_durations(trace)
        for name, dur in segments.items():
            per_segment.setdefault(name, []).append(dur)
        root = trace["root"]
        attrs = (root or {}).get("attrs") or {}
        group = "{}/{}".format(
            attrs.get("tenant") or "-", attrs.get("lane") or "-"
        )
        g = per_group.setdefault(group, {})
        for name, dur in segments.items():
            g[name] = g.get(name, 0.0) + dur
        rows.append({
            "trace": trace_id,
            "dur_s": float((root or {}).get("dur_s", 0.0)),
            "status": attrs.get("status"),
            "tenant": attrs.get("tenant"),
            "lane": attrs.get("lane"),
            "slo_missed": bool(attrs.get("slo_missed")),
            "spans": len(trace["spans"]),
            "dominant": dominant_segment(trace),
        })
    rows.sort(key=lambda r: -r["dur_s"])
    return {
        "traces": len(traces),
        "segments": {
            name: {
                "count": len(durs),
                "p50_s": round(_percentile(durs, 0.50), 6),
                "p99_s": round(_percentile(durs, 0.99), 6),
                "total_s": round(sum(durs), 6),
            }
            for name, durs in sorted(per_segment.items())
        },
        "groups": {
            group: {k: round(v, 6) for k, v in sorted(g.items())}
            for group, g in sorted(per_group.items())
        },
        "slowest": rows[:20],
    }
