"""Eyes into the compiled program: cost/memory accounting + trace capture.

The telemetry layer (PR 3) reports wall-clock and throughput; jaxlint
(PR 4) catches anti-patterns — but neither can say what XLA actually
*compiled*, which is where "why is this step slow" and "how much HBM does
this bucket cost" live. This module closes that gap with three pieces:

- :func:`instrument` wraps a jitted program so that every NOVEL shape
  signature (= every bucket) gets its compiled executable's
  ``cost_analysis()`` (FLOPs, bytes accessed) and ``memory_analysis()``
  (argument/output/temp/peak bytes) captured once, recorded process-wide
  (:func:`captured`) and — when a telemetry run is active — emitted as a
  ``compile`` event and exported as
  ``hydragnn_train_flops_per_step{bucket=...}`` /
  ``hydragnn_train_hbm_peak_bytes{bucket=...}`` gauges.
- :class:`TraceCapture` arms ``jax.profiler`` device-trace capture for
  the next N steps of a LIVE run — driven by ``/profile?steps=N`` on the
  observability endpoint or ``HYDRAGNN_PROFILE_AT_STEP=<epoch>:<step>``.
- :class:`Profiler` — the wait/warmup/active step schedule absorbed from
  ``utils/profile.py`` (which is now a deprecation shim); the schedule is
  the reference-parity surface, :class:`TraceCapture` the on-demand one.

Cost model: detection of a fresh compile is ONE ``_cache_size()`` read
per dispatch (the same signal ``analysis/guards.CompileSentinel`` uses),
so the steady-state overhead of an instrumented program is a global read
and an int compare. The analysis itself runs the AOT
``lower().compile()`` path once per novel signature — with the
persistent compile cache (``utils/compile_cache``, enabled by every
Trainer front door) the backend compile is absorbed and only tracing is
re-paid, at warmup, never in steady state. When no telemetry is active
and ``HYDRAGNN_INTROSPECT`` does not force it, the wrapper is a pure
passthrough.
"""

import hashlib
import os
import threading
import warnings
from typing import Callable, Dict, List, Optional, Tuple

_FALSY = ("", "0", "false", "no", "off")


def enabled() -> bool:
    """Introspection live? Default: exactly when a telemetry run is
    active. ``HYDRAGNN_INTROSPECT=0`` kills it even then (a hot path that
    cannot afford the per-dispatch cache-size read); ``=1`` forces it on
    with no telemetry run (serving, benchmarks — records still land in
    :func:`captured`)."""
    env = os.getenv("HYDRAGNN_INTROSPECT")
    if env is not None:
        return env.strip().lower() not in _FALSY
    from hydragnn_tpu.obs import runtime as _rt

    return _rt.active() is not None


# ---- compiled-program analysis -------------------------------------------


def normalize_cost_analysis(cost) -> Dict[str, float]:
    """``Compiled.cost_analysis()`` -> a flat, JSON-able dict. jax returns
    a list of one dict on some versions, a plain dict on others, None on
    backends without a cost model; key spellings vary ('bytes accessed').
    """
    if isinstance(cost, (list, tuple)):
        cost = cost[0] if cost else None
    if not cost:
        return {}
    out = {}
    for key, new in (
        ("flops", "flops"),
        ("bytes accessed", "bytes_accessed"),
        ("transcendentals", "transcendentals"),
    ):
        v = cost.get(key)
        if v is not None:
            out[new] = float(v)
    return out


def normalize_memory_analysis(mem) -> Dict[str, float]:
    """``Compiled.memory_analysis()`` -> flat dict with a derived
    ``peak_bytes`` (argument + output + temp + generated code − aliased:
    the executable's worst-case simultaneous HBM footprint, the figure
    the budget ratchet tracks). Returns {} when the backend reports
    nothing."""
    if mem is None:
        return {}
    out = {}
    for attr, new in (
        ("argument_size_in_bytes", "argument_bytes"),
        ("output_size_in_bytes", "output_bytes"),
        ("temp_size_in_bytes", "temp_bytes"),
        ("alias_size_in_bytes", "alias_bytes"),
        ("generated_code_size_in_bytes", "generated_code_bytes"),
    ):
        v = getattr(mem, attr, None)
        if v is not None:
            out[new] = float(v)
    if out:
        out["peak_bytes"] = max(
            out.get("argument_bytes", 0.0)
            + out.get("output_bytes", 0.0)
            + out.get("temp_bytes", 0.0)
            + out.get("generated_code_bytes", 0.0)
            - out.get("alias_bytes", 0.0),
            0.0,
        )
    return out


def analyze_compiled(compiled) -> Tuple[Dict[str, float], Dict[str, float]]:
    """(cost, memory) dicts for one ``jax.stages.Compiled``."""
    try:
        cost = normalize_cost_analysis(compiled.cost_analysis())
    except Exception:
        cost = {}
    try:
        mem = normalize_memory_analysis(compiled.memory_analysis())
    except Exception:
        mem = {}
    return cost, mem


def signature_key(args, kwargs=None) -> Tuple:
    """Hashable (treedef, per-leaf shape/dtype) signature — the same
    notion of "bucket" the jit cache keys on."""
    import jax

    leaves, treedef = jax.tree_util.tree_flatten((args, kwargs or {}))
    sig = []
    for leaf in leaves:
        shape = getattr(leaf, "shape", None)
        dtype = getattr(leaf, "dtype", None)
        if shape is not None and dtype is not None:
            sig.append((tuple(shape), str(dtype)))
        else:
            sig.append(repr(leaf))
    return (str(treedef), tuple(sig))


def bucket_label(name: str, key: Tuple) -> str:
    """Stable short id for one (program, shape signature): the gauge's
    ``bucket`` label and the budget ratchet's key. hashlib, not hash() —
    must agree across processes and runs."""
    digest = hashlib.sha1(repr(key).encode()).hexdigest()[:8]
    return f"{name}/{digest}"


# ambient mesh context (parallel/mesh.py registers it): lets the capture
# attribute collective bytes in the compiled HLO to mesh axes
_mesh_axes: Optional[Tuple[str, ...]] = None
_mesh_shape: Optional[Tuple[int, ...]] = None


def set_mesh_context(axes, shape):
    """Register (or clear, with Nones) the active mesh's axis names and
    shape for collective-byte attribution."""
    global _mesh_axes, _mesh_shape
    _mesh_axes = tuple(axes) if axes else None
    _mesh_shape = tuple(int(s) for s in shape) if shape else None


def mesh_context():
    return _mesh_axes, _mesh_shape


def _collective_bytes(compiled) -> Dict[str, float]:
    """Per-axis collective result bytes of one compiled executable ({}
    without a registered mesh or on parse failure — accounting must
    never break a capture)."""
    if _mesh_axes is None or _mesh_shape is None:
        return {}
    try:
        from hydragnn_tpu.parallel.collectives import collective_bytes_by_axis

        return collective_bytes_by_axis(
            compiled.as_text(), _mesh_axes, _mesh_shape
        )
    except Exception:
        return {}


# process-global record of every captured compile — serving and benches
# read this even with no telemetry run active
_captured: List[Dict] = []
_captured_lock = threading.Lock()


def captured(name: Optional[str] = None) -> List[Dict]:
    """Compile records captured so far (optionally for one program)."""
    with _captured_lock:
        recs = list(_captured)
    if name is not None:
        recs = [r for r in recs if r["name"] == name]
    return recs


def reset_captured():
    with _captured_lock:
        _captured.clear()


def _record(rec: Dict):
    with _captured_lock:
        _captured.append(rec)
    from hydragnn_tpu.obs import runtime as _rt

    t = _rt.active()
    if t is not None:
        t.record_compile(rec)


class InstrumentedJit:
    """Transparent wrapper over one jitted program.

    Dispatch goes STRAIGHT to the wrapped jit; after each call, if the
    jit's signature cache grew (a fresh trace+compile just happened), the
    executable for THIS call's signature is analyzed once via the AOT
    path and recorded. Attribute access (``.lower``, ``._cache_size``,
    ...) forwards to the wrapped jit, so existing callers — benchmarks'
    ``_train_step.lower(...)``, the recompile sentinel's cache probe —
    see the program they always saw.
    """

    def __init__(self, name: str, fn: Callable,
                 on_capture: Optional[Callable[[Dict], None]] = None):
        self._name = name
        self._fn = fn
        self._on_capture = on_capture
        self._ncached = None  # jit cache size at last capture check
        self._keys_seen = set()
        self._warned = False

    def __call__(self, *args, **kwargs):
        if not enabled():
            return self._fn(*args, **kwargs)
        out = self._fn(*args, **kwargs)
        try:
            n = self._fn._cache_size()
        except Exception:
            n = None
        if n is not None and n != self._ncached:
            self._ncached = n
            self._capture(args, kwargs)
        return out

    def __getattr__(self, attr):
        return getattr(self._fn, attr)

    def _capture(self, args, kwargs):
        """Analyze the executable for this call's signature; never raises
        into the training loop."""
        try:
            key = signature_key(args, kwargs)
            if key in self._keys_seen:
                return
            self._keys_seen.add(key)
            compiled = self._fn.lower(*args, **kwargs).compile()
            cost, mem = analyze_compiled(compiled)
            rec = {
                "name": self._name,
                "bucket": bucket_label(self._name, key),
                "cost": cost,
                "memory": mem,
                "collectives": _collective_bytes(compiled),
            }
            _record(rec)
            if self._on_capture is not None:
                self._on_capture(rec)
        except Exception as e:
            if not self._warned:
                self._warned = True
                warnings.warn(
                    f"introspection capture failed for {self._name!r}: {e} "
                    "(further failures for this program are silent)",
                    stacklevel=2,
                )


def instrument(name: str, fn: Callable,
               on_capture: Optional[Callable[[Dict], None]] = None):
    """Wrap a jitted program for compile-time accounting."""
    return InstrumentedJit(name, fn, on_capture=on_capture)


# ---- on-demand trace capture ---------------------------------------------


def _start_device_trace(trace_dir: str):
    """ONE trace-startup sequence for both capture styles (on-demand
    TraceCapture and the scheduled Profiler) — jax.profiler resolved at
    call time so test fakes apply."""
    import jax.profiler

    os.makedirs(trace_dir, exist_ok=True)
    jax.profiler.start_trace(trace_dir)


def _stop_device_trace():
    import jax.profiler

    jax.profiler.stop_trace()


class TraceCapture:
    """Arm ``jax.profiler`` device tracing for the next N steps of a live
    run. ``arm()`` is called from any thread (the ``/profile`` HTTP
    handler); ``tick()`` is called once per step from the training thread
    and owns every profiler start/stop — the jax profiler is
    process-global and must not be driven from two threads."""

    def __init__(self, trace_dir: str):
        self.trace_dir = trace_dir
        self._lock = threading.Lock()
        self._armed_steps = 0
        self._remaining = 0
        self._tracing = False

    def arm(self, steps: int) -> Dict:
        """Request capture of the next ``steps`` steps. Returns the
        ``/profile`` response payload."""
        steps = int(steps)
        if steps <= 0:
            return {"status": "error", "error": "steps must be >= 1"}
        with self._lock:
            if self._tracing or self._armed_steps:
                return {
                    "status": "busy",
                    "remaining_steps": self._remaining or self._armed_steps,
                    "trace_dir": self.trace_dir,
                }
            self._armed_steps = steps
        return {
            "status": "armed",
            "steps": steps,
            "trace_dir": self.trace_dir,
        }

    def tick(self) -> Optional[Dict]:
        """Advance one step; returns a ``profile`` event payload on the
        started/done transitions, else None. Profiler failures (e.g.
        another jax.profiler session already active) surface as an
        ``error`` payload — never as an exception into the training
        loop."""
        with self._lock:
            if self._armed_steps:
                steps, self._armed_steps = self._armed_steps, 0
                try:
                    self._start()
                except Exception as e:
                    return {
                        "status": "error",
                        "error": str(e),
                        "trace_dir": self.trace_dir,
                    }
                self._remaining = steps
                self._tracing = True
                return {
                    "status": "started",
                    "steps": steps,
                    "trace_dir": self.trace_dir,
                }
            if self._tracing:
                self._remaining -= 1
                if self._remaining <= 0:
                    self._tracing = False
                    try:
                        self._stop()
                    except Exception as e:
                        return {
                            "status": "error",
                            "error": str(e),
                            "trace_dir": self.trace_dir,
                        }
                    return {"status": "done", "trace_dir": self.trace_dir}
        return None

    def close(self) -> Optional[Dict]:
        """Stop an open trace (run teardown) so a mid-capture shutdown
        still flushes a loadable trace."""
        with self._lock:
            if not self._tracing:
                return None
            self._tracing = False
            self._remaining = 0
            try:
                self._stop()
            except Exception as e:
                return {
                    "status": "error",
                    "error": str(e),
                    "trace_dir": self.trace_dir,
                }
            return {"status": "done", "trace_dir": self.trace_dir}

    def _start(self):
        _start_device_trace(self.trace_dir)

    def _stop(self):
        _stop_device_trace()


def parse_profile_at_step(value: Optional[str]) -> Optional[Tuple[int, int]]:
    """``HYDRAGNN_PROFILE_AT_STEP`` -> (epoch, step): ``"<epoch>:<step>"``
    or a bare ``"<step>"`` (epoch 0). None/malformed -> None (malformed
    warns — a typo'd arm target silently never firing is the worst
    outcome for a knob you set before a 6-hour run)."""
    if value is None or not value.strip():
        return None
    try:
        parts = value.split(":")
        if len(parts) == 1:
            return (0, int(parts[0]))
        if len(parts) == 2:
            return (int(parts[0]), int(parts[1]))
    except ValueError:
        pass
    warnings.warn(
        f"HYDRAGNN_PROFILE_AT_STEP={value!r} is not '<epoch>:<step>' or "
        "'<step>' — profiling will not arm",
        stacklevel=2,
    )
    return None


# ---- reference-parity step schedule (absorbed from utils/profile.py) -----


class Profiler:
    """Step-scheduled device tracing for TensorBoard.

    Parity with the reference's ``Profiler(torch.profiler.profile)``
    (``hydragnn/utils/profile.py:9-70``): a wait/warmup/active step
    schedule, a target-epoch gate, TensorBoard-consumable output, and a
    no-op object when disabled so call sites stay unconditional. The
    backend is ``jax.profiler`` (XLA device traces, viewable in
    TensorBoard's profile plugin or perfetto).

    Lives here since the introspection PR; ``hydragnn_tpu.utils.profile``
    re-exports it as a deprecation shim. For profiling a LIVE run without
    a pre-planned schedule, use ``/profile?steps=N`` on the observability
    endpoint (:class:`TraceCapture`) instead.
    """

    def __init__(
        self,
        trace_dir: str = "./logs/profile",
        wait: int = 5,
        warmup: int = 3,
        active: int = 3,
        target_epoch: Optional[int] = 1,
    ):
        self.trace_dir = trace_dir
        self.wait = wait
        self.warmup = warmup
        self.active = active
        self.target_epoch = target_epoch
        self.enabled = False
        self._epoch = None
        self._step = 0
        self._tracing = False

    def setup(self, config: dict):
        """Config section ``{"Profile": {"enable": 1, "trace_dir": ...}}``
        (reference reads ``config["Profile"]``, ``profile.py:22-29``)."""
        if not config:
            return
        self.enabled = bool(config.get("enable", 0))
        self.trace_dir = config.get("trace_dir", self.trace_dir)
        self.wait = int(config.get("wait", self.wait))
        self.warmup = int(config.get("warmup", self.warmup))
        self.active = int(config.get("active", self.active))
        self.target_epoch = config.get("target_epoch", self.target_epoch)

    def set_current_epoch(self, epoch: int):
        self._epoch = epoch

    def _armed(self) -> bool:
        if not self.enabled:
            return False
        return self.target_epoch is None or self._epoch == self.target_epoch

    # -- context manager ---------------------------------------------------
    def __enter__(self):
        self._step = 0
        return self

    def __exit__(self, *exc):
        self._stop_trace()
        return False

    def step(self):
        """Advance the schedule; starts/stops the device trace at the
        wait→warmup→active window boundaries."""
        if not self._armed():
            return
        self._step += 1
        # trace through warmup+active, discard-by-convention the warmup part
        if self._step == self.wait + 1:
            self._start_trace()
        elif self._step == self.wait + self.warmup + self.active + 1:
            self._stop_trace()

    def _start_trace(self):
        if self._tracing:
            return
        _start_device_trace(self.trace_dir)
        self._tracing = True

    def _stop_trace(self):
        if not self._tracing:
            return
        _stop_device_trace()
        self._tracing = False


def record_function(name: str):
    """Annotation context (torch.profiler.record_function analog) — shows
    up inside the XLA trace timeline."""
    import jax.profiler

    return jax.profiler.TraceAnnotation(name)
