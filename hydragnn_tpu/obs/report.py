"""Post-mortem run reports from ``events.jsonl`` + the perf-budget ratchet.

``python -m hydragnn_tpu.obs report <logs/run>`` renders what a finished
(or crashed) run did — epoch table, throughput trend, padding waste,
guard/checkpoint/compile/stall timeline, per-bucket compiled cost — from
the structured event stream alone, so a post-mortem needs no access to
the machine the run died on.

The budget ratchet (``--check-budget .perf-baseline.json``) compares the
run's per-bucket compiled FLOPs / peak-HBM figures against a committed
baseline with tolerances — the same pattern as ``.jaxlint-baseline.json``:
CI fails when a hot program got measurably more expensive, and the
baseline only moves by an explicit ``--write-budget`` commit.

Unlike :func:`~hydragnn_tpu.obs.events.validate_events` (the strict CI
schema gate), loading here is TOLERANT: a torn stream from a crashed run
is exactly when a post-mortem matters, so unparseable lines are skipped,
not fatal.
"""

import json
import os
from typing import Dict, List, Optional, Tuple

BUDGET_VERSION = 1
DEFAULT_TOLERANCE = 0.10
# the per-program figures the ratchet tracks (report key -> budget key)
BUDGET_METRICS = ("flops", "bytes_accessed", "peak_bytes")


def resolve_events_path(path: str) -> str:
    """Accept a run directory or the ``events.jsonl`` itself."""
    if os.path.isdir(path):
        return os.path.join(path, "events.jsonl")
    return path


def load_events(path: str) -> List[Dict]:
    """Tolerantly parse an event stream (run dir or file path)."""
    path = resolve_events_path(path)
    records = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                continue  # torn tail / partial write: skip, don't die
            if isinstance(rec, dict) and "event" in rec:
                records.append(rec)
    return records


def _num(value) -> Optional[float]:
    """Numeric field or None (nulled NaNs stay None)."""
    return float(value) if isinstance(value, (int, float)) else None


def build_report(records: List[Dict]) -> Dict:
    """Fold the event stream into the report structure all three
    renderers (and the budget check) consume."""
    manifest = next(
        (r for r in records if r["event"] == "run_manifest"), {}
    )
    run_end = next(
        (r for r in reversed(records) if r["event"] == "run_end"), None
    )
    ts = [r["ts"] for r in records if isinstance(r.get("ts"), (int, float))]

    epochs = []
    for r in records:
        if r["event"] != "epoch":
            continue
        epochs.append(
            {
                "epoch": r.get("epoch"),
                "train_loss": _num(r.get("train_loss")),
                "val_loss": _num(r.get("val_loss")),
                "test_loss": _num(r.get("test_loss")),
                "wall_time_s": _num(r.get("wall_time_s")),
                "graphs_per_sec": _num(r.get("graphs_per_sec")),
                "nodes_per_sec": _num(r.get("nodes_per_sec")),
                "padding_waste": _num(r.get("padding_waste")),
                "mode": r.get("mode"),
            }
        )

    gps = [e["graphs_per_sec"] for e in epochs if e["graphs_per_sec"]]
    waste = [
        e["padding_waste"] for e in epochs if e["padding_waste"] is not None
    ]
    throughput = {}
    if gps:
        throughput = {
            "first_graphs_per_sec": gps[0],
            "last_graphs_per_sec": gps[-1],
            "best_graphs_per_sec": max(gps),
            "mean_graphs_per_sec": sum(gps) / len(gps),
        }
    if waste:
        throughput["mean_padding_waste"] = sum(waste) / len(waste)

    # per-bucket compiled cost: LAST capture wins (a resumed run's
    # recompile re-reports the same bucket). Collective result bytes
    # (PR 10's per-axis accounting riding the compile events) roll up
    # per axis over the DEDUPED programs — summing raw records would
    # double-count every bucket a resumed run recompiled.
    programs: Dict[str, Dict] = {}
    for r in records:
        if r["event"] != "compile":
            continue
        cost = r.get("cost") or {}
        mem = r.get("memory") or {}
        programs[r["bucket"]] = {
            "name": r.get("name"),
            "bucket": r["bucket"],
            "flops": _num(cost.get("flops")),
            "bytes_accessed": _num(cost.get("bytes_accessed")),
            "peak_bytes": _num(mem.get("peak_bytes")),
            "argument_bytes": _num(mem.get("argument_bytes")),
            "output_bytes": _num(mem.get("output_bytes")),
            "temp_bytes": _num(mem.get("temp_bytes")),
            "collectives": {
                str(axis): _num(v)
                for axis, v in (r.get("collectives") or {}).items()
            },
        }
    collectives: Dict[str, float] = {}
    for p in programs.values():
        for axis, v in (p.get("collectives") or {}).items():
            if v is not None:
                collectives[axis] = collectives.get(axis, 0.0) + float(v)

    # goodput ledger events (obs/ledger.py): per-epoch category fractions
    # + the per-bucket MFU figures (LAST value per bucket wins — it saw
    # the most warmed-up steps); the MFU lands on the program entry so
    # the budget ratchet can floor it
    goodput = []
    for r in records:
        if r["event"] != "goodput":
            continue
        goodput.append(
            {
                "epoch": r.get("epoch"),
                "wall_s": _num(r.get("wall_s")),
                "fractions": r.get("fractions") or {},
                "goodput_fraction": _num(r.get("goodput_fraction")),
                "mfu": r.get("mfu") or {},
            }
        )
        for bucket, m in (r.get("mfu") or {}).items():
            if bucket in programs and isinstance(m, dict):
                if _num(m.get("mfu")) is not None:
                    programs[bucket]["mfu"] = float(m["mfu"])

    # the run's device mesh (parallel/mesh.py announce_mesh): the header
    # should say what hardware layout produced these figures
    mesh = next(
        (r for r in reversed(records) if r["event"] == "mesh_shape"), None
    )

    # canary ladder (serve/canary.py): every candidate's journey from
    # publication to verdict, in stream order — a rejected candidate's
    # reason string is the post-mortem
    canary = []
    for r in records:
        if r["event"] not in (
            "candidate_published", "canary_started",
            "canary_promoted", "canary_rejected",
        ):
            continue
        canary.append(
            {
                "event": r["event"],
                "candidate": r.get("candidate"),
                "checkpoint": r.get("checkpoint"),
                "samples": _num(r.get("samples")),
                "reason": r.get("reason"),
            }
        )

    # request tracing (obs/trace.py): when the stream carries flushed
    # span events, fold them into the latency-anatomy rollup the obs
    # trace CLI prints — a serving run's report answers "where did the
    # slow requests spend their time" inline
    trace_anatomy = None
    spans = [r for r in records if r["event"] == "span" and r.get("trace")]
    if spans:
        from hydragnn_tpu.obs import trace as trace_mod

        trace_anatomy = trace_mod.anatomy(trace_mod.build_traces(spans))

    # tenant bill (serve/costs.py): tenant_cost events carry CUMULATIVE
    # per-tenant attribution, so the LAST record per tenant wins
    tenant_bill: Dict[str, Dict] = {}
    for r in records:
        if r["event"] != "tenant_cost":
            continue
        tenant_bill[str(r.get("tenant") or "-")] = {
            "device_s": _num(r.get("device_s")),
            "flops": _num(r.get("flops")),
            "requests": _num(r.get("requests")),
            "replica_s": _num(r.get("replica_s")),
        }

    # model-quality observatory (obs/drift.py): folded only when the
    # stream actually carries quality events, so reports over old
    # streams omit the section instead of rendering an empty one
    quality = None
    from hydragnn_tpu.obs.drift import QUALITY_EVENTS, build_drift_report

    if any(r["event"] in QUALITY_EVENTS for r in records):
        quality = build_drift_report(
            [r for r in records if r["event"] in QUALITY_EVENTS]
        )

    counts = {
        key: sum(1 for r in records if r["event"] == key)
        for key in (
            "compile", "stall", "checkpoint_saved", "checkpoint_restored",
            "guard_skip", "guard_restore", "resume", "staged", "fit_chunk",
            "candidate_published", "canary_promoted", "canary_rejected",
            "span", "quota_adjusted",
            "drift_window", "drift_alert", "feedback_sink",
        )
    }
    counts["profile_done"] = sum(
        1
        for r in records
        if r["event"] == "profile" and r.get("status") == "done"
    )

    timeline = []
    t0 = ts[0] if ts else 0.0
    for r in records:
        ev = r["event"]
        if ev == "compile":
            c, m = r.get("cost") or {}, r.get("memory") or {}
            desc = (
                f"{r.get('name')} [{r.get('bucket')}] "
                f"flops={_fmt_num(c.get('flops'))} "
                f"peak={_fmt_bytes(m.get('peak_bytes'))}"
            )
        elif ev == "stall":
            desc = (
                f"step {r.get('step')}: {r.get('seconds')}s vs median "
                f"{r.get('median')}s (x{r.get('factor')})"
            )
        elif ev == "checkpoint_saved":
            desc = f"{r.get('name')} ({r.get('kind')})"
        elif ev == "checkpoint_restored":
            desc = f"{r.get('name')} from {r.get('source')}"
        elif ev == "guard_skip":
            desc = f"scope={r.get('scope')} skipped={r.get('skipped')}"
        elif ev == "guard_restore":
            desc = f"restores={r.get('restores')} lr={r.get('lr')}"
        elif ev == "resume":
            desc = f"start_epoch={r.get('start_epoch')}"
        elif ev in ("early_stop", "wallclock_stop"):
            desc = f"epoch={r.get('epoch')}"
        elif ev == "profile":
            desc = f"{r.get('status')} ({r.get('trace_dir', '')})"
        elif ev in ("candidate_published", "canary_started"):
            desc = f"candidate={r.get('candidate')} {r.get('checkpoint')}"
        elif ev == "canary_promoted":
            desc = (
                f"candidate={r.get('candidate')} {r.get('checkpoint')} "
                f"samples={r.get('samples')}"
            )
        elif ev == "canary_rejected":
            desc = (
                f"candidate={r.get('candidate')} {r.get('checkpoint')}: "
                f"{r.get('reason')}"
            )
        elif ev == "drift_alert":
            desc = (
                f"{r.get('status')} tenant={r.get('tenant')} "
                f"feature={r.get('feature')} head={r.get('head')} "
                f"{r.get('kind')}={r.get('score')}"
            )
        else:
            continue
        timeline.append(
            {
                "t": round(float(r.get("ts", t0)) - t0, 3),
                "event": ev,
                "detail": desc,
            }
        )

    return {
        "run": {
            "run": manifest.get("run"),
            "config_hash": manifest.get("config_hash"),
            "git_rev": manifest.get("git_rev"),
            "world_size": manifest.get("world_size"),
            "device_kind": manifest.get("device_kind"),
            "device_count": manifest.get("device_count"),
            "num_epoch": manifest.get("num_epoch"),
            "status": run_end["status"] if run_end else "incomplete",
            "duration_s": round(ts[-1] - ts[0], 3) if len(ts) > 1 else None,
            "events": len(records),
            "mesh_shape": mesh.get("shape") if mesh else None,
            "mesh_axes": mesh.get("axes") if mesh else None,
        },
        "epochs": epochs,
        "canary": canary,
        "throughput": throughput,
        "programs": programs,
        "collectives": collectives,
        "goodput": goodput,
        "trace_anatomy": trace_anatomy,
        "tenant_bill": tenant_bill,
        "quality": quality,
        "counts": counts,
        "timeline": timeline,
    }


# ---- rendering -----------------------------------------------------------


def _fmt_num(v) -> str:
    if v is None:
        return "-"
    v = float(v)
    for scale, suffix in ((1e12, "T"), (1e9, "G"), (1e6, "M"), (1e3, "k")):
        if abs(v) >= scale:
            return f"{v / scale:.2f}{suffix}"
    return f"{v:.6g}"


def _fmt_bytes(v) -> str:
    if v is None:
        return "-"
    v = float(v)
    for scale, suffix in ((2**30, "GiB"), (2**20, "MiB"), (2**10, "KiB")):
        if abs(v) >= scale:
            return f"{v / scale:.2f}{suffix}"
    return f"{v:.0f}B"


def _fmt(v, digits=6) -> str:
    if v is None:
        return "-"
    if isinstance(v, float):
        return f"{v:.{digits}g}"
    return str(v)


_EPOCH_COLS = (
    ("epoch", "epoch"),
    ("train", "train_loss"),
    ("val", "val_loss"),
    ("test", "test_loss"),
    ("wall_s", "wall_time_s"),
    ("graphs/s", "graphs_per_sec"),
    ("waste", "padding_waste"),
    ("mode", "mode"),
)

_PROGRAM_COLS = (
    ("program", "name"),
    ("bucket", "bucket"),
    ("flops", "flops"),
    ("bytes_accessed", "bytes_accessed"),
    ("peak_hbm", "peak_bytes"),
    ("args", "argument_bytes"),
    ("out", "output_bytes"),
    ("temp", "temp_bytes"),
    ("mfu", "mfu"),
)


def _fmt_pct(v) -> str:
    return "-" if v is None else f"{100.0 * float(v):.2f}%"


def _program_rows(report) -> List[List[str]]:
    rows = []
    for key in sorted(report["programs"]):
        p = report["programs"][key]
        rows.append(
            [
                str(p.get("name") or "-"),
                key.split("/", 1)[1] if "/" in key else key,
                _fmt_num(p.get("flops")),
                _fmt_num(p.get("bytes_accessed")),
                _fmt_bytes(p.get("peak_bytes")),
                _fmt_bytes(p.get("argument_bytes")),
                _fmt_bytes(p.get("output_bytes")),
                _fmt_bytes(p.get("temp_bytes")),
                _fmt_pct(p.get("mfu")),
            ]
        )
    return rows


def _epoch_rows(report) -> List[List[str]]:
    return [
        [_fmt(e[field], 4) for _, field in _EPOCH_COLS]
        for e in report["epochs"]
    ]


def _text_table(headers, rows) -> List[str]:
    widths = [
        max(len(h), *(len(r[i]) for r in rows)) if rows else len(h)
        for i, h in enumerate(headers)
    ]
    out = ["  ".join(h.ljust(w) for h, w in zip(headers, widths)).rstrip()]
    for r in rows:
        out.append(
            "  ".join(c.ljust(w) for c, w in zip(r, widths)).rstrip()
        )
    return out


def _md_table(headers, rows) -> List[str]:
    out = ["| " + " | ".join(headers) + " |"]
    out.append("|" + "|".join(" --- " for _ in headers) + "|")
    for r in rows:
        out.append("| " + " | ".join(r) + " |")
    return out


def _summary_lines(report) -> List[str]:
    run = report["run"]
    c = report["counts"]
    mesh = ""
    if run.get("mesh_shape"):
        axes = run.get("mesh_axes") or []
        mesh = (
            "  mesh: "
            + "x".join(str(v) for v in run["mesh_shape"])
            + (f" ({', '.join(str(a) for a in axes)})" if axes else "")
        )
    lines = [
        f"run: {run['run']}  status: {run['status']}  "
        f"git: {run['git_rev']}  config: {run['config_hash']}",
        f"world: {run['world_size']} process(es) x "
        f"{run['device_count']} {run['device_kind']} device(s){mesh}  "
        f"epochs: {len(report['epochs'])}/{run['num_epoch']}  "
        f"duration: {_fmt(run['duration_s'], 5)}s",
        "counts: "
        + "  ".join(f"{k}={v}" for k, v in sorted(c.items()) if v),
    ]
    t = report["throughput"]
    if t:
        lines.append(
            "throughput: "
            f"first {_fmt(t.get('first_graphs_per_sec'), 4)} -> "
            f"last {_fmt(t.get('last_graphs_per_sec'), 4)} graphs/s "
            f"(best {_fmt(t.get('best_graphs_per_sec'), 4)}, "
            f"mean {_fmt(t.get('mean_graphs_per_sec'), 4)})"
            + (
                f", mean padding waste "
                f"{_fmt(t.get('mean_padding_waste'), 3)}"
                if t.get("mean_padding_waste") is not None
                else ""
            )
        )
    return lines


_CANARY_HEADERS = ("event", "candidate", "checkpoint", "samples", "reason")


def _canary_rows(report) -> List[List[str]]:
    return [
        [
            str(c.get("event") or "-"),
            _fmt(c.get("candidate")),
            str(c.get("checkpoint") or "-"),
            _fmt(c.get("samples"), 4),
            str(c.get("reason") or "-"),
        ]
        for c in report.get("canary", [])
    ]


def _goodput_cols(report):
    """(headers, rows) of the per-epoch goodput table — epoch, wall, and
    one fraction column per category that ever appeared."""
    from hydragnn_tpu.obs.ledger import CATEGORIES

    seen = set()
    for g in report.get("goodput", []):
        seen.update(g.get("fractions") or {})
    cats = [c for c in CATEGORIES if c in seen] + sorted(
        seen - set(CATEGORIES)
    )
    headers = ["epoch", "wall_s"] + list(cats)
    rows = []
    for g in report.get("goodput", []):
        fr = g.get("fractions") or {}
        rows.append(
            [_fmt(g.get("epoch"), 4), _fmt(g.get("wall_s"), 4)]
            + [_fmt_pct(_num(fr.get(c))) for c in cats]
        )
    return headers, rows


_ANATOMY_HEADERS = ("segment", "count", "p50_s", "p99_s", "total_s")
_BILL_HEADERS = ("tenant", "device_s", "flops", "requests", "replica_s")


def _anatomy_rows(report) -> List[List[str]]:
    anatomy = report.get("trace_anatomy") or {}
    return [
        [
            name,
            str(seg.get("count", 0)),
            _fmt(seg.get("p50_s"), 5),
            _fmt(seg.get("p99_s"), 5),
            _fmt(seg.get("total_s"), 5),
        ]
        for name, seg in (anatomy.get("segments") or {}).items()
    ]


_QUALITY_HEADERS = ("tenant", "feature", "head", "psi", "ks", "ref_ver")


def _quality_rows(report) -> List[List[str]]:
    q = report.get("quality") or {}
    rows = []
    for key in sorted(q.get("scores") or {}):
        tenant, feature, head = (key.split("|") + ["-", "-"])[:3]
        sc = q["scores"][key]
        rows.append(
            [
                tenant, feature, head,
                _fmt(_num(sc.get("psi")), 4),
                _fmt(_num(sc.get("ks")), 4),
                _fmt(sc.get("version")),
            ]
        )
    return rows


def _quality_summary(report) -> List[str]:
    q = report.get("quality") or {}
    lines = [
        f"windows: {q.get('windows', 0)}  "
        f"alert events: {len(q.get('alerts') or [])}  "
        f"active: {len(q.get('alerts_active') or [])}"
    ]
    for key in q.get("alerts_active") or []:
        lines.append(f"ACTIVE ALERT: {key}")
    sink = q.get("sink")
    if sink:
        lines.append(
            f"feedback sink: accepted={sink.get('accepted')} "
            f"deduped={sink.get('deduped')} graphs={sink.get('graphs')} "
            f"packs={sink.get('packs')}"
        )
    return lines


def _bill_rows(report) -> List[List[str]]:
    return [
        [
            tenant,
            _fmt(row.get("device_s"), 5),
            _fmt_num(row.get("flops")),
            _fmt(row.get("requests"), 6),
            _fmt(row.get("replica_s"), 5),
        ]
        for tenant, row in sorted(report.get("tenant_bill", {}).items())
    ]


def render_text(report: Dict) -> str:
    lines = ["== run report =="]
    lines += _summary_lines(report)
    if report["epochs"]:
        lines += ["", "-- epochs --"]
        lines += _text_table(
            [h for h, _ in _EPOCH_COLS], _epoch_rows(report)
        )
    if report.get("goodput"):
        lines += ["", "-- goodput (wall-time fraction per category) --"]
        headers, rows = _goodput_cols(report)
        lines += _text_table(headers, rows)
    if report.get("canary"):
        lines += ["", "-- canary ladder (publish -> shadow -> verdict) --"]
        lines += _text_table(list(_CANARY_HEADERS), _canary_rows(report))
    if report["programs"]:
        lines += ["", "-- compiled programs (XLA cost/memory) --"]
        lines += _text_table(
            [h for h, _ in _PROGRAM_COLS], _program_rows(report)
        )
    if report.get("collectives"):
        lines += ["", "-- collective bytes (per mesh axis, summed over "
                  "captured programs) --"]
        for axis in sorted(report["collectives"]):
            lines.append(
                f"{axis}: {_fmt_bytes(report['collectives'][axis])}"
            )
    if report.get("trace_anatomy"):
        n = report["trace_anatomy"].get("traces", 0)
        lines += ["", f"-- request latency anatomy ({n} traced "
                  "request(s)) --"]
        lines += _text_table(list(_ANATOMY_HEADERS), _anatomy_rows(report))
    if report.get("tenant_bill"):
        lines += ["", "-- tenant bill (device-time attribution) --"]
        lines += _text_table(list(_BILL_HEADERS), _bill_rows(report))
    if report.get("quality"):
        lines += ["", "-- model quality (drift vs pinned reference) --"]
        lines += _quality_summary(report)
        rows = _quality_rows(report)
        if rows:
            lines += _text_table(list(_QUALITY_HEADERS), rows)
    if report["timeline"]:
        lines += ["", "-- timeline (s after first event) --"]
        for item in report["timeline"]:
            lines.append(
                f"{item['t']:>10.3f}  {item['event']:<20} {item['detail']}"
            )
    return "\n".join(lines) + "\n"


def render_markdown(report: Dict) -> str:
    lines = [f"# Run report: {report['run']['run']}", ""]
    lines += [line + "  " for line in _summary_lines(report)]
    if report["epochs"]:
        lines += ["", "## Epochs", ""]
        lines += _md_table([h for h, _ in _EPOCH_COLS], _epoch_rows(report))
    if report.get("goodput"):
        lines += ["", "## Goodput (wall-time fraction per category)", ""]
        headers, rows = _goodput_cols(report)
        lines += _md_table(headers, rows)
    if report.get("canary"):
        lines += ["", "## Canary ladder (publish -> shadow -> verdict)", ""]
        lines += _md_table(list(_CANARY_HEADERS), _canary_rows(report))
    if report["programs"]:
        lines += ["", "## Compiled programs (XLA cost/memory)", ""]
        lines += _md_table(
            [h for h, _ in _PROGRAM_COLS], _program_rows(report)
        )
    if report.get("collectives"):
        lines += ["", "## Collective bytes (per mesh axis)", ""]
        lines += _md_table(
            ["axis", "bytes"],
            [
                [axis, _fmt_bytes(report["collectives"][axis])]
                for axis in sorted(report["collectives"])
            ],
        )
    if report.get("trace_anatomy"):
        n = report["trace_anatomy"].get("traces", 0)
        lines += ["", f"## Request latency anatomy ({n} traced "
                  "request(s))", ""]
        lines += _md_table(list(_ANATOMY_HEADERS), _anatomy_rows(report))
    if report.get("tenant_bill"):
        lines += ["", "## Tenant bill (device-time attribution)", ""]
        lines += _md_table(list(_BILL_HEADERS), _bill_rows(report))
    if report.get("quality"):
        lines += ["", "## Model quality (drift vs pinned reference)", ""]
        lines += [line + "  " for line in _quality_summary(report)]
        rows = _quality_rows(report)
        if rows:
            lines += [""] + _md_table(list(_QUALITY_HEADERS), rows)
    if report["timeline"]:
        lines += ["", "## Timeline", ""]
        lines += _md_table(
            ["t (s)", "event", "detail"],
            [
                [f"{i['t']:.3f}", i["event"], i["detail"]]
                for i in report["timeline"]
            ],
        )
    return "\n".join(lines) + "\n"


def render_json(report: Dict) -> str:
    return json.dumps(report, indent=2, sort_keys=True) + "\n"


RENDERERS = {
    "text": render_text,
    "markdown": render_markdown,
    "json": render_json,
}


# ---- perf-budget ratchet -------------------------------------------------


def budget_from_report(report: Dict,
                       tolerance: float = DEFAULT_TOLERANCE) -> Dict:
    """The committed-baseline content for this run's compiled programs.

    When the run produced an MFU figure for a bucket (goodput ledger +
    a resolvable peak — see docs/observability.md "Goodput & MFU"), it is
    recorded as that bucket's ``mfu_floor``: the check direction INVERTS
    for it (dropping below floor x (1 - tolerance) fails), so an MFU
    regression gates CI exactly like a step-cost regression."""
    programs = {}
    for key, p in sorted(report["programs"].items()):
        entry = {
            m: p[m] for m in BUDGET_METRICS if p.get(m) is not None
        }
        if p.get("mfu") is not None:
            entry["mfu_floor"] = p["mfu"]
        if entry:
            programs[key] = entry
    return {
        "version": BUDGET_VERSION,
        "tolerance": tolerance,
        "programs": programs,
    }


def load_budget(path: str) -> Dict:
    with open(path) as f:
        budget = json.load(f)
    if not isinstance(budget, dict) or "programs" not in budget:
        raise ValueError(f"{path}: not a perf-budget file (no 'programs')")
    if budget.get("version", BUDGET_VERSION) != BUDGET_VERSION:
        raise ValueError(
            f"{path}: budget version {budget.get('version')} != "
            f"{BUDGET_VERSION}"
        )
    return budget


def check_budget(
    report: Dict, budget: Dict, tolerance: Optional[float] = None
) -> Tuple[List[Dict], List[str], List[str]]:
    """(violations, unbudgeted, stale).

    A VIOLATION is a budgeted figure the run exceeded beyond tolerance —
    the gate's exit-1 condition. ``unbudgeted`` programs (in the run, not
    the baseline) and ``stale`` entries (in the baseline, not the run)
    are surfaced for the operator but do not fail: new buckets appear
    legitimately, and the ratchet only tightens by an explicit
    ``--write-budget`` commit."""
    tol = (
        float(tolerance)
        if tolerance is not None
        else float(budget.get("tolerance", DEFAULT_TOLERANCE))
    )
    violations = []
    for key, baseline in sorted(budget["programs"].items()):
        current = report["programs"].get(key)
        if current is None:
            continue
        for metric, base in baseline.items():
            if metric == "mfu_floor":
                # lower-bound metric: the run's MFU must not DROP below
                # floor x (1 - tolerance). A run with no MFU at all
                # (no peak-FLOPs entry, introspection off) is a note in
                # the CLI, never a silent pass-as-violation.
                cur = current.get("mfu")
                if cur is None or base is None:
                    continue
                limit = float(base) * (1.0 - tol)
                if float(cur) < limit:
                    violations.append(
                        {
                            "bucket": key,
                            "metric": metric,
                            "baseline": float(base),
                            "limit": limit,
                            "current": float(cur),
                            "ratio": float(cur) / float(base)
                            if base
                            else 0.0,
                        }
                    )
                continue
            cur = current.get(metric)
            if cur is None or base is None:
                continue
            limit = float(base) * (1.0 + tol)
            if float(cur) > limit:
                violations.append(
                    {
                        "bucket": key,
                        "metric": metric,
                        "baseline": float(base),
                        "limit": limit,
                        "current": float(cur),
                        "ratio": float(cur) / float(base)
                        if base
                        else float("inf"),
                    }
                )
    unbudgeted = sorted(
        set(report["programs"]) - set(budget["programs"])
    )
    stale = sorted(set(budget["programs"]) - set(report["programs"]))
    return violations, unbudgeted, stale
