"""Structured run events: one append-only JSONL stream per training run.

The "what did this run do" half of the telemetry layer (the live
``/metrics`` endpoint is the "what is it doing right now" half — both are
fed from the same recording sites). Every line is one JSON object with a
fixed envelope:

    {"event": <type>, "ts": <unix seconds>, "seq": <per-run monotonic int>, ...}

plus the event-type payload fields listed in :data:`EVENT_FIELDS` (the
documented schema — docs/observability.md mirrors this table). Unknown
event types are allowed (forward compatibility: a newer writer must not
break an older validator), but a KNOWN type missing a required field is a
schema violation.

Writes are line-buffered appends by rank 0 only; a killed job leaves a
valid prefix (every fsync'd line parses), never a torn stream.
"""

import json
import math
import os
import threading
import time
from typing import Dict, List, Optional

SCHEMA_VERSION = 1

# event type -> required payload fields (on top of the envelope)
EVENT_FIELDS: Dict[str, tuple] = {
    "run_manifest": (
        "schema_version", "run", "config_hash", "git_rev", "world_size",
        "device_kind", "device_count", "num_epoch",
    ),
    "epoch": (
        "epoch", "train_loss", "val_loss", "test_loss", "mode",
    ),
    "fit_chunk": ("epoch_start", "epochs", "wall_time_s"),
    "staged": ("num_batches",),
    "checkpoint_saved": ("name", "kind"),
    "checkpoint_restored": ("name", "source"),
    "guard_skip": ("scope", "skipped"),
    "guard_restore": ("restores", "lr"),
    "resume": ("start_epoch",),
    "early_stop": ("epoch",),
    "wallclock_stop": ("epoch",),
    "tracer_totals": ("regions",),
    "run_end": ("status",),
    # XLA introspection (obs/introspect.py): one per novel compiled
    # (program, shape-signature); cost/memory are the normalized
    # cost_analysis()/memory_analysis() dicts ({} on backends without the
    # respective model)
    "compile": ("name", "bucket", "cost", "memory"),
    # flight recorder: a step dispatch exceeded stall_factor x the rolling
    # median of the last K steps
    "stall": ("step", "seconds", "median", "factor"),
    # on-demand trace capture lifecycle (armed -> started -> done)
    "profile": ("status",),
    # device memory report (parallel.distributed.print_peak_memory)
    "device_memory": ("devices",),
    # lock sanitizer watchdog (analysis/guards.py): a lock acquisition
    # blocked past the threshold; threads carries every thread's held
    # locks + stack at the moment of the dump
    "deadlock_suspect": ("lock", "waited_s", "threads"),
    # aggregation autotuner (ops/autotune.py): which kernel family one
    # bucket layout uses and why — source is env|cache|measured (optional
    # timings_ms carries the measured candidate times)
    "agg_choice": ("bucket", "choice", "source"),
    # elastic training (train/elastic.py): a peer's heartbeat lease
    # expired — emitted by the detecting watchdog just before it breaks
    # the survivors out of the hung collective
    "host_lost": ("host",),
    # elastic training: the world re-formed at a new size and took its
    # first optimizer step; recovery_s spans loss detection -> first step
    # (teardown + re-bootstrap + checkpoint restore + recompile). 2-D
    # runs also carry mesh_shape=[d, m] (parallel/mesh.py re-derivation)
    "world_resize": ("old_world", "new_world", "gen", "recovery_s"),
    # mesh resolution (parallel/mesh.py): the run's device mesh — axis
    # names, [d, m] shape ([] when running unmeshed on one device), and
    # the visible device count the shape was derived from
    "mesh_shape": ("axes", "shape", "devices"),
    # partition-rule placement summary (parallel/rules.py): how many
    # train-state leaves (and bytes) the rule engine sharded vs
    # replicated — "everything silently replicated" regressions are
    # visible from the event stream alone
    "param_sharding": (
        "total_leaves", "sharded", "replicated", "sharded_bytes",
        "replicated_bytes",
    ),
    # streaming bucket planner (data/stream/planner.py): an auto-tuned
    # bucket plan was built from a streamed size histogram — bounds are
    # the inclusive node-count bucket boundaries, est_waste the simulated
    # padding-waste ratio of the plan over the scanned samples
    "bucket_plan": ("num_buckets", "bounds", "samples_scanned", "est_waste"),
    # HPO trial lifecycle (hpo/launcher.py trials.jsonl): status is
    # completed|failed|killed, reason names the failure/kill cause
    # (garbled_output, heartbeat_timeout, divergence, timeout, exit_<rc>)
    "hpo_trial": ("trial", "status"),
    # serving fleet (serve/fleet.py): a replica's lease expired or its
    # process died — the serving twin of host_lost (reason is
    # exit|lease_expired|killed)
    "replica_lost": ("replica", "reason"),
    # serving fleet: the supervisor respawned a lost replica and its new
    # incarnation reported serving; downtime_s spans detection -> first
    # serving lease (the serving twin of world_resize's recovery_s)
    "replica_respawned": ("replica", "downtime_s"),
    # hot-swap (serve/fleet.py + serve/registry.py): a candidate version
    # was warmed on every live replica (per-bucket, compile-counter
    # verified) and atomically promoted to serve version-less requests
    "model_promoted": ("name", "version"),
    # hot-swap: a candidate was rejected (CRC/strict-load failure, warmup
    # failure, ack timeout) — the old version never stopped serving
    "model_rollback": ("name", "reason"),
    # serving fleet: live replica count dropped below target (the
    # degradation ladder's trigger — the router sheds low-priority lanes
    # while this holds)
    "fleet_degraded": ("live", "target"),
    # closed-loop load generator (benchmarks/serve_bench.py --fleet,
    # tests/_fleet_smoke.py): one measured traffic window — availability
    # = terminally-succeeded / submitted logical requests
    "fleet_report": ("submitted", "succeeded", "availability"),
    # canary channel (serve/registry.py CandidateChannel): rank 0 of the
    # training side published a candidate checkpoint snapshot at
    # end-of-epoch cadence for the canary controller to prove out —
    # `candidate` is the channel sequence number (NOT the envelope seq)
    "candidate_published": ("candidate", "checkpoint"),
    # canary controller (serve/canary.py): a published candidate booted
    # on a dedicated canary replica and entered shadow evaluation —
    # live traffic is mirrored to it, its answers never returned
    "canary_started": ("candidate", "checkpoint"),
    # canary controller: every statistical gate passed over >= the
    # min-sample floor and the PR 15 all-acked hot-swap promoted the
    # candidate to active
    "canary_promoted": ("candidate", "checkpoint", "samples"),
    # canary controller: the candidate was rejected before ever serving
    # a live request — reason names the failed gate (nan_outputs,
    # head_mae, latency, shadow_errors, crash_loop, insufficient_samples,
    # superseded, or the hot-swap's own rollback reason)
    "canary_rejected": ("candidate", "checkpoint", "reason"),
    # goodput ledger (obs/ledger.py): one per epoch window — `seconds`
    # and `fractions` map every CATEGORIES entry (compute/data_stall/
    # collective/checkpoint/compile/guard_recovery/eval/other) to its
    # attributed wall time / fraction (fractions sum to 1 by
    # construction); optional `mfu` carries per-bucket
    # {mfu, flops, steps_per_sec, peak_flops}
    "goodput": ("epoch", "wall_s", "seconds", "fractions",
                "goodput_fraction"),
    # multi-tenant serving (serve/tenants.py): one per spec'd tenant at
    # fleet start — the audit record of who is HBM-packed into the fleet
    # with which model and what admission quota
    "tenant_admitted": ("tenant", "model", "quota"),
    # response cache (serve/cache.py): a measured traffic window's cache
    # counters, appended by the bench/smoke load generators
    "cache_stats": ("hits", "misses", "evictions", "bytes"),
    # predictive autoscaler (serve/autoscale.py) / ServingFleet.resize:
    # the supervised replica target moved (reason names the trigger —
    # slo_pressure, forecast, scale_down, manual)
    "fleet_scaled": ("old_target", "new_target", "reason"),
    # request tracing (obs/trace.py): one span of one request's causal
    # tree — trace/span/parent are the tree ids (parent "" on the root),
    # name is the segment (route/admit/cache_lookup/backoff/attempt on
    # the router; queue_wait/batch_form/dispatch/readback on the
    # replica), start is wall-clock unix seconds, dur_s the span's
    # duration, attrs the per-span labels (tenant, lane, bucket, replica
    # rid, cache hit/miss, retry ordinal, shed reason, ...). Flushed
    # tail-based at the request's terminal outcome
    "span": ("trace", "span", "parent", "name", "start", "dur_s", "attrs"),
    # cost->quota feedback (serve/costs.py, HYDRAGNN_TENANT_COST_QUOTAS):
    # a tenant's admission quota was shaved (reason over_cost) or its
    # base quota restored (reason restored); cost_share is the tenant's
    # share of the window's device time, fair_share its weight-
    # proportional entitlement
    "quota_adjusted": ("tenant", "old_quota", "new_quota", "reason",
                       "cost_share", "fair_share"),
    # tenant cost ledger (serve/costs.py): one per-tenant bill row for a
    # measured window, appended by the bench/smoke load generators —
    # device_s is attributed device wall-time, replica_s the window's
    # fleet integrated replica-seconds the rows (plus idle) sum to
    "tenant_cost": ("tenant", "device_s", "flops", "requests",
                    "replica_s"),
    # model-quality observatory (obs/drift.py DriftDetector): one per
    # completed tumbling window — scores maps "tenant|feature|head" to
    # {psi, ks} vs the version-pinned reference; optional `uncertainty`
    # carries per-"tenant|head" predictive-variance quantiles
    "drift_window": ("version", "window", "scores"),
    # model-quality observatory: a feature's drift score crossed the
    # hysteresis threshold (status raised) or came back under it for
    # clear_after consecutive windows (status cleared) — always scored
    # vs what `version` was vetted on, never a moving baseline
    "drift_alert": (
        "tenant", "feature", "head", "kind", "score", "status",
        "version",
    ),
    # feedback sink (serve/quality.py FeedbackSink): cumulative queue-
    # dir counters at each pack flush — accepted (buffered for
    # labeling), deduped (canonical_graph_key repeats), graphs/packs
    # (persisted shard_store totals)
    "feedback_sink": ("accepted", "deduped", "graphs", "packs"),
    # NaN sentinel (analysis/guards.py nan_sentinel / nan_origin): the
    # runtime half of the numlint numerics suite — a wrapped step or a
    # canary shadow answer produced a non-finite value. scope names the
    # wrapped region (train_step, canary:<candidate>), origin the FIRST
    # non-finite leaf's pytree path, subtree its leading component (the
    # head/param group to blame), leaves/total the non-finite/total leaf
    # counts of the output tree
    "nan_origin": ("scope", "origin", "subtree", "leaves", "total"),
}

_ENVELOPE = ("event", "ts", "seq")


def _jsonable(obj):
    """json.dump default hook: numpy scalars/arrays -> plain python."""
    import numpy as np

    if isinstance(obj, np.integer):
        return int(obj)
    if isinstance(obj, np.floating):
        return float(obj)
    if isinstance(obj, np.ndarray):
        return obj.tolist()
    return str(obj)


def _nullify_nonfinite(obj):
    """Strict JSON has no NaN/Infinity tokens; a diverged epoch's losses
    map to null instead of producing a line jq/JS/Go consumers reject."""
    if isinstance(obj, float) and not math.isfinite(obj):
        return None
    if isinstance(obj, dict):
        return {k: _nullify_nonfinite(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [_nullify_nonfinite(v) for v in obj]
    return obj


def _repair_torn_tail(path: str):
    """A hard kill mid-write can leave a final line with no terminating
    newline; appending to it would merge the partial garbage with the
    resumed run's first event into one corrupt line. The partial line
    never completed — drop it (truncate to the last newline) so the
    stream stays a valid prefix."""
    try:
        size = os.path.getsize(path)
        if size == 0:
            return
        with open(path, "rb+") as f:
            f.seek(max(size - 65536, 0))
            tail = f.read()
            if tail.endswith(b"\n"):
                return
            cut = tail.rfind(b"\n")
            f.truncate(size - len(tail) + (cut + 1 if cut >= 0 else 0))
    except OSError:
        pass


def _next_seq(path: str) -> int:
    """seq the next event appended to ``path`` should carry: last line's
    seq + 1 (0 for a fresh/empty/unreadable stream). Reads only the tail."""
    try:
        size = os.path.getsize(path)
        if size == 0:
            return 0
        with open(path, "rb") as f:
            f.seek(max(size - 65536, 0))
            tail = f.read().decode(errors="replace").strip().splitlines()
        for line in reversed(tail):
            line = line.strip()
            if not line:
                continue
            try:
                return int(json.loads(line).get("seq", -1)) + 1
            except (ValueError, TypeError):
                continue  # unparseable line — walk back to a complete one
        return 0
    except OSError:
        return 0


class RunEventLog:
    """Append-only JSONL event stream for one run (thread-safe)."""

    def __init__(self, path: str):
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        self.path = path
        self._lock = threading.Lock()
        # a rerun/resume of the same run name APPENDS to the existing
        # stream — seq must continue where the previous process left off,
        # or the stream reads as torn
        _repair_torn_tail(path)
        self._seq = _next_seq(path)
        self._f = open(path, "a", buffering=1)  # line-buffered: crash-safe

    def emit(self, event: str, **fields):
        """Append one event. Never raises into the training loop — a full
        disk must not kill a run that would otherwise finish."""
        with self._lock:
            if self._f is None:
                return
            rec = {"event": event, "ts": round(time.time(), 6),
                   "seq": self._seq}
            rec.update(fields)
            try:
                try:
                    line = json.dumps(
                        rec, default=_jsonable, allow_nan=False
                    )
                except ValueError:
                    # non-finite floats (a diverged epoch's NaN losses —
                    # exactly what this stream must record): null them
                    # rather than emit a non-standard NaN token or drop
                    # the event
                    line = json.dumps(
                        _nullify_nonfinite(
                            json.loads(json.dumps(rec, default=_jsonable))
                        ),
                        allow_nan=False,
                    )
                # the write must stay in the critical section: seq order
                # ON DISK must match assignment order, and interleaved
                # writes from two emitters would tear the JSONL stream
                # threadlint: disable=blocking-under-lock
                self._f.write(line + "\n")
                self._seq += 1
            except (OSError, ValueError, TypeError):
                pass

    def close(self):
        with self._lock:
            if self._f is not None:
                try:
                    self._f.close()
                finally:
                    self._f = None


def validate_events(
    path: str, require: Optional[List[str]] = None
) -> List[Dict]:
    """Parse + schema-check an ``events.jsonl`` stream.

    Checks every line parses, envelopes are complete, ``seq`` is strictly
    increasing from 0, known event types carry their required fields
    (:data:`EVENT_FIELDS`), and each type in ``require`` appears at least
    once. Returns the parsed records; raises ``ValueError`` on the first
    violation — this is the CI gate's validator as well as the tests'.
    """
    records = []
    with open(path) as f:
        for lineno, line in enumerate(f, 1):
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError as e:
                raise ValueError(
                    f"{path}:{lineno}: unparseable event line ({e})"
                ) from e
            for k in _ENVELOPE:
                if k not in rec:
                    raise ValueError(
                        f"{path}:{lineno}: event missing envelope "
                        f"field {k!r}"
                    )
            if rec["seq"] != len(records):
                raise ValueError(
                    f"{path}:{lineno}: seq {rec['seq']} != expected "
                    f"{len(records)} (stream torn or interleaved)"
                )
            needed = EVENT_FIELDS.get(rec["event"], ())
            missing = [k for k in needed if k not in rec]
            if missing:
                raise ValueError(
                    f"{path}:{lineno}: event {rec['event']!r} missing "
                    f"required fields {missing}"
                )
            records.append(rec)
    if require:
        seen = {r["event"] for r in records}
        absent = [t for t in require if t not in seen]
        if absent:
            raise ValueError(
                f"{path}: required event types never emitted: {absent}"
            )
    return records
