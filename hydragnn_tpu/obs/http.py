"""Stdlib ``/healthz`` + ``/metrics`` listener, shared by serving and training.

Promoted out of ``hydragnn_tpu/serve/http.py`` (PR 2): the listener never
cared that its provider was an inference server — it needs exactly two
things, a ``health() -> dict`` method (``status`` key decides 200 vs 503)
and a ``metrics.render_prometheus() -> str`` attribute. Training's
:class:`~hydragnn_tpu.obs.runtime.RunTelemetry` satisfies the same
protocol, so one listener serves both; ``hydragnn_tpu.serve.http``
re-exports this class unchanged.

``GET /healthz`` — JSON liveness/readiness; non-2xx when the provider
reports a non-ok status, so a load balancer can eject the replica (or an
operator can spot a wedged training job). ``GET /metrics`` — Prometheus
text exposition. ``GET /profile?steps=N`` — arm ``jax.profiler`` device
trace capture for the next N steps of the live run, when the provider
implements ``profile(steps) -> dict`` (training's ``RunTelemetry`` does;
providers without it answer 501).

``http.server`` only (the container bakes in no web framework); the
listener runs on a daemon thread and ``port=0`` binds an ephemeral port
(tests and multi-replica hosts), readable from ``address`` after
``start()``.
"""

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional, Tuple


class _Listener(ThreadingHTTPServer):
    """SO_REUSEADDR explicitly on: tests and CI smokes restart listeners
    back-to-back, and a close()d socket lingering in TIME_WAIT must not
    fail the rebind. Handler threads are daemonic so one hung in-flight
    scrape cannot block interpreter exit (the listener thread itself is
    joined with a bounded timeout in :meth:`ObservabilityServer.stop`)."""

    allow_reuse_address = True  # SO_REUSEADDR
    daemon_threads = True


class ObservabilityServer:
    """Serves ``/healthz`` + ``/metrics`` for one provider object
    (an :class:`~hydragnn_tpu.serve.server.InferenceServer`, a training
    :class:`~hydragnn_tpu.obs.runtime.RunTelemetry`, ...).

    Lifecycle is idempotent and thread-safe: ``start()`` on a started
    listener and ``stop()`` on a stopped one are no-ops, and concurrent
    ``stop()`` calls race safely. Two locks, always lifecycle -> state:
    ``_lifecycle_lock`` serializes whole start/stop TRANSITIONS (so a
    restart on a fixed port cannot bind before the previous socket is
    actually closed — SO_REUSEADDR covers TIME_WAIT, not a still-open
    listener), while the quick ``_state_lock`` guards the handle pair so
    :attr:`address` never blocks behind a slow shutdown. ``port=0``
    binds an ephemeral port; read the real one from :attr:`address`
    after ``start()`` — fixed test ports collide under parallel CI,
    ephemeral ones cannot."""

    def __init__(self, provider, port: int = 8080,
                 host: str = "127.0.0.1"):
        self._provider = provider
        self._host = host
        self._port = port
        self._lifecycle_lock = threading.Lock()
        self._state_lock = threading.Lock()
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None

    def start(self):
        provider = self._provider

        class Handler(BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802 (stdlib naming)
                if self.path == "/healthz":
                    health = provider.health()
                    body = json.dumps(health).encode()
                    code = 200 if health.get("status") == "ok" else 503
                    ctype = "application/json"
                elif self.path == "/metrics":
                    body = provider.metrics.render_prometheus().encode()
                    code = 200
                    ctype = "text/plain; version=0.0.4"
                elif self.path.split("?", 1)[0] == "/profile":
                    profile = getattr(provider, "profile", None)
                    if profile is None:
                        body = (
                            b"this provider does not support on-demand "
                            b"profiling\n"
                        )
                        code = 501
                        ctype = "text/plain"
                    else:
                        from urllib.parse import parse_qs, urlsplit

                        qs = parse_qs(urlsplit(self.path).query)
                        try:
                            steps = int(qs.get("steps", ["3"])[0])
                        except ValueError:
                            steps = -1  # profile() rejects with an error
                        result = profile(steps)
                        body = json.dumps(result).encode()
                        code = 200 if result.get("status") in (
                            "armed", "busy"
                        ) else 400
                        ctype = "application/json"
                else:
                    body = (
                        b"not found: this endpoint exposes /healthz, "
                        b"/metrics and /profile\n"
                    )
                    code = 404
                    ctype = "text/plain"
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *args):  # scrape spam off stderr
                pass

        with self._lifecycle_lock:
            with self._state_lock:
                if self._httpd is not None:
                    return self
            httpd = _Listener((self._host, self._port), Handler)
            # daemon=True is the crashed-caller backstop; the orderly
            # path is stop(), which shuts the loop down and joins
            thread = threading.Thread(
                target=httpd.serve_forever,
                name="hydragnn-observability",
                daemon=True,
            )
            thread.start()
            with self._state_lock:
                self._httpd, self._thread = httpd, thread
        return self

    @property
    def address(self) -> Optional[Tuple[str, int]]:
        """(host, port) actually bound — port 0 resolves here."""
        with self._state_lock:
            if self._httpd is None:
                return None
            return self._httpd.server_address[:2]

    def stop(self, timeout: float = 5.0):
        # the whole teardown runs under the lifecycle lock: a concurrent
        # start() on the same fixed port must wait until server_close()
        # has actually released the socket, or its bind hits EADDRINUSE.
        # The quick state lock still hands the pair to exactly one
        # closer (concurrent/repeated stop() calls are race-free
        # no-ops) and is dropped before the blocking shutdown/join, so
        # address readers never stall behind a slow teardown.
        with self._lifecycle_lock:
            with self._state_lock:
                httpd, thread = self._httpd, self._thread
                self._httpd = None
                self._thread = None
            if httpd is None:
                return
            httpd.shutdown()
            httpd.server_close()
            if thread is not None:
                thread.join(timeout)
