"""Stdlib ``/healthz`` + ``/metrics`` listener, shared by serving and training.

Promoted out of ``hydragnn_tpu/serve/http.py`` (PR 2): the listener never
cared that its provider was an inference server — it needs exactly two
things, a ``health() -> dict`` method (``status`` key decides 200 vs 503)
and a ``metrics.render_prometheus() -> str`` attribute. Training's
:class:`~hydragnn_tpu.obs.runtime.RunTelemetry` satisfies the same
protocol, so one listener serves both; ``hydragnn_tpu.serve.http``
re-exports this class unchanged.

``GET /healthz`` — JSON liveness/readiness; non-2xx when the provider
reports a non-ok status, so a load balancer can eject the replica (or an
operator can spot a wedged training job). ``GET /metrics`` — Prometheus
text exposition. ``GET /profile?steps=N`` — arm ``jax.profiler`` device
trace capture for the next N steps of the live run, when the provider
implements ``profile(steps) -> dict`` (training's ``RunTelemetry`` does;
providers without it answer 501).

``http.server`` only (the container bakes in no web framework); the
listener runs on a daemon thread and ``port=0`` binds an ephemeral port
(tests and multi-replica hosts), readable from ``address`` after
``start()``.
"""

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional, Tuple


class ObservabilityServer:
    """Serves ``/healthz`` + ``/metrics`` for one provider object
    (an :class:`~hydragnn_tpu.serve.server.InferenceServer`, a training
    :class:`~hydragnn_tpu.obs.runtime.RunTelemetry`, ...)."""

    def __init__(self, provider, port: int = 8080,
                 host: str = "127.0.0.1"):
        self._provider = provider
        self._host = host
        self._port = port
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None

    def start(self):
        if self._httpd is not None:
            return self
        provider = self._provider

        class Handler(BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802 (stdlib naming)
                if self.path == "/healthz":
                    health = provider.health()
                    body = json.dumps(health).encode()
                    code = 200 if health.get("status") == "ok" else 503
                    ctype = "application/json"
                elif self.path == "/metrics":
                    body = provider.metrics.render_prometheus().encode()
                    code = 200
                    ctype = "text/plain; version=0.0.4"
                elif self.path.split("?", 1)[0] == "/profile":
                    profile = getattr(provider, "profile", None)
                    if profile is None:
                        body = (
                            b"this provider does not support on-demand "
                            b"profiling\n"
                        )
                        code = 501
                        ctype = "text/plain"
                    else:
                        from urllib.parse import parse_qs, urlsplit

                        qs = parse_qs(urlsplit(self.path).query)
                        try:
                            steps = int(qs.get("steps", ["3"])[0])
                        except ValueError:
                            steps = -1  # profile() rejects with an error
                        result = profile(steps)
                        body = json.dumps(result).encode()
                        code = 200 if result.get("status") in (
                            "armed", "busy"
                        ) else 400
                        ctype = "application/json"
                else:
                    body = (
                        b"not found: this endpoint exposes /healthz, "
                        b"/metrics and /profile\n"
                    )
                    code = 404
                    ctype = "text/plain"
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *args):  # scrape spam off stderr
                pass

        self._httpd = ThreadingHTTPServer((self._host, self._port), Handler)
        self._thread = threading.Thread(
            target=self._httpd.serve_forever,
            name="hydragnn-observability",
            daemon=True,
        )
        self._thread.start()
        return self

    @property
    def address(self) -> Optional[Tuple[str, int]]:
        """(host, port) actually bound — port 0 resolves here."""
        if self._httpd is None:
            return None
        return self._httpd.server_address[:2]

    def stop(self):
        if self._httpd is None:
            return
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread is not None:
            self._thread.join(5.0)
            self._thread = None
        self._httpd = None
