"""ScalarWriter: backend-agnostic per-epoch scalar fan-out.

Replaces the epoch driver's direct TensorBoard coupling: the driver used
to import ``torch.utils.tensorboard`` behind a bare ``except Exception``
and silently log NOTHING when torch was absent (``driver.py``). Now every
run gets an always-on plain-file backend (JSONL by default, CSV via
``HYDRAGNN_SCALAR_FORMAT=csv``) with zero optional dependencies, and the
TensorBoard backend rides along when torch is importable — its absence is
warned exactly once per process, on rank 0, instead of swallowed.

The writer implements the subset of the ``SummaryWriter`` protocol the
epoch driver uses (``add_scalar(tag, value, step)``, ``close()``), so it
drops into the existing ``writer=`` plumbing unchanged. Tracer region
totals are forwarded through the same fan-out at end of run
(:meth:`ScalarWriter.add_regions`).
"""

import csv
import json
import os
import time
import warnings
from typing import Dict, List, Optional

_tb_warned = False  # TensorBoard-unavailable warning fires once per process


class JsonlScalarBackend:
    """Always-on backend: one JSON object per scalar, append-only."""

    def __init__(self, path: str):
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        self._f = open(path, "a", buffering=1)

    def add_scalar(self, tag: str, value, step):
        try:
            self._f.write(
                json.dumps(
                    {
                        "tag": tag,
                        "value": float(value),
                        "step": int(step),
                        "ts": round(time.time(), 6),
                    }
                )
                + "\n"
            )
        except (OSError, ValueError, TypeError):
            pass

    def close(self):
        try:
            self._f.close()
        except OSError:
            pass


class CsvScalarBackend:
    """Plain-file alternative for spreadsheet-side consumers."""

    _HEADER = ("tag", "value", "step", "ts")

    def __init__(self, path: str):
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        write_header = not os.path.exists(path) or os.path.getsize(path) == 0
        self._f = open(path, "a", newline="", buffering=1)
        self._w = csv.writer(self._f)
        if write_header:
            self._w.writerow(self._HEADER)

    def add_scalar(self, tag: str, value, step):
        try:
            self._w.writerow(
                [tag, float(value), int(step), round(time.time(), 6)]
            )
        except (OSError, ValueError, TypeError):
            pass

    def close(self):
        try:
            self._f.close()
        except OSError:
            pass


class TensorBoardScalarBackend:
    """The historical backend, kept when torch is importable."""

    def __init__(self, log_dir: str):
        from torch.utils.tensorboard import SummaryWriter

        self._writer = SummaryWriter(log_dir)

    def add_scalar(self, tag: str, value, step):
        self._writer.add_scalar(tag, value, step)

    def close(self):
        self._writer.close()


class ScalarWriter:
    """Fan one ``add_scalar`` call out to every configured backend.

    Backend failures are isolated: a TensorBoard event file hitting a full
    disk mid-run must not kill a training run that would otherwise finish
    (the file backends swallow their own OSErrors for the same reason)."""

    def __init__(self, backends: List):
        self.backends = list(backends)

    def add_scalar(self, tag: str, value, step):
        for b in self.backends:
            try:
                b.add_scalar(tag, value, step)
            except Exception:
                pass

    def add_regions(self, regions: Dict[str, float], step: int = 0):
        """Forward tracer region totals (``tracer.totals()``) as
        ``tracer/<region>_seconds`` scalars."""
        for name, seconds in sorted(regions.items()):
            self.add_scalar(f"tracer/{name}_seconds", seconds, step)

    def close(self):
        for b in self.backends:
            try:
                b.close()
            except Exception:
                pass  # one backend's close failure must not skip the rest

    @classmethod
    def for_run(
        cls, log_name: str, path: str = "./logs/"
    ) -> Optional["ScalarWriter"]:
        """The run-scoped writer: rank 0 only (None elsewhere, same
        contract as the old ``_get_summary_writer``), file backend always,
        TensorBoard when available."""
        global _tb_warned
        from hydragnn_tpu.parallel.distributed import get_comm_size_and_rank

        _, rank = get_comm_size_and_rank()
        if rank != 0:
            return None
        log_dir = os.path.join(path, log_name)
        fmt = os.getenv("HYDRAGNN_SCALAR_FORMAT", "jsonl").strip().lower()
        if fmt == "csv":
            backends = [CsvScalarBackend(os.path.join(log_dir, "scalars.csv"))]
        else:
            backends = [
                JsonlScalarBackend(os.path.join(log_dir, "scalars.jsonl"))
            ]
        try:
            backends.append(TensorBoardScalarBackend(log_dir))
        except Exception as e:
            if not _tb_warned:
                _tb_warned = True
                warnings.warn(
                    "TensorBoard scalar backend unavailable "
                    f"({type(e).__name__}: {e}); scalars still recorded by "
                    f"the {fmt} backend under {log_dir}",
                    stacklevel=2,
                )
        return cls(backends)
