"""``python -m hydragnn_tpu.obs`` — observability CLI.

Subcommands::

    report <logs/run | events.jsonl>
        [--format text|markdown|json]
        [--check-budget .perf-baseline.json] [--tolerance F]
        [--write-budget .perf-baseline.json]

    fleet <run-or-coordination dir>
        [--format text|markdown|json]
        [--straggler-factor F] [--min-steps N]

    trace <run-or-coordination dir | events.jsonl>
        [--format text|json] [--slow N]

    drift <run-or-coordination dir | events.jsonl>
        [--format text|json]

``fleet`` merges every per-host event stream (rank 0's ``events.jsonl``
plus the elastic hosts' ``events-host<k>.jsonl``) and the elastic
heartbeat leases' step-time digests found under the directory into one
cross-host view: per-host step-time distributions, straggler flags (host
p50 > factor x the leave-one-out fleet median), and ``world_resize``
recovery windows priced as lost goodput.

Exit status: 0 clean, 1 when ``--check-budget`` finds a figure over
budget (or under its MFU floor), 2 on usage errors (missing stream,
malformed budget, no fleet data). The CI gate runs the smoke training,
then::

    python -m hydragnn_tpu.obs report <run> --check-budget \
        .perf-baseline.json
"""

import argparse
import os
import sys

from hydragnn_tpu.obs import drift as drift_mod
from hydragnn_tpu.obs import ledger as ledger_mod
from hydragnn_tpu.obs import report as report_mod
from hydragnn_tpu.obs import trace as trace_mod


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m hydragnn_tpu.obs",
        description=(
            "post-mortem run reports + perf-budget ratchet "
            "(docs/observability.md)"
        ),
    )
    sub = p.add_subparsers(dest="command")
    rep = sub.add_parser(
        "report",
        help="render a run report from its events.jsonl",
    )
    rep.add_argument(
        "run", help="run directory (containing events.jsonl) or the "
        "stream itself",
    )
    rep.add_argument(
        "--format",
        choices=sorted(report_mod.RENDERERS),
        default="text",
        help="output format (default: text)",
    )
    rep.add_argument(
        "--check-budget",
        metavar="FILE",
        help="compare per-bucket compiled FLOPs/HBM against this "
        "baseline; exit 1 on any figure beyond tolerance",
    )
    rep.add_argument(
        "--tolerance",
        type=float,
        default=None,
        help="override the budget file's tolerance (fraction, e.g. 0.1)",
    )
    rep.add_argument(
        "--write-budget",
        metavar="FILE",
        help="write this run's compiled-cost figures (and MFU floors, "
        "when measured) as the new baseline",
    )
    fl = sub.add_parser(
        "fleet",
        help="merge an elastic run's per-host streams + heartbeat "
        "digests into one cross-host rollup",
    )
    fl.add_argument(
        "dir",
        help="run or coordination directory (searched recursively for "
        "events*.jsonl streams and workers/host-*.json leases)",
    )
    fl.add_argument(
        "--format",
        choices=sorted(ledger_mod.FLEET_RENDERERS),
        default="text",
        help="output format (default: text)",
    )
    fl.add_argument(
        "--straggler-factor",
        type=float,
        default=2.0,
        help="flag a host when its step p50 exceeds this multiple of "
        "the leave-one-out fleet median (default: 2.0)",
    )
    fl.add_argument(
        "--min-steps",
        type=int,
        default=3,
        help="hosts with fewer recorded steps neither flag nor count "
        "toward the median (default: 3)",
    )
    tr = sub.add_parser(
        "trace",
        help="reconstruct request span trees from the merged event "
        "streams and break down where the latency went",
    )
    tr.add_argument(
        "dir",
        help="run or coordination directory (searched recursively for "
        "events*.jsonl) or one stream file",
    )
    tr.add_argument(
        "--format",
        choices=("text", "json"),
        default="text",
        help="output format (default: text)",
    )
    tr.add_argument(
        "--slow",
        type=int,
        default=10,
        help="slowest traces to list with their dominant segment "
        "(default: 10)",
    )
    dr = sub.add_parser(
        "drift",
        help="model-quality report: drift scores vs the pinned "
        "reference, alert ledger, uncertainty quantiles, feedback sink",
    )
    dr.add_argument(
        "dir",
        help="run or coordination directory (searched recursively for "
        "events*.jsonl) or one stream file",
    )
    dr.add_argument(
        "--format",
        choices=("text", "json"),
        default="text",
        help="output format (default: text)",
    )
    return p


def _run_report(args) -> int:
    events_path = report_mod.resolve_events_path(args.run)
    if not os.path.exists(events_path):
        print(f"obs report: no event stream at {events_path}",
              file=sys.stderr)
        return 2
    report = report_mod.build_report(report_mod.load_events(events_path))
    print(report_mod.RENDERERS[args.format](report), end="")

    if args.write_budget:
        budget = report_mod.budget_from_report(
            report,
            tolerance=(
                args.tolerance
                if args.tolerance is not None
                else report_mod.DEFAULT_TOLERANCE
            ),
        )
        if not budget["programs"]:
            print(
                "obs report: no compile events in the stream — nothing "
                "to budget (was introspection enabled?)",
                file=sys.stderr,
            )
            return 2
        import json

        with open(args.write_budget, "w") as f:
            json.dump(budget, f, indent=2, sort_keys=True)
            f.write("\n")
        print(
            f"obs report: wrote {len(budget['programs'])} program "
            f"budget(s) to {args.write_budget}",
            file=sys.stderr,
        )

    if args.check_budget:
        try:
            budget = report_mod.load_budget(args.check_budget)
        except FileNotFoundError:
            print(
                f"obs report: budget {args.check_budget} not found",
                file=sys.stderr,
            )
            return 2
        except ValueError as e:
            print(f"obs report: {e}", file=sys.stderr)
            return 2
        if budget["programs"] and not report["programs"]:
            # every baseline entry would degrade to a non-fatal 'stale'
            # note and the gate would pass having checked NOTHING —
            # a run with no compile events cannot satisfy a non-empty
            # budget (introspection off? telemetry never active?)
            print(
                "obs report: stream has no compile events but the "
                f"budget expects {len(budget['programs'])} program(s) — "
                "was introspection enabled for this run?",
                file=sys.stderr,
            )
            return 2
        violations, unbudgeted, stale = report_mod.check_budget(
            report, budget, tolerance=args.tolerance
        )
        for name in unbudgeted:
            print(
                f"obs report: note: {name} has no budget entry "
                "(new bucket? --write-budget to adopt it)",
                file=sys.stderr,
            )
        for name in stale:
            print(
                f"obs report: note: budget entry {name} matched no "
                "compiled program in this run",
                file=sys.stderr,
            )
        # an MFU floor the run could not measure (no peak-FLOPs entry,
        # telemetry off) is a NOTE, never a silent pass or a failure
        for name, entry in sorted(budget["programs"].items()):
            if "mfu_floor" not in entry:
                continue
            current = report["programs"].get(name)
            if current is not None and current.get("mfu") is None:
                print(
                    f"obs report: note: budget entry {name} has an MFU "
                    "floor but this run measured no MFU (peak FLOPs "
                    "unresolvable? goodput ledger inactive?)",
                    file=sys.stderr,
                )
        for v in violations:
            if v["metric"] == "mfu_floor":
                print(
                    f"obs report: UNDER MFU FLOOR: {v['bucket']} mfu "
                    f"{v['current']:.6g} < limit {v['limit']:.6g} "
                    f"(floor {v['baseline']:.6g}, x{v['ratio']:.3f})",
                    file=sys.stderr,
                )
            else:
                print(
                    f"obs report: OVER BUDGET: {v['bucket']} {v['metric']} "
                    f"{v['current']:.6g} > limit {v['limit']:.6g} "
                    f"(baseline {v['baseline']:.6g}, x{v['ratio']:.3f})",
                    file=sys.stderr,
                )
        if violations:
            return 1
        print(
            f"obs report: budget ok ({len(budget['programs'])} "
            f"program(s) checked)",
            file=sys.stderr,
        )
    return 0


def _run_fleet(args) -> int:
    if not os.path.isdir(args.dir):
        print(f"obs fleet: {args.dir} is not a directory", file=sys.stderr)
        return 2
    report = ledger_mod.build_fleet_report(
        args.dir,
        straggler_factor=args.straggler_factor,
        min_steps=args.min_steps,
    )
    if not report["streams"] and not report["hosts"]:
        print(
            f"obs fleet: no event streams or worker leases found under "
            f"{args.dir}",
            file=sys.stderr,
        )
        return 2
    print(ledger_mod.FLEET_RENDERERS[args.format](report), end="")
    return 0


def _run_trace(args) -> int:
    spans = trace_mod.load_span_events(args.dir)
    if not spans:
        print(
            f"obs trace: no span events under {args.dir} "
            "(was HYDRAGNN_TRACE_SAMPLE set for the run?)",
            file=sys.stderr,
        )
        return 2
    traces = trace_mod.build_traces(spans)
    rollup = trace_mod.anatomy(traces)
    if args.format == "json":
        import json

        rollup["slowest"] = rollup["slowest"][:max(args.slow, 0)]
        print(json.dumps(rollup, indent=2, sort_keys=True))
        return 0
    print(f"request latency anatomy — {rollup['traces']} trace(s), "
          f"{len(spans)} span(s)")
    print()
    print(f"  {'segment':<14} {'count':>6} {'p50 s':>10} {'p99 s':>10} "
          f"{'total s':>10}")
    for name, seg in rollup["segments"].items():
        print(f"  {name:<14} {seg['count']:>6} {seg['p50_s']:>10.6f} "
              f"{seg['p99_s']:>10.6f} {seg['total_s']:>10.6f}")
    if rollup["groups"]:
        print()
        print("per tenant/lane (total seconds per segment):")
        for group, segs in rollup["groups"].items():
            parts = ", ".join(
                f"{k}={v:.4f}" for k, v in segs.items() if k != "other"
            )
            print(f"  {group:<20} {parts}")
    slow = rollup["slowest"][:max(args.slow, 0)]
    if slow:
        print()
        print(f"slowest {len(slow)} trace(s):")
        for row in slow:
            flags = []
            if row["slo_missed"]:
                flags.append("SLO-MISSED")
            if row["status"] not in (None, "ok"):
                flags.append(str(row["status"]))
            suffix = f"  [{' '.join(flags)}]" if flags else ""
            print(
                f"  {row['trace']}  {row['dur_s']:.6f}s  "
                f"tenant={row['tenant'] or '-'} lane={row['lane'] or '-'} "
                f"spans={row['spans']} "
                f"dominant={row['dominant'] or '-'}{suffix}"
            )
    return 0


def _run_drift(args) -> int:
    records = drift_mod.load_quality_events(args.dir)
    if not records:
        print(
            f"obs drift: no drift/quality events under {args.dir} "
            "(was HYDRAGNN_DRIFT_WINDOW set for the run?)",
            file=sys.stderr,
        )
        return 2
    report = drift_mod.build_drift_report(records)
    if args.format == "json":
        import json

        print(json.dumps(report, indent=2, sort_keys=True))
        return 0
    print(drift_mod.render_drift_text(report), end="")
    return 0


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    if args.command == "report":
        return _run_report(args)
    if args.command == "fleet":
        return _run_fleet(args)
    if args.command == "trace":
        return _run_trace(args)
    if args.command == "drift":
        return _run_drift(args)
    build_parser().print_help(sys.stderr)
    return 2


if __name__ == "__main__":
    sys.exit(main())
