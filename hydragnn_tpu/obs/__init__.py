"""hydragnn_tpu.obs — unified telemetry (docs/observability.md).

One coherent observability layer for training AND serving:

- :mod:`~hydragnn_tpu.obs.metrics` — the shared metrics core (counters,
  gauges, latency histograms, Prometheus text), promoted from
  ``serve/metrics.py``; serving re-exports it unchanged.
- :mod:`~hydragnn_tpu.obs.events` — structured run events: append-only
  JSONL per run with a documented schema (manifest, per-epoch records,
  checkpoint/guard/resume lifecycle).
- :mod:`~hydragnn_tpu.obs.scalars` — backend-agnostic ``ScalarWriter``
  fan-out (always-on JSONL/CSV, TensorBoard when torch is importable).
- :mod:`~hydragnn_tpu.obs.http` — the stdlib ``/healthz`` + ``/metrics``
  listener, shared by the predict server and live training runs.
- :mod:`~hydragnn_tpu.obs.runtime` — per-run glue: ``RunTelemetry``,
  ``TrainingMetrics``, and the no-op-when-inactive module hooks the
  training code calls.

Quick start (training side)::

    HYDRAGNN_OBS_PORT=8090 python train.py   # live /metrics + /healthz
    tail -f logs/<run>/events.jsonl          # structured run events
"""

from hydragnn_tpu.obs.events import (
    EVENT_FIELDS,
    SCHEMA_VERSION,
    RunEventLog,
    validate_events,
)
from hydragnn_tpu.obs.http import ObservabilityServer
from hydragnn_tpu.obs.metrics import (
    DEFAULT_LATENCY_BOUNDS,
    EPOCH_LATENCY_BOUNDS,
    LatencyHistogram,
    MetricsRegistry,
    ServeMetrics,
)
from hydragnn_tpu.obs.runtime import (
    RunTelemetry,
    TrainingMetrics,
    activate,
    active,
    deactivate,
    init_run_telemetry,
)
from hydragnn_tpu.obs.scalars import ScalarWriter

__all__ = [
    "DEFAULT_LATENCY_BOUNDS",
    "EPOCH_LATENCY_BOUNDS",
    "EVENT_FIELDS",
    "LatencyHistogram",
    "MetricsRegistry",
    "ObservabilityServer",
    "RunEventLog",
    "RunTelemetry",
    "SCHEMA_VERSION",
    "ScalarWriter",
    "ServeMetrics",
    "TrainingMetrics",
    "activate",
    "active",
    "deactivate",
    "init_run_telemetry",
    "validate_events",
]
