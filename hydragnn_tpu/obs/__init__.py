"""hydragnn_tpu.obs — unified telemetry (docs/observability.md).

One coherent observability layer for training AND serving:

- :mod:`~hydragnn_tpu.obs.metrics` — the shared metrics core (counters,
  gauges, latency histograms, Prometheus text), promoted from
  ``serve/metrics.py``; serving re-exports it unchanged.
- :mod:`~hydragnn_tpu.obs.events` — structured run events: append-only
  JSONL per run with a documented schema (manifest, per-epoch records,
  checkpoint/guard/resume lifecycle).
- :mod:`~hydragnn_tpu.obs.scalars` — backend-agnostic ``ScalarWriter``
  fan-out (always-on JSONL/CSV, TensorBoard when torch is importable).
- :mod:`~hydragnn_tpu.obs.http` — the stdlib ``/healthz`` + ``/metrics``
  listener, shared by the predict server and live training runs.
- :mod:`~hydragnn_tpu.obs.runtime` — per-run glue: ``RunTelemetry``,
  ``TrainingMetrics``, the step-time ``FlightRecorder`` (stall alerts),
  and the no-op-when-inactive module hooks the training code calls.
- :mod:`~hydragnn_tpu.obs.introspect` — XLA introspection: compiled
  cost/memory accounting per (program, bucket), on-demand
  ``/profile?steps=N`` trace capture, the reference-parity ``Profiler``
  schedule.
- :mod:`~hydragnn_tpu.obs.report` — post-mortem run reports from
  ``events.jsonl`` + the perf-budget ratchet
  (``python -m hydragnn_tpu.obs report``).

Quick start (training side)::

    HYDRAGNN_OBS_PORT=8090 python train.py   # live /metrics + /healthz
    tail -f logs/<run>/events.jsonl          # structured run events
"""

from hydragnn_tpu.obs.events import (
    EVENT_FIELDS,
    SCHEMA_VERSION,
    RunEventLog,
    validate_events,
)
from hydragnn_tpu.obs.http import ObservabilityServer
from hydragnn_tpu.obs.metrics import (
    DEFAULT_LATENCY_BOUNDS,
    EPOCH_LATENCY_BOUNDS,
    LatencyHistogram,
    MetricsRegistry,
    ServeMetrics,
)
from hydragnn_tpu.obs.introspect import (
    InstrumentedJit,
    Profiler,
    TraceCapture,
    instrument,
)
from hydragnn_tpu.obs.ledger import (
    CATEGORIES,
    GoodputLedger,
    build_fleet_report,
    flag_stragglers,
    resolve_peak_flops,
)
from hydragnn_tpu.obs.runtime import (
    FlightRecorder,
    RunTelemetry,
    TrainingMetrics,
    activate,
    active,
    deactivate,
    init_run_telemetry,
)
from hydragnn_tpu.obs.scalars import ScalarWriter

__all__ = [
    "CATEGORIES",
    "DEFAULT_LATENCY_BOUNDS",
    "EPOCH_LATENCY_BOUNDS",
    "EVENT_FIELDS",
    "FlightRecorder",
    "GoodputLedger",
    "InstrumentedJit",
    "LatencyHistogram",
    "MetricsRegistry",
    "ObservabilityServer",
    "Profiler",
    "RunEventLog",
    "RunTelemetry",
    "SCHEMA_VERSION",
    "ScalarWriter",
    "ServeMetrics",
    "TraceCapture",
    "TrainingMetrics",
    "activate",
    "active",
    "build_fleet_report",
    "deactivate",
    "flag_stragglers",
    "init_run_telemetry",
    "instrument",
    "resolve_peak_flops",
    "validate_events",
]
