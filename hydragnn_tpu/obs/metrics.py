"""Shared metrics core: counters, gauges, latency histograms, Prometheus text.

Promoted out of ``hydragnn_tpu/serve/metrics.py`` (PR 2) so training and
serving report through ONE machinery — ``hydragnn_tpu.serve.metrics``
re-exports every public name unchanged, and the serving ``/metrics``
output is byte-identical to the pre-refactor module (locked by
``tests/test_observability.py``).

Stdlib-only by design — the repo bakes in no prometheus_client; the
Prometheus text exposition format is simple enough to emit directly, and
``snapshot()`` returns the same numbers as a plain dict for tests,
benchmarks, and ``/healthz``.

Two layers:

- :class:`LatencyHistogram` + :class:`ServeMetrics` — the serving
  contract (docs/serving.md "Metrics schema"), moved here verbatim.
- :class:`MetricsRegistry` — a generic declare-then-record registry
  (counter/gauge/histogram under one lock) that the training-side
  telemetry (``obs/runtime.py``) builds on; new subsystems declare their
  own registry instead of hand-rolling another metrics class.
"""

import bisect
import threading
from typing import Dict, List, Optional

# log-spaced seconds, 500us .. 10s — single-graph GNN inference spans
# ~1ms (warm CPU/TPU bucket hit) to seconds (cold compile / queueing)
DEFAULT_LATENCY_BOUNDS = (
    0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
    0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
)

# log-spaced seconds, 50ms .. 1h — training epochs span sub-second (tiny
# CI runs) to tens of minutes (at-scale multi-host epochs)
EPOCH_LATENCY_BOUNDS = (
    0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 25.0, 60.0,
    150.0, 300.0, 600.0, 1500.0, 3600.0,
)


class LatencyHistogram:
    """Fixed-bound histogram with quantile estimates.

    Quantiles interpolate linearly inside the winning bucket (the
    Prometheus ``histogram_quantile`` rule) — exact enough for p50/p99
    reporting without retaining per-request samples."""

    def __init__(self, bounds=DEFAULT_LATENCY_BOUNDS):
        self.bounds: List[float] = list(bounds)
        self.counts: List[int] = [0] * (len(self.bounds) + 1)  # +inf tail
        self.total = 0
        self.sum = 0.0

    def observe(self, seconds: float):
        self.counts[bisect.bisect_left(self.bounds, seconds)] += 1
        self.total += 1
        self.sum += seconds

    def quantile(self, q: float) -> float:
        """Estimated q-quantile in seconds (0 with no observations; the
        last finite bound when the target falls in the +inf tail)."""
        if self.total == 0:
            return 0.0
        target = q * self.total
        seen = 0
        for i, c in enumerate(self.counts):
            if seen + c >= target and c > 0:
                lo = self.bounds[i - 1] if i > 0 else 0.0
                hi = (
                    self.bounds[i]
                    if i < len(self.bounds)
                    else self.bounds[-1]
                )
                return lo + (hi - lo) * (target - seen) / c
            seen += c
        return self.bounds[-1]

    def state(self) -> Dict:
        return {
            "count": self.total,
            "sum": round(self.sum, 6),
            "p50": round(self.quantile(0.50), 6),
            "p99": round(self.quantile(0.99), 6),
        }


def render_summary(prefix: str, name: str, hist_state: Dict) -> List[str]:
    """Prometheus summary lines for one :meth:`LatencyHistogram.state` —
    the ONE place the summary exposition format lives."""
    return [
        f"# TYPE {prefix}_{name} summary",
        f'{prefix}_{name}{{quantile="0.5"}} {hist_state["p50"]}',
        f'{prefix}_{name}{{quantile="0.99"}} {hist_state["p99"]}',
        f"{prefix}_{name}_sum {hist_state['sum']}",
        f"{prefix}_{name}_count {hist_state['count']}",
    ]


class MetricsRegistry:
    """Generic thread-safe metrics: declare once, record from any thread.

    The declaration order is the exposition order. Histograms render as
    Prometheus summaries (p50/p99 + sum + count), matching the serving
    exposition so dashboards treat training and serving series alike.
    """

    def __init__(self, prefix: str):
        self.prefix = prefix
        self._lock = threading.Lock()
        self._kinds: Dict[str, str] = {}   # name -> counter|gauge|histogram
        self._help: Dict[str, str] = {}
        self._values: Dict[str, object] = {}

    def _declare(self, name: str, kind: str, help_text: str, init):
        with self._lock:
            if name in self._kinds:
                raise ValueError(f"metric {name!r} already declared")
            self._kinds[name] = kind
            self._help[name] = help_text
            self._values[name] = init
        return self

    def counter(self, name: str, help_text: str = ""):
        return self._declare(name, "counter", help_text, 0)

    def gauge(self, name: str, help_text: str = ""):
        return self._declare(name, "gauge", help_text, 0.0)

    def histogram(self, name: str, help_text: str = "",
                  bounds=DEFAULT_LATENCY_BOUNDS):
        return self._declare(
            name, "histogram", help_text, LatencyHistogram(bounds)
        )

    def labeled_gauge(self, name: str, help_text: str = ""):
        """A gauge family keyed by label sets (e.g. per-bucket compiled
        FLOPs): one declaration, one exposition line per distinct label
        combination recorded via :meth:`set_labeled`."""
        return self._declare(name, "labeled_gauge", help_text, {})

    def inc(self, name: str, value: int = 1):
        with self._lock:
            self._values[name] += value

    def set(self, name: str, value: float):
        with self._lock:
            self._values[name] = value

    def set_labeled(self, name: str, value: float, **labels):
        """Record one label-set's value on a :meth:`labeled_gauge`.
        Label RENDER order is the sorted key order — deterministic
        exposition regardless of call-site kwarg order."""
        key = tuple(sorted((k, str(v)) for k, v in labels.items()))
        with self._lock:
            self._values[name][key] = value

    def clear_labeled(self, name: str):
        """Drop every label set of a :meth:`labeled_gauge` — for families
        whose membership is a LIVE view (e.g. per-host fleet gauges): a
        member that disappeared must stop being exported, not freeze at
        its last value."""
        with self._lock:
            self._values[name].clear()

    def observe(self, name: str, seconds: float):
        with self._lock:
            self._values[name].observe(seconds)

    def get(self, name: str):
        with self._lock:
            return self._freeze(self._kinds[name], self._values[name])

    @staticmethod
    def _freeze(kind, value):
        if kind == "histogram":
            return value.state()
        if kind == "labeled_gauge":
            return {
                ",".join(f"{k}={v}" for k, v in key): val
                for key, val in value.items()
            }
        return value

    def snapshot(self) -> Dict:
        with self._lock:
            return {
                n: self._freeze(self._kinds[n], v)
                for n, v in self._values.items()
            }

    def render_prometheus(self, prefix: Optional[str] = None) -> str:
        prefix = prefix or self.prefix
        with self._lock:
            names = list(self._kinds)
            kinds = dict(self._kinds)
            helps = dict(self._help)
            values = {
                n: (dict(v) if isinstance(v, dict) else
                    v.state() if isinstance(v, LatencyHistogram) else v)
                for n, v in self._values.items()
            }
        lines = []
        for name in names:
            kind = kinds[name]
            if kind == "histogram":
                lines.extend(render_summary(prefix, name, values[name]))
                continue
            if kind == "labeled_gauge":
                series = values[name]
                if not series:  # no label sets yet: no exposition lines
                    continue
                lines.append(f"# HELP {prefix}_{name} {helps[name]}")
                lines.append(f"# TYPE {prefix}_{name} gauge")
                for key in sorted(series):
                    labels = ",".join(f'{k}="{v}"' for k, v in key)
                    v = series[key]
                    if isinstance(v, float):
                        v = round(v, 6)
                    lines.append(f"{prefix}_{name}{{{labels}}} {v}")
                continue
            lines.append(f"# HELP {prefix}_{name} {helps[name]}")
            lines.append(f"# TYPE {prefix}_{name} {kind}")
            v = values[name]
            if isinstance(v, float):
                v = round(v, 6)
            lines.append(f"{prefix}_{name} {v}")
        return "\n".join(lines) + "\n"


class ServeMetrics:
    """All counters the predict server reports (thread-safe).

    ``requests_total`` counts every accepted submit; a request then ends
    in exactly one of ``responses_total``, ``timeouts_total``, or
    ``errors_total``. ``shed_total`` counts queue-full rejections (never
    accepted, so not in ``requests_total``). Padding waste is tracked as
    the two raw integrals (real vs padded node rows) so the ratio stays
    exact under any aggregation window.

    SLO accounting (roadmap item 3 prerequisite): every DEADLINE-CARRYING
    request that reaches a terminal serving outcome resolves to exactly
    one ``deadline_met_total`` / ``deadline_missed_total`` — missed
    covers both in-queue expiry (``on_timeout`` counts it automatically)
    and a response delivered after its deadline. Requests that FAIL
    (``errors_total``) are serving failures, not deadline outcomes, and
    touch neither counter — reconcile against ``errors_total``
    separately. ``slo_misses_total`` is the alertable counter
    (== missed); ``slo_miss_ratio`` the derived gauge. Requests without
    deadlines never touch these series."""

    def __init__(self):
        self._lock = threading.Lock()
        self.requests_total = 0
        self.responses_total = 0
        self.shed_total = 0
        self.timeouts_total = 0
        self.errors_total = 0
        self.batches_total = 0
        self.compiles_total = 0
        self.bucket_hits: Dict[int, int] = {}
        self.bucket_fallbacks = 0  # graph served by a larger bucket
        self.real_node_rows = 0
        self.padded_node_rows = 0
        self.queue_depth = 0
        self.deadline_met_total = 0
        self.deadline_missed_total = 0
        # response-cache series (serve/cache.py): hits answer without a
        # bucket slot, bytes is the cache's CURRENT payload residency
        self.cache_hits_total = 0
        self.cache_misses_total = 0
        self.cache_evictions_total = 0
        self.cache_bytes = 0
        self.request_latency = LatencyHistogram()
        self.batch_latency = LatencyHistogram()

    # ---- recording -----------------------------------------------------
    def on_submit(self):
        with self._lock:
            self.requests_total += 1

    def on_shed(self):
        with self._lock:
            self.shed_total += 1

    def on_response(self, n: int = 1):
        """A request reached a successful terminal response without a
        packed batch to account it (the fleet router's path; the
        in-process server counts responses per batch via
        :meth:`on_batch`)."""
        with self._lock:
            self.responses_total += n

    def on_timeout(self, n: int = 1):
        # an in-queue expiry IS a missed deadline (only deadline-carrying
        # requests can time out)
        with self._lock:
            self.timeouts_total += n
            self.deadline_missed_total += n

    def on_deadline(self, met: bool, n: int = 1):
        """A deadline-carrying request completed: did its response land
        before the deadline?"""
        with self._lock:
            if met:
                self.deadline_met_total += n
            else:
                self.deadline_missed_total += n

    def on_error(self, n: int = 1):
        with self._lock:
            self.errors_total += n

    def on_compile(self):
        with self._lock:
            self.compiles_total += 1

    def on_cache_hit(self, n: int = 1):
        with self._lock:
            self.cache_hits_total += n

    def on_cache_miss(self, n: int = 1):
        with self._lock:
            self.cache_misses_total += n

    def on_cache_evict(self, n: int = 1):
        with self._lock:
            self.cache_evictions_total += n

    def set_cache_bytes(self, nbytes: int):
        with self._lock:
            self.cache_bytes = int(nbytes)

    def set_queue_depth(self, depth: int):
        with self._lock:
            self.queue_depth = depth

    def on_batch(
        self,
        bucket: int,
        num_requests: int,
        real_nodes: int,
        padded_nodes: int,
        batch_seconds: float,
        fallbacks: int = 0,
    ):
        with self._lock:
            self.batches_total += 1
            self.responses_total += num_requests
            self.bucket_hits[bucket] = (
                self.bucket_hits.get(bucket, 0) + num_requests
            )
            self.bucket_fallbacks += fallbacks
            self.real_node_rows += real_nodes
            self.padded_node_rows += padded_nodes
            self.batch_latency.observe(batch_seconds)

    def on_response_latency(self, seconds: float):
        with self._lock:
            self.request_latency.observe(seconds)

    # ---- reading -------------------------------------------------------
    def padding_waste_ratio(self) -> float:
        """Fraction of padded node rows that carried no real node — 0 is
        a perfectly full batch, 1-ish means the padding dominates."""
        with self._lock:
            if self.padded_node_rows == 0:
                return 0.0
            return 1.0 - self.real_node_rows / self.padded_node_rows

    def snapshot(self) -> Dict:
        with self._lock:
            return {
                "requests_total": self.requests_total,
                "responses_total": self.responses_total,
                "shed_total": self.shed_total,
                "timeouts_total": self.timeouts_total,
                "errors_total": self.errors_total,
                "batches_total": self.batches_total,
                "compiles_total": self.compiles_total,
                "bucket_hits": dict(self.bucket_hits),
                "bucket_fallbacks": self.bucket_fallbacks,
                "queue_depth": self.queue_depth,
                "padding_waste_ratio": round(
                    0.0
                    if self.padded_node_rows == 0
                    else 1.0 - self.real_node_rows / self.padded_node_rows,
                    6,
                ),
                "deadline_met_total": self.deadline_met_total,
                "deadline_missed_total": self.deadline_missed_total,
                "slo_miss_ratio": round(
                    self.deadline_missed_total
                    / max(
                        self.deadline_met_total
                        + self.deadline_missed_total,
                        1,
                    ),
                    6,
                ),
                "cache_hits_total": self.cache_hits_total,
                "cache_misses_total": self.cache_misses_total,
                "cache_evictions_total": self.cache_evictions_total,
                "cache_bytes": self.cache_bytes,
                "request_latency": self.request_latency.state(),
                "batch_latency": self.batch_latency.state(),
            }

    def render_prometheus(self, prefix: str = "hydragnn_serve") -> str:
        """Prometheus text exposition of :meth:`snapshot`."""
        s = self.snapshot()
        lines = []

        def counter(name, value, help_text):
            lines.append(f"# HELP {prefix}_{name} {help_text}")
            kind = "gauge" if name.endswith(("_depth", "_ratio")) else "counter"
            lines.append(f"# TYPE {prefix}_{name} {kind}")
            lines.append(f"{prefix}_{name} {value}")

        counter("requests_total", s["requests_total"], "Accepted requests")
        counter("responses_total", s["responses_total"], "Completed requests")
        counter("shed_total", s["shed_total"], "Queue-full rejections")
        counter("timeouts_total", s["timeouts_total"], "Deadline expiries")
        counter("errors_total", s["errors_total"], "Failed requests")
        counter("batches_total", s["batches_total"], "Dispatched micro-batches")
        counter("compiles_total", s["compiles_total"], "Novel-shape compiles")
        counter(
            "bucket_fallbacks_total",
            s["bucket_fallbacks"],
            "Requests served by a larger bucket than their node count",
        )
        counter("queue_depth", s["queue_depth"], "Requests waiting")
        counter(
            "padding_waste_ratio",
            s["padding_waste_ratio"],
            "Padded node rows carrying no real node",
        )
        for b, hits in sorted(s["bucket_hits"].items()):
            lines.append(
                f'{prefix}_bucket_hits_total{{bucket="{b}"}} {hits}'
            )
        for name, hist in (
            ("request_latency_seconds", s["request_latency"]),
            ("batch_latency_seconds", s["batch_latency"]),
        ):
            lines.extend(render_summary(prefix, name, hist))
        # SLO series appended AFTER the historical exposition so existing
        # consumers' byte offsets are untouched (the golden parity test
        # was updated deliberately for these lines)
        counter(
            "slo_misses_total",
            s["deadline_missed_total"],
            "Deadline-carrying requests that missed their deadline",
        )
        lines.append(
            f'{prefix}_deadline_outcomes_total{{outcome="met"}} '
            f'{s["deadline_met_total"]}'
        )
        lines.append(
            f'{prefix}_deadline_outcomes_total{{outcome="missed"}} '
            f'{s["deadline_missed_total"]}'
        )
        counter(
            "slo_miss_ratio",
            s["slo_miss_ratio"],
            "Fraction of deadline-carrying requests that missed",
        )
        # response-cache series appended after the SLO tail for the same
        # reason the SLO tail followed the historical block: existing
        # consumers' byte offsets stay put, the golden grows by this tail
        counter(
            "cache_hits_total",
            s["cache_hits_total"],
            "Requests answered from the response cache",
        )
        counter(
            "cache_misses_total",
            s["cache_misses_total"],
            "Cache lookups that fell through to dispatch",
        )
        counter(
            "cache_evictions_total",
            s["cache_evictions_total"],
            "Entries evicted by the LRU bounds",
        )
        lines.append(
            f"# HELP {prefix}_cache_bytes Resident response-cache payload "
            "bytes"
        )
        lines.append(f"# TYPE {prefix}_cache_bytes gauge")
        lines.append(f"{prefix}_cache_bytes {s['cache_bytes']}")
        return "\n".join(lines) + "\n"
