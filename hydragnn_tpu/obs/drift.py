"""Streaming drift detection — the model-quality half of observability.

Everything so far watches the *machine* (MFU, XLA cost, traces, cost
attribution); this module watches the *model*: are the inputs it serves
(and the predictions it returns) still distributed like the traffic it
was vetted on when it was promoted?

Three layers, bottom-up:

- **Sketches** — :class:`P2Quantile` (Jain & Chlamtac 1985 P², one
  quantile in O(1) state, NOT mergeable — used for the per-head
  uncertainty quantiles) and :class:`StreamingHistogram` (Ben-Haim &
  Tom-Tov style bounded centroid histogram, mergeable: merging two
  sketches of two streams approximates the sketch of the concatenated
  stream regardless of merge order — the property the fleet rollup and
  the reference-window snapshot both rely on).
- **Scores** — :func:`psi` (population stability index over
  reference-quantile bins) and :func:`ks` (max CDF gap), both scipy-free
  and computed sketch-vs-sketch, never sample-vs-sample.
- **Detector** — :class:`DriftDetector` folds per-request input features
  (node/edge counts, species values, edge lengths) and per-head
  prediction/uncertainty scalars into tumbling-window sketches, scores
  each window against a *version-pinned reference window* and raises /
  clears ``drift_alert`` events with hysteresis.

Reference-window lifecycle (the no-aliasing invariant): the reference is
snapshotted to ``drift-ref-v<version>.json`` the first time a version
activates — promote snapshots the traffic the candidate was just vetted
on; a ROLLBACK re-activates an older version whose file already exists
and is reloaded, never re-snapshotted. Scores are therefore always "vs
what this exact version was vetted on"; two versions can never share (or
overwrite) a baseline.
"""

import json
import math
import os
import threading
from typing import Dict, List, Optional

import numpy as np

# knob defaults (docs/observability.md "Model-quality observatory" —
# the unit-lock tests pin these names and semantics)
DEFAULT_WINDOW = 64        # requests per tumbling evaluation window
DEFAULT_PSI = 0.25         # PSI at/above => window counts toward raise
DEFAULT_KS = 0.35          # KS  at/above => window counts toward raise
DEFAULT_RAISE = 2          # consecutive over-threshold windows to raise
DEFAULT_CLEAR = 2          # consecutive clean windows to clear
DEFAULT_BINS = 64          # StreamingHistogram centroid budget

# per-request caps on the unbounded feature streams (species values,
# edge lengths): drift needs the distribution, not every sample
_SPECIES_CAP = 128
_EDGE_CAP = 64


class P2Quantile:
    """P² single-quantile estimator (Jain & Chlamtac 1985).

    O(1) state (5 markers), no buffering past the first 5 samples.
    Exact below 5 observations. NOT mergeable — use
    :class:`StreamingHistogram` where sketches must combine.
    """

    def __init__(self, q: float):
        if not 0.0 < q < 1.0:
            raise ValueError(f"quantile must be in (0, 1), got {q}")
        self.q = float(q)
        self.n = 0
        self._heights: List[float] = []
        self._pos: List[float] = []
        self._want: List[float] = []
        self._inc = [0.0, q / 2.0, q, (1.0 + q) / 2.0, 1.0]

    def add(self, x: float):
        x = float(x)
        self.n += 1
        if self.n <= 5:
            self._heights.append(x)
            self._heights.sort()
            if self.n == 5:
                self._pos = [1.0, 2.0, 3.0, 4.0, 5.0]
                q = self.q
                self._want = [
                    1.0, 1.0 + 2.0 * q, 1.0 + 4.0 * q, 3.0 + 2.0 * q, 5.0,
                ]
            return
        h, pos = self._heights, self._pos
        if x < h[0]:
            h[0] = x
            k = 0
        elif x >= h[4]:
            h[4] = x
            k = 3
        else:
            k = 0
            while k < 3 and x >= h[k + 1]:
                k += 1
        for i in range(k + 1, 5):
            pos[i] += 1.0
        for i in range(5):
            self._want[i] += self._inc[i]
        # adjust interior markers toward their desired positions
        for i in (1, 2, 3):
            d = self._want[i] - pos[i]
            if (d >= 1.0 and pos[i + 1] - pos[i] > 1.0) or (
                d <= -1.0 and pos[i - 1] - pos[i] < -1.0
            ):
                s = 1.0 if d >= 1.0 else -1.0
                hp = self._parabolic(i, s)
                if h[i - 1] < hp < h[i + 1]:
                    h[i] = hp
                else:  # parabolic overshot: linear fallback
                    j = i + int(s)
                    h[i] = h[i] + s * (h[j] - h[i]) / (pos[j] - pos[i])
                pos[i] += s

    def _parabolic(self, i: int, s: float) -> float:
        h, n = self._heights, self._pos
        return h[i] + s / (n[i + 1] - n[i - 1]) * (
            (n[i] - n[i - 1] + s) * (h[i + 1] - h[i]) / (n[i + 1] - n[i])
            + (n[i + 1] - n[i] - s) * (h[i] - h[i - 1]) / (n[i] - n[i - 1])
        )

    def value(self) -> Optional[float]:
        if self.n == 0:
            return None
        if self.n <= 5:  # exact: nearest-rank over the sorted buffer
            idx = min(int(math.ceil(self.q * self.n)) - 1, self.n - 1)
            return self._heights[max(idx, 0)]
        return self._heights[2]


class StreamingHistogram:
    """Bounded mergeable centroid histogram (Ben-Haim & Tom-Tov style).

    At most ``max_bins`` (centroid, count) pairs; inserting past the
    budget merges the two closest centroids (weighted). ``merge`` feeds
    one sketch's bins into another, so combining per-stream sketches
    approximates the sketch of the concatenated stream — merge order
    only moves estimates within the sketch's own approximation error
    (the merge-associativity property test pins this).
    """

    def __init__(self, max_bins: int = DEFAULT_BINS):
        if max_bins < 2:
            raise ValueError(f"max_bins must be >= 2, got {max_bins}")
        self.max_bins = int(max_bins)
        self.bins: List[List[float]] = []  # [centroid, count], sorted
        self.total = 0.0
        self.min: Optional[float] = None
        self.max: Optional[float] = None

    def add(self, x: float, count: float = 1.0):
        x, count = float(x), float(count)
        if count <= 0.0 or not math.isfinite(x):
            return
        self.total += count
        self.min = x if self.min is None else min(self.min, x)
        self.max = x if self.max is None else max(self.max, x)
        bins = self.bins
        lo, hi = 0, len(bins)
        while lo < hi:
            mid = (lo + hi) // 2
            if bins[mid][0] < x:
                lo = mid + 1
            else:
                hi = mid
        if lo < len(bins) and bins[lo][0] == x:
            bins[lo][1] += count
            return
        bins.insert(lo, [x, count])
        while len(bins) > self.max_bins:
            # merge the closest adjacent pair (weighted centroid)
            gaps = [
                bins[i + 1][0] - bins[i][0] for i in range(len(bins) - 1)
            ]
            i = gaps.index(min(gaps))
            c1, w1 = bins[i]
            c2, w2 = bins[i + 1]
            w = w1 + w2
            bins[i] = [(c1 * w1 + c2 * w2) / w, w]
            del bins[i + 1]

    def merge(self, other: "StreamingHistogram"):
        for c, w in other.bins:
            self.add(c, w)
        if other.min is not None:
            self.min = (
                other.min if self.min is None else min(self.min, other.min)
            )
        if other.max is not None:
            self.max = (
                other.max if self.max is None else max(self.max, other.max)
            )

    def copy(self) -> "StreamingHistogram":
        h = StreamingHistogram(self.max_bins)
        h.bins = [list(b) for b in self.bins]
        h.total, h.min, h.max = self.total, self.min, self.max
        return h

    def cdf(self, x: float) -> float:
        """Fraction of mass <= x, with each bin's mass split linearly
        around its centroid (the BHTT sum convention)."""
        if self.total <= 0.0 or self.min is None:
            return 0.0
        if x < self.min:
            return 0.0
        if x >= self.max:
            return 1.0
        bins = self.bins
        acc = 0.0
        for i, (c, w) in enumerate(bins):
            if c <= x:
                acc += w
                continue
            # x sits between centroid i-1 and centroid i: interpolate
            # the half-masses each centroid contributes to the gap
            if i == 0:
                lo_c, lo_w = self.min, 0.0
            else:
                lo_c, lo_w = bins[i - 1][0], bins[i - 1][1]
            if c == lo_c:
                break
            frac = (x - lo_c) / (c - lo_c)
            acc += -lo_w / 2.0 + (lo_w + w) / 2.0 * frac
            break
        return min(max(acc / self.total, 0.0), 1.0)

    def quantile(self, q: float) -> Optional[float]:
        """Inverse of :meth:`cdf` by interpolation between centroids."""
        if self.total <= 0.0 or self.min is None:
            return None
        q = min(max(float(q), 0.0), 1.0)
        target = q * self.total
        acc = 0.0
        prev_c, prev_half = self.min, 0.0
        for c, w in self.bins:
            step = prev_half + w / 2.0
            if acc + step >= target:
                frac = (target - acc) / step if step > 0 else 0.0
                return prev_c + (c - prev_c) * frac
            acc += step
            prev_c, prev_half = c, w / 2.0
        return self.max

    def to_dict(self) -> Dict:
        return {
            "max_bins": self.max_bins,
            "bins": [[float(c), float(w)] for c, w in self.bins],
            "min": self.min,
            "max": self.max,
            "total": self.total,
        }

    @classmethod
    def from_dict(cls, d: Dict) -> "StreamingHistogram":
        h = cls(int(d.get("max_bins", DEFAULT_BINS)))
        h.bins = [[float(c), float(w)] for c, w in d.get("bins", [])]
        h.total = float(d.get("total", sum(w for _, w in h.bins)))
        h.min = d.get("min")
        h.max = d.get("max")
        return h


# ---- drift scores (scipy-free, sketch vs sketch) -------------------------


def psi(ref: StreamingHistogram, live: StreamingHistogram,
        bins: int = 10, eps: float = 1e-4) -> float:
    """Population stability index: bin edges from the REFERENCE sketch's
    quantiles (so every reference bin holds ~equal mass), fractions from
    both sketches' CDFs, ``sum((p - q) * ln(p / q))`` with epsilon
    smoothing. Rule of thumb: < 0.1 stable, > 0.25 drifted."""
    if ref.total <= 0.0 or live.total <= 0.0:
        return 0.0
    edges = []
    for i in range(1, bins):
        e = ref.quantile(i / bins)
        if e is not None and (not edges or e > edges[-1]):
            edges.append(e)
    if not edges:  # constant reference: PSI over {<=c, >c}
        edges = [ref.bins[0][0]] if ref.bins else [0.0]
    score = 0.0
    prev_r = prev_v = 0.0
    for e in edges + [float("inf")]:
        r = ref.cdf(e) if math.isfinite(e) else 1.0
        v = live.cdf(e) if math.isfinite(e) else 1.0
        p = max(r - prev_r, eps)
        q = max(v - prev_v, eps)
        score += (p - q) * math.log(p / q)
        prev_r, prev_v = r, v
    return float(score)


def ks(ref: StreamingHistogram, live: StreamingHistogram) -> float:
    """Two-sample Kolmogorov–Smirnov statistic between the sketches'
    CDFs, evaluated at every centroid of either (<= 2 x max_bins
    points — where piecewise-linear CDFs can attain their max gap)."""
    if ref.total <= 0.0 or live.total <= 0.0:
        return 0.0
    points = sorted(
        {c for c, _ in ref.bins} | {c for c, _ in live.bins}
    )
    gap = 0.0
    for x in points:
        gap = max(gap, abs(ref.cdf(x) - live.cdf(x)))
    return float(gap)


# ---- per-request feature extraction --------------------------------------


def graph_features(graph) -> Dict[str, List[float]]:
    """The input-distribution features one request contributes, straight
    off the collate-layout fields (``GraphData``): node/edge counts,
    species values (first node-feature column), edge lengths (``pos``
    distances when present, else the first ``edge_attr`` column).
    Unbounded streams are capped per request — drift needs the
    distribution, not the census."""
    feats: Dict[str, List[float]] = {
        "num_nodes": [float(graph.num_nodes)],
        "num_edges": [float(graph.num_edges)],
    }
    x = getattr(graph, "x", None)
    if x is not None and x.ndim == 2 and x.shape[1] >= 1:
        feats["species"] = [
            float(v) for v in np.asarray(x[:_SPECIES_CAP, 0], np.float64)
        ]
    ei = getattr(graph, "edge_index", None)
    pos = getattr(graph, "pos", None)
    if ei is not None and ei.size and pos is not None:
        src = np.asarray(ei[0, :_EDGE_CAP], np.int64)
        dst = np.asarray(ei[1, :_EDGE_CAP], np.int64)
        n = pos.shape[0]
        ok = (src >= 0) & (src < n) & (dst >= 0) & (dst < n)
        if ok.any():
            d = np.linalg.norm(
                np.asarray(pos, np.float64)[src[ok]]
                - np.asarray(pos, np.float64)[dst[ok]],
                axis=1,
            )
            feats["edge_len"] = [float(v) for v in d]
    elif getattr(graph, "edge_attr", None) is not None:
        ea = graph.edge_attr
        if ea.ndim == 2 and ea.shape[1] >= 1 and ea.shape[0]:
            feats["edge_len"] = [
                float(v)
                for v in np.asarray(ea[:_EDGE_CAP, 0], np.float64)
            ]
    return feats


def _key_str(tenant, feature, head) -> str:
    return f"{tenant or '-'}|{feature}|{head or '-'}"


def _key_parts(key: str):
    tenant, feature, head = key.split("|", 2)
    return tenant, feature, head


class DriftDetector:
    """Tumbling-window drift scoring against a version-pinned reference.

    Thread-safe; ``observe`` is called per served request (fleet replica
    request path), ``on_activate`` is registered as a registry
    activation listener so promote/rollback snapshot/reload the
    reference. ``emit`` (when given) receives ``drift_window`` /
    ``drift_alert`` events; gauges render through
    :meth:`render_prometheus` as
    ``hydragnn_drift_score{tenant,head,feature}``.
    """

    def __init__(
        self,
        ref_dir: str,
        *,
        window: int = DEFAULT_WINDOW,
        psi_threshold: float = DEFAULT_PSI,
        ks_threshold: float = DEFAULT_KS,
        raise_after: int = DEFAULT_RAISE,
        clear_after: int = DEFAULT_CLEAR,
        max_bins: int = DEFAULT_BINS,
        emit=None,
        metrics=None,
    ):
        from hydragnn_tpu.obs.metrics import MetricsRegistry

        self.ref_dir = ref_dir
        self.window = max(int(window), 1)
        self.psi_threshold = float(psi_threshold)
        self.ks_threshold = float(ks_threshold)
        self.raise_after = max(int(raise_after), 1)
        self.clear_after = max(int(clear_after), 1)
        self.max_bins = int(max_bins)
        self.emit = emit
        self.metrics = metrics or MetricsRegistry("hydragnn")
        self.metrics.labeled_gauge(
            "drift_score",
            "live-window PSI vs the version-pinned reference window",
        )
        self._lock = threading.Lock()
        self._live: Dict[str, StreamingHistogram] = {}
        self._last: Dict[str, StreamingHistogram] = {}
        self._ref: Optional[Dict[str, StreamingHistogram]] = None
        self._ref_version: Optional[int] = None
        self._count = 0
        self._alerts: Dict[str, Dict] = {}
        self._active: Dict[str, set] = {}  # tenant -> alerted keys
        self.windows = 0
        self.raised = 0
        self.cleared = 0
        self.requests = 0

    # ---- reference lifecycle -------------------------------------------
    def _ref_path(self, version) -> str:
        return os.path.join(self.ref_dir, f"drift-ref-v{version}.json")

    def on_activate(self, version: int):
        """Registry activation listener: pin the reference to the newly
        active version. A version seen before (rollback) RELOADS its
        frozen file; a new version (promote) snapshots the most recent
        traffic — never the other way around, so baselines cannot
        alias."""
        path = self._ref_path(version)
        if os.path.exists(path):
            try:
                with open(path) as f:
                    payload = json.load(f)
                sketches = {
                    k: StreamingHistogram.from_dict(d)
                    for k, d in payload.get("sketches", {}).items()
                }
            except (OSError, ValueError):
                sketches = {}
            with self._lock:
                self._ref = sketches or None
                self._ref_version = version
                self._reset_alerts_locked()
            return
        with self._lock:
            # snapshot the freshest traffic this process has: the last
            # completed window merged with the in-flight one
            snap: Dict[str, StreamingHistogram] = {}
            for k, h in self._last.items():
                snap[k] = h.copy()
            for k, h in self._live.items():
                if k in snap:
                    snap[k].merge(h)
                else:
                    snap[k] = h.copy()
            self._ref = snap or None
            self._ref_version = version
            self._reset_alerts_locked()
        if snap:
            self._persist_ref(version, snap)

    def _persist_ref(self, version, sketches: Dict[str, StreamingHistogram]):
        try:
            os.makedirs(self.ref_dir, exist_ok=True)
            path = self._ref_path(version)
            tmp = path + ".tmp"
            with open(tmp, "w") as f:
                json.dump(
                    {
                        "version": version,
                        "sketches": {
                            k: h.to_dict() for k, h in sketches.items()
                        },
                    },
                    f,
                )
            os.replace(tmp, path)
        except OSError:
            pass  # a full disk must not kill serving

    def _reset_alerts_locked(self):
        self._alerts.clear()
        self._active.clear()

    # ---- observation ----------------------------------------------------
    def observe(self, tenant, graph=None, heads=None, uncertainty=None):
        """Fold one served request into the live window; returns True
        when any drift alert is currently active for ``tenant`` (the
        feedback sink's "drifted" admission signal)."""
        evaluate = False
        with self._lock:
            self.requests += 1
            if graph is not None:
                for feature, values in graph_features(graph).items():
                    sk = self._sketch_locked(
                        _key_str(tenant, feature, None)
                    )
                    for v in values:
                        sk.add(v)
            if heads is not None:
                for ihead, out in enumerate(heads):
                    v = _mean_scalar(out)
                    if v is not None:
                        self._sketch_locked(
                            _key_str(tenant, "pred", str(ihead))
                        ).add(v)
            if uncertainty is not None:
                for ihead, v in enumerate(uncertainty):
                    if v is not None and math.isfinite(float(v)):
                        self._sketch_locked(
                            _key_str(tenant, "unc", str(ihead))
                        ).add(float(v))
            self._count += 1
            if self._count >= self.window:
                evaluate = True
            active = bool(self._active.get(tenant or "-"))
        if evaluate:
            self.evaluate_window()
            with self._lock:
                active = bool(self._active.get(tenant or "-"))
        return active

    def _sketch_locked(self, key: str) -> StreamingHistogram:
        sk = self._live.get(key)
        if sk is None:
            sk = self._live[key] = StreamingHistogram(self.max_bins)
        return sk

    def alert_active(self, tenant=None) -> bool:
        with self._lock:
            if tenant is None:
                return any(bool(v) for v in self._active.values())
            return bool(self._active.get(tenant or "-"))

    # ---- evaluation ------------------------------------------------------
    def evaluate_window(self):
        """Close the current window: score every live sketch against the
        reference, update gauges + hysteresis, emit events, reset."""
        alerts = []
        with self._lock:
            if self._count == 0:
                return
            live, self._live = self._live, {}
            count, self._count = self._count, 0
            self._last = live
            self.windows += 1
            version = self._ref_version
            if self._ref is None:
                # bootstrap: the first completed window becomes the
                # reference for whatever version is serving it
                self._ref = {k: h.copy() for k, h in live.items()}
                ref_snapshot = dict(self._ref)
            else:
                ref_snapshot = None
            scores: Dict[str, Dict[str, float]] = {}
            unc: Dict[str, Dict[str, float]] = {}
            if ref_snapshot is None:
                for key, sk in sorted(live.items()):
                    ref = self._ref.get(key)
                    if ref is None or ref.total <= 0.0:
                        continue  # feature new since the reference
                    s_psi = psi(ref, sk)
                    s_ks = ks(ref, sk)
                    scores[key] = {
                        "psi": round(s_psi, 6), "ks": round(s_ks, 6),
                    }
                    tenant, feature, head = _key_parts(key)
                    self.metrics.set_labeled(
                        "drift_score", s_psi,
                        tenant=tenant, feature=feature, head=head,
                    )
                    alerts.extend(
                        self._hysteresis_locked(
                            key, s_psi, s_ks, version
                        )
                    )
            for key, sk in sorted(live.items()):
                tenant, feature, head = _key_parts(key)
                if feature != "unc":
                    continue
                unc[f"{tenant}|{head}"] = {
                    "p50": _round_opt(sk.quantile(0.5)),
                    "p90": _round_opt(sk.quantile(0.9)),
                    "p99": _round_opt(sk.quantile(0.99)),
                }
        if ref_snapshot is not None and version is not None:
            self._persist_ref(version, ref_snapshot)
        if self.emit is not None:
            payload = {
                "version": version, "window": count, "scores": scores,
            }
            if unc:
                payload["uncertainty"] = unc
            self.emit("drift_window", **payload)
            for a in alerts:
                self.emit("drift_alert", **a)

    def _hysteresis_locked(self, key, s_psi, s_ks, version) -> List[Dict]:
        over = s_psi >= self.psi_threshold or s_ks >= self.ks_threshold
        st = self._alerts.setdefault(
            key, {"active": False, "over": 0, "under": 0}
        )
        out = []
        tenant, feature, head = _key_parts(key)
        if over:
            st["over"] += 1
            st["under"] = 0
            if not st["active"] and st["over"] >= self.raise_after:
                st["active"] = True
                self.raised += 1
                self._active.setdefault(tenant, set()).add(key)
                kind = "psi" if s_psi >= self.psi_threshold else "ks"
                out.append(
                    {
                        "tenant": tenant, "feature": feature,
                        "head": head, "kind": kind,
                        "score": round(
                            s_psi if kind == "psi" else s_ks, 6
                        ),
                        "status": "raised", "version": version,
                    }
                )
        else:
            st["under"] += 1
            st["over"] = 0
            if st["active"] and st["under"] >= self.clear_after:
                st["active"] = False
                self.cleared += 1
                self._active.get(tenant, set()).discard(key)
                out.append(
                    {
                        "tenant": tenant, "feature": feature,
                        "head": head, "kind": "psi",
                        "score": round(s_psi, 6),
                        "status": "cleared", "version": version,
                    }
                )
        return out

    # ---- surfacing -------------------------------------------------------
    def stats(self) -> Dict:
        with self._lock:
            return {
                "reference_version": self._ref_version,
                "reference_features": (
                    len(self._ref) if self._ref else 0
                ),
                "window": self.window,
                "windows_evaluated": self.windows,
                "requests": self.requests,
                "alerts_active": sum(
                    len(v) for v in self._active.values()
                ),
                "alerts_raised": self.raised,
                "alerts_cleared": self.cleared,
            }

    def render_prometheus(self) -> str:
        return self.metrics.render_prometheus()

    @classmethod
    def from_env(cls, ref_dir: str, emit=None) -> Optional["DriftDetector"]:
        """Knob-driven constructor (all via ``utils/envparse`` — the
        error message names the variable). ``HYDRAGNN_DRIFT_WINDOW=0``
        disables detection entirely."""
        from hydragnn_tpu.utils.envparse import env_float, env_int

        window = env_int("HYDRAGNN_DRIFT_WINDOW", DEFAULT_WINDOW)
        if window == 0:
            return None
        return cls(
            ref_dir,
            window=window,
            psi_threshold=env_float("HYDRAGNN_DRIFT_PSI", DEFAULT_PSI),
            ks_threshold=env_float("HYDRAGNN_DRIFT_KS", DEFAULT_KS),
            raise_after=env_int(
                "HYDRAGNN_DRIFT_RAISE", DEFAULT_RAISE, minimum=1
            ),
            clear_after=env_int(
                "HYDRAGNN_DRIFT_CLEAR", DEFAULT_CLEAR, minimum=1
            ),
            max_bins=env_int("HYDRAGNN_DRIFT_BINS", DEFAULT_BINS,
                             minimum=8),
            emit=emit,
        )


def _mean_scalar(out) -> Optional[float]:
    try:
        v = float(np.mean(np.asarray(out, np.float64)))
    except (TypeError, ValueError):
        return None
    return v if math.isfinite(v) else None


def _round_opt(v, digits: int = 6):
    return None if v is None else round(float(v), digits)


# ---- `obs drift` CLI report ----------------------------------------------

QUALITY_EVENTS = ("drift_window", "drift_alert", "feedback_sink")


def load_quality_events(path: str) -> List[Dict]:
    """Every quality event under a run/coordination dir (searched
    recursively for ``events*.jsonl``, the fleet layout) or in one
    stream file, tolerant-parsed and merged in (ts, seq) order."""
    import glob as glob_mod

    from hydragnn_tpu.obs.report import load_events

    if os.path.isdir(path):
        streams = sorted(
            glob_mod.glob(
                os.path.join(path, "**", "events*.jsonl"), recursive=True
            )
        )
    else:
        streams = [path]
    records: List[Dict] = []
    for stream in streams:
        try:
            records.extend(
                r for r in load_events(stream)
                if r.get("event") in QUALITY_EVENTS
            )
        except OSError:
            continue
    records.sort(key=lambda r: (r.get("ts", 0.0), r.get("seq", 0)))
    return records


def build_drift_report(records: List[Dict]) -> Dict:
    """Fold quality events into the CLI/report structure: latest scores
    per (tenant, feature, head), the alert ledger, per-head uncertainty
    quantiles, and the sink's fill/dedup counters."""
    scores: Dict[str, Dict] = {}
    uncertainty: Dict[str, Dict] = {}
    alerts: List[Dict] = []
    sink: Optional[Dict] = None
    windows = 0
    for r in records:
        ev = r.get("event")
        if ev == "drift_window":
            windows += 1
            for key, sc in (r.get("scores") or {}).items():
                if isinstance(sc, dict):
                    scores[key] = {
                        "psi": sc.get("psi"), "ks": sc.get("ks"),
                        "version": r.get("version"),
                    }
            for key, qs in (r.get("uncertainty") or {}).items():
                if isinstance(qs, dict):
                    uncertainty[key] = qs
        elif ev == "drift_alert":
            alerts.append(
                {
                    "tenant": r.get("tenant"),
                    "feature": r.get("feature"),
                    "head": r.get("head"),
                    "kind": r.get("kind"),
                    "score": r.get("score"),
                    "status": r.get("status"),
                    "version": r.get("version"),
                    "ts": r.get("ts"),
                }
            )
        elif ev == "feedback_sink":
            sink = {  # cumulative counters: last record wins
                "accepted": r.get("accepted"),
                "deduped": r.get("deduped"),
                "graphs": r.get("graphs"),
                "packs": r.get("packs"),
            }
    active = set()
    for a in alerts:
        key = (a["tenant"], a["feature"], a["head"])
        if a["status"] == "raised":
            active.add(key)
        else:
            active.discard(key)
    return {
        "windows": windows,
        "scores": scores,
        "uncertainty": uncertainty,
        "alerts": alerts,
        "alerts_active": sorted(
            "|".join(str(p) for p in key) for key in active
        ),
        "sink": sink,
    }


def render_drift_text(report: Dict) -> str:
    lines = ["== model-quality (drift) report =="]
    lines.append(
        f"windows: {report['windows']}  alerts: "
        f"{len(report['alerts'])} event(s), "
        f"{len(report['alerts_active'])} active"
    )
    if report["scores"]:
        lines += ["", "-- drift scores (latest window, vs pinned "
                  "reference) --"]
        lines.append(
            f"{'tenant':<12} {'feature':<12} {'head':<6} "
            f"{'psi':>10} {'ks':>10} {'ref_ver':>8}"
        )
        for key in sorted(report["scores"]):
            tenant, feature, head = _key_parts(key)
            sc = report["scores"][key]
            ver = sc.get("version")
            lines.append(
                f"{tenant:<12} {feature:<12} {head:<6} "
                f"{_fmt_score(sc.get('psi')):>10} "
                f"{_fmt_score(sc.get('ks')):>10} "
                f"{str(ver if ver is not None else '-'):>8}"
            )
    if report["uncertainty"]:
        lines += ["", "-- uncertainty quantiles (per tenant/head "
                  "predictive variance) --"]
        lines.append(
            f"{'tenant':<12} {'head':<6} {'p50':>12} {'p90':>12} "
            f"{'p99':>12}"
        )
        for key in sorted(report["uncertainty"]):
            tenant, _, head = (key.split("|") + ["-", "-"])[:3]
            qs = report["uncertainty"][key]
            lines.append(
                f"{tenant:<12} {head:<6} "
                f"{_fmt_score(qs.get('p50')):>12} "
                f"{_fmt_score(qs.get('p90')):>12} "
                f"{_fmt_score(qs.get('p99')):>12}"
            )
    if report["alerts"]:
        lines += ["", "-- alert ledger --"]
        for a in report["alerts"]:
            lines.append(
                f"{a['status']:<8} tenant={a['tenant']} "
                f"feature={a['feature']} head={a['head']} "
                f"{a['kind']}={_fmt_score(a['score'])} "
                f"version={a['version']}"
            )
    if report["sink"]:
        s = report["sink"]
        lines += ["", "-- feedback sink --"]
        lines.append(
            f"accepted={s.get('accepted')} deduped={s.get('deduped')} "
            f"persisted graphs={s.get('graphs')} packs={s.get('packs')}"
        )
    return "\n".join(lines) + "\n"


def _fmt_score(v) -> str:
    return "-" if v is None else f"{float(v):.4g}"
